"""Conv formulation A/B on device clock (round 5, VERDICT item 1b):
XLA's native conv_general_dilated autodiff vs MXU-dot reformulations —
1x1 convs as channel GEMMs, kxk backward via conv_general_dilated_patches
+ dot_general (the im2col/implicit-GEMM route the reference itself uses,
SpatialConvolution.scala:409, NNPrimitive.scala:106).

Each case times one jitted value_and_grad(sum(conv(x,w))) wrt (x, w):
fwd + dx + dw on device clock, interleave-free (device clock is stable).

Usage: python tools/ab_conv_form.py [case ...]
"""
import os as _os, sys as _sys
_REPO = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
_sys.path.insert(0, _REPO); _sys.path.insert(0, _os.path.join(_REPO, "tools"))
import shutil

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from profile_step import _trace_device_ops

DN = ("NCHW", "OIHW", "NCHW")


def native(stride, pad):
    def f(x, w):
        return lax.conv_general_dilated(
            x, w, window_strides=stride, padding=pad,
            dimension_numbers=DN)
    return f


def dot_1x1(stride, pad):
    """1x1 conv as a channel GEMM (pad must be 0)."""
    def f(x, w):
        if stride != (1, 1):
            x = x[:, :, ::stride[0], ::stride[1]]
        n, ci, h, wd = x.shape
        co = w.shape[0]
        # (N,Ci,H,W) x (Co,Ci) -> (N,Co,H,W), contract over Ci
        y = lax.dot_general(w.reshape(co, ci), x,
                            (((1,), (1,)), ((), ())))
        return y.transpose(1, 0, 2, 3)
    return f


def patches_bwd(stride, pad, k):
    """Native fwd; custom VJP computes dw and dx via patches+dot."""
    @jax.custom_vjp
    def f(x, w):
        return lax.conv_general_dilated(
            x, w, window_strides=stride, padding=pad,
            dimension_numbers=DN)

    def fwd(x, w):
        return f(x, w), (x, w)

    def bwd(res, g):
        x, w = res
        n, ci, h, wd = x.shape
        co, _, kh, kw = w.shape
        _, _, oh, ow = g.shape
        # dw[o, i*kh*kw] = sum_{n,oh,ow} g[n,o,oh,ow] * patches(x)[n, i*kh*kw, oh, ow]
        px = lax.conv_general_dilated_patches(
            x, (kh, kw), stride, pad, dimension_numbers=DN)
        dw = lax.dot_general(
            g.reshape(n, co, oh * ow), px.reshape(n, ci * kh * kw, oh * ow),
            (((2,), (2,)), ((0,), (0,))))  # (n, co, ci*kh*kw) batched? no:
        dw = dw.sum(0) if dw.ndim == 3 else dw
        dw = dw.reshape(co, ci, kh, kw)
        # dx = conv(g_dilated, w_flipped^T) via patches on g
        pg = lax.conv_general_dilated_patches(
            g, (kh, kw),  (1, 1),
            [(kh - 1 - pad[0][0], kh - 1 - pad[0][1]),
             (kw - 1 - pad[1][0], kw - 1 - pad[1][1])],
            lhs_dilation=stride, dimension_numbers=DN)
        wf = w[:, :, ::-1, ::-1].transpose(1, 0, 2, 3)  # (ci, co, kh, kw)
        dx = lax.dot_general(wf.reshape(ci, co * kh * kw),
                             pg.reshape(n, co * kh * kw, h * wd),
                             (((1,), (1,)), ((), ())))
        dx = dx.transpose(1, 0, 2).reshape(n, ci, h, wd)
        return dx, dw

    f.defvjp(fwd, bwd)
    return f


CASES = {
    # name: (N, Ci, H, W, Co, k, stride, pad)
    "resnet_1x1_a": (64, 64, 56, 56, 256, 1, 1, 0),
    "resnet_1x1_b": (64, 128, 28, 28, 512, 1, 1, 0),
    "resnet_1x1_s2": (64, 256, 56, 56, 512, 1, 2, 0),
    "vgg_3x3_a": (128, 64, 32, 32, 64, 3, 1, 1),
    "vgg_3x3_b": (128, 512, 4, 4, 512, 3, 1, 1),
    "incep_3x3": (128, 64, 56, 56, 192, 3, 1, 1),
    "incep_1x1_a": (128, 288, 28, 28, 256, 1, 1, 0),
    "incep_1x1_b": (128, 64, 56, 56, 64, 1, 1, 0),
    "incep_1x1_c": (128, 192, 56, 56, 64, 1, 1, 0),
    "resnet_1x1_c": (64, 256, 56, 56, 64, 1, 1, 0),
    "resnet_1x1_d": (64, 512, 28, 28, 128, 1, 1, 0),
}


def run_case(name):
    n, ci, h, wd, co, k, s, p = CASES[name]
    stride, pad = (s, s), [(p, p), (p, p)]
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(n, ci, h, wd), jnp.bfloat16)
    w = jnp.asarray(rs.randn(co, ci, k, k) * 0.05, jnp.bfloat16)
    forms = {"native": native(stride, pad)}
    if k == 1 and p == 0:
        forms["dot1x1"] = dot_1x1(stride, pad)
    if k > 1:
        forms["patches"] = patches_bwd(stride, pad, k)
    flops = 2 * n * ci * co * k * k * (h // s) * (wd // s) * 3  # fwd+dx+dw
    for fname, f in forms.items():
        def loss(x, w, f=f):
            return jnp.sum(f(x, w).astype(jnp.float32))
        g = jax.jit(jax.grad(loss, argnums=(0, 1)))
        # correctness vs native (loose: bf16)
        if fname != "native":
            gn = jax.jit(jax.grad(
                lambda x, w: jnp.sum(
                    native(stride, pad)(x, w).astype(jnp.float32)),
                argnums=(0, 1)))
            dx1, dw1 = g(x, w)
            dx0, dw0 = gn(x, w)
            ex = float(jnp.max(jnp.abs(dx1.astype(jnp.float32)
                                       - dx0.astype(jnp.float32))))
            ew = float(jnp.max(jnp.abs(dw1.astype(jnp.float32)
                                       - dw0.astype(jnp.float32))))
        else:
            ex = ew = 0.0
        out = g(x, w)
        jax.block_until_ready(out)

        def thunk():
            o = None
            for _ in range(10):
                o = g(x, w)
            return o

        per_op, tmpdir = _trace_device_ops(
            thunk, lambda o: float(jnp.sum(o[1].astype(jnp.float32))))
        shutil.rmtree(tmpdir, ignore_errors=True)
        us = sum(t for nm, t in per_op.items()
                 if not nm.startswith("while")) / 10
        tf = flops / (us / 1e6) / 1e12
        print(f"{name:14s} {fname:8s} {us/1e3:8.3f} ms  {tf:6.1f} TF/s"
              f"  maxerr dx {ex:.3g} dw {ew:.3g}", flush=True)


if __name__ == "__main__":
    for case in (_sys.argv[1:] or CASES):
        run_case(case)
