"""Deterministic offline replay of a flight-recorded decode request
(docs/observability.md "Request forensics").

The flight recorder (``bigdl_tpu/obs/recorder.py``) captures, per
request, everything the decode path consumed: the committed token row
(seed included), the seed length and hash, the decoder's construction
flags (paged/prefix/spec/quant recipe), and the served weight version.
That is a complete re-execution recipe: ``replay_request`` builds a
FRESH :class:`~bigdl_tpu.serve.decode.ContinuousDecoder` with the
recorded flags, pins the recorded weight version from a
:class:`~bigdl_tpu.serve.cluster.WeightStore` when one is supplied,
re-submits the recorded seed, and diffs the replayed token row against
the committed one.  Greedy decode is deterministic, and SAMPLED decode
is too — the recorded ``sampling`` params carry the request's resolved
PRNG seed, and the served draw keys are a pure function of (request
seed, generated index) — so the replay must be token-identical either
way.  A non-empty diff means the weights rolled (reported as
``version_mismatch``), the flags lied, or the decode stack has a real
reproducibility bug; a sampled record whose params LACK a resolved
seed is reported as ``param_mismatch`` (like ``version_mismatch``, the
replay proceeds and the diff shows the fresh draws).

Usage (CLI reads ``forensic`` events out of a run dir, or any JSONL of
records; the smoke drill and tests drive the Python API directly):

    python tools/request_replay.py RUN_DIR --model pkg.mod:factory
    python tools/request_replay.py RUN_DIR --model pkg.mod:factory \\
        --trace-id 1f2e3d...

``factory`` is a zero-arg callable returning the served model (same
architecture AND weights — replay against different weights reports
the divergence, which is the point of the version check, not a crash).
"""
from __future__ import annotations

import argparse
import importlib
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: ContinuousDecoder kwargs a recorded ``flags`` dict maps onto —
#: exactly decode_flags()'s keys (anything else in the record is
#: provenance, not construction input)
FLAG_KEYS = ("max_slots", "n_pos", "sync_interval", "paged",
             "page_size", "n_pages", "prefix_cache", "spec_k",
             "draft_layers", "kv_quant", "max_stop_seqs",
             "max_stop_len")


def _first_divergence(a, b):
    """Index of the first differing token, or None when equal."""
    for i, (x, y) in enumerate(zip(a, b)):
        if int(x) != int(y):
            return i
    if len(a) != len(b):
        return min(len(a), len(b))
    return None


def replay_request(record: dict, model, store=None) -> dict:
    """Re-execute one recorded request and diff the token stream.

    ``record`` is a flight-recorder record (the ``record`` field of a
    ``forensic`` event, or ``FlightRecorder.get``'s copy) that carries
    ``tokens``, ``seed_len`` and ``flags``.  ``model`` is the served
    model; when ``store`` (a :class:`WeightStore`) is given and the
    record names a ``weights_version``, the snapshot of that version is
    loaded into ``model`` first — a version the store no longer retains
    is reported as ``version_mismatch`` and the replay proceeds on the
    model's current weights (the diff then SHOWS the roll).

    Returns a report dict::

        {trace_id, match, diverge_at, replayed, recorded,
         weights_version, version_mismatch, sampling, param_mismatch,
         seed_hash_ok}
    """
    from bigdl_tpu.obs import recorder as obs_recorder
    from bigdl_tpu.serve.decode import ContinuousDecoder
    from bigdl_tpu.serve.sampling import SamplingParams

    tokens = record.get("tokens")
    seed_len = record.get("seed_len")
    flags = record.get("flags")
    if not tokens or not seed_len or flags is None:
        raise ValueError(
            "record is not replayable: needs tokens + seed_len + flags "
            f"(have {sorted(k for k in record if record[k] is not None)})")
    seed = [int(t) for t in tokens[:seed_len]]
    n_words = int(record.get("n_words") or (len(tokens) - seed_len))

    version = record.get("weights_version")
    version_mismatch = None
    if store is not None and version is not None:
        try:
            params, state = store.get(version)
            model.load_params(params)
            model.load_state(state)
        except KeyError as e:
            version_mismatch = str(e)

    sampling = record.get("sampling")
    param_mismatch = None
    if sampling:
        sp = SamplingParams.of(sampling)
        if not sp.greedy and sp.seed is None:
            # a sampled record without its resolved PRNG seed cannot
            # redraw the recorded stream — report it like a weight
            # roll and let the diff show the fresh draws
            param_mismatch = ("sampled record carries no resolved "
                              "seed; replay draws a fresh stream")

    kwargs = {k: flags[k] for k in FLAG_KEYS
              if flags.get(k) is not None}
    dec = ContinuousDecoder(model, **kwargs)
    fut = dec.submit(seed, n_words, sampling=sampling)
    dec.run()
    replayed = [int(t) for t in fut.result()]

    recorded = [int(t) for t in tokens]
    diverge_at = _first_divergence(replayed, recorded)
    want_hash = record.get("seed_hash")
    return {
        "trace_id": record.get("trace_id"),
        "match": diverge_at is None,
        "diverge_at": diverge_at,
        "replayed": replayed,
        "recorded": recorded,
        "weights_version": version,
        "version_mismatch": version_mismatch,
        "sampling": sampling,
        "param_mismatch": param_mismatch,
        "seed_hash_ok": (want_hash is None
                         or obs_recorder.seed_hash(seed) == want_hash),
    }


def load_records(path: str) -> list:
    """Replayable records out of a run dir's ``forensic`` events (or
    any JSONL whose lines are events or bare records)."""
    if os.path.isdir(path):
        from obs_report import load_run
        events, _, _ = load_run(path)
        return [e["record"] for e in events
                if e.get("type") == "forensic" and e.get("record")]
    out = []
    with open(path) as fh:
        for ln in fh:
            ln = ln.strip()
            if not ln:
                continue
            obj = json.loads(ln)
            if obj.get("type") == "forensic" and obj.get("record"):
                out.append(obj["record"])
            elif "tokens" in obj and "flags" in obj:
                out.append(obj)
    return out


def _load_factory(spec: str):
    mod, _, attr = spec.partition(":")
    if not attr:
        raise SystemExit(f"--model wants module:factory, got {spec!r}")
    return getattr(importlib.import_module(mod), attr)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="run dir (BIGDL_OBS_DIR) or a JSONL "
                    "of forensic events / records")
    ap.add_argument("--model", required=True,
                    help="module:factory returning the served model")
    ap.add_argument("--trace-id", help="replay only this trace id "
                    "(prefix match); default: every replayable record")
    args = ap.parse_args(argv)

    records = load_records(args.path)
    if args.trace_id:
        records = [r for r in records
                   if str(r.get("trace_id", "")).startswith(args.trace_id)]
    records = [r for r in records
               if r.get("tokens") and r.get("seed_len")
               and r.get("flags") is not None]
    if not records:
        print("no replayable records found")
        return 1

    factory = _load_factory(args.model)
    failures = 0
    for rec in records:
        rep = replay_request(rec, factory())
        tid = str(rep["trace_id"])[:8]
        if rep["match"]:
            print(f"{tid}  MATCH  ({len(rep['replayed'])} tokens)")
        else:
            failures += 1
            print(f"{tid}  DIVERGED at token {rep['diverge_at']}  "
                  f"(recorded {rep['recorded'][rep['diverge_at']:][:4]}... "
                  f"replayed {rep['replayed'][rep['diverge_at']:][:4]}...)")
        if rep["param_mismatch"]:
            print(f"{tid}  WARNING: param mismatch — "
                  f"{rep['param_mismatch']}")
        if not rep["seed_hash_ok"]:
            print(f"{tid}  WARNING: seed hash mismatch — the record's "
                  "token row does not match its own seed hash")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
