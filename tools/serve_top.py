"""Live terminal dashboard for a serving fleet (docs/observability.md
"Serving telemetry").

Polls a metrics exporter's ``/snapshot`` endpoint
(``bigdl_tpu/obs/export.py`` — start one with
``ReplicaPool.start_exporter()`` or ``BIGDL_SERVE_EXPORT_PORT``) and
renders, per engine and fleet-wide:

    rows/s   queue   inflt   shed/s   p50/p95/p99 (ms)   SLO burn

plus, when a paged continuous decoder is exporting, one trailing
``decode:`` line with KV page-pool occupancy, the prefix-cache
hit-rate and the speculative acceptance p50 (docs/serving.md "Paged
KV + speculative decode"), a ``stream:`` line with the windowed
TTFT/ITL quantiles and streamed-token rate when streaming delivery is
live (docs/observability.md "Streaming telemetry"), a ``fleet:`` line
with the dynamic-membership counts (``n=<live>
(+<warming>/-<draining>)`` from the ``fleet_replicas`` gauges, windowed
scale-action counts, and a ``SCALE FROZEN`` marker while the
autoscaler's spawn circuit breaker is open — docs/serving.md
"Autoscaling") plus the affinity/prefill/host-tier telemetry, and —
when an alert engine is exporting
``alert_active`` gauges (``obs/alerts.py``) — one ``alerts:`` line
naming every firing rule (``alerts: none`` when quiet), and — when the
flight recorder has bundled anomalies (``obs/recorder.py``,
docs/observability.md "Request forensics") — one ``anomalies:`` line
with the windowed per-kind counts and the worst anomalous e2e.

Rates are differences between consecutive snapshots (the counters are
monotonic, so the math survives engine restarts landing mid-window as a
one-frame glitch, not corruption).  Quantiles come from the merged
fixed-bucket histograms — the fleet row's p99 is the TRUE pooled p99,
not an average of per-replica p99s — and are WINDOWED the same way the
rates are (bucket counts difference just like counters), so a latency
regression shows in the next frame instead of being averaged away
under a long healthy history; an idle window falls back to the
lifetime histogram (last known latency beats a blank column).

SLO burn rate: (shed+failed)/offered over the window — offered =
accepted+shed, so every request counts exactly once (failed is a
subset of accepted) — divided by the error budget (``--budget``,
default 0.01 = a 99% success objective).  1.0 means the budget is
being consumed exactly as fast as it accrues, >1 means the fleet is
eating into reserves.

Usage:
    python tools/serve_top.py http://127.0.0.1:9090 [--interval 1]
    python tools/serve_top.py snapshots.jsonl --once   # offline replay

``--once`` prints a single frame and exits (CI smoke; for a JSONL file
the last two snapshots give the rates).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bigdl_tpu.obs import metrics  # noqa: E402


def fetch_snapshot(source: str):
    """``(ts, snapshot)`` from an exporter URL or the LAST line of a
    snapshots JSONL file."""
    if source.startswith("http://") or source.startswith("https://"):
        with urllib.request.urlopen(source.rstrip("/") + "/snapshot",
                                    timeout=5) as resp:
            rec = json.loads(resp.read())
        return float(rec["ts"]), rec["snapshot"]
    with open(source) as f:
        lines = [ln for ln in f if ln.strip()]
    if not lines:
        raise ValueError(f"no snapshots in {source}")
    rec = json.loads(lines[-1])
    return float(rec["ts"]), rec["snapshot"]


def fetch_prev_jsonl(source: str):
    """Second-to-last snapshot of a JSONL file (rates for --once)."""
    with open(source) as f:
        lines = [ln for ln in f if ln.strip()]
    if len(lines) < 2:
        return None
    rec = json.loads(lines[-2])
    return float(rec["ts"]), rec["snapshot"]


def engines_in(snapshot: dict) -> list:
    """Engine label values present in the admission-counter family."""
    fam = snapshot.get("serve_requests_total", {"series": []})
    return sorted({row["labels"]["engine"] for row in fam["series"]
                   if "engine" in row["labels"]})


def _rate(cur, prev, dt, name, **match):
    if prev is None or dt <= 0:
        return 0.0
    d = (metrics.family_total(cur, name, **match)
         - metrics.family_total(prev, name, **match))
    return max(d, 0.0) / dt


def _window_quantiles(cur, prev, name, **match):
    """p50/p95/p99 of the observations that landed BETWEEN the two
    snapshots (``metrics.windowed_counts`` — the one windowing rule
    this dashboard and the alert engine share).  Falls back to the
    lifetime histogram when there is no prev snapshot or the window
    saw no observations (last known latency beats a blank column)."""
    wc = metrics.windowed_counts(cur, prev, name, **match)
    if wc is None or prev is None or sum(wc[1]) == 0:
        return metrics.histogram_quantiles(cur, name, **match)
    bounds, counts = wc
    return {f"p{q}": metrics.quantile(bounds, counts, q)
            for q in (50, 95, 99)}


def frame_rows(cur: dict, prev: dict | None, dt: float,
               budget: float = 0.01) -> list:
    """One dict per engine plus a trailing ``fleet`` row; pure function
    of two snapshots (testable offline)."""
    rows = []
    roles = replica_roles(cur)
    scopes = [({"engine": e}, e) for e in engines_in(cur)]
    scopes.append(({}, "fleet"))
    for match, label in scopes:
        qs = _window_quantiles(cur, prev, "serve_latency_seconds",
                               **match)
        comp = _rate(cur, prev, dt, "serve_requests_total",
                     outcome="completed", **match)
        acc = _rate(cur, prev, dt, "serve_requests_total",
                    outcome="accepted", **match)
        shed = _rate(cur, prev, dt, "serve_requests_total",
                     outcome="shed", **match)
        if not match:
            # fleet row: router admission-stage sheds never reached an
            # engine (replica-stage sheds are already in the engine
            # counters), so the SLO-overload scenario this column
            # exists for shows up here and in the burn rate
            shed += _rate(cur, prev, dt, "router_requests_total",
                          outcome="shed", stage="admission")
        failed = _rate(cur, prev, dt, "serve_requests_total",
                       outcome="failed", **match)
        # failed is a SUBSET of accepted (completed+failed+inflight ==
        # accepted); only shed lives outside it — so the offered total
        # is accepted+shed and each request counts once in the burn
        bad, offered = shed + failed, acc + shed
        rows.append({
            "name": label,
            "role": roles.get(label),
            "rows_s": comp,
            "queue": int(metrics.family_total(cur, "serve_queue_depth",
                                              **match)),
            "inflight": int(metrics.family_total(cur, "serve_inflight",
                                                 **match)),
            "shed_s": shed,
            "p50_ms": None if qs["p50"] is None else qs["p50"] * 1e3,
            "p95_ms": None if qs["p95"] is None else qs["p95"] * 1e3,
            "p99_ms": None if qs["p99"] is None else qs["p99"] * 1e3,
            "burn": (bad / offered / budget) if offered > 0 else 0.0,
        })
    # disaggregated-fleet replicas are decoders/prefill workers, not
    # engines — synthesize their rows from the decoder/prefill series
    # so the role tags land on real rows (fleet row stays last)
    engine_names = {r["name"] for r in rows}
    fleet_rows = []
    for name, role in sorted(roles.items()):
        if name in engine_names:
            continue
        if role == "decode":
            comp = _rate(cur, prev, dt, "decode_retired_total",
                         decoder=name)
            occupied = int(metrics.family_total(
                cur, "decode_slots_active", decoder=name))
        else:
            comp = _rate(cur, prev, dt, "fleet_prefill_requests_total",
                         replica=name)
            occupied = 0
        fleet_rows.append({
            "name": name, "role": role, "rows_s": comp, "queue": 0,
            "inflight": occupied, "shed_s": 0.0, "p50_ms": None,
            "p95_ms": None, "p99_ms": None, "burn": 0.0,
        })
    if fleet_rows:
        rows[-1:-1] = fleet_rows       # before the trailing fleet row
    return rows


def replica_roles(snapshot: dict) -> dict:
    """``replica name -> role`` from the fleet's ``serve_replica_role``
    gauges (prefill/decode disaggregation, docs/serving.md
    "Disaggregated fleet"); empty for non-fleet snapshots.  Only
    series with value > 0 count — a replica drained out by the
    autoscaler sets (or drops) its gauge and must leave the roster."""
    fam = snapshot.get("serve_replica_role", {"series": []})
    return {row["labels"].get("replica"): row["labels"].get("role")
            for row in fam["series"]
            if row["labels"].get("replica") and row.get("value")}


def membership_part(cur: dict, prev: dict | None) -> str | None:
    """``n=<live> (+<warming>/-<draining>)`` from the ``fleet_replicas``
    membership gauges (dynamic membership / autoscaler —
    docs/serving.md "Autoscaling"), with the windowed scale-action
    counts when any landed in the window (the lifetime totals on the
    first frame — the engine rows' fallback rule) and a ``SCALE
    FROZEN`` marker while the spawn circuit breaker is open.  None when
    no membership gauges are exported."""
    if "fleet_replicas" not in cur:
        return None

    def state(s):
        return int(metrics.family_total(cur, "fleet_replicas", state=s))

    part = (f"n={state('live')} "
            f"(+{state('warming')}/-{state('draining')})")
    ups = metrics.family_total(cur, "fleet_scale_events_total",
                               direction="up")
    downs = metrics.family_total(cur, "fleet_scale_events_total",
                                 direction="down")
    if prev is not None:
        ups -= metrics.family_total(prev, "fleet_scale_events_total",
                                    direction="up")
        downs -= metrics.family_total(prev, "fleet_scale_events_total",
                                      direction="down")
    if ups or downs:
        part += f"  scaled +{int(ups)}/-{int(downs)}"
    if metrics.family_total(cur, "fleet_scale_frozen") > 0:
        part += "  SCALE FROZEN"
    return part


def fleet_line(cur: dict, prev: dict | None, dt: float) -> str | None:
    """One trailing line of fleet telemetry when a fleet router / host
    KV tier / dynamic-membership pool is exporting: the membership
    counts (``n=<live> (+<warming>/-<draining>)``), affinity hit-rate
    (windowed like the engine rates), prefill ship/skip/fallback
    counts, and the host tier's resident bytes + spill/re-admit
    counters.  None when no fleet series are present."""
    member = membership_part(cur, prev)
    has_aff = "fleet_affinity_hits_total" in cur
    has_tier = "kv_host_bytes" in cur
    if not has_aff and not has_tier and member is None:
        return None
    parts = []
    if member is not None:
        parts.append(member)
    roles = replica_roles(cur)
    if roles:
        n_dec = sum(1 for r in roles.values() if r == "decode")
        n_pre = sum(1 for r in roles.values() if r == "prefill")
        parts.append(f"{n_dec} decode + {n_pre} prefill")
    if has_aff:
        h = _rate(cur, prev, dt, "fleet_affinity_hits_total") * dt
        m = _rate(cur, prev, dt, "fleet_affinity_misses_total") * dt
        if h + m == 0:          # idle window: last known rate
            h = metrics.family_total(cur, "fleet_affinity_hits_total")
            m = metrics.family_total(cur, "fleet_affinity_misses_total")
        rate = h / (h + m) if (h + m) else None
        parts.append("affinity hit "
                     + (f"{rate:.0%}" if rate is not None else "-"))
        shipped = metrics.family_total(cur, "fleet_prefill_shipped_total")
        skipped = metrics.family_total(cur, "fleet_prefill_skipped_total")
        fallback = metrics.family_total(cur,
                                        "fleet_prefill_fallback_total")
        if shipped or skipped or fallback:
            parts.append(f"prefill {int(shipped)} shipped / "
                         f"{int(skipped)} skipped / "
                         f"{int(fallback)} colocated")
    if has_tier:
        mb = metrics.family_total(cur, "kv_host_bytes") / (1 << 20)
        spilled = metrics.family_total(cur, "kv_host_spilled_pages_total")
        readm = metrics.family_total(cur,
                                     "kv_host_readmitted_pages_total")
        parts.append(f"kv host {mb:.1f} MiB "
                     f"({int(spilled)} spilled / {int(readm)} re-admitted)")
    return "fleet: " + "   ".join(parts)


def decode_line(cur: dict, prev: dict | None, dt: float) -> str | None:
    """One trailing line of continuous-decode telemetry when a paged
    decoder is exporting: KV page-pool occupancy (current gauges),
    prefix-cache hit-rate, speculative acceptance p50 — the latter two
    WINDOWED like the engine rates (lifetime fallback when the window
    saw no admissions/windows) — and the lifetime sampled fraction of
    admitted requests.  None when no decoder series are present."""
    if "decode_pages_total" not in cur:
        return None
    total = metrics.family_total(cur, "decode_pages_total")
    in_use = metrics.family_total(cur, "decode_pages_in_use")
    occ = in_use / total if total else 0.0
    h = _rate(cur, prev, dt, "decode_prefix_hits_total") * dt
    m = _rate(cur, prev, dt, "decode_prefix_misses_total") * dt
    if h + m == 0:          # idle window: last known hit-rate
        h = metrics.family_total(cur, "decode_prefix_hits_total")
        m = metrics.family_total(cur, "decode_prefix_misses_total")
    hit_rate = h / (h + m) if (h + m) else None
    accept = _window_quantiles(cur, prev,
                               "decode_spec_accept_len").get("p50")
    adm = metrics.family_total(cur, "decode_admitted_total")
    samp = metrics.family_total(cur, "decode_sampled_total")
    frac = samp / adm if adm else None
    return (f"decode: pages {int(in_use)}/{int(total)} ({occ:.0%})   "
            f"prefix hit "
            + (f"{hit_rate:.0%}" if hit_rate is not None else "-")
            + "   spec accept p50 "
            + (f"{accept:.1f}" if accept is not None else "-")
            + "   sampled "
            + (f"{frac:.0%}" if frac is not None else "-"))


def stream_line(cur: dict, prev: dict | None, dt: float) -> str | None:
    """One trailing line of streaming-decode SLO telemetry when any
    decoder is exporting the TTFT/ITL histograms: windowed TTFT
    p50/p99, windowed ITL p50/p99 (the finer ``ITL_BUCKETS`` scale —
    rendered in ms) and the streamed-token rate.  Windowing is the
    engine-row math (bucket-count deltas, lifetime fallback on an idle
    window).  None when no streaming series are present."""
    if "decode_ttft_seconds" not in cur:
        return None
    tq = _window_quantiles(cur, prev, "decode_ttft_seconds")
    iq = _window_quantiles(cur, prev, "decode_itl_seconds")
    # the first frame has no window to rate over — render "-" like the
    # quantile fallbacks (lifetime-total / interval would inflate the
    # rate by however long the fleet has been up)
    toks = (None if prev is None
            else _rate(cur, prev, dt, "decode_stream_tokens_total"))

    def ms(v):
        return "-" if v is None else f"{v * 1e3:.2f}"

    return (f"stream: ttft p50/p99 {ms(tq['p50'])}/{ms(tq['p99'])} ms   "
            f"itl p50/p99 {ms(iq['p50'])}/{ms(iq['p99'])} ms   "
            + ("-" if toks is None else f"{toks:.1f}")
            + " tok/s streamed")


def anomalies_line(cur: dict, prev: dict | None,
                   dt: float) -> str | None:
    """One trailing ``anomalies:`` line from the flight recorder's
    ``forensic_requests_total{kind=...}`` counter (obs/recorder.py
    tail-based forensics): windowed per-kind anomaly counts (lifetime
    totals on the first frame — the engine rows' fallback rule) and the
    worst anomalous end-to-end latency high-water mark.  None when no
    recorder has ever bundled an anomaly (family absent)."""
    fam = cur.get("forensic_requests_total")
    if fam is None:
        return None
    kinds = sorted({row["labels"].get("kind", "?")
                    for row in fam["series"]})
    parts = []
    for kind in kinds:
        n = _rate(cur, prev, dt, "forensic_requests_total",
                  kind=kind) * dt
        if prev is None:       # first frame: lifetime totals
            n = metrics.family_total(cur, "forensic_requests_total",
                                     kind=kind)
        if n:
            parts.append(f"{kind}={int(n)}")
    if not parts:
        return "anomalies: none"
    worst = metrics.family_total(cur, "forensic_worst_e2e_ms")
    line = "anomalies: " + " ".join(parts)
    if worst:
        line += f"   worst e2e {worst:.1f} ms"
    return line


def alerts_line(cur: dict) -> str | None:
    """One trailing ``alerts:`` line from the ``alert_active`` gauges
    the declarative alert engine exports (``obs/alerts.py`` — rides the
    merged registry, so a rule firing on ANY replica shows here).  None
    when no alert engine has ever exported (family absent)."""
    fam = cur.get("alert_active")
    if fam is None:
        return None
    firing = sorted(row["labels"].get("rule", "?")
                    for row in fam["series"] if row.get("value"))
    if not firing:
        return "alerts: none"
    return "alerts: FIRING " + ", ".join(firing)


def _ms(v):
    return "-" if v is None else f"{v:8.2f}"


def render(rows: list, source: str, dt: float,
           decode: str | None = None,
           stream: str | None = None,
           fleet: str | None = None,
           anomalies: str | None = None,
           alerts: str | None = None) -> str:
    out = [f"serve_top — {source}  (window {dt:.1f}s)", "",
           f"{'engine':<12} {'rows/s':>8} {'queue':>6} {'inflt':>6} "
           f"{'shed/s':>7} {'p50 ms':>8} {'p95 ms':>8} {'p99 ms':>8} "
           f"{'burn':>6}"]
    for r in rows:
        marker = "*" if r["name"] == "fleet" else " "
        # disaggregated-fleet role label (prefill/decode) when known
        name = r["name"] if not r.get("role") \
            else f"{r['name']}[{r['role'][0]}]"
        out.append(
            f"{marker}{name:<11} {r['rows_s']:8.1f} {r['queue']:6d} "
            f"{r['inflight']:6d} {r['shed_s']:7.1f} {_ms(r['p50_ms'])} "
            f"{_ms(r['p95_ms'])} {_ms(r['p99_ms'])} {r['burn']:6.2f}")
    for line in (decode, stream, fleet, anomalies, alerts):
        if line:
            out += ["", line]
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("source", help="exporter base URL (http://host:port) "
                    "or a snapshots JSONL file")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="poll interval seconds (default 1)")
    ap.add_argument("--budget", type=float, default=0.01,
                    help="SLO error budget fraction (default 0.01)")
    ap.add_argument("--once", action="store_true",
                    help="print one frame and exit")
    args = ap.parse_args(argv)

    prev = None
    if args.once and not args.source.startswith("http"):
        prev = fetch_prev_jsonl(args.source)
    while True:
        ts, cur = fetch_snapshot(args.source)
        dt = (ts - prev[0]) if prev else args.interval
        rows = frame_rows(cur, prev[1] if prev else None, dt,
                          budget=args.budget)
        frame = render(rows, args.source, dt,
                       decode=decode_line(cur, prev[1] if prev else None,
                                          dt),
                       stream=stream_line(cur, prev[1] if prev else None,
                                          dt),
                       fleet=fleet_line(cur, prev[1] if prev else None,
                                        dt),
                       anomalies=anomalies_line(
                           cur, prev[1] if prev else None, dt),
                       alerts=alerts_line(cur))
        if args.once:
            print(frame)
            return 0
        sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
        sys.stdout.flush()
        prev = (ts, cur)
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
