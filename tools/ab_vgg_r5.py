"""Round-5 VGG-CIFAR campaign A/B on the bench's scanned device-side
loop (8 steps/dispatch): baseline vs rbg dropout keys vs batch size.

Within one process, interleaved windows, per-variant min — the only
timing comparison the relay-attached chip supports (PERF_NOTES).

Usage: python tools/ab_vgg_r5.py
"""
import os as _os, sys as _sys
_REPO = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
_sys.path.insert(0, _REPO)
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    import bench
    from bigdl_tpu import tensor as bt
    from bigdl_tpu import nn
    from bigdl_tpu.utils.random import set_seed

    bench._enable_compile_cache()
    bt.set_policy(bt.BF16_COMPUTE)
    N = 8

    def build(batch):
        from bigdl_tpu.models.vgg import VggForCifar10
        set_seed(1)
        rs = np.random.RandomState(0)
        x = jnp.asarray(rs.randn(batch, 3, 32, 32), jnp.float32)
        y = jnp.asarray(rs.randint(1, 11, (batch,)))
        return VggForCifar10(class_num=10), nn.ClassNLLCriterion(), x, y

    variants = []
    for batch in (128, 256):
        for impl in ("threefry2x32", "rbg"):
            jax.config.update("jax_default_prng_impl", impl)
            model, criterion, x, y = build(batch)
            rs = np.random.RandomState(7)
            xs = jnp.stack([jnp.asarray(np.asarray(x) * (1 + 0.01 * rs.randn()),
                                        x.dtype) for _ in range(N)])
            ys = jnp.stack([y] * N)
            step, params, net_state, opt_state = bench.make_chunk_step(
                model, criterion, N)
            key = jax.random.PRNGKey(0)
            name = f"bs{batch} {impl}"
            t0 = time.perf_counter()
            for _ in range(3):
                params, net_state, opt_state, loss = step(
                    params, net_state, opt_state, xs, ys, key)
            float(loss)
            print(f"compile+3 {name}: {time.perf_counter()-t0:.1f}s",
                  flush=True)
            variants.append([name, step,
                             [params, net_state, opt_state, xs, ys, key],
                             batch, []])
    jax.config.update("jax_default_prng_impl", "threefry2x32")

    for _ in range(5):
        for v in variants:
            name, step, st, batch, times = v
            t0 = time.perf_counter()
            for _ in range(4):   # 4 dispatches x N steps
                st[0], st[1], st[2], loss = step(st[0], st[1], st[2],
                                                 st[3], st[4], st[5])
            float(loss)
            times.append((time.perf_counter() - t0) / (4 * N) * 1e3)
    for name, step, st, batch, times in variants:
        best = min(times)
        print(f"{name}: min {best:.3f} ms/step  {batch/best*1e3:,.0f} img/s"
              f"  (all: {['%.3f' % m for m in times]})", flush=True)


if __name__ == "__main__":
    main()
