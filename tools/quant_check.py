"""Quantization accuracy harness: calibrate, quantize, and pin
top1/top5 against the fp32 baseline within the declared budget
(docs/serving.md "Quantized serving"; the adoption gate for
``BIGDL_SERVE_QUANT``).

The drill is the real-data loop (``models/utils/real_data.py`` — decode
actual image files through the framework pipeline, train the small
convnet, evaluate with ``Top1Accuracy``/``Top5Accuracy``), then:

1. **calibrate**: one eval sweep with activation taps installed
   (``quant/calibrate.py``) collects per-input-channel amax AND the
   fp32 baseline metrics in the same pass;
2. **quantize**: per-channel int8 (and fp8 ``e4m3`` when the installed
   XLA supports it — the capability gate reports "unsupported on this
   XLA" cleanly instead of failing) with the activation-aware clip
   search;
3. **evaluate**: the SAME ``optim.validate`` loop over the dequantized
   pack — mathematically the exact values a quantized ServeEngine
   serves (dequant is deterministic) — and assert top1/top5 within
   ``bigdl_tpu.quant.WEIGHT_TOP1_BUDGET`` / ``WEIGHT_TOP5_BUDGET`` of
   the baseline.

``--data`` points at any class-per-subfolder image directory (the
reference's shipped CIFAR PNG folders are the canonical input); without
one, a deterministic synthetic PNG folder is generated so the harness
runs anywhere Pillow does.  One JSON line per mode (``quant_check:``
prefix) plus a summary table; ``--strict`` exits non-zero on a budget
violation (wired into ``scripts/serve_smoke.sh``).
"""
from __future__ import annotations

import argparse
import json
import os as _os
import sys as _sys
import tempfile

import numpy as np

_REPO = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
_sys.path.insert(0, _REPO)


def synth_image_folder(root: str, n_classes: int = 2, per_class: int = 4,
                       size: int = 16, seed: int = 7) -> str:
    """Write a deterministic class-per-subfolder PNG set: each class is
    a distinct base color plus pixel noise, so the small convnet
    separates them in a few dozen iterations.  Real files through the
    real decode path — the harness exercises the same pipeline as the
    reference-shipped CIFAR folders."""
    from PIL import Image

    rng = np.random.RandomState(seed)
    base = rng.randint(30, 220, (n_classes, 3))
    for c in range(n_classes):
        d = _os.path.join(root, f"class{c}")
        _os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            img = np.clip(base[c] + rng.randint(-40, 40, (size, size, 3)),
                          0, 255).astype(np.uint8)
            Image.fromarray(img).save(_os.path.join(d, f"{i}.png"))
    return root


def _dataset(folder: str, image_size: int, batch: int):
    from bigdl_tpu.dataset.image import ImgToBatch
    from bigdl_tpu.models.utils.real_data import _byte_record_dataset
    ds, recs, n_classes = _byte_record_dataset(folder, image_size)
    return ds >> ImgToBatch(min(batch, len(recs))), len(recs), n_classes


def _accuracy(results) -> dict:
    (_, top1), (_, top5) = results
    return {"top1": round(float(top1.result()[0]), 4),
            "top5": round(float(top5.result()[0]), 4)}


def run_mode(model, batched, calib, mode: str, budget_top1: float,
             budget_top5: float, baseline: dict) -> dict:
    """Quantize under ``mode`` (with the calibration) and evaluate the
    dequantized pack through the shared validate loop.  Returns the
    pinned JSON row for this mode."""
    from bigdl_tpu.optim import Top1Accuracy, Top5Accuracy, validate
    from bigdl_tpu.quant import (UnsupportedQuantError, WeightQuantizer,
                                 dequantize_params)

    row = {"mode": mode, "baseline": baseline,
           "budget": {"top1": budget_top1, "top5": budget_top5}}
    try:
        quantizer = WeightQuantizer(model, mode, calibration=calib)
    except UnsupportedQuantError as e:
        # the capability gate: report cleanly, never a trace failure
        row.update(supported=False, reason=str(e), passed=True)
        return row
    pack = quantizer.quantize(model.params())
    qparams = dequantize_params(pack)
    results = validate(model, qparams, model.state(), batched,
                       [Top1Accuracy(), Top5Accuracy()])
    acc = _accuracy(results)
    row.update(supported=True, quantized=acc,
               leaves=len(quantizer.leaves),
               drop_top1=round(baseline["top1"] - acc["top1"], 4),
               drop_top5=round(baseline["top5"] - acc["top5"], 4))
    row["passed"] = (row["drop_top1"] <= budget_top1
                     and row["drop_top5"] <= budget_top5)
    return row


def main(argv=None):
    from bigdl_tpu import quant
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--data", default=None,
                    help="class-per-subfolder image directory (default: "
                         "a deterministic synthetic PNG set)")
    ap.add_argument("--mode", default="both",
                    choices=("int8", "fp8", "both"))
    ap.add_argument("--image-size", type=int, default=16)
    ap.add_argument("--iterations", type=int, default=60,
                    help="training iterations for the fp baseline model")
    ap.add_argument("--calib-batches", type=int, default=4)
    ap.add_argument("--budget-top1", type=float,
                    default=quant.WEIGHT_TOP1_BUDGET)
    ap.add_argument("--budget-top5", type=float,
                    default=quant.WEIGHT_TOP5_BUDGET)
    ap.add_argument("--strict", action="store_true",
                    help="exit non-zero when any supported mode misses "
                         "the accuracy budget")
    args = ap.parse_args(argv)

    from bigdl_tpu.models.utils.real_data import (
        train_and_eval_image_folder)

    tmp = None
    folder = args.data
    if folder is None:
        tmp = tempfile.TemporaryDirectory(prefix="quant_check_")
        folder = synth_image_folder(tmp.name, size=args.image_size)

    try:
        # fp32 baseline: decode -> train -> validate (the model comes
        # back trained in place, so the quantizer sees the real
        # weights).  Class count comes from the folder LISTING — no
        # image decode; the pixels are decoded by the train pass and
        # once more for the calibration/eval dataset below.
        from bigdl_tpu.dataset.dataset import DataSet
        from bigdl_tpu.models.utils.real_data import small_convnet
        paths = DataSet.image_folder(folder).data(train=False)
        n_classes = len({lab for p, lab in paths if p.lower().endswith(
            (".png", ".jpeg", ".jpg", ".bmp"))})
        model = small_convnet(n_classes, args.image_size)
        fp = train_and_eval_image_folder(
            folder, image_size=args.image_size,
            iterations=args.iterations, model=model)
        baseline = {"top1": fp["top1"], "top5": fp["top5"]}

        # calibration sweep: activation amax over the eval split (the
        # accuracy anchor is the FULL-set validate above — the sweep's
        # optional methods= pass is not needed here)
        from bigdl_tpu.quant import calibrate
        batched, n_records, _ = _dataset(folder, args.image_size, 32)
        calib = calibrate.collect(model, batched,
                                  max_batches=args.calib_batches)

        modes = ("int8", "fp8") if args.mode == "both" else (args.mode,)
        rows, failed = [], []
        for mode in modes:
            row = run_mode(model, batched, calib, mode,
                           args.budget_top1, args.budget_top5, baseline)
            rows.append(row)
            print(f"quant_check: {json.dumps(row)}")
            if not row["passed"]:
                failed.append(mode)

        print(f"\nquant_check over {n_records} records "
              f"({len(calib)} calibrated layers, "
              f"{calib.n_batches} calibration batches):")
        print(f"  fp32 baseline: top1 {baseline['top1']:.4f}  "
              f"top5 {baseline['top5']:.4f}")
        for row in rows:
            if not row["supported"]:
                print(f"  {row['mode']:>5}: unsupported on this XLA "
                      f"(capability gate) — skipped")
                continue
            acc = row["quantized"]
            print(f"  {row['mode']:>5}: top1 {acc['top1']:.4f} "
                  f"(drop {row['drop_top1']:+.4f})  top5 "
                  f"{acc['top5']:.4f} (drop {row['drop_top5']:+.4f})  "
                  f"-> {'PASS' if row['passed'] else 'FAIL'} (budget "
                  f"{row['budget']['top1']:.3f}/{row['budget']['top5']:.3f})")
        if failed:
            msg = (f"quantized accuracy outside the declared budget: "
                   f"{', '.join(failed)}")
            if args.strict:
                raise SystemExit(msg)
            print(f"  WARNING: {msg}")
        return rows
    finally:
        if tmp is not None:
            tmp.cleanup()


if __name__ == "__main__":
    main()
