"""Pallas fused-LSTM-step vs lax.scan on the Bi-LSTM flagship shapes,
DEVICE-clock (VERDICT r4 item 5: confirm the Mosaic-vs-emitter verdict
in the recurrence regime with the current direction-batched form).

The kernels under test are the PRODUCTION ones
(`bigdl_tpu.ops.pallas_kernels.bilstm_recurrence` and its fwd/bwd
calls) — this tool only provides the lax.scan oracle and the timing.
Both paths consume the same precomputed input projection zx
(T, 2, B, 4H) and direction-batched recurrent weight wht (2, H, 4H),
mirroring Recurrent._apply_fused_lstm's scan body exactly.

Usage: python tools/ab_lstm_pallas.py [T B H]
"""
import os as _os, sys as _sys
_REPO = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
_sys.path.insert(0, _REPO); _sys.path.insert(0, _os.path.join(_REPO, "tools"))
import shutil

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.ops.pallas_kernels import (_bilstm_bwd_call,
                                          _bilstm_fwd_call,
                                          bilstm_recurrence)
from profile_step import _trace_device_ops


@jax.jit
def bilstm_scan(zx, wht):
    """The production scan body (Recurrent._apply_fused_lstm, f32 zx)."""
    b, h = zx.shape[2], wht.shape[1]
    z0 = jnp.zeros((2, b, h))

    def step(carry, zx_t):
        hh, cc = carry
        z = zx_t.astype(jnp.float32) + lax.dot_general(
            hh.astype(wht.dtype), wht, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        i, f, g, o = jnp.split(z, 4, axis=-1)
        c_new = jax.nn.sigmoid(f) * cc + jax.nn.sigmoid(i) * jnp.tanh(g)
        h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
        return (h_new, c_new), h_new

    _, outs = lax.scan(step, (z0, z0), zx)
    return outs


def _device_ms(fn, args, sync, iters=10):
    out = fn(*args)
    jax.block_until_ready(out)

    def thunk():
        o = None
        for _ in range(iters):
            o = fn(*args)
        return o

    per_op, tmpdir = _trace_device_ops(thunk, sync)
    shutil.rmtree(tmpdir, ignore_errors=True)
    return sum(v for k, v in per_op.items()
               if not k.startswith("while")) / iters / 1e3


def main():
    args = [int(a) for a in _sys.argv[1:4]]
    t, b, h = (args + [500, 128, 128][len(args):])
    rs = np.random.RandomState(0)
    zx = jnp.asarray(rs.randn(t, 2, b, 4 * h) * 0.5, jnp.float32)
    wht = jnp.asarray(rs.randn(2, h, 4 * h) * 0.05, jnp.float32)
    gout = jnp.asarray(rs.randn(t, 2, b, h), jnp.float32)

    # ---- forward equivalence + timing
    a = bilstm_scan(zx, wht)
    p = bilstm_recurrence(zx, wht)
    print(f"T{t} B{b} H{h}  fwd maxerr scan-vs-pallas: "
          f"{float(jnp.max(jnp.abs(a - p))):.3g}")
    sync = lambda o: float(jnp.sum(o))
    ms_scan = _device_ms(bilstm_scan, (zx, wht), sync)
    ms_pal = _device_ms(lambda zx, wht: bilstm_recurrence(zx, wht),
                        (zx, wht), sync)
    print(f"fwd   lax.scan {ms_scan:7.3f} ms   pallas {ms_pal:7.3f} ms",
          flush=True)

    # ---- backward equivalence + timing (production bwd kernel vs the
    # scan's autodiff)
    def loss(zx, wht):
        return jnp.sum(bilstm_scan(zx, wht) * gout)

    grad_fn = jax.jit(jax.grad(loss, argnums=(0, 1)))
    dzx0, dwh0 = grad_fn(zx, wht)
    hs, cs = _bilstm_fwd_call(zx, wht)
    dzx1, dwh1 = _bilstm_bwd_call(zx, wht, hs, cs, gout)
    rz = float(jnp.max(jnp.abs(dzx1 - dzx0)) / jnp.max(jnp.abs(dzx0)))
    rw = float(jnp.max(jnp.abs(dwh1 - dwh0)) / jnp.max(jnp.abs(dwh0)))
    print(f"bwd relerr dzx {rz:.3g}  dwh {rw:.3g}")
    sync2 = lambda o: float(jnp.sum(o[1]))
    ms_ad = _device_ms(grad_fn, (zx, wht), sync2)
    ms_pb = _device_ms(lambda *a: _bilstm_bwd_call(*a),
                       (zx, wht, hs, cs, gout), sync2)
    print(f"bwd   scan AD fwd+bwd {ms_ad:7.3f} ms   pallas bwd-only "
          f"{ms_pb:7.3f} ms  (+fwd {ms_pal:.3f} = "
          f"{ms_pb + ms_pal:.3f} ms)", flush=True)


if __name__ == "__main__":
    main()
