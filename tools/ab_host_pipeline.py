"""Wall-clock A/B of the training loop's HOST pipeline (ISSUE 4).

The device clock (tools/ab_device_clock.py) cannot see this change: the
prefetch pipeline and the cadenced host sync move work OFF the critical
path of the host loop, so the instrument is per-step WALL time of the
real ``LocalOptimizer.optimize`` loop over a real transformer-chain
dataset — the quantity the relay's 80-120 ms sync round-trip and the
serial Transformer chain were inflating (PERF_NOTES r1).

Staged for the on-chip run (host-side overlap is provable on CPU — see
tests/test_prefetch.py::TestOverlap — so adoption is not gated on it):

  python tools/ab_host_pipeline.py lenet 256 40 base prefetch_off \
      sync_every_step serial

Variants:
  base             prefetch on (depth 2) + cadenced sync (the defaults)
  prefetch_off     BIGDL_PREFETCH=0, cadenced sync
  sync_every_step  prefetch on, BIGDL_SYNC_EVERY_STEP=1
  serial           both off — the pre-ISSUE-4 loop
"""
import os as _os
import sys as _sys
_REPO = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
_sys.path.insert(0, _REPO)
import time

import numpy as np

VARIANTS = {
    "base": {},
    "prefetch_off": {"BIGDL_PREFETCH": "0"},
    "sync_every_step": {"BIGDL_SYNC_EVERY_STEP": "1"},
    "serial": {"BIGDL_PREFETCH": "0", "BIGDL_SYNC_EVERY_STEP": "1"},
}


def build_opt(model_name, batch):
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, ByteRecord
    from bigdl_tpu.dataset.image import (BytesToGreyImg, BytesToImg,
                                         HFlip, ImgNormalizer,
                                         ImgRdmCropper, ImgToBatch)
    from bigdl_tpu.optim import LocalOptimizer
    from bigdl_tpu.utils.random import set_seed
    from bigdl_tpu.utils.table import T

    set_seed(1)
    rs = np.random.RandomState(0)
    if model_name == "lenet":
        from bigdl_tpu.models.lenet import LeNet5
        recs = [ByteRecord(rs.randint(0, 255, 32 * 32, np.uint8).tobytes(),
                           float(rs.randint(1, 11)))
                for _ in range(batch * 4)]
        ds = (DataSet.array(recs) >> BytesToGreyImg(32, 32)
              >> ImgNormalizer(128.0, 128.0) >> ImgRdmCropper(28, 28)
              >> HFlip() >> ImgToBatch(batch))
        model = LeNet5(class_num=10)
    elif model_name == "inception":
        from bigdl_tpu.models.inception import Inception_v1
        try:
            import io
            from PIL import Image
            buf = io.BytesIO()
            Image.fromarray(rs.randint(0, 255, (256, 256, 3), np.uint8)
                            ).save(buf, format="JPEG")
            raw = buf.getvalue()
        except ImportError:
            raise SystemExit("inception A/B needs Pillow (JPEG decode is "
                             "the host load being measured)")
        recs = [ByteRecord(raw, float(rs.randint(1, 1001)))
                for _ in range(batch * 4)]
        ds = (DataSet.array(recs) >> BytesToImg(scale_to=256)
              >> ImgNormalizer((124.0, 117.0, 104.0), (59.0, 57.0, 57.0))
              >> ImgRdmCropper(224, 224) >> HFlip() >> ImgToBatch(batch))
        model = Inception_v1(class_num=1000)
    else:
        raise SystemExit(f"unknown model {model_name!r}")
    opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion())
    opt.set_state(T(learningRate=0.05))
    return opt


def run_variant(model_name, batch, steps, name):
    from bigdl_tpu.optim import max_iteration
    env = VARIANTS[name]
    old = {k: _os.environ.get(k) for k in env}
    _os.environ.update(env)
    try:
        opt = build_opt(model_name, batch)
        opt.set_end_when(max_iteration(steps))
        t0 = time.perf_counter()
        opt.optimize()
        wall = time.perf_counter() - t0
    finally:
        for k, v in old.items():
            if v is None:
                _os.environ.pop(k, None)
            else:
                _os.environ[k] = v
    m = opt.metrics
    spans = {s: m.get("span: " + s) for s in
             ("data-load", "data-load/fetch", "h2d", "dispatch",
              "host-wait")}
    return wall, spans


def main():
    model_name = _sys.argv[1] if len(_sys.argv) > 1 else "lenet"
    batch = int(_sys.argv[2]) if len(_sys.argv) > 2 else 256
    steps = int(_sys.argv[3]) if len(_sys.argv) > 3 else 40
    variants = _sys.argv[4:] or ["base", "prefetch_off", "sync_every_step",
                                 "serial"]
    run_variant(model_name, batch, min(steps, 5), variants[0])  # warm
    print(f"{'variant':<16} {'wall_ms/step':>12}  span totals (s)")
    for name in variants:
        wall, spans = run_variant(model_name, batch, steps, name)
        detail = " ".join(f"{k}={v[0]:.3f}" for k, v in spans.items()
                          if v[1])
        print(f"{name:<16} {wall / steps * 1e3:>12.2f}  {detail}")


if __name__ == "__main__":
    main()
