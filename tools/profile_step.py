"""Per-op device profile of a training step (VERDICT round-1 item 1).

Builds the same jitted train step as ``bench.py`` for a chosen model,
captures a ``jax.profiler`` device trace, and joins the per-op device
timings against the optimized HLO module's **metadata** (op_name +
source_file, attached by XLA to every instruction) to attribute every
microsecond of device time to (a) an op kind (conv fwd/bwd, pool fwd/bwd,
matmul, rng, eltwise...) and (b) the framework module that emitted it
(conv.py, pooling.py, normalization.py, ...).

The reference's profiling analogue is per-module wall timers
(AbstractModule.scala:125-136) and conv im2col/col2im counters
(SpatialConvolution.scala:73-78); on TPU the per-op device trace is the
honest equivalent because XLA fuses across module boundaries.

Usage:  python tools/profile_step.py \
            [inception|vgg16|lenet|resnet50|bilstm|transformer] [batch]
Writes ``PROFILE_<model>.md`` at the repo root and prints the table.
"""
from __future__ import annotations

import os as _os
import sys as _sys

_REPO_ROOT = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
if _REPO_ROOT not in _sys.path:
    _sys.path.insert(0, _REPO_ROOT)  # run without an installed package

import collections
import glob
import gzip
import json
import re
import sys
import tempfile


# --------------------------------------------------------------- HLO parsing

_CALLS_RE = re.compile(r"calls=%?([\w.\-]+)")
_DEF_RE = re.compile(r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*(.*)$")
_OPCODE_RE = re.compile(r"(?:^|[\s)])([a-z][a-z0-9\-]*)\(")
_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([\d,]*)\]")


class Instr:
    __slots__ = ("name", "comp", "opcode", "shape", "operands", "op_name",
                 "src", "line")


def parse_hlo_module(hlo_text: str):
    """Parse optimized HLO text into {instr_name: Instr} + entry name.

    Handles tuple-typed instructions; opcode = first bare lowercase word
    followed by '(' after the '=' (type annotations like T(8,128) are
    uppercase; tuple-open parens are not preceded by letters).
    """
    instrs = {}
    entry = None
    cur = None
    for line in hlo_text.splitlines():
        stripped = line.strip()
        if stripped.endswith("{") and "=" not in stripped.split("(")[0]:
            mc = re.match(r"^(ENTRY\s+)?%?([\w.\-]+)\s*\(", stripped)
            if mc:
                cur = mc.group(2)
                if mc.group(1):
                    entry = cur
                continue
        md = _DEF_RE.match(line)
        if not md or "=" not in line:
            continue
        name, rest = md.groups()
        mo = _OPCODE_RE.search(rest)
        if not mo:
            continue
        it = Instr()
        it.name, it.comp, it.opcode = name, cur, mo.group(1)
        ms = _SHAPE_RE.search(rest)
        it.shape = [int(s) for s in ms.group(2).split(",") if s] if ms else []
        # operand names: first (...) group after the opcode
        ops = rest[mo.end():]
        depth, buf = 1, []
        for ch in ops:
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    break
            buf.append(ch)
        it.operands = re.findall(r"%([\w.\-]+)", "".join(buf))
        mm = re.search(r'op_name="([^"]*)"', rest)
        it.op_name = mm.group(1) if mm else ""
        mm = re.search(r'source_file="([^"]*)"', rest)
        it.src = mm.group(1).split("/")[-1] if mm else ""
        it.line = line
        instrs[(cur, name)] = it
    return instrs, entry


def build_indexes(instrs):
    """name -> Instr within each computation + global last-wins name map."""
    by_comp = collections.defaultdict(dict)
    for (comp, name), it in instrs.items():
        by_comp[comp][name] = it
    return by_comp


def _window_params(line, nspatial):
    """Parse window={size=.. stride=.. pad=.. lhs_dilate=.. rhs_dilate=..}
    into per-spatial-dim tuples (defaults: stride 1, pad 0, dilation 1)."""
    win = re.search(r"window=\{([^}]*)\}", line)
    fields = {"size": None, "stride": None, "pad": None,
              "lhs_dilate": None, "rhs_dilate": None}
    if win:
        for part in win.group(1).split():
            if "=" in part:
                k, v = part.split("=", 1)
                if k in fields:
                    fields[k] = v.split("x")
    size = [int(s) for s in fields["size"]] if fields["size"] else [1] * nspatial
    stride = [int(s) for s in fields["stride"]] if fields["stride"] else [1] * nspatial
    ldil = [int(s) for s in fields["lhs_dilate"]] if fields["lhs_dilate"] else [1] * nspatial
    rdil = [int(s) for s in fields["rhs_dilate"]] if fields["rhs_dilate"] else [1] * nspatial
    if fields["pad"]:
        pad = [tuple(int(p) for p in s.split("_")) for s in fields["pad"]]
    else:
        pad = [(0, 0)] * nspatial
    return size, stride, pad, ldil, rdil


def _valid_pairs(o_size, k_size, stride, pad_low, l_size, lhs_dil, rhs_dil):
    """Count (output position, kernel position) pairs along one spatial
    dim whose lhs index lands on a real element — excluding zero padding
    and lhs-dilation zeros, which contribute no useful multiply.  This is
    XLA cost-analysis semantics."""
    l_span = (l_size - 1) * lhs_dil  # highest real lhs coordinate
    total = 0
    for o in range(o_size):
        base = o * stride - pad_low
        for k in range(k_size):
            l = base + k * rhs_dil
            if 0 <= l <= l_span and l % lhs_dil == 0:
                total += 1
    return total


def conv_flops(it, comp_map) -> float:
    """Useful FLOPs of a convolution Instr, directly from its own HLO
    signature — valid for ANY conv form XLA emits (forward
    ``bf01_oi01->bf01``, data-grad incl. the transposed big-window
    ``fb01_oi01->fb01`` formulation with pad K-1, filter-grad
    ``fb01_io01->fb01``): MACs = prod(out non-spatial) * (rhs 'i' dim) *
    prod over spatial dims of valid (output, kernel) index pairs.
    Padded and lhs-dilation-zero positions are excluded, so all three
    grad forms of one layer count the same FLOPs as its forward — which
    is what makes >100%%-of-roofline rows impossible by construction
    (the round-2 table's 242%% rows came from shape-matching
    heuristics).  Validated against XLA cost_analysis."""
    if not it.shape or len(it.operands) < 2:
        return 0.0
    lhs_it = comp_map.get(it.operands[0])
    rhs_it = comp_map.get(it.operands[1])
    if (lhs_it is None or rhs_it is None or not rhs_it.shape
            or not lhs_it.shape):
        return 0.0
    dl = re.search(r"dim_labels=([\w]+)_([\w]+)->([\w]+)", it.line)
    if not dl:
        return 0.0
    lhs_l, rhs_l, out_l = dl.groups()
    spatial = [c for c in out_l if c.isdigit()]
    nsp = len(spatial)
    lhs_sp = {lab: dim for dim, lab in zip(lhs_it.shape, lhs_l)}
    out_nonspatial = 1
    for dim, lab in zip(it.shape, out_l):
        if not lab.isdigit():
            out_nonspatial *= dim
    cin = 1
    for dim, lab in zip(rhs_it.shape, rhs_l):
        if lab == "i":
            cin = dim
    size, stride, pad, ldil, rdil = _window_params(it.line, nsp)
    out_sp = [dim for dim, lab in zip(it.shape, out_l) if lab.isdigit()]
    pairs = 1
    for d, lab in enumerate(spatial):
        pairs *= _valid_pairs(out_sp[d], size[d], stride[d], pad[d][0],
                              lhs_sp.get(lab, 1), ldil[d], rdil[d])
    # grouped convs need no correction: out 'f' spans all groups while
    # cin (rhs 'i') is already the per-group fan-in
    return 2.0 * out_nonspatial * cin * pairs


def conv_sig(it, comp_map) -> str:
    lhs_it = comp_map.get(it.operands[0]) if it.operands else None
    rhs_it = comp_map.get(it.operands[1]) if len(it.operands) > 1 else None
    win = re.search(r"window=\{([^}]*)\}", it.line)
    dl = re.search(r"dim_labels=(\S+?)[, ]", it.line)
    fmt = lambda s: ",".join(map(str, s)) if s else "?"
    return "out[%s]<-lhs[%s]*rhs[%s] %s %s" % (
        fmt(it.shape), fmt(lhs_it.shape if lhs_it else None),
        fmt(rhs_it.shape if rhs_it else None),
        win.group(1).split(" ")[0] if win else "",
        dl.group(1) if dl else "")


def categorize(opcode: str, op_name: str, src: str) -> str:
    o = op_name
    if (opcode == "custom-call" and "tpu_custom_call" in o) \
            or "pallas" in o or "mosaic" in o.lower():
        # Pallas kernels compile to tpu_custom_call; attribute them to
        # their own bucket so a pool/LRN/recurrence kernel adoption
        # shows up as PALLAS time, not ELTWISE/OTHER (round 6)
        return "PALLAS-KERNEL"
    if opcode == "select-and-scatter" or "select_and_scatter" in o:
        return "POOL-BWD"
    if "conv_general_dilated" in o or opcode == "convolution":
        if "transpose(" in o:
            return "CONV-BWD"
        return "CONV-FWD"
    if opcode == "reduce-window" or "reduce_window" in o:
        return "POOL-FWD(reduce_window)"
    if opcode == "dot" or "dot_general" in o:
        return "MATMUL"
    if "threefry" in o or "random" in o or "_uniform" in o or "bernoulli" in o:
        return "RNG"
    if opcode in ("copy", "copy-start", "copy-done", "transpose", "bitcast"):
        return "LAYOUT"
    if opcode in ("all-reduce", "all-gather", "reduce-scatter"):
        return "COLLECTIVE"
    return "ELTWISE/OTHER"


# ----------------------------------------------------------------- the step


def build_step(model_name: str, batch: int):
    import jax
    import jax.numpy as jnp
    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu import tensor as bt
    from bigdl_tpu.nn.module import Context
    from bigdl_tpu.optim.optim_method import SGD
    from bigdl_tpu.utils.random import RNG, set_device_prng, set_seed

    set_seed(1)
    # match the bench's device-PRNG selection (rbg) unless overridden:
    # dropout-mask generation is part of the step being profiled
    set_device_prng(_os.environ.get("BIGDL_PRNG", "rbg") or None)
    pol = _os.environ.get("BIGDL_POLICY", "BF16_COMPUTE")
    if pol not in ("FP32", "BF16_COMPUTE", "BF16_ACT"):
        raise SystemExit("BIGDL_POLICY must be one of FP32/BF16_COMPUTE/"
                         "BF16_ACT, got %r" % pol)
    bt.set_policy(getattr(bt, pol))

    if model_name == "inception":
        from bigdl_tpu.models.inception import Inception_v1
        model = Inception_v1(class_num=1000)
        xshape, nclass = (batch, 3, 224, 224), 1000
    elif model_name == "vgg16":
        from bigdl_tpu.models.vgg import Vgg_16
        model = Vgg_16(class_num=1000)
        xshape, nclass = (batch, 3, 224, 224), 1000
    elif model_name == "vgg_cifar":
        # the bench config (VGG-16 bs128 CIFAR-10)
        from bigdl_tpu.models.vgg import VggForCifar10
        model = VggForCifar10(class_num=10)
        xshape, nclass = (batch, 3, 32, 32), 10
    elif model_name == "resnet50":
        from bigdl_tpu.models.resnet import ResNet
        model = ResNet(depth=50, class_num=1000)
        xshape, nclass = (batch, 3, 224, 224), 1000
    elif model_name == "lenet":
        from bigdl_tpu.models.lenet import LeNet5
        model = LeNet5(class_num=10)
        xshape, nclass = (batch, 1, 28, 28), 10
    elif model_name == "bilstm":
        from bigdl_tpu.models.textclassifier import TextClassifierBiLSTM
        model = TextClassifierBiLSTM(20, 200, hidden_size=128)
        xshape, nclass = (batch, 500, 200), 20
    elif model_name == "transformer":
        # the bench flagship geometry (bench.py configs): d_model 1024,
        # 4 heads (d_head 256 — K<=128 batched gemms are emitter-bound,
        # PERF_NOTES), ffn 4096, L6
        from bigdl_tpu.models.transformer import TransformerClassifier
        model = TransformerClassifier(class_num=20, d_model=1024,
                                      n_heads=4, n_layers=6, hidden=4096)
        xshape, nclass = (batch, 512, 1024), 20
    else:
        raise SystemExit("unknown model %s" % model_name)

    criterion = nn.ClassNLLCriterion()
    method = SGD()
    params, net_state = model.params(), model.state()
    opt_state = method.init_state(params)
    hyper = {"lr": 0.01, "momentum": 0.9, "dampening": 0.0,
             "weight_decay": 0.0001, "nesterov": False}

    def train_step(params, net_state, opt_state, x, y, key):
        def loss_fn(p):
            out, ns = model.apply(p, x, net_state, Context(training=True, key=key))
            return criterion.apply_loss(out, y), ns
        (loss, ns), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        new_params, new_opt = method.update(grads, opt_state, params, hyper)
        return new_params, ns, new_opt, loss

    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(*xshape), jnp.float32)
    y = jnp.asarray(rs.randint(1, nclass + 1, (batch,)))
    key = RNG.next_key()  # honors the device-PRNG selection above
    step = jax.jit(train_step, donate_argnums=(0, 1, 2))
    return step, (params, net_state, opt_state, x, y, key)


def _trace_device_ops(thunk, sync):
    """Run ``thunk`` under a jax.profiler trace; return
    Counter{op_name: total device us} from the TPU 'XLA Ops' rows."""
    import jax

    tmpdir = tempfile.mkdtemp(prefix="bigdl_prof_")
    jax.profiler.start_trace(tmpdir)
    sync(thunk())
    jax.profiler.stop_trace()
    fn = sorted(glob.glob(tmpdir + "/plugins/profile/*/*.trace.json.gz"))[-1]
    with gzip.open(fn) as f:
        tr = json.load(f)
    ev = tr["traceEvents"]
    pids = {e["pid"]: e["args"]["name"] for e in ev
            if e.get("ph") == "M" and e.get("name") == "process_name"}
    tids = {(e["pid"], e["tid"]): e["args"]["name"] for e in ev
            if e.get("ph") == "M" and e.get("name") == "thread_name"}
    dev_pid = [p for p, n in pids.items() if "TPU" in n][0]
    per_op = collections.Counter()
    for e in ev:
        if (e.get("ph") == "X" and e.get("pid") == dev_pid
                and tids.get((e["pid"], e["tid"])) == "XLA Ops"):
            per_op[e["name"]] += e.get("dur", 0)
    return per_op, tmpdir


def measure_matmul_roofline(iters: int = 10) -> float:
    """Achievable bf16 matmul TF/s from DEVICE-CLOCK kernel durations
    (own jax.profiler trace), not host wall time: the relay tunnel adds
    host-side latency noise of 2x run-to-run, which is how the round-2
    profile paired a fast trace with a slow roofline and reported conv
    rows above 100%%.  Kernel durations and the per-op table now share
    the same clock domain."""
    import jax
    import jax.numpy as jnp

    a = (jax.random.normal(jax.random.PRNGKey(1), (8192, 8192),
                           jnp.bfloat16) * 0.01)
    mm = jax.jit(lambda v: (v @ a).astype(jnp.bfloat16) * 0.001)
    z = mm(a)
    float(jnp.sum(z).astype(jnp.float32))  # warm

    def thunk():
        w = z
        for _ in range(iters):
            w = mm(w)
        return w

    per_op, tmpdir = _trace_device_ops(
        thunk, lambda w: float(jnp.sum(w).astype(jnp.float32)))
    import shutil
    shutil.rmtree(tmpdir, ignore_errors=True)  # roofline trace is transient
    # the dominant device op is the matmul kernel itself; everything else
    # (scale fusion, transfers) is excluded from the roofline division
    mm_us = max(per_op.values())
    return 2 * 8192 ** 3 * iters / (mm_us / 1e6) / 1e12


def profile(model_name="inception", batch=128, nsteps=5, step=None, args=None):
    import jax

    if step is None:
        step, args = build_step(model_name, batch)
    compiled = step.lower(*args).compile()
    hlo_text = compiled.as_text()
    instrs, entry = parse_hlo_module(hlo_text)
    by_comp = build_indexes(instrs)

    def comp_conv_info(comp_name, seen=None):
        """(flops, sigs, op_names, srcs) of convs in a computation,
        recursing into nested fusions."""
        seen = seen or set()
        if comp_name in seen:
            return 0.0, [], [], []
        seen.add(comp_name)
        fl, sigs, onames, srcs = 0.0, [], [], []
        cmap = by_comp.get(comp_name, {})
        for it in cmap.values():
            if it.opcode == "convolution":
                fl += conv_flops(it, cmap)
                sigs.append(conv_sig(it, cmap))
                onames.append(it.op_name)
            if it.src:
                srcs.append(it.src)
            if it.opcode == "fusion":
                mc = _CALLS_RE.search(it.line)
                if mc:
                    f2, s2, o2, r2 = comp_conv_info(mc.group(1), seen)
                    fl += f2
                    sigs += s2
                    onames += o2
                    srcs += r2
        return fl, sigs, onames, srcs

    # one cost code path (obs/ledger.py): the ledger normalizes the
    # dict/list cost_analysis forms and records the entry next to the
    # runtime captures, so this probe and bench.py report ONE number
    from bigdl_tpu.obs import ledger as cost_ledger
    _entry = cost_ledger.get().capture_compiled(("profile_step",),
                                                compiled)
    total_flops = _entry.flops if _entry is not None else float("nan")

    params, net_state, opt_state, x, y, key = args
    state = {"a": (params, net_state, opt_state)}
    for _ in range(3):
        p, n, o = state["a"]
        p, n, o, loss = step(p, n, o, x, y, key)
        state["a"] = (p, n, o)
    float(loss)

    def thunk():
        loss = None
        for _ in range(nsteps):
            p, n, o = state["a"]
            p, n, o, loss = step(p, n, o, x, y, key)
            state["a"] = (p, n, o)
        return loss

    per_op, tmpdir = _trace_device_ops(thunk, lambda l: float(l))
    roofline = measure_matmul_roofline()
    entry_map = by_comp.get(entry, {})
    rows = []
    for name, us in per_op.items():
        ms = us / 1e3 / nsteps
        it = entry_map.get(name)
        opcode = it.opcode if it else "?"
        op_name = it.op_name if it else ""
        src = it.src if it else ""
        fl, sigs = 0.0, []
        if it is not None and it.opcode == "fusion":
            mc = _CALLS_RE.search(it.line)
            if mc:
                fl, sigs, conv_onames, srcs = comp_conv_info(mc.group(1))
                if not op_name and conv_onames:
                    op_name = conv_onames[0]
                if not src and srcs:
                    src = collections.Counter(srcs).most_common(1)[0][0]
        elif it is not None and it.opcode == "convolution":
            fl = conv_flops(it, entry_map)
            sigs = [conv_sig(it, entry_map)]
        cat = categorize(opcode, op_name, src)
        if fl and cat not in ("CONV-FWD", "CONV-BWD"):
            cat = "CONV-BWD" if "transpose(" in op_name else "CONV-FWD"
        tfs = fl / (ms / 1e3) / 1e12 if ms > 0 and fl else 0.0
        rows.append({
            "name": name, "category": cat, "ms": ms, "gflop": fl / 1e9,
            "tflops": tfs,
            "pct_roofline": 100.0 * tfs / roofline if tfs else 0.0,
            "src": src, "op_name": op_name.replace("jit(train_step)/", ""),
            "sigs": sigs,
        })
    rows.sort(key=lambda r: -r["ms"])
    return rows, total_flops, roofline, tmpdir


def report(rows, total_flops, roofline, model_name, batch, path=None):
    total_ms = sum(r["ms"] for r in rows)
    by_cat = collections.defaultdict(lambda: [0.0, 0.0])
    by_src = collections.defaultdict(float)
    for r in rows:
        by_cat[r["category"]][0] += r["ms"]
        by_cat[r["category"]][1] += r["gflop"]
        by_src[r["src"] or "?"] += r["ms"]

    lines = []
    lines.append("# Per-op device profile — %s bs%d train step" % (model_name, batch))
    lines.append("")
    lines.append("Same-run matmul roofline: **%.1f TF/s**; XLA step FLOPs %.1f G; "
                 "device-busy %.2f ms/step; device-busy TF/s %.1f."
                 % (roofline, total_flops / 1e9, total_ms,
                    total_flops / total_ms / 1e9))
    lines.append("")
    lines.append("## By op kind")
    lines.append("")
    lines.append("| kind | ms/step | % busy | GFLOP | achieved TF/s | % roofline |")
    lines.append("|---|---|---|---|---|---|")
    overs = []
    for cat, (ms, gf) in sorted(by_cat.items(), key=lambda kv: -kv[1][0]):
        tfs = gf / ms if ms else 0.0          # GFLOP/ms == TF/s
        if tfs > roofline:
            overs.append(cat)
        lines.append("| %s | %.2f | %.1f%% | %.1f | %.1f | %.0f%% |"
                     % (cat, ms, 100 * ms / total_ms, gf, tfs,
                        100 * tfs / roofline))
    if overs:
        lines.append("")
        lines.append("**WARNING: %s exceed the same-run roofline — the FLOP "
                     "attribution or roofline measurement is broken; do not "
                     "trust this table.**" % ", ".join(overs))
    lines.append("")
    lines.append("## By emitting module (source_file of the fusion root)")
    lines.append("")
    lines.append("| source | ms/step | % busy |")
    lines.append("|---|---|---|")
    for src, ms in sorted(by_src.items(), key=lambda kv: -kv[1]):
        lines.append("| %s | %.2f | %.1f%% |" % (src, ms, 100 * ms / total_ms))
    lines.append("")
    lines.append("## Top ops")
    lines.append("")
    lines.append("| op | kind | ms/step | GFLOP | TF/s | %roof | source | op_name / conv |")
    lines.append("|---|---|---|---|---|---|---|---|")
    for r in rows[:45]:
        what = r["sigs"][0] if r["sigs"] else r["op_name"]
        lines.append("| %s | %s | %.3f | %.1f | %.1f | %.0f%% | %s | %s |" % (
            r["name"], r["category"], r["ms"], r["gflop"], r["tflops"],
            r["pct_roofline"], r["src"], what[:70]))
    out = "\n".join(lines) + "\n"
    if path:
        with open(path, "w") as f:
            f.write(out)
    return out


def main():
    model_name = sys.argv[1] if len(sys.argv) > 1 else "inception"
    # per-model default batch = the bench.py config geometry (a bs128
    # transformer would be 8x the benchmarked flagship and overrun HBM)
    default_batch = {"transformer": 16, "resnet50": 64, "lenet": 256}
    batch = (int(sys.argv[2]) if len(sys.argv) > 2
             else default_batch.get(model_name, 128))
    rows, total_flops, roofline, tmpdir = profile(model_name, batch)
    path = "PROFILE_%s.md" % model_name
    print(report(rows, total_flops, roofline, model_name, batch, path))
    print("written:", path, " trace:", tmpdir)


if __name__ == "__main__":
    main()
