"""Regenerate PARITY.md — the SURVEY.md §2 inventory → `file:line` map.

  python tools/gen_parity.py        # rewrites PARITY.md in place

Checked by tests/test_parity_doc.py (references must resolve).
"""
import inspect
import os
import sys
import types

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

NN_NAMES = """Sequential Concat ConcatTable ParallelTable MapTable Bottle Recurrent TimeDistributed
SpatialConvolution SpatialShareConvolution SpatialFullConvolution SpatialDilatedConvolution SpatialConvolutionMap
SpatialMaxPooling SpatialAveragePooling SpatialBatchNormalization BatchNormalization SpatialCrossMapLRN
SpatialContrastiveNormalization SpatialDivisiveNormalization SpatialSubtractiveNormalization SpatialZeroPadding RoiPooling Nms
Linear Bilinear CMul CAdd Mul Add MulConstant AddConstant MM MV Cosine Euclidean LookupTable
Mean Sum Max Min Index Select Narrow MaskedSelect
ReLU ReLU6 PReLU RReLU LeakyReLU ELU Tanh TanhShrink Sigmoid LogSigmoid LogSoftMax SoftMax SoftMin SoftPlus
SoftShrink SoftSign HardTanh HardShrink Threshold Clamp Abs Sqrt Square Power Exp Log GradientReversal
CAddTable CSubTable CMulTable CDivTable CMaxTable CMinTable JoinTable SelectTable NarrowTable FlattenTable
MixtureTable CriterionTable DotProduct PairwiseDistance CosineDistance
Reshape InferReshape View Transpose Replicate Squeeze Unsqueeze Padding Contiguous Copy Identity Echo
RnnCell LSTMCell GRUCell BiRecurrent TimeDistributedCriterion Dropout L1Penalty
ClassNLLCriterion CrossEntropyCriterion MSECriterion AbsCriterion BCECriterion DistKLDivCriterion
ClassSimplexCriterion CosineEmbeddingCriterion HingeEmbeddingCriterion L1HingeEmbeddingCriterion
MarginCriterion MarginRankingCriterion MultiCriterion ParallelCriterion MultiLabelMarginCriterion
MultiLabelSoftMarginCriterion MultiMarginCriterion SmoothL1Criterion SmoothL1CriterionWithWeights
SoftMarginCriterion SoftmaxWithCriterion L1Cost""".split()

OPTIM_NAMES = ("Optimizer DistriOptimizer LocalOptimizer SGD Adagrad LBFGS "
               "OptimMethod Trigger Top1Accuracy Top5Accuracy Loss "
               "EvaluateMethods Metrics Validator LocalValidator "
               "DistriValidator Predictor DLClassifier save_model "
               "save_state").split()

DATASET_NAMES = ("DataSet LocalDataSet DistributedDataSet ShardedDataSet "
                 "Transformer ChainedTransformer SampleToBatch PreFetch "
                 "Sample MiniBatch ByteRecord BytesToBGRImg BytesToGreyImg "
                 "BGRImgNormalizer BGRImgPixelNormalizer BGRImgCropper "
                 "BGRImgRdmCropper HFlip ColoJitter Lighting BGRImgToBatch "
                 "MTLabeledBGRImgToBatch BGRImgToImageVector LabeledSentence "
                 "LabeledSentenceToSample Dictionary WordTokenizer").split()

UTILS_NAMES = ("Engine Table T File TorchFile CaffeLoader RandomGenerator "
               "kth_largest ModelBroadcast").split()

MODEL_NAMES = ("LeNet5 VggForCifar10 Vgg_16 Vgg_19 Inception_v1 "
               "Inception_v1_NoAuxClassifier Inception_v2 ResNet ResNetCifar "
               "Autoencoder SimpleRNN AlexNet AlexNet_OWT "
               "TextClassifierConv TextClassifierBiLSTM").split()


def loc(obj):
    if isinstance(obj, types.ModuleType):
        return f"`{obj.__file__.split(ROOT + '/')[-1]}`"
    try:
        f = inspect.getsourcefile(obj).split(ROOT + "/")[-1]
        return f"`{f}:{inspect.getsourcelines(obj)[1]}`"
    except TypeError:
        return "(builtin/alias)"


def table(mod, names):
    rows = []
    for n in names:
        obj = getattr(mod, n)
        where = loc(obj)
        if n == "Engine":
            where = "`bigdl_tpu/utils/engine.py:20` (`_Engine` singleton instance)"
        rows.append(f"| {n} | {where} |")
    return "\n".join(rows)


def main():
    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as o
    import bigdl_tpu.dataset as d
    import bigdl_tpu.utils as u
    import bigdl_tpu.models as m

    doc = f"""# PARITY — SURVEY.md §2 component inventory → implementation

Machine-generated name→`file:line` map (regenerate with
``python tools/gen_parity.py``) so the reference's component inventory can
be checked line by line.  Every name resolves from the package namespaces
exactly as listed.  Reference citations live in each implementation's
docstring.

## §2.2 Tensor package

The reference's 6.5k-LoC tensor layer dissolves into jnp + XLA by design
(SURVEY.md §7 item 1).  What remains: `bigdl_tpu/tensor/__init__.py` —
`DTypePolicy` (the TensorNumeric dtype role), `narrow`/`select`
Torch-shape helpers.  Tensor *capabilities* (views, elementwise, BLAS) are
jnp; the MKL-fallback seam maps to `bigdl_tpu/native/` (C++ hostops with
numpy fallback, the MKL.java discovery/fallback role).

## §2.3 NN package (nn/ — containers, layers, activations, criterions)

| Component | Implementation |
|---|---|
{table(nn, NN_NAMES)}

## §2.4 Dataset package

| Component | Implementation |
|---|---|
{table(d, DATASET_NAMES)}

Shard streaming (SeqFileFolder/ImageNetSeqFileGenerator roles):
`bigdl_tpu/dataset/shardfile.py`, `bigdl_tpu/dataset/imagenet_tools.py`,
`DataSet.seq_file_folder` — which, as of round 5, also ingests ACTUAL
Hadoop SequenceFiles in the reference's wire format
(`bigdl_tpu/dataset/seqfile.py`: version-6 reader/writer,
BGRImgToLocalSeqFile/LocalSeqFileToBytes/SeqBytesToBGRImg transformers,
readLabel/readName key semantics, class_num filter — ref
DataSet.scala:384-455, BGRImgToLocalSeqFile.scala,
LocalSeqFileToBytes.scala).  20-newsgroups + GloVe ingestion (the Python
news20.py role): `bigdl_tpu/dataset/news20.py` (offline, pre-extracted
trees).  Built-in readers: `bigdl_tpu/dataset/mnist.py`,
`bigdl_tpu/dataset/cifar.py`.

## §2.5 Parameters package (communication backend)

| Reference component | TPU-native equivalent |
|---|---|
| AllReduceParameter reduce-scatter/all-gather | XLA all-reduce emitted by the jit train step (`bigdl_tpu/optim/distri_optimizer.py` `_core_step`); explicit collectives in `bigdl_tpu/parallel/collectives.py` |
| FP16CompressedTensor / FP16SplitsCompressedTensor | `DistriOptimizer(gradient_compression="bf16")` — `bigdl_tpu/optim/distri_optimizer.py` `_build_step_compressed` (bf16 gradient all-reduce over the wire) |
| per-partition weight update (owner slice) | `DistriOptimizer(zero1=True)` — `bigdl_tpu/parallel/sharding.py` `zero1_rule` |
| syncPool / parallel fp16 add | XLA collective scheduling (no user-facing equivalent needed) |

## §2.6 Optim package

| Component | Implementation |
|---|---|
{table(o, OPTIM_NAMES)}

## §2.7 Utils package

| Component | Implementation |
|---|---|
{table(u, UTILS_NAMES)}

Also: `bigdl_tpu/utils/log.py` (log4j.properties role),
`bigdl_tpu/utils/profiler.py` (per-module times + jax.profiler traces),
`Engine.check_singleton` (race-detection role, §5.2).

## §2.8 Models & examples

| Component | Implementation |
|---|---|
{table(m, MODEL_NAMES)}

Train/Test mains: `examples/train_*.py`, `examples/model_validator.py`,
`examples/image_classification.py`, `examples/text_classifier.py`.
Perf CLIs: `bigdl_tpu/models/utils/perf.py` +
`local_optimizer_perf.py` / `distri_optimizer_perf.py`.

## §2.9 Parallelism strategies

| Strategy | Status | Where |
|---|---|---|
| Data parallelism (inter+intra node) | YES | `DistriOptimizer` (mesh `data` axis; intra-node splitting dissolves into XLA, SURVEY §2.9) |
| Parameter sharding all-reduce | YES | jit-emitted reduce-scatter/all-gather; `parallel/collectives.py` |
| Gradient compression | YES | `gradient_compression="bf16"` |
| Straggler mitigation | YES (as gradient masking) | `set_drop_module_property` / `drop_percentage=` — kth-largest time threshold, masked `psum(w*g)/sum(w)`, max-drop rejection (`optim/straggler.py`; ref DistriOptimizer.scala:154-172,:245-278) |
| Intra-op threading | YES (free) | XLA fusion |
| Tensor parallelism | YES (beyond ref) | `parallel/sharding.py` + `tensor_parallel=True` |
| Pipeline parallelism | YES (beyond ref) | `parallel/pipeline.py` |
| Sequence/context parallelism | YES (beyond ref) | `parallel/ring_attention.py` |
| Expert parallelism (MoE) | YES (beyond ref) | `parallel/moe.py` |
| ZeRO-1 | YES (beyond ref) | `zero1=True` |
| Per-param learning rates | YES | `T(learningRates=...)` in the jit SGD path |

## Documented intentional divergences

Deliberate behavior differences from the reference (not bugs; parity
audits should not flag these):

- `Lighting` (`bigdl_tpu/dataset/image.py`): alpha drawn from
  `normal(0, alphastd)` per fb.resnet.torch, where Lighting.scala:41 draws
  `uniform(0, alphastd)`; the RGB-ordered eigen rows are flipped for
  BGR-decoded images, where the reference applies them unflipped.
- `BGRImgCropper` defaults to random crop (reference default CropRandom);
  the framework-native `ImgCropper` spelling defaults to center crop for
  validation pipelines.
- Straggler dropping masks gradients instead of cancelling tasks: an XLA
  dispatch cannot be cancelled mid-flight, so a replica whose measured time
  exceeded the threshold is masked out of the NEXT iteration's aggregation
  (one-dispatch lag vs the reference's in-flight `invokeAndWait2` timeout);
  threshold arithmetic, finished-count division, and the max-drop rejection
  follow the reference exactly (`optim/straggler.py`).
- Maxpool gradient tie rule (`_RESHAPE_POOL`, `bigdl_tpu/nn/pooling.py`):
  exact non-overlapping pools (kernel == stride, unpadded — the VGG/LeNet
  shape) use a reshape+max formulation whose backward splits the gradient
  EVENLY among tied in-window maxima; the reference/Torch routes the full
  gradient to the FIRST maximum in row-major order (overlapping/padded
  pools here use XLA select-and-scatter: one winner, possibly a different
  tie).  Ties are common with byte-quantized image inputs, so gradients
  diverge from the reference there while per-window gradient mass is
  identical (porting guide #6).
- RNG: seeded determinism is preserved, but streams are JAX counter-based
  PRNG, not Torch's Mersenne-Twister (SURVEY §7 hard parts).
- RNN generation (`models/rnn.generate`) samples the standard inverse-CDF
  index `(cumsum < rand).sum()`; the reference's
  `cumsum.filter(_ < rand).length - 1` (rnn/Test.scala:70-77) is off by
  one against its own cumulative array and can yield -1.
"""
    out = os.path.join(ROOT, "PARITY.md")
    with open(out, "w") as f:
        f.write(doc)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
