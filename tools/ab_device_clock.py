"""Device-clock A/B of bench chunk-step variants: total device-busy
us/step per variant from jax.profiler traces (the relay-noise-immune
comparison used for every round-4/5 perf decision).

Usage: python tools/ab_device_clock.py vgg_cifar 128 [variant ...]
Variants:
  base          defaults
  rbg           hardware RngBitGenerator dropout keys
  pallas_pool   round-6 Mosaic maxpool kernel pair (nn/pooling.py
                _PALLAS_POOL — argmax fwd + gather bwd)
  pallas_lrn    round-6 fused LRN kernel pair (SpatialCrossMapLRN._PALLAS
                — stored-z residual backward)
  pallas_winops pallas_pool + pallas_lrn together (the Inception case)
  blockt4/blockt8
                multi-timestep recurrence blocking (recurrent._BLOCK_T)
  paged_attn    round-7 Mosaic paged-attention decode kernel
                (models/transformer._PALLAS_PAGED_ATTN — in-kernel
                page walk + online softmax + fused int8 dequant)
  spec_verify   round-7 fused speculative (k+1)-window verify kernel
                (transformer._PALLAS_SPEC_VERIFY)
  paged_decode  paged_attn + spec_verify together
The round-6 adoption A/Bs (run when a chip is attached):
  python tools/ab_device_clock.py inception 128 base pallas_pool \
      pallas_lrn pallas_winops
  python tools/ab_device_clock.py bilstm 128 base blockt4 blockt8
The round-7 decode-kernel A/Bs live on the DECODE harness — this
chunk-step instrument never runs the paged decode path, so the
device-clock comparison is the sweep's wall clock and
decode_model_flops_util gauge with the kernel column flipped:
  python tools/bench_serve.py --decode-sweep --kv-quant int8 --check
  python tools/bench_serve.py --decode-sweep --kv-quant int8 --check \
      --attn-kernel paged
  python tools/bench_serve.py --decode-sweep --kv-quant int8 --check \
      --attn-kernel paged+spec
(the `paged_attn`/`spec_verify`/`paged_decode` variants above flip the
same flags for any harness that drives serve/decode.py through this
module)

The ISSUE-4 host-pipeline change (prefetch-to-device + cadenced sync) is
invisible to this device-clock instrument by construction — its staged
on-chip A/B is the WALL-clock loop comparison:
  python tools/ab_host_pipeline.py lenet 256 40
  python tools/ab_host_pipeline.py inception 128 20
"""
import os as _os, sys as _sys
_REPO = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
_sys.path.insert(0, _REPO); _sys.path.insert(0, _os.path.join(_REPO, "tools"))
import shutil
import time

import numpy as np


def build_chunk(model_name, batch, impl, n=8):
    import jax
    import jax.numpy as jnp
    import bench
    from bigdl_tpu import nn
    from bigdl_tpu.utils.random import set_seed

    jax.config.update("jax_default_prng_impl", impl)
    set_seed(1)
    rs = np.random.RandomState(0)
    if model_name == "vgg_cifar":
        from bigdl_tpu.models.vgg import VggForCifar10
        model = VggForCifar10(class_num=10)
        xshape, nclass = (batch, 3, 32, 32), 10
    elif model_name == "inception":
        from bigdl_tpu.models.inception import Inception_v1
        model = Inception_v1(class_num=1000)
        xshape, nclass = (batch, 3, 224, 224), 1000
    elif model_name == "resnet50":
        from bigdl_tpu.models.resnet import ResNet
        model = ResNet(depth=50, class_num=1000)
        xshape, nclass = (batch, 3, 224, 224), 1000
    elif model_name == "lenet":
        from bigdl_tpu.models.lenet import LeNet5
        model = LeNet5(class_num=10)
        xshape, nclass = (batch, 1, 28, 28), 10
    elif model_name == "bilstm":
        from bigdl_tpu.models.textclassifier import TextClassifierBiLSTM
        model = TextClassifierBiLSTM(20, 200, hidden_size=128)
        xshape, nclass = (batch, 500, 200), 20
    elif model_name == "transformer":
        from bigdl_tpu.models.transformer import TransformerClassifier
        model = TransformerClassifier(class_num=20, d_model=1024,
                                      n_heads=4, n_layers=6, hidden=4096)
        xshape, nclass = (batch, 512, 1024), 20
    else:
        raise SystemExit("unknown model " + model_name)
    x = jnp.asarray(rs.randn(*xshape), jnp.float32)
    y = jnp.asarray(rs.randint(1, nclass + 1, (batch,)))
    xs = jnp.stack([x * (1 + 0.01 * rs.randn()) for _ in range(n)])
    ys = jnp.stack([y] * n)
    criterion = nn.ClassNLLCriterion()
    step, params, net_state, opt_state = bench.make_chunk_step(
        model, criterion, n)
    key = jax.random.PRNGKey(0)
    return step, [params, net_state, opt_state, xs, ys, key]


def device_us_per_step(step, st, n=8, dispatches=4):
    from profile_step import _trace_device_ops
    for _ in range(3):
        st[0], st[1], st[2], loss = step(st[0], st[1], st[2], st[3], st[4],
                                         st[5])
    float(loss)

    def thunk():
        loss = None
        for _ in range(dispatches):
            st[0], st[1], st[2], loss = step(st[0], st[1], st[2], st[3],
                                             st[4], st[5])
        return loss

    per_op, tmpdir = _trace_device_ops(thunk, lambda l: float(l))
    shutil.rmtree(tmpdir, ignore_errors=True)
    # the scan compiles to a while op whose trace row CONTAINS its body's
    # rows — summing both double-counts; kernel time = non-while rows
    kernel_us = sum(t for nm, t in per_op.items()
                    if not nm.startswith("while"))
    return kernel_us / (n * dispatches), per_op


def _apply_variant(name):
    """Set the module flags for ``name``; returns an undo callable."""
    from bigdl_tpu import nn
    from bigdl_tpu.models import transformer
    from bigdl_tpu.nn import pooling, recurrent
    old = (pooling._PALLAS_POOL, nn.SpatialCrossMapLRN._PALLAS,
           recurrent._BLOCK_T, transformer._PALLAS_PAGED_ATTN,
           transformer._PALLAS_SPEC_VERIFY)
    if name in ("pallas_pool", "pallas_winops"):
        pooling._PALLAS_POOL = True
    if name in ("pallas_lrn", "pallas_winops"):
        nn.SpatialCrossMapLRN._PALLAS = True
    if name.startswith("blockt"):
        recurrent._BLOCK_T = int(name[len("blockt"):])
    if name in ("paged_attn", "paged_decode"):
        transformer._PALLAS_PAGED_ATTN = True
    if name in ("spec_verify", "paged_decode"):
        transformer._PALLAS_SPEC_VERIFY = True

    def undo():
        (pooling._PALLAS_POOL, nn.SpatialCrossMapLRN._PALLAS,
         recurrent._BLOCK_T, transformer._PALLAS_PAGED_ATTN,
         transformer._PALLAS_SPEC_VERIFY) = old
    return undo


def main():
    from bigdl_tpu import tensor as bt
    import bench
    bench._enable_compile_cache()
    bt.set_policy(getattr(bt, _os.environ.get("BIGDL_POLICY", "BF16_COMPUTE")))
    model_name = _sys.argv[1] if len(_sys.argv) > 1 else "vgg_cifar"
    batch = int(_sys.argv[2]) if len(_sys.argv) > 2 else 128
    variants = _sys.argv[3:] or ["base", "rbg"]
    import jax
    for name in variants:
        impl = "rbg" if name == "rbg" else "threefry2x32"
        t0 = time.perf_counter()
        jax.config.update("jax_default_prng_impl", impl)
        undo = _apply_variant(name)
        try:
            step, st = build_chunk(model_name, batch, impl)
            us, per_op = device_us_per_step(step, st)
        finally:
            undo()
        print(f"{model_name} bs{batch} {name}: device-busy "
              f"{us/1e3:.3f} ms/step  (setup {time.perf_counter()-t0:.0f}s)",
              flush=True)


if __name__ == "__main__":
    main()
