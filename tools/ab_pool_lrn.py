"""A/B microbenchmarks for maxpool-backward and LRN variants on the real
Inception-v1 shapes (one process, chained dispatches, hard sync).

Variants are timed as full forward+backward of a scalar loss so each
candidate pays its true residual/fusion cost.  Used to choose the
implementations in nn/pooling.py and nn/normalization.py; results are
recorded in PERF_NOTES.md.
"""
from __future__ import annotations

import os as _os
import sys as _sys

_REPO_ROOT = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
if _REPO_ROOT not in _sys.path:
    _sys.path.insert(0, _REPO_ROOT)  # run without an installed package

import sys
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


def timeit_grad(grad_fn, x, iters=30):
    """ms per fwd+bwd, with all ``iters`` executions inside ONE dispatch
    (fori_loop chaining x through the gradient) so relay dispatch latency
    (~5 ms/call here) cannot mask sub-ms device-time differences."""
    eps = jnp.asarray(1e-6, x.dtype)

    @jax.jit
    def chained(v):
        return lax.fori_loop(
            0, iters, lambda i, u: u - eps * grad_fn(u).astype(u.dtype), v)

    out = chained(x)
    float(jnp.sum(out.astype(jnp.float32)))  # hard sync (relay-safe)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out = chained(x)
        float(jnp.sum(out.astype(jnp.float32)))
        best = min(best, (time.perf_counter() - t0) / iters * 1e3)
    return best


# ---------------------------------------------------------------- maxpool

def sas_pool(x, window, strides, padding):
    """Baseline: reduce_window with XLA's default select-and-scatter VJP."""
    kh, kw = window
    dh, dw = strides
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        window_dimensions=(1, 1, kh, kw),
        window_strides=(1, 1, dh, dw),
        padding=((0, 0), (0, 0)) + padding)


def pool_cases(batch):
    # (shape, window, strides, padding) — every maxpool in Inception-v1
    return [
        ((batch, 64, 112, 112), (3, 3), (2, 2), ((0, 1), (0, 1))),
        ((batch, 192, 56, 56), (3, 3), (2, 2), ((0, 1), (0, 1))),
        ((batch, 256, 28, 28), (3, 3), (1, 1), ((1, 1), (1, 1))),
        ((batch, 480, 28, 28), (3, 3), (2, 2), ((0, 1), (0, 1))),
        ((batch, 480, 14, 14), (3, 3), (1, 1), ((1, 1), (1, 1))),
        ((batch, 512, 14, 14), (3, 3), (1, 1), ((1, 1), (1, 1))),
        ((batch, 832, 14, 14), (3, 3), (2, 2), ((0, 1), (0, 1))),
        ((batch, 832, 7, 7), (3, 3), (1, 1), ((1, 1), (1, 1))),
    ]


def run_pool_ab(batch=128, dtype=jnp.float32):
    from bigdl_tpu.nn.pooling import _max_pool2d
    rs = np.random.RandomState(0)
    print("%-28s %10s %10s" % ("maxpool case", "s&s ms", "stencil ms"))
    tot_a = tot_b = 0.0
    for shape, window, strides, padding in pool_cases(batch):
        x = jnp.asarray(np.maximum(rs.randn(*shape), 0), dtype)

        def loss_sas(v):
            return (sas_pool(v, window, strides, padding)
                    .astype(jnp.float32) ** 2).sum()

        def loss_stencil(v):
            return (_max_pool2d(v, window, strides, padding)
                    .astype(jnp.float32) ** 2).sum()

        ta = timeit_grad(jax.grad(loss_sas), x)
        tb = timeit_grad(jax.grad(loss_stencil), x)
        tot_a += ta
        tot_b += tb
        print("%-28s %10.3f %10.3f" % (
            "%s k%s s%s" % (shape, window, strides), ta, tb))
    print("%-28s %10.3f %10.3f" % ("TOTAL", tot_a, tot_b))


# -------------------------------------------------------------------- LRN

def lrn_reduce_window(x, size=5, alpha=0.0001, beta=0.75, k=1.0):
    lo = (size - 1) // 2
    hi = size - 1 - lo
    s = lax.reduce_window(
        x * x, 0.0, lax.add,
        window_dimensions=(1, size, 1, 1), window_strides=(1, 1, 1, 1),
        padding=((0, 0), (lo, hi), (0, 0), (0, 0)))
    denom = (k + (alpha / size) * s) ** beta
    return x / denom


def lrn_band_matmul(x, size=5, alpha=0.0001, beta=0.75, k=1.0):
    lo = (size - 1) // 2
    hi = size - 1 - lo
    b, c, h, w = x.shape
    band = np.zeros((c, c), np.float32)
    for d in range(c):
        band[d, max(0, d - lo):min(c, d + hi + 1)] = 1.0
    sq = (x * x).reshape(b, c, h * w)
    s = jnp.einsum("dc,bcs->bds", jnp.asarray(band, x.dtype), sq,
                   preferred_element_type=jnp.float32)
    s = s.astype(x.dtype).reshape(b, c, h, w)
    denom = (k + (alpha / size) * s) ** beta
    return x / denom


def lrn_stencil(x, size=5, alpha=0.0001, beta=0.75, k=1.0):
    lo = (size - 1) // 2
    hi = size - 1 - lo
    sq = x * x
    sqp = jnp.pad(sq, ((0, 0), (lo, hi), (0, 0), (0, 0)))
    c = x.shape[1]
    s = sum(lax.slice_in_dim(sqp, t, t + c, axis=1) for t in range(size))
    denom = (k + (alpha / size) * s) ** beta
    return x / denom


def lrn_stencil_sqrt(x, size=5, alpha=0.0001, beta=0.75, k=1.0):
    lo = (size - 1) // 2
    hi = size - 1 - lo
    sq = x * x
    sqp = jnp.pad(sq, ((0, 0), (lo, hi), (0, 0), (0, 0)))
    c = x.shape[1]
    s = sum(lax.slice_in_dim(sqp, t, t + c, axis=1) for t in range(size))
    z = k + (alpha / size) * s
    if beta == 0.75:
        denom = jnp.sqrt(jnp.sqrt(z)) ** 3  # z^(3/4) without exp/log
    else:
        denom = z ** beta
    return x / denom


def run_lrn_ab(batch=128, dtype=jnp.float32):
    rs = np.random.RandomState(0)
    cases = [((batch, 64, 56, 56),), ((batch, 192, 28, 28),)]
    variants = [("reduce_window", lrn_reduce_window),
                ("band_matmul", lrn_band_matmul),
                ("stencil_pow", lrn_stencil),
                ("stencil_sqrt", lrn_stencil_sqrt)]
    print("%-22s" % "LRN case" + "".join("%15s" % n for n, _ in variants))
    for (shape,) in cases:
        x = jnp.asarray(rs.randn(*shape), dtype)
        row = "%-22s" % str(shape)
        for name, fn in variants:
            def loss(v, fn=fn):
                return (fn(v).astype(jnp.float32) ** 2).sum()
            row += "%15.3f" % timeit_grad(jax.grad(loss), x)
        print(row)




# ------------------------------------------------- shifted-slices maxpool

def shift_pool(x, window, strides, padding):
    """Maxpool as a folded maximum over kh*kw strided shifted slices —
    pure eltwise ops the fuser can handle, no reduce_window/select-and-
    scatter emitter.  Autodiff backward = chain of eltwise select grads."""
    kh, kw = window
    dh, dw = strides
    (plh, phh), (plw, phw) = padding
    neg = jnp.asarray(-jnp.inf, x.dtype)
    xp = jnp.pad(x, ((0, 0), (0, 0), (plh, phh), (plw, phw)),
                 constant_values=neg)
    b, c, hp, wp = xp.shape
    oh = (hp - kh) // dh + 1
    ow = (wp - kw) // dw + 1
    y = None
    for i in range(kh):
        for j in range(kw):
            s = lax.slice(xp, (0, 0, i, j),
                          (b, c, i + (oh - 1) * dh + 1, j + (ow - 1) * dw + 1),
                          (1, 1, dh, dw))
            y = s if y is None else jnp.maximum(y, s)
    return y


def run_pool_variant_ab(candidate, label, batch=128, dtype=jnp.float32):
    """A/B an alternative maxpool implementation vs the shipped
    reduce_window/select-and-scatter path on every Inception pool shape.

    NOTE (round 3): this chained-fori_loop harness serializes on its
    dependency chain (~280 GB/s ceiling vs 662+ GB/s isolated), so treat
    small deltas as noise — use tools/profile_step._trace_device_ops for
    sub-ms decisions (PERF_NOTES "Round-3 MFU attack")."""
    rs = np.random.RandomState(0)
    from bigdl_tpu.nn.pooling import _max_pool2d
    print("%-34s %10s %10s" % ("maxpool case", "s&s ms", label + " ms"))
    tot_a = tot_b = 0.0
    for shape, window, strides, padding in pool_cases(batch):
        x = jnp.asarray(np.maximum(rs.randn(*shape), 0), dtype)

        def loss_sas(v):
            return (_max_pool2d(v, window, strides, padding)
                    .astype(jnp.float32) ** 2).sum()

        def loss_cand(v):
            return (candidate(v, window, strides, padding)
                    .astype(jnp.float32) ** 2).sum()

        ta = timeit_grad(jax.grad(loss_sas), x)
        tb = timeit_grad(jax.grad(loss_cand), x)
        tot_a += ta
        tot_b += tb
        print("%-34s %10.3f %10.3f" % (
            "%s k%s s%s" % (shape, window, strides), ta, tb))
    print("%-34s %10.3f %10.3f" % ("TOTAL", tot_a, tot_b))


def sep_pool(x, window, strides, padding):
    """Separable maxpool: 1-D row-window max then 1-D column-window max.
    max is associative so the result is exact; each pass gives the
    emitter a tiny 1-D window, and the VJP becomes two 1-D
    select-and-scatters."""
    kh, kw = window
    dh, dw = strides
    (plh, phh), (plw, phw) = padding
    y = lax.reduce_window(
        x, -jnp.inf, lax.max,
        window_dimensions=(1, 1, 1, kw), window_strides=(1, 1, 1, dw),
        padding=((0, 0), (0, 0), (0, 0), (plw, phw)))
    return lax.reduce_window(
        y, -jnp.inf, lax.max,
        window_dimensions=(1, 1, kh, 1), window_strides=(1, 1, dh, 1),
        padding=((0, 0), (0, 0), (plh, phh), (0, 0)))


def run_shift_ab(batch=128, dtype=jnp.float32):
    run_pool_variant_ab(shift_pool, "shift", batch, dtype)


def run_sep_ab(batch=128, dtype=jnp.float32):
    run_pool_variant_ab(sep_pool, "sep", batch, dtype)


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    dtype = jnp.bfloat16 if (len(sys.argv) > 2 and sys.argv[2] == "bf16") else jnp.float32
    if which in ("pool", "all"):
        run_pool_ab(dtype=dtype)
    if which in ("lrn", "all"):
        run_lrn_ab(dtype=dtype)
    if which in ("shift", "all"):
        run_shift_ab(dtype=dtype)
    if which in ("sep", "all"):
        run_sep_ab(dtype=dtype)
