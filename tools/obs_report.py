"""Render a run's obs event stream (JSONL) into a markdown report.

Usage:
    python tools/obs_report.py RUN_DIR [-o report.md]
    python tools/obs_report.py events.p0.jsonl

RUN_DIR is a ``BIGDL_OBS_DIR`` directory: every ``events.p*.jsonl`` in
it is loaded (one per process), crash bundles (``crash-*/``) are
listed.  The report covers: run configuration, the throughput/loss
trajectory (bucketed), tap trends, phase breakdown, skip/straggler
summary, fault/watchdog/preemption timeline, the elastic recovery
timeline (``recover`` events), the serving section
(rollout timeline, shed/error/replica-death counts, decode summary,
a per-request TOKEN waterfall for streamed decode requests — admit →
first token → per-boundary counts → retire, from the ``stream``
events — and a per-hop latency waterfall for the slowest traced
requests — ``--waterfall N``), the scale timeline (``scale`` events:
autoscaler up/down decisions with reasons, spawn failures, circuit
breaker — docs/serving.md "Autoscaling"), the performance ledger
(top executables by flops, HBM tenant breakdown, device-memory
timeline), the alert timeline (``alert`` firing/resolved transitions),
crash bundles.

Lines that fail schema validation are counted and quoted, not fatal —
a postmortem tool that dies on the interesting input is useless.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bigdl_tpu.obs.events import validate_event  # noqa: E402


def load_run(path):
    """(events, bad_lines, bundle_dirs) from a run dir or one jsonl.
    Rotated segments (``events.p0.jsonl.1`` ... — the
    ``BIGDL_OBS_MAX_MB`` size cap) are loaded too; the ts-sort below
    restores stream order."""
    if os.path.isdir(path):
        files = sorted(glob.glob(os.path.join(path, "events.p*.jsonl"))
                       + glob.glob(os.path.join(path,
                                                "events.p*.jsonl.*")))
        bundles = sorted(g for g in glob.glob(os.path.join(path, "crash-*"))
                         if os.path.isdir(g))
    else:
        files, bundles = [path], []
    events, bad = [], []
    for f in files:
        with open(f) as fh:
            for i, line in enumerate(fh, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(validate_event(json.loads(line)))
                except (ValueError, json.JSONDecodeError) as e:
                    bad.append((f, i, str(e)[:120]))
    events.sort(key=lambda e: e.get("ts", 0.0))
    return events, bad, bundles


def _by_type(events, etype):
    return [e for e in events if e["type"] == etype]


def _fmt(v):
    return f"{v:.4g}" if isinstance(v, float) else str(v)


def _trajectory(steps, n_buckets=8):
    """Bucket step events into at most n_buckets rows of
    (step range, mean loss, mean throughput, last taps)."""
    if not steps:
        return []
    size = max(1, (len(steps) + n_buckets - 1) // n_buckets)
    rows = []
    for i in range(0, len(steps), size):
        chunk = steps[i:i + size]
        taps = next((e["taps"] for e in reversed(chunk) if "taps" in e), None)
        rows.append((chunk[0]["step"], chunk[-1]["step"],
                     sum(e["loss"] for e in chunk) / len(chunk),
                     sum(e["throughput"] for e in chunk) / len(chunk),
                     taps))
    return rows


def _serving_section(events, waterfall=5):
    """Markdown lines for the ``serve`` + ``trace`` event types (empty
    when the run never served)."""
    from bigdl_tpu.obs.trace import hop_deltas

    serves = _by_type(events, "serve")
    traces = _by_type(events, "trace")
    if not serves and not traces:
        return []
    out = ["## Serving", ""]

    kinds = {}
    for e in serves:
        kinds[e["kind"]] = kinds.get(e["kind"], 0) + 1
    out.append("- serve events: " + ", ".join(
        f"{k}={n}" for k, n in sorted(kinds.items())))
    errors = [e for e in serves if e["kind"] == "error"]
    if errors:
        failed = sum(int(e.get("requests", 1)) for e in errors)
        out.append(f"- failed requests: **{failed}** across "
                   f"{len(errors)} error event(s); last: "
                   f"`{errors[-1].get('error', '?')}`")
    sheds = kinds.get("shed", 0)
    if sheds:
        out.append(f"- shed events: **{sheds}**")
    deaths = [e for e in serves if e["kind"] == "replica_dead"]
    for e in deaths:
        out.append(f"- replica death: **{e.get('replica', '?')}** "
                   f"(p{e['proc']})")
    out.append("")

    rollouts = [e for e in serves if e["kind"].startswith("rollout_")
                or e["kind"] in ("weights_commit", "weights_revert")]
    if rollouts:
        t0 = rollouts[0]["ts"]
        out += ["### Rollout timeline", "",
                "| t (s) | event | version | detail |", "|---|---|---|---|"]
        for e in rollouts:
            detail = ", ".join(
                f"{k}={_fmt(v)}" for k, v in sorted(e.items())
                if k not in ("v", "ts", "proc", "type", "kind", "version"))
            out.append(f"| {e['ts'] - t0:+.3f} | {e['kind']} | "
                       f"{e.get('version', '-')} | {detail or '-'} |")
        out.append("")

    decodes = [e for e in serves if e["kind"] == "decode"]
    if decodes:
        steps = sum(int(e["steps"]) for e in decodes)
        retired = sum(int(e.get("retired", 0)) for e in decodes)
        syncs = sum(int(e.get("host_syncs", 0)) for e in decodes)
        out.append(f"- decode: {len(decodes)} run(s), {steps} steps, "
                   f"{retired} requests retired, {syncs} host syncs")
        paged = [e for e in decodes if e.get("paged")]
        if paged:
            hits = sum(int(e.get("prefix_hits", 0)) for e in paged)
            misses = sum(int(e.get("prefix_misses", 0)) for e in paged)
            hwm = max(int(e.get("pages_hwm", 0)) for e in paged)
            live = max(int(e.get("live_hwm", 0)) for e in paged)
            line = (f"- paged KV: {len(paged)} run(s), page-pool hwm "
                    f"{hwm} pages, live-request hwm {live}")
            if hits + misses:
                line += (f", prefix hit-rate {hits / (hits + misses):.0%}"
                         f" ({hits}/{hits + misses})")
            out.append(line)
        streamed = [e for e in decodes if e.get("streaming")]
        if streamed:
            n = sum(int(e.get("streams", 0)) for e in streamed)
            bounds = sum(int(e.get("stream_boundaries", 0))
                         for e in streamed)
            ttft = sum(float(e.get("first_token_ms", 0.0))
                       * int(e.get("streams", 0)) for e in streamed)
            out.append(f"- streaming: {n} streamed request(s) over "
                       f"{bounds} delivery boundaries, mean ttft "
                       f"{ttft / n if n else 0.0:.2f} ms")
        specs = [e for e in decodes if e.get("spec_k")]
        if specs:
            wins = sum(int(e.get("spec_windows", 0)) for e in specs)
            acc = sum(float(e.get("accept_mean", 0.0))
                      * int(e.get("spec_windows", 0)) for e in specs)
            ks = sorted({int(e["spec_k"]) for e in specs})
            out.append(f"- speculative: k={ks}, {wins} verify windows, "
                       f"mean accepted "
                       f"{acc / wins if wins else 0.0:.2f} drafts")
        tiers = [e for e in decodes if "kv_host_spilled" in e]
        if tiers:
            spilled = sum(int(e["kv_host_spilled"]) for e in tiers)
            readm = sum(int(e.get("kv_host_readmitted", 0))
                        for e in tiers)
            dropped = sum(int(e.get("kv_host_dropped", 0))
                          for e in tiers)
            out.append(f"- host KV tier: {spilled} pages spilled, "
                       f"{readm} re-admitted as prefix hits, "
                       f"{dropped} dropped under budget")
        out.append("")

    fleets = [e for e in serves if e["kind"] == "fleet_stop"]
    if fleets:
        out.append("### Disaggregated fleet")
        out.append("")
        for e in fleets:
            hits = int(e.get("affinity_hits", 0))
            misses = int(e.get("affinity_misses", 0))
            line = (f"- fleet of {e.get('replicas', '?')} decode + "
                    f"{e.get('prefill_replicas', 0)} prefill: affinity "
                    f"{hits}/{hits + misses} dispatches on a cached "
                    f"chain" if hits + misses else
                    f"- fleet of {e.get('replicas', '?')} decode + "
                    f"{e.get('prefill_replicas', 0)} prefill "
                    f"(affinity off)")
            shipped = int(e.get("prefill_shipped", 0))
            fallback = int(e.get("prefill_fallback", 0))
            if shipped or fallback:
                line += (f"; prefill shipped {shipped}, colocated "
                         f"fallback {fallback}")
            out.append(line)
        out.append("")

    streams = [e for e in serves if e["kind"] == "stream"]
    if streams and waterfall > 0:
        # per-request token waterfall: admit → first token → retire
        # with the per-boundary token counts (the `stream` events the
        # decoder emits at retire — docs/observability.md "Streaming
        # telemetry"); slowest first-token latencies first
        n_tok = sum(int(e.get("tokens", 0)) for e in streams)
        ttfts = sorted(float(e["ttft_ms"]) for e in streams)
        p50 = ttfts[len(ttfts) // 2]
        out.append(f"### Token waterfall (slowest {waterfall} of "
                   f"{len(streams)} streamed requests; {n_tok} tokens, "
                   f"ttft p50 {p50:.2f} ms)")
        out += ["", "| request | admit ms | ttft ms | retire ms | "
                "tokens | per-boundary |", "|---|---|---|---|---|---|"]
        slowest = sorted(streams, key=lambda e: -float(e["ttft_ms"]))
        for e in slowest[:waterfall]:
            tl = " ".join(f"+{n}@{t:.1f}" for t, n in e["timeline"])
            admit = e.get("admit_ms")
            out.append(
                f"| `{e.get('request', '?')}` | "
                f"{'-' if admit is None else f'{admit:.2f}'} | "
                f"{float(e['ttft_ms']):.2f} | "
                f"{float(e.get('retire_ms', 0.0)):.2f} | "
                f"{e.get('tokens', '?')} | {tl} |")
        out.append("")

    if traces and waterfall > 0:
        ok = sum(1 for e in traces if e.get("status") == "ok")
        out.append(f"### Trace waterfall (slowest {waterfall} of "
                   f"{len(traces)} sampled; {ok} ok)")
        out.append("")
        slowest = sorted(traces, key=lambda e: e.get("duration_ms", 0.0),
                         reverse=True)[:waterfall]
        phases = []
        for e in slowest:       # union of hop names, first-seen order
            for ph, _ in hop_deltas(e["hops"]):
                if ph not in phases:
                    phases.append(ph)
        out.append("| trace | status | total ms | "
                   + " | ".join(phases) + " |")
        out.append("|---|---|---|" + "---|" * len(phases))
        for e in slowest:
            cells = {ph: 0.0 for ph in phases}
            for ph, dt in hop_deltas(e["hops"]):
                cells[ph] = cells.get(ph, 0.0) + dt * 1e3
            row = " | ".join(f"{cells[ph]:.2f}" for ph in phases)
            out.append(f"| `{e['trace_id'][:8]}` | {e['status']} | "
                       f"{e.get('duration_ms', 0.0):.2f} | {row} |")
        out.append("")
    return out


def _forensics_section(events, waterfall=5):
    """Markdown lines for the ``forensic`` event type (obs/recorder.py
    flight recorder, schema v7): anomaly counts by kind plus failed-
    and slowest-request waterfalls rendered from the recorder RECORDS
    riding the bundles — populated even when head sampling is 0
    (tail-based retention keeps exactly the anomalous chains)."""
    from bigdl_tpu.obs.trace import hop_deltas

    forensics = _by_type(events, "forensic")
    if not forensics:
        return []
    out = ["## Forensics", ""]
    kinds = {}
    for e in forensics:
        kinds[e["kind"]] = kinds.get(e["kind"], 0) + 1
    out.append(f"- anomalous requests bundled: **{len(forensics)}** ("
               + ", ".join(f"{k}={n}" for k, n in sorted(kinds.items()))
               + ")")
    out.append("")

    def _hop_table(rows, title):
        if not rows:
            return
        phases = []
        for e in rows:          # union of hop names, first-seen order
            for ph, _ in hop_deltas(e["record"].get("hops") or []):
                if ph not in phases:
                    phases.append(ph)
        out.append(title)
        out.append("")
        out.append("| trace | kind | replica | e2e ms | "
                   + " | ".join(phases) + " |")
        out.append("|---|---|---|---|" + "---|" * len(phases))
        for e in rows:
            rec = e["record"]
            cells = {ph: 0.0 for ph in phases}
            for ph, dt in hop_deltas(rec.get("hops") or []):
                cells[ph] = cells.get(ph, 0.0) + dt * 1e3
            hop_row = " | ".join(f"{cells[ph]:.2f}" for ph in phases)
            e2e = rec.get("e2e_ms")
            out.append(
                f"| `{e['trace_id'][:8]}` | {e['kind']} | "
                f"{rec.get('replica', '-')} | "
                f"{'-' if e2e is None else f'{e2e:.2f}'} | {hop_row} |")
        out.append("")

    hard = [e for e in forensics
            if e["kind"] in ("error", "shed", "replica_death",
                             "requeue", "partition")]
    if hard and waterfall > 0:
        _hop_table(hard[-waterfall:],
                   f"### Failed / disrupted requests (last "
                   f"{min(waterfall, len(hard))} of {len(hard)})")
    if waterfall > 0:
        slow = sorted(forensics,
                      key=lambda e: -(e["record"].get("e2e_ms") or 0.0))
        slow = slow[:waterfall]
        _hop_table(slow, f"### Slowest anomalous requests (top "
                         f"{len(slow)} of {len(forensics)})")
    return out


def _bytes_h(n) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:.1f} GiB"   # pragma: no cover - loop always returns


def _ledger_section(events):
    """Markdown lines for the ``ledger`` event type (obs/ledger.py):
    top compiled executables by flops, the HBM tenant breakdown (last
    reported bytes per tenant series), and the device-memory timeline
    from the sampler's ``hbm`` ticks."""
    ledgers = _by_type(events, "ledger")
    if not ledgers:
        return []
    out = ["## Performance ledger", ""]

    execs = [e for e in ledgers if e["kind"] == "exec"]
    if execs:
        out.append(f"- compiled executables captured: **{len(execs)}**")
        out += ["", "| fn | key | Gflops/dispatch | MiB accessed | "
                "peak HBM |", "|---|---|---|---|---|"]
        top = sorted(execs, key=lambda e: -(e.get("flops") or 0))[:10]
        for e in top:
            peak = e.get("peak_bytes")
            out.append(
                f"| `{e['fn']}` | `{e.get('key', '-')}` | "
                f"{(e.get('flops') or 0) / 1e9:.3f} | "
                f"{(e.get('bytes_accessed') or 0) / (1 << 20):.2f} | "
                f"{_bytes_h(peak) if peak is not None else '-'} |")
        out.append("")

    tenants = [e for e in ledgers if e["kind"] == "tenant"]
    if tenants:
        # last report per tenant series (the extra labels — decoder,
        # engine — keep one replica's pool distinct from another's)
        latest = {}
        for e in tenants:
            key = tuple(sorted((k, str(v)) for k, v in e.items()
                               if k not in ("v", "ts", "proc", "type",
                                            "kind", "bytes")))
            latest[key] = e
        rows = [e for e in latest.values() if e.get("bytes")]
        if rows:
            out += ["### HBM breakdown (known tenants, last reported)",
                    "", "| tenant | owner | bytes |", "|---|---|---|"]
            for e in sorted(rows, key=lambda e: -e["bytes"]):
                owner = ", ".join(
                    f"{k}={_fmt(v)}" for k, v in sorted(e.items())
                    if k not in ("v", "ts", "proc", "type", "kind",
                                 "tenant", "bytes"))
                out.append(f"| {e['tenant']} | {owner or '-'} | "
                           f"{_bytes_h(e['bytes'])} |")
            out.append("")

    hbms = [e for e in ledgers if e["kind"] == "hbm"]
    if hbms:
        t0 = hbms[0]["ts"]
        peak = max(int(e.get("peak", e["in_use"])) for e in hbms)
        out.append(f"### HBM timeline ({len(hbms)} samples, watermark "
                   f"{_bytes_h(peak)})")
        out += ["", "| t (s) | in use | watermark | limit |",
                "|---|---|---|---|"]
        step = max(1, len(hbms) // 12)      # at most ~12 rows
        for e in hbms[::step]:
            lim = e.get("limit")
            out.append(f"| {e['ts'] - t0:+.1f} | "
                       f"{_bytes_h(e['in_use'])} | "
                       f"{_bytes_h(e.get('peak', e['in_use']))} | "
                       f"{_bytes_h(lim) if lim else '-'} |")
        out.append("")
    return out


def _scale_section(events):
    """Markdown lines for the ``scale`` event type (serve/autoscale.py,
    dynamic membership): the scale/recovery timeline — every committed
    up/down with its policy reason, spawn failures, and the circuit
    breaker's frozen/unfrozen transitions."""
    scales = _by_type(events, "scale")
    if not scales:
        return []
    out = ["## Scale timeline (autoscaler)", ""]
    ups = sum(1 for e in scales if e["kind"] == "up")
    downs = sum(1 for e in scales if e["kind"] == "down")
    fails = sum(1 for e in scales if e["kind"] == "spawn_failed")
    line = f"- scale actions: **+{ups} / -{downs}**"
    if fails:
        line += f"; spawn attempts failed: **{fails}**"
    frozen = any(e["kind"] == "frozen" for e in scales)
    if frozen:
        still = True
        for e in scales:
            if e["kind"] == "frozen":
                still = True
            elif e["kind"] == "unfrozen":
                still = False
        line += ("; spawn circuit breaker tripped"
                 + (" — **still frozen at end of log**" if still
                    else " (recovered)"))
    out.append(line)
    out += ["", "| t (s) | kind | replica | detail |", "|---|---|---|---|"]
    t0 = scales[0]["ts"]
    for e in scales:
        detail = ", ".join(
            f"{k}={_fmt(v)}" for k, v in sorted(e.items())
            if k not in ("v", "ts", "proc", "type", "kind", "replica"))
        out.append(f"| {e['ts'] - t0:+.3f} | {e['kind']} | "
                   f"{e.get('replica', '-')} | {detail or '-'} |")
    out.append("")
    return out


def _alerts_section(events):
    """Markdown lines for the ``alert`` event type (obs/alerts.py):
    the firing/resolved transition timeline plus the rules still
    firing at end of log."""
    alerts = _by_type(events, "alert")
    if not alerts:
        return []
    out = ["## Alert timeline", ""]
    fired = sum(1 for e in alerts if e["kind"] == "firing")
    active = {}
    for e in alerts:
        active[e["rule"]] = (e["kind"] == "firing")
    still = sorted(r for r, on in active.items() if on)
    out.append(f"- transitions: **{fired}** firing / "
               f"{len(alerts) - fired} resolved"
               + (f"; still firing at end of log: **{', '.join(still)}**"
                  if still else ""))
    out += ["", "| t (s) | rule | transition | value | threshold |",
            "|---|---|---|---|---|"]
    t0 = alerts[0]["ts"]
    for e in alerts:
        out.append(f"| {e['ts'] - t0:+.3f} | {e['rule']} | {e['kind']} "
                   f"| {_fmt(e.get('value', '-'))} | "
                   f"{_fmt(e.get('threshold', '-'))} |")
    out.append("")
    return out


def _recovery_section(events):
    """Markdown lines for the ``recover`` event type (elastic training,
    docs/resilience.md): the trip→quiesce→reform→reshard→resume chain
    per process, plus the membership change and the recovery pause."""
    recovers = _by_type(events, "recover")
    if not recovers:
        return []
    out = ["## Recovery timeline (elastic)", ""]
    resumes = [e for e in recovers if e["kind"] == "resume"]
    aborts = [e for e in recovers if e["kind"] == "abort"]
    for e in resumes:
        out.append(f"- p{e['proc']} recovered: world "
                   f"**{e['world_before']} → {e['world_after']}**, "
                   f"resumed at step {e['step']} after a "
                   f"**{e['pause_s']:.2f}s** pause")
    for e in aborts:
        out.append(f"- p{e['proc']} recovery ABORTED: "
                   f"`{e.get('reason', '?')}` (fail-fast exit)")
    out += ["", "| t (s) | proc | kind | detail |", "|---|---|---|---|"]
    t0 = recovers[0]["ts"]
    for e in recovers:
        detail = ", ".join(
            f"{k}={_fmt(v)}" for k, v in sorted(e.items())
            if k not in ("v", "ts", "proc", "type", "kind"))
        out.append(f"| {e['ts'] - t0:+.3f} | p{e['proc']} | {e['kind']} | "
                   f"{detail or '-'} |")
    out.append("")
    return out


def render(events, bad, bundles, title="obs run report",
           waterfall=5) -> str:
    out = [f"# {title}", ""]
    procs = sorted({e["proc"] for e in events})
    steps = _by_type(events, "step")
    out.append(f"- events: **{len(events)}** across {len(procs)} "
               f"process(es) {procs}; invalid lines: {len(bad)}")
    for start in _by_type(events, "run_start"):
        flags = ", ".join(f"{k}={_fmt(v)}" for k, v in
                          sorted(start.get("flags", {}).items()))
        out.append(f"- run_start (p{start['proc']}): {flags}")
    for end in _by_type(events, "run_end"):
        out.append(f"- run_end (p{end['proc']}): {end['steps']} steps in "
                   f"{end['wall']:.1f}s")
    out.append("")

    if steps:
        out += ["## Throughput / loss trajectory", "",
                "| steps | mean loss | mean records/s | grad_norm | "
                "update_ratio |", "|---|---|---|---|---|"]
        for s0, s1, loss, thr, taps in _trajectory(steps):
            g = _fmt(taps["grad_norm"]) if taps else "-"
            u = _fmt(taps["update_ratio"]) if taps else "-"
            out.append(f"| {s0}-{s1} | {loss:.5f} | {thr:.1f} | {g} | {u} |")
        out.append("")

    phases = _by_type(events, "phase")
    if phases:
        # keep the LAST cumulative sample per (proc, name)
        latest = {}
        for e in phases:
            latest[(e["proc"], e["name"])] = e
        out += ["## Phase breakdown (cumulative mean s/iter)", "",
                "| phase | " + " | ".join(f"p{p}" for p in procs) + " |",
                "|---|" + "---|" * len(procs)]
        names = sorted({n for _, n in latest})
        for name in names:
            cells = []
            for p in procs:
                e = latest.get((p, name))
                cells.append(f"{e['seconds']:.4f}" if e else "-")
            out.append(f"| {name} | " + " | ".join(cells) + " |")
        out.append("")

    skips = max((e.get("skips", 0) for e in steps), default=0)
    dropped = sum(e.get("straggler_dropped", 0) for e in steps)
    vals = _by_type(events, "validation")
    if skips or dropped or vals:
        out.append("## Skips / stragglers / validation")
        out.append("")
        if skips:
            out.append(f"- non-finite steps skipped: **{skips}**")
        if dropped:
            out.append(f"- straggler replicas dropped (replica-steps): "
                       f"**{dropped}**")
        for e in vals[-8:]:
            out.append(f"- step {e['step']}: {e['method']} = "
                       f"{_fmt(e['value'])}")
        out.append("")

    out.extend(_serving_section(events, waterfall))
    out.extend(_forensics_section(events, waterfall))
    out.extend(_scale_section(events))
    out.extend(_ledger_section(events))
    out.extend(_alerts_section(events))
    out.extend(_recovery_section(events))

    incidents = [e for e in events if e["type"] in
                 ("fault", "watchdog", "preempt", "abort", "crash_bundle")]
    if incidents:
        out += ["## Incident timeline", ""]
        for e in incidents:
            detail = {k: v for k, v in e.items()
                      if k not in ("v", "ts", "proc", "type")}
            out.append(f"- p{e['proc']} **{e['type']}**: " + ", ".join(
                f"{k}={_fmt(v)}" for k, v in sorted(detail.items())))
        out.append("")

    if bundles:
        out += ["## Crash bundles", ""]
        for b in bundles:
            files = ", ".join(sorted(os.listdir(b)))
            out.append(f"- `{os.path.basename(b)}`: {files}")
        out.append("")

    if bad:
        out += ["## Invalid event lines", ""]
        for f, i, err in bad[:20]:
            out.append(f"- {os.path.basename(f)}:{i}: {err}")
        out.append("")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="run dir (BIGDL_OBS_DIR) or one .jsonl")
    ap.add_argument("-o", "--output", help="write markdown here "
                    "(default: stdout)")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 if any event line fails validation")
    ap.add_argument("--waterfall", type=int, default=5,
                    help="trace waterfall: slowest N sampled requests "
                    "(default 5; 0 disables)")
    args = ap.parse_args(argv)
    events, bad, bundles = load_run(args.path)
    md = render(events, bad, bundles,
                title=f"obs report: {os.path.basename(args.path.rstrip('/'))}",
                waterfall=args.waterfall)
    if args.output:
        with open(args.output, "w") as f:
            f.write(md + "\n")
    else:
        print(md)
    if args.strict and bad:
        print(f"STRICT: {len(bad)} invalid event line(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
