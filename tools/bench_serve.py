"""Serving benchmark: throughput-vs-latency curve under an offered-load
sweep, plus the dynamic-batching speedup over the one-request-at-a-time
baseline (docs/serving.md).

Scoring (``--model lenet|inception``): each sweep point submits
``--requests`` single-row requests to a :class:`ServeEngine` at the
offered rate (requests/second; ``inf`` = closed-loop, all at once) and
reports achieved throughput with p50/p95/p99 latency.  The baseline is
the serial loop a naive deployment runs — one row, one forward, one
host sync at a time — at the SAME model/shape, so the headline ratio
isolates exactly what dynamic batching + bucketed AOT executables buy.

Decode (``--model transformer``): serial per-request ``lm_decode``
versus the continuous-batching slot driver at equal token budgets,
reported as tokens/second.

Decode sweep (``--decode-sweep``): the paged-KV concurrency-scaling
story (docs/serving.md "Paged KV + speculative decode").  At a FIXED
pooled-token budget — exactly the HBM a ``--decode-slots``-wide slab of
``--decode-npos`` rows holds — the sweep offers increasing concurrency
and reports tokens/sec/slot for the legacy slab (live requests capped
at the slab width) against the paged pool (live requests capped only by
pooled tokens), asserts paged output token-for-token equal to serial
``lm_decode``, and finishes with a mixed-length SPECULATIVE stream
(``--spec-k``) audited for zero cold compiles after warmup through the
shared executable-cache counter.  Three SAMPLED-decode points follow
(docs/serving.md "Sampled decode"): a uniformly sampled stream
(``--temperature/--top-k/--top-p``), a mixed-param rotation whose
greedy rows must stay byte-identical, and a stop-sequence
early-retirement point (``--stop-len``) whose stops are cut from each
request's own greedy oracle so every row retires early.  Every point
STREAMS its tokens (``StreamFuture.on_tokens``), so rows carry the
client-observed ``ttft_p50``/``ttft_p99``/``itl_p50`` SLO columns next
to throughput.  One JSON row per point (contract pinned by
``tests/test_paged_decode.py``); ``--check`` enforces the acceptance
bar: more live requests than the slab bound, parity (streamed chunks
included), zero cold compiles (sampled and mixed-param streams
included), sampled throughput >= 0.9x the greedy point, a wall-clock
win from stop retirement, and TTFT p50 below the e2e p50 on a
long-generation point.

Traffic (``--traffic``): seeded OPEN-LOOP bursty/diurnal load — Poisson
arrivals whose instantaneous rate follows a declared burst window
(``--burst-factor/--burst-start-s/--burst-len-s``) and an optional
sinusoidal diurnal envelope, mixed priority classes
(``--priority-mix``), and shared-prefix request families when the
target is a decode fleet (``--model transformer``).  The run resolves
every submitted future exactly once (completed + shed + failed ==
accepted — the capstone accounting ``--check`` enforces), splits sheds
into inside/outside the declared overload window, and with
``--autoscale`` closes the loop through ``serve/autoscale.py``
(replica counts + scale actions land in the row).  One JSON row per
run (contract pinned by ``tests/test_autoscale.py``).

Router (``--replicas N``, N > 1): the same offered-load sweep through a
:class:`ReplicaPool` — N engine replicas behind the SLO router — with
per-replica and aggregate rows/s plus the shed rate per point
(``--slo-ms`` arms the deadline/shed policy; 0 = serve everything).
The JSON row contract is pinned by ``tests/test_serve_cluster.py``.

Runs on CPU (small defaults) and on a chip unchanged; emits one JSON
line per sweep point (``bench_serve:`` prefix) plus a summary table.
The acceptance bar — batched throughput >= 2x serial — is asserted with
``--check`` (used by scripts/serve_smoke.sh on the scoring path).
"""
from __future__ import annotations

import argparse
import json
import math
import os as _os
import sys as _sys
import time

import numpy as np

_REPO = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
_sys.path.insert(0, _REPO)


def _build(name: str):
    from bigdl_tpu.utils.random import set_seed
    set_seed(1)
    if name == "lenet":
        from bigdl_tpu.models.lenet import LeNet5
        return LeNet5(10), (28, 28)
    if name == "inception":
        from bigdl_tpu.models.inception import Inception_v1
        return Inception_v1(1000), (3, 224, 224)
    raise SystemExit(f"unknown scoring model {name!r}")


def serial_baseline(model, rows):
    """One-request-at-a-time: jitted batch-1 forward, full host sync per
    request — the Predictor-loop deployment this engine replaces."""
    import jax

    from bigdl_tpu.nn.module import Context

    p, s = model.params(), model.state()

    @jax.jit
    def fwd(x):
        out, _ = model.apply(p, x, s,
                             Context(training=False,
                                     key=jax.random.PRNGKey(0)))
        return out

    np.asarray(fwd(rows[:1]))          # compile outside the clock
    lats = []
    t0 = time.perf_counter()
    for r in rows:
        t1 = time.perf_counter()
        np.asarray(fwd(r[None]))
        lats.append(time.perf_counter() - t1)
    wall = time.perf_counter() - t0
    return {"mode": "serial", "requests": len(rows), "wall_s": wall,
            "throughput_rps": len(rows) / wall,
            **_quantiles(lats)}


def _quantiles(lats):
    lats = np.asarray(lats, np.float64)
    return {f"p{q}_ms": float(np.percentile(lats, q)) * 1e3
            for q in (50, 95, 99)}


def engine_point(eng, rows, rate):
    """One sweep point: submit at ``rate`` req/s (inf = closed loop).
    Latency is submit->completion, stamped by a done-callback on the
    engine's compute thread (not when the collector happens to look)."""
    gap = 0.0 if np.isinf(rate) else 1.0 / rate
    done_at = [None] * len(rows)

    def _stamp(i):
        def cb(_f):
            done_at[i] = time.perf_counter()
        return cb

    futs = []
    t0 = time.perf_counter()
    for i, r in enumerate(rows):
        if gap:
            delay = t0 + i * gap - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
        t_sub = time.perf_counter()
        f = eng.submit(r)
        f.add_done_callback(_stamp(i))
        futs.append((f, t_sub))
    for f, _ in futs:
        f.result()
    wall = time.perf_counter() - t0
    # result() waiters wake BEFORE done-callbacks run (CPython Future
    # semantics), so give the last stamps a moment to land
    t_spin = time.perf_counter()
    while any(d is None for d in done_at):
        if time.perf_counter() - t_spin > 5.0:
            raise RuntimeError("latency stamps missing after 5s")
        time.sleep(0.001)
    lats = [done - t_sub for (_, t_sub), done in zip(futs, done_at)]
    return {"mode": "engine", "offered_rps": None if np.isinf(rate)
            else rate, "requests": len(rows), "wall_s": wall,
            "throughput_rps": len(rows) / wall, **_quantiles(lats)}


def router_point(pool, rows, rate, slo_ms):
    """One router sweep point: submit at ``rate`` req/s through the
    pool; shed futures count against the shed rate, completions against
    throughput/latency."""
    from bigdl_tpu.serve import SheddedError

    gap = 0.0 if np.isinf(rate) else 1.0 / rate
    done_at = [None] * len(rows)

    def _stamp(i):
        def cb(_f):
            done_at[i] = time.perf_counter()
        return cb

    futs = []
    t0 = time.perf_counter()
    for i, r in enumerate(rows):
        if gap:
            delay = t0 + i * gap - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
        t_sub = time.perf_counter()
        f = pool.submit(r, slo_ms=slo_ms or None)
        f.add_done_callback(_stamp(i))
        futs.append((t_sub, f))
    lats, shed = [], 0
    for i, (t_sub, f) in enumerate(futs):
        try:
            f.result()
        except SheddedError:
            shed += 1
            continue
        # completion stamped by the done-callback (result() waiters wake
        # before callbacks run — engine_point's spin covers the race)
        t_spin = time.perf_counter()
        while done_at[i] is None:
            if time.perf_counter() - t_spin > 5.0:
                raise RuntimeError("latency stamp missing after 5s")
            time.sleep(0.0005)
        lats.append(done_at[i] - t_sub)
    wall = time.perf_counter() - t0
    return {"offered_rps": None if np.isinf(rate) else rate,
            "requests": len(rows), "completed": len(lats), "shed": shed,
            "wall_s": wall, "throughput_rps": len(lats) / wall,
            "shed_rate": shed / len(rows),
            **(_quantiles(lats) if lats
               else {"p50_ms": None, "p95_ms": None, "p99_ms": None})}


def router_row(model_name, replicas, point, replica_stats,
               wall_s, quant="off", kv_quant="off") -> dict:
    """The pinned JSON contract for one ``--replicas`` sweep point:
    aggregate throughput/latency/shed plus a per-replica breakdown and
    the replica weight-quant recipe (``quant``/``kv_quant`` — KV quant
    never applies to the scoring path, the column keeps the row shape
    uniform with the decode sweep).  ``tests/test_serve_cluster.py``
    keeps this shape honest."""
    per_replica = [{"name": s.get("name", f"r{i}"),
                    "completed": s.get("completed", 0),
                    "rps": (s.get("completed", 0) / wall_s
                            if wall_s else 0.0),
                    "shed": s.get("shed", 0),
                    "alive": s.get("alive", True)}
                   for i, s in enumerate(replica_stats)]
    return {"model": model_name, "mode": "router",
            "replicas": replicas, "quant": quant, "kv_quant": kv_quant,
            **point, "per_replica": per_replica}


def bench_router(args):
    from bigdl_tpu.serve import ReplicaPool
    model, shape = _build(args.model)
    rng = np.random.RandomState(0)
    rows = rng.rand(args.requests, *shape).astype(np.float32)

    pool = ReplicaPool(model, n_replicas=args.replicas,
                       max_batch=args.max_batch,
                       max_wait_ms=args.max_wait_ms, input_shape=shape,
                       slo_ms=args.slo_ms or None, quant=args.quant)
    try:
        pool.predict(rows[:args.max_batch])          # warm every bucket
        prev = [r.stats() for r in pool.replicas]
        points = []
        for rate in args.loads:
            t0 = time.perf_counter()
            pt = router_point(pool, rows, rate, args.slo_ms)
            wall = time.perf_counter() - t0
            # per-replica deltas over this point (rate-differenced
            # monotonic counters — the documented stats contract)
            cur = [r.stats() for r in pool.replicas]
            deltas = [{"name": getattr(r, "name", f"r{i}"),
                       "completed": (c.get("completed", 0)
                                     - p.get("completed", 0)),
                       "shed": c.get("shed", 0) - p.get("shed", 0),
                       "alive": r.alive()}
                      for i, (r, p, c) in enumerate(
                          zip(pool.replicas, prev, cur))]
            prev = cur
            row = router_row(args.model, args.replicas, pt, deltas, wall,
                             quant=args.quant)
            points.append(row)
            print(f"bench_serve: {json.dumps(row)}")
        rstats = pool.router.stats()
    finally:
        pool.close()

    print(f"\n{args.model} router x{args.replicas}:")
    for pt in points:
        off = ("closed-loop" if pt["offered_rps"] is None
               else f"{pt['offered_rps']:g} req/s offered")
        per = ", ".join(f"{p['name']} {p['rps']:.0f} r/s"
                        for p in pt["per_replica"])
        p95 = pt["p95_ms"]
        print(f"  {off}: {pt['throughput_rps']:.1f} req/s aggregate "
              f"(shed {pt['shed_rate']:.1%}; "
              f"p95 {p95:.2f} ms; {per})" if p95 is not None else
              f"  {off}: everything shed")
    print(f"  router: accepted {rstats['accepted']}, completed "
          f"{rstats['completed']}, shed {rstats['shed']}, requeued "
          f"{rstats['requeued']}")
    return points


def bench_scoring(args):
    from bigdl_tpu.serve import ServeEngine
    model, shape = _build(args.model)
    rng = np.random.RandomState(0)
    rows = rng.rand(args.requests, *shape).astype(np.float32)

    base = serial_baseline(model, rows)
    print(f"bench_serve: {json.dumps({'model': args.model, **base})}")

    eng = ServeEngine(model, max_batch=args.max_batch,
                      max_wait_ms=args.max_wait_ms, input_shape=shape,
                      quant=args.quant)
    try:
        eng.predict(rows[:eng.max_batch])        # warm every hot bucket
        points = []
        for rate in args.loads:
            pt = engine_point(eng, rows, rate)
            pt["compiles"] = eng.stats()["compiles"]
            pt["quant"] = args.quant
            points.append(pt)
            print(f"bench_serve: {json.dumps({'model': args.model, **pt})}")
        stats = eng.stats()
    finally:
        eng.close()

    best = max(p["throughput_rps"] for p in points)
    ratio = best / base["throughput_rps"]
    print(f"\n{args.model}: serial {base['throughput_rps']:.1f} req/s "
          f"(p95 {base['p95_ms']:.2f} ms)")
    for pt in points:
        off = ("closed-loop" if pt["offered_rps"] is None
               else f"{pt['offered_rps']:g} req/s offered")
        print(f"  engine {off}: {pt['throughput_rps']:.1f} req/s, "
              f"p50 {pt['p50_ms']:.2f} / p95 {pt['p95_ms']:.2f} / "
              f"p99 {pt['p99_ms']:.2f} ms")
    print(f"  batching speedup (best/serial): {ratio:.2f}x; compiles "
          f"{stats['compiles']} (all warmup), bucket hits "
          f"{stats['bucket_hits']}")
    if args.check and ratio < 2.0:
        raise SystemExit(
            f"dynamic batching speedup {ratio:.2f}x < required 2x")
    return ratio


def bench_decode(args):
    from bigdl_tpu.models.transformer import TransformerLM, lm_decode
    from bigdl_tpu.serve.decode import ContinuousDecoder
    from bigdl_tpu.utils.random import set_seed
    set_seed(1)
    model = TransformerLM(vocab_size=128, d_model=64, n_heads=4,
                          n_layers=2, hidden=128)
    rng = np.random.RandomState(0)
    n_words = args.decode_words
    seeds = [rng.randint(1, 128, rng.randint(2, 6)).tolist()
             for _ in range(args.requests)]
    n_pos = max(len(s) for s in seeds) + n_words - 1

    # compile outside the clock — ONCE PER DISTINCT SEED LENGTH: the
    # serial path recompiles its scan for every (n_seed, n_pos) pair,
    # which is exactly the cold-compile tax the bucketed/slotted serving
    # paths exist to avoid; warming all shapes keeps the comparison to
    # steady-state math only
    for length in {len(s) for s in seeds}:
        lm_decode(model, seeds[0][:1] * length, n_words)
    t0 = time.perf_counter()
    for s in seeds:
        lm_decode(model, s, n_words)
    serial_wall = time.perf_counter() - t0
    toks = len(seeds) * n_words
    print(f"bench_serve: {json.dumps({'model': 'transformer', 'mode': 'serial', 'tokens': toks, 'wall_s': serial_wall, 'tok_per_s': toks / serial_wall})}")

    dec = ContinuousDecoder(model, max_slots=args.decode_slots,
                            n_pos=n_pos, sync_interval=args.decode_sync)
    futs = [dec.submit(seeds[0], n_words)]
    dec.run()                                    # compile outside clock
    t0 = time.perf_counter()
    futs = [dec.submit(s, n_words) for s in seeds]
    dec.run()
    cont_wall = time.perf_counter() - t0
    assert all(f.done() for f in futs)
    print(f"bench_serve: {json.dumps({'model': 'transformer', 'mode': 'continuous', 'tokens': toks, 'wall_s': cont_wall, 'tok_per_s': toks / cont_wall, **dec.stats()})}")
    print(f"\ntransformer decode: serial {toks / serial_wall:.1f} tok/s, "
          f"continuous ({args.decode_slots} slots) "
          f"{toks / cont_wall:.1f} tok/s "
          f"({serial_wall / cont_wall:.2f}x), host syncs "
          f"{dec.host_syncs} for {dec.steps} steps")
    return serial_wall / cont_wall


def decode_sweep_row(impl, offered, tokens, wall_s, dec_stats,
                     compiles, stream=None, attn_kernel=None) -> dict:
    """The pinned JSON contract for one ``--decode-sweep`` point:
    throughput per live slot plus the paging/prefix/speculation/quant
    counters that explain it, and the streaming SLO columns
    (``ttft_p50``/``ttft_p99``/``itl_p50``, milliseconds,
    client-observed through ``StreamFuture.on_tokens`` — None when the
    point did not stream, so old parsers keep working).
    ``attn_kernel`` names the Mosaic decode kernel active for the point
    (``--attn-kernel``; None — the default XLA gathered view — keeps
    old parsers working).  ``sampled``/``steps_saved`` surface the
    sampled-decode counters (None on points that used neither, so old
    parsers keep working).  ``tests/test_paged_decode.py`` keeps this
    shape honest."""
    live = dec_stats.get("live_hwm") or dec_stats["slots"]
    pool = dec_stats.get("pool") or {}
    prefix = dec_stats.get("prefix") or {}
    rate = tokens / wall_s if wall_s else 0.0
    pool_tokens = pool["pages"] * pool["page_size"] if pool else None
    bpt = dec_stats.get("kv_bytes_per_token")
    stream = stream or {}
    return {"model": "transformer", "mode": "decode_sweep", "impl": impl,
            "offered": offered, "tokens": tokens, "wall_s": wall_s,
            "tok_per_s": rate,
            "tok_per_s_per_slot": rate / max(1, live),
            "live_max": live, "slots": dec_stats["slots"],
            "pool_tokens": pool_tokens,
            # the quant columns: weight mode (decode serves fp weights),
            # KV-page mode, and the pooled-token HBM budget in BYTES —
            # the quantity held constant across fp-vs-int8 points
            "quant": dec_stats.get("quant", "off"),
            "kv_quant": dec_stats.get("kv_quant", "off"),
            "pool_bytes": (pool_tokens * bpt
                           if pool_tokens is not None and bpt else None),
            "spec_k": dec_stats.get("spec_k", 0),
            "accept_mean": dec_stats.get("accept_mean"),
            "accept_p50": dec_stats.get("accept_p50"),
            "prefix_hits": prefix.get("hits", 0),
            "ttft_p50": stream.get("ttft_p50"),
            "ttft_p99": stream.get("ttft_p99"),
            "itl_p50": stream.get("itl_p50"),
            "e2e_p50": stream.get("e2e_p50"),
            "attn_kernel": attn_kernel,
            "sampled": dec_stats.get("sampled") or None,
            "steps_saved": dec_stats.get("steps_saved") or None,
            "compiles": compiles}


def bench_decode_sweep(args):
    from bigdl_tpu import quant
    from bigdl_tpu.models.transformer import TransformerLM, lm_decode
    from bigdl_tpu.quant import kv as kvq
    from bigdl_tpu.serve import xcache
    from bigdl_tpu.serve.decode import ContinuousDecoder
    from bigdl_tpu.utils.random import set_seed
    set_seed(1)
    model = TransformerLM(vocab_size=128, d_model=64, n_heads=4,
                          n_layers=2, hidden=128)
    rng = np.random.RandomState(0)
    n_words, ps = args.decode_words, args.page_size
    seeds = [rng.randint(1, 128, rng.randint(2, 6)).tolist()
             for _ in range(args.requests)]
    n_pos = max(args.decode_npos,
                max(len(s) for s in seeds) + n_words - 1)
    slab_slots = args.decode_slots
    # the FIXED HBM budget both implementations get: what the slab holds
    pool_pages = slab_slots * (-(-n_pos // ps))
    toks = len(seeds) * n_words
    kv_quant = args.kv_quant

    # serial oracle (and scan warmup per distinct seed length)
    for length in {len(s) for s in seeds}:
        lm_decode(model, [1] * length, n_words)
    oracle = [lm_decode(model, s, n_words) for s in seeds]

    # --attn-kernel: flip the Mosaic decode-kernel flags for the sweep's
    # paged points (interpreter off-TPU, the staged on-chip A/B runs the
    # same command with a chip attached); each row's attn_kernel column
    # records what was ACTIVE for that point, None = XLA gathered view
    from bigdl_tpu.models import transformer as _tf
    from bigdl_tpu.ops import pallas_kernels as _pk
    attn_mode = getattr(args, "attn_kernel", "off")
    _flags_prev = (_tf._PALLAS_PAGED_ATTN, _tf._PALLAS_SPEC_VERIFY)
    if attn_mode != "off":
        on = True if _pk._on_tpu() else "interpret"
        if attn_mode in ("paged", "paged+spec"):
            _tf._PALLAS_PAGED_ATTN = on
        if attn_mode in ("spec", "paged+spec"):
            _tf._PALLAS_SPEC_VERIFY = on

    def _active_attn_kernel(kw):
        parts = []
        if kw.get("page_size") is not None:
            if _tf._PALLAS_PAGED_ATTN:
                parts.append("paged")
            if kw.get("spec_k") and _tf._PALLAS_SPEC_VERIFY:
                parts.append("spec")
        return "+".join(parts) or None

    def run_point(impl, offered, sampling=None, parity_mode="exact",
                  **kw):
        # ``sampling`` is a per-request list of SamplingParams dicts
        # (None entries stay greedy); ``parity_mode`` picks the oracle
        # comparison — "exact" (every row byte-identical), "greedy_rows"
        # (only the greedy rows of a mixed-param stream), "prefix"
        # (stop-retired rows are exact PREFIXES of their oracle rows),
        # or "none" (sampled rows have no greedy oracle — parity=None
        # keeps the --check fp gate out of their way)
        dec = ContinuousDecoder(model, n_pos=n_pos,
                                sync_interval=args.decode_sync, **kw)
        c0 = xcache.get().stats()["compiles"]
        # every point streams: per-request token-arrival stamps give
        # the client-observed TTFT/ITL columns, and the chunk-sum
        # parity check below holds the streamed sequence to the
        # all-at-once result (zero compiled-program cost — delivery is
        # host bookkeeping on the boundary's existing materialization)
        arrivals = [[] for _ in seeds]
        sub_at = [0.0] * len(seeds)
        done_at = [None] * len(seeds)
        t0 = time.perf_counter()
        futs = []
        for i, s in enumerate(seeds):
            sub_at[i] = time.perf_counter()
            f = dec.submit(s, n_words,
                           sampling=sampling[i] if sampling else None)
            f.on_tokens(lambda toks, i=i: arrivals[i].append(
                (time.perf_counter(), len(toks))))
            f.add_done_callback(lambda _f, i=i: done_at.__setitem__(
                i, time.perf_counter()))
            futs.append(f)
        dec.run()
        wall = time.perf_counter() - t0
        rows = [f.result() for f in futs]
        t_spin = time.perf_counter()
        while any(d is None for d in done_at):   # callbacks race result()
            if time.perf_counter() - t_spin > 5.0:
                raise RuntimeError("latency stamps missing after 5s")
            time.sleep(0.001)
        streamed = [f.streamed() for f in futs]
        stream_parity = all(
            st == list(r[len(s):])
            for st, r, s in zip(streamed, rows, seeds))
        ttfts = [a[0][0] - sub_at[i]
                 for i, a in enumerate(arrivals) if a]
        itls = []
        for a in arrivals:
            for (t1, _n1), (t2, n2) in zip(a, a[1:]):
                itls += [(t2 - t1) / n2] * n2
        e2e = [d - s for d, s in zip(done_at, sub_at)]

        def pct(vals, q):
            return (float(np.percentile(np.asarray(vals), q)) * 1e3
                    if vals else None)

        stream = {"ttft_p50": pct(ttfts, 50), "ttft_p99": pct(ttfts, 99),
                  "itl_p50": pct(itls, 50), "e2e_p50": pct(e2e, 50)}
        # per-token agreement with the serial fp oracle over the
        # GENERATED tail (truncated to the replayed row's length, so
        # stop-retired rows compare what they actually generated): 1.0
        # on every fp greedy point (exact parity contract); sampled
        # rows and quantized-KV points may diverge within their budget
        agree = float(np.mean([
            np.mean(np.asarray(r[len(s):])
                    == np.asarray(o[len(s):len(r)]))
            for r, o, s in zip(rows, oracle, seeds)]))
        n_tok = sum(len(r) - len(s) for r, s in zip(rows, seeds))
        row = decode_sweep_row(impl, offered, n_tok, wall, dec.stats(),
                               xcache.get().stats()["compiles"] - c0,
                               stream=stream,
                               attn_kernel=_active_attn_kernel(kw))
        if parity_mode == "exact":
            row["parity"] = rows == oracle
        elif parity_mode == "greedy_rows":
            row["parity"] = all(
                r == o for r, o, sp in zip(rows, oracle, sampling)
                if sp is None)
        elif parity_mode == "prefix":
            row["parity"] = all(
                len(r) <= len(o) and list(r) == list(o[:len(r)])
                for r, o in zip(rows, oracle))
        else:
            row["parity"] = None
        row["stream_parity"] = stream_parity
        row["agreement"] = agree
        dec.close()
        print(f"bench_serve: {json.dumps(row)}")
        return row

    try:
        points = [run_point("slab", slab_slots, max_slots=slab_slots,
                            paged=False)]
        for offered in (slab_slots, 2 * slab_slots, 4 * slab_slots):
            points.append(run_point(
                "paged", offered, max_slots=offered, page_size=ps,
                n_pages=pool_pages, prefix_cache=False))
        spec = run_point("paged+spec", 2 * slab_slots,
                         max_slots=2 * slab_slots, page_size=ps,
                         n_pages=pool_pages, prefix_cache=True,
                         spec_k=args.spec_k)
        points.append(spec)

        # the sampled-decode points ride the SAME paged config as
        # points[1] (offered == slots), so a cold compile here would
        # mean sampling params leaked into the program shape
        samp = run_point(
            "paged+sampled", slab_slots, max_slots=slab_slots,
            page_size=ps, n_pages=pool_pages, prefix_cache=False,
            parity_mode="none",
            sampling=[{"temperature": args.temperature,
                       "top_k": args.top_k, "top_p": args.top_p,
                       "seed": 1000 + i} for i in range(len(seeds))])
        points.append(samp)

        # mixed-param rotation: greedy / temp / temp+top_k / temp+top_p
        # interleave across one stream — one compiled program serves
        # all four, and the greedy rows must stay byte-identical
        def _rot(i):
            j = i % 4
            if j == 0:
                return None
            p = {"temperature": args.temperature, "seed": 2000 + i}
            if j == 2:
                p["top_k"] = args.top_k or 8
            elif j == 3:
                p["top_p"] = args.top_p or 0.9
            return p
        mixed = run_point(
            "paged+mixed", slab_slots, max_slots=slab_slots,
            page_size=ps, n_pages=pool_pages, prefix_cache=False,
            parity_mode="greedy_rows",
            sampling=[_rot(i) for i in range(len(seeds))])
        points.append(mixed)

        # stop-sequence early retirement: each request's stop is cut
        # from its OWN greedy oracle a quarter of the way in, so every
        # row retires early and the point's rows/s beats the full run
        cut = max(1, n_words // 4)
        stop_pt = run_point(
            "paged+stop", slab_slots, max_slots=slab_slots,
            page_size=ps, n_pages=pool_pages, prefix_cache=False,
            max_stop_len=max(8, args.stop_len), parity_mode="prefix",
            sampling=[{"stop": [list(o[len(s):])[
                max(0, cut - args.stop_len):cut]]}
                for s, o in zip(seeds, oracle)])
        points.append(stop_pt)

        qpoints = []
        qspec = None
        if kv_quant != "off":
            # int8 KV points at the SAME pooled-token HBM BUDGET: the
            # fp pool's bytes re-divided by the quantized bytes/token
            # (scales included), so extra live concurrency is pure
            # density win
            from bigdl_tpu.models.transformer import _lm_handles
            h = _lm_handles(model)
            budget_bytes = pool_pages * ps * kvq.bytes_per_token(
                h.n_layers, h.n_heads, h.hd, "off")
            pages_q = budget_bytes // (ps * kvq.bytes_per_token(
                h.n_layers, h.n_heads, h.hd, kv_quant))
            for offered in (2 * slab_slots, 4 * slab_slots,
                            8 * slab_slots):
                qpoints.append(run_point(
                    f"paged[{kv_quant}]", offered, max_slots=offered,
                    page_size=ps, n_pages=pages_q, prefix_cache=False,
                    kv_quant=kv_quant))
            qspec = run_point(f"paged+spec[{kv_quant}]", 4 * slab_slots,
                              max_slots=4 * slab_slots, page_size=ps,
                              n_pages=pages_q, prefix_cache=True,
                              spec_k=args.spec_k, kv_quant=kv_quant)
            qpoints.append(qspec)
            points += qpoints
    finally:
        (_tf._PALLAS_PAGED_ATTN, _tf._PALLAS_SPEC_VERIFY) = _flags_prev

    slab = points[0]
    print(f"\ntransformer decode sweep (pool {pool_pages} pages x {ps} "
          f"tokens = slab {slab_slots} x {n_pos}"
          + (f"; kv_quant={kv_quant}" if kv_quant != "off" else "")
          + "):")
    for pt in points:
        ttft = pt.get("ttft_p50")
        print(f"  {pt['impl']:<12} offered {pt['offered']:>3}: "
              f"{pt['live_max']:>3} live max, "
              f"{pt['tok_per_s']:8.1f} tok/s "
              f"({pt['tok_per_s_per_slot']:.1f}/slot), "
              f"agreement {pt['agreement']:.3f}, "
              f"cold compiles {pt['compiles']}"
              + (f", ttft p50 {ttft:.1f} ms / itl p50 "
                 + (f"{pt['itl_p50']:.2f} ms" if pt["itl_p50"]
                    is not None else "-")
                 if ttft is not None else "")
              + (f", accept mean {pt['accept_mean']:.2f}"
                 if pt["spec_k"] else "")
              + (f", sampled {pt['sampled']}" if pt["sampled"] else "")
              + (f", steps saved {pt['steps_saved']}"
                 if pt["steps_saved"] else ""))
    scaled = [p for p in points if p["impl"] == "paged"
              and p["offered"] > slab_slots]
    best_live = max(p["live_max"] for p in scaled)
    # the fp pool's live bound is only MEASURED when some fp point is
    # pool-bound (live < offered — admission queued on page exhaustion);
    # an offered-limited ladder underestimates it, which would make the
    # quant density ratio below spuriously strict
    fp_saturated = any(p["live_max"] < p["offered"] for p in scaled)
    print(f"  live-concurrency: slab bound {slab['live_max']}, paged "
          f"reaches {best_live} on the same pooled tokens"
          + ("" if fp_saturated else
             " (fp pool never saturated at this offered ladder)"))
    if qpoints:
        best_live_q = max(p["live_max"] for p in qpoints)
        print(f"  {kv_quant} KV at the same HBM budget: {best_live_q} "
              f"live ({best_live_q / max(1, best_live):.2f}x the fp-KV "
              f"bound), agreement >= "
              f"{min(p['agreement'] for p in qpoints):.3f}")
    if args.check:
        fp_points = [p for p in points if p["kv_quant"] == "off"
                     and p["parity"] is not None]
        if not all(p["parity"] for p in fp_points):
            raise SystemExit("decode sweep lost token parity")
        if not all(p["stream_parity"] for p in points):
            raise SystemExit("streamed chunks diverged from the "
                             "all-at-once rows")
        # the streaming SLO point: on a long generation (n_words spans
        # several sync boundaries) the first token must land well
        # before retire — TTFT below the e2e completion latency
        lp = points[1]     # paged @ offered == slots: uncontended
        if (lp["ttft_p50"] is not None and lp["e2e_p50"] is not None
                and lp["ttft_p50"] >= lp["e2e_p50"]):
            raise SystemExit(
                f"streaming ttft p50 {lp['ttft_p50']:.1f} ms did not "
                f"beat the e2e p50 {lp['e2e_p50']:.1f} ms on a "
                f"long-generation point")
        if best_live <= slab["live_max"]:
            raise SystemExit(
                f"paged concurrency {best_live} did not scale past the "
                f"slab bound {slab['live_max']}")
        if spec["compiles"]:
            raise SystemExit(
                f"speculative stream hit {spec['compiles']} cold "
                f"compiles after warmup")
        # sampled decode rides the greedy fast path: same compiled
        # program (zero cold compiles on sampled AND mixed-param
        # streams) at no worse than a 10% throughput haircut
        base = points[1]       # greedy paged @ offered == slots
        for pt in (samp, mixed):
            if pt["compiles"]:
                raise SystemExit(
                    f"{pt['impl']} stream hit {pt['compiles']} cold "
                    f"compiles — sampling params leaked into the "
                    f"program shape")
        if samp["tok_per_s"] < 0.9 * base["tok_per_s"]:
            raise SystemExit(
                f"sampled throughput {samp['tok_per_s']:.1f} tok/s "
                f"fell below 0.9x the greedy point "
                f"{base['tok_per_s']:.1f} tok/s")
        if not stop_pt["steps_saved"]:
            raise SystemExit("stop point retired no request early")
        if stop_pt["wall_s"] >= base["wall_s"]:
            raise SystemExit(
                f"stop-retirement point took {stop_pt['wall_s']:.2f}s "
                f"for the same request count the greedy point "
                f"finished in {base['wall_s']:.2f}s — early "
                f"retirement saved nothing")
        if qpoints:
            if not fp_saturated:
                print("  note: density gate not evaluable — the fp "
                      "pool never saturated at this offered ladder; "
                      "raise --requests or lower --decode-npos to "
                      "measure the fp live bound")
            elif best_live_q < 1.8 * best_live:
                raise SystemExit(
                    f"{kv_quant} KV live-concurrency {best_live_q} < "
                    f"1.8x the fp bound {best_live} at equal HBM")
            worst = min(p["agreement"] for p in qpoints)
            if worst < 1.0 - quant.KV_TOKEN_DRIFT_BUDGET:
                raise SystemExit(
                    f"{kv_quant} KV greedy drift {1 - worst:.3f} "
                    f"exceeds the declared budget "
                    f"{quant.KV_TOKEN_DRIFT_BUDGET}")
            if qspec["compiles"]:
                raise SystemExit(
                    f"quantized speculative stream hit "
                    f"{qspec['compiles']} cold compiles after warmup")
            if (spec["accept_p50"] is not None
                    and qspec["accept_p50"] is not None
                    and abs(spec["accept_p50"]
                            - qspec["accept_p50"]) > 1):
                raise SystemExit(
                    f"quantized spec acceptance p50 "
                    f"{qspec['accept_p50']} drifted more than one "
                    f"bucket from fp {spec['accept_p50']}")
    return points


# ---------------------------------------------------------------------------
# open-loop traffic generator (--traffic; docs/serving.md "Autoscaling")
# ---------------------------------------------------------------------------

def traffic_envelope(t: float, base_rps: float, burst_factor: float = 1.0,
                     burst_start_s: float = 0.0, burst_len_s: float = 0.0,
                     diurnal_amp: float = 0.0,
                     diurnal_period_s: float = 60.0) -> float:
    """Offered rate (req/s) at offset ``t``: the base rate modulated by
    a sinusoidal diurnal envelope (``amp`` in [0, 1) scales the swing)
    and multiplied by ``burst_factor`` inside the declared burst window
    ``[burst_start_s, burst_start_s + burst_len_s)`` — the overload
    window the chaos drill asserts sheds stay inside."""
    rate = base_rps
    if diurnal_amp:
        rate *= 1.0 + diurnal_amp * math.sin(
            2.0 * math.pi * t / max(diurnal_period_s, 1e-9))
    if burst_len_s > 0 and burst_start_s <= t < burst_start_s + burst_len_s:
        rate *= burst_factor
    return max(rate, 1e-9)


def traffic_arrivals(rng, n: int, base_rps: float, **envelope) -> list:
    """``n`` seeded open-loop arrival offsets (seconds from start):
    Poisson arrivals whose instantaneous rate follows
    :func:`traffic_envelope` (each inter-arrival gap drawn at the rate
    in effect at the PREVIOUS arrival — piecewise approximation of the
    inhomogeneous process, deterministic under a seeded ``rng``)."""
    t, out = 0.0, []
    for _ in range(int(n)):
        t += rng.exponential(1.0 / traffic_envelope(t, base_rps,
                                                    **envelope))
        out.append(t)
    return out


def parse_priority_mix(s: str) -> list:
    """``"0:0.2,2:0.8"`` → normalized ``[(class, weight), ...]`` —
    the mixed-priority-class contract of the ``--traffic`` flag."""
    out = []
    for tok in str(s).split(","):
        tok = tok.strip()
        if not tok:
            continue
        cls, w = tok.split(":")
        out.append((int(cls), float(w)))
    if not out:
        raise ValueError(f"empty priority mix: {s!r}")
    total = sum(w for _, w in out)
    if total <= 0:
        raise ValueError(f"priority mix weights sum to {total}: {s!r}")
    return [(c, w / total) for c, w in out]


def traffic_priorities(rng, n: int, mix) -> list:
    """``n`` seeded priority classes drawn from a normalized mix."""
    classes = [c for c, _ in mix]
    probs = [w for _, w in mix]
    return [int(c) for c in rng.choice(classes, size=int(n), p=probs)]


def traffic_row(model_name, spec: dict, outcome: dict,
                autoscale: dict | None = None,
                families: int | None = None) -> dict:
    """The pinned JSON contract for one ``--traffic`` run: the seeded
    traffic spec (replayable), the resolution accounting (accepted ==
    completed + failed + shed — every future resolves exactly once),
    the shed split against the DECLARED overload window, per-priority
    outcomes, and the autoscaler's actions when one ran.
    ``tests/test_autoscale.py::TestBenchTrafficContract`` keeps this
    shape honest."""
    row = {"model": model_name, "mode": "traffic", "families": families,
           **spec, **outcome}
    scale = autoscale or {}
    row.update(autoscale=bool(autoscale),
               scale_ups=scale.get("scale_ups", 0),
               scale_downs=scale.get("scale_downs", 0),
               replicas_start=scale.get("replicas_start"),
               replicas_final=scale.get("replicas_final"))
    return row


def run_traffic(submit, rows, arrivals, priorities, burst_window,
                timeout: float = 300.0) -> dict:
    """Drive one open-loop traffic schedule: ``submit(row, priority)``
    at each arrival offset, resolve every future, and account each
    exactly once (completed / shed / failed — the capstone bar).
    ``burst_window = (t0, t1)`` splits sheds into in-window vs outside
    (the declared-overload assertion)."""
    from bigdl_tpu.serve import SheddedError

    done_at = [None] * len(rows)

    def _stamp(i):
        def cb(_f):
            done_at[i] = time.perf_counter()
        return cb

    futs = []
    t0 = time.perf_counter()
    for i, (r, off, p) in enumerate(zip(rows, arrivals, priorities)):
        delay = t0 + off - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        t_sub = time.perf_counter()
        f = submit(r, p)
        f.add_done_callback(_stamp(i))
        futs.append((f, t_sub, off))
    lats, shed_in, shed_out = [], 0, 0
    per: dict = {}
    completed = failed = shed = 0
    for i, ((f, t_sub, off), p) in enumerate(zip(futs, priorities)):
        d = per.setdefault(p, {"priority": p, "requests": 0,
                               "completed": 0, "shed": 0, "failed": 0})
        d["requests"] += 1
        try:
            f.result(timeout=timeout)
        except SheddedError:
            shed += 1
            d["shed"] += 1
            if burst_window[0] <= off <= burst_window[1]:
                shed_in += 1
            else:
                shed_out += 1
            continue
        except Exception:
            failed += 1
            d["failed"] += 1
            continue
        completed += 1
        d["completed"] += 1
        t_spin = time.perf_counter()
        while done_at[i] is None:    # callbacks race result()
            if time.perf_counter() - t_spin > 5.0:
                raise RuntimeError("latency stamp missing after 5s")
            time.sleep(0.0005)
        lats.append(done_at[i] - t_sub)
    wall = time.perf_counter() - t0
    n = len(rows)
    return {"requests": n, "wall_s": wall,
            "offered_rps": n / arrivals[-1] if arrivals[-1] else None,
            "accepted": n, "completed": completed, "shed": shed,
            "failed": failed,
            "throughput_rps": completed / wall if wall else 0.0,
            "shed_rate": shed / n if n else 0.0,
            "shed_in_window": shed_in, "shed_outside_window": shed_out,
            **(_quantiles(lats) if lats
               else {"p50_ms": None, "p95_ms": None, "p99_ms": None}),
            "per_priority": [per[k] for k in sorted(per)]}


def bench_traffic(args):
    """``--traffic``: seeded bursty/diurnal open-loop load — mixed
    priority classes, Poisson arrivals, the declared overload window —
    through a ReplicaPool (scoring models) or a DecodeFleet with
    shared-prefix families (``--model transformer``), optionally with
    the SLO-driven autoscaler closed-loop (``--autoscale``)."""
    spec = {"requests": args.requests, "seed": args.traffic_seed,
            "base_rps": args.base_rps, "burst_factor": args.burst_factor,
            "burst_start_s": args.burst_start_s,
            "burst_len_s": args.burst_len_s,
            "diurnal_amp": args.diurnal_amp,
            "diurnal_period_s": args.diurnal_period_s,
            "priority_mix": args.priority_mix}
    envelope = dict(burst_factor=args.burst_factor,
                    burst_start_s=args.burst_start_s,
                    burst_len_s=args.burst_len_s,
                    diurnal_amp=args.diurnal_amp,
                    diurnal_period_s=args.diurnal_period_s)
    rng = np.random.RandomState(args.traffic_seed)
    arrivals = traffic_arrivals(rng, args.requests, args.base_rps,
                                **envelope)
    priorities = traffic_priorities(
        rng, args.requests, parse_priority_mix(args.priority_mix))
    burst_window = (args.burst_start_s,
                    args.burst_start_s + args.burst_len_s
                    + args.burst_margin_s)

    def autoscale_of(target):
        if not args.autoscale:
            return None, None
        scaler = target.start_autoscaler(
            min_replicas=args.min_replicas or args.replicas,
            max_replicas=args.max_replicas,
            interval=args.scale_interval, window_s=args.scale_interval * 4)
        return scaler, len(target.replicas)

    families = None
    if args.model == "transformer":
        from bigdl_tpu.models.transformer import TransformerLM
        from bigdl_tpu.serve.fleet import DecodeFleet
        from bigdl_tpu.utils.random import set_seed
        set_seed(1)
        model = TransformerLM(vocab_size=128, d_model=64, n_heads=4,
                              n_layers=2, hidden=128)
        families = args.families
        seeds, _f = fleet_families(rng, args.families, args.requests,
                                   args.zipf_a, args.prefix_pages,
                                   args.page_size, 128)
        n_pos = max(len(s) for s in seeds) + args.decode_words - 1
        fleet = DecodeFleet(model, n_decode=args.replicas,
                            slo_ms=args.slo_ms or None,
                            max_slots=args.decode_slots, n_pos=n_pos,
                            page_size=args.page_size,
                            sync_interval=args.decode_sync)
        scaler, start = autoscale_of(fleet)
        try:
            outcome = run_traffic(
                lambda s, p: fleet.submit(s, args.decode_words,
                                          priority=p,
                                          slo_ms=args.slo_ms or None),
                seeds, arrivals, priorities, burst_window)
            rstats = fleet.router.stats()
            scale = None if scaler is None else {
                "scale_ups": scaler.scale_ups,
                "scale_downs": scaler.scale_downs,
                "replicas_start": start,
                "replicas_final": len(fleet.replicas)}
        finally:
            fleet.close()
    else:
        from bigdl_tpu.serve import ReplicaPool
        model, shape = _build(args.model)
        rows = rng.rand(args.requests, *shape).astype(np.float32)
        pool = ReplicaPool(model, n_replicas=args.replicas,
                           max_batch=args.max_batch,
                           max_wait_ms=args.max_wait_ms,
                           input_shape=shape,
                           slo_ms=args.slo_ms or None, quant=args.quant)
        scaler, start = autoscale_of(pool)
        try:
            # warm every bucket OUTSIDE the SLO policy (slo_ms=0 = no
            # deadline): a cold-compile warmup burst must not shed
            for f in pool.submit_many(rows[:args.max_batch], slo_ms=0):
                f.result(timeout=300)
            outcome = run_traffic(
                lambda r, p: pool.submit(r, priority=p,
                                         slo_ms=args.slo_ms or None),
                rows, arrivals, priorities, burst_window)
            rstats = pool.router.stats()
            scale = None if scaler is None else {
                "scale_ups": scaler.scale_ups,
                "scale_downs": scaler.scale_downs,
                "replicas_start": start,
                "replicas_final": len(pool.replicas)}
        finally:
            pool.close()

    row = traffic_row(args.model, spec, outcome, autoscale=scale,
                      families=families)
    print(f"bench_serve: {json.dumps(row)}")
    print(f"\n{args.model} traffic ({args.requests} req, base "
          f"{args.base_rps:g} rps, burst x{args.burst_factor:g} @ "
          f"[{args.burst_start_s:g}, "
          f"{args.burst_start_s + args.burst_len_s:g}]s):")
    print(f"  {outcome['throughput_rps']:.1f} req/s served; "
          f"completed {outcome['completed']}, shed {outcome['shed']} "
          f"({outcome['shed_in_window']} in window / "
          f"{outcome['shed_outside_window']} outside), failed "
          f"{outcome['failed']}")
    if outcome["p95_ms"] is not None:
        print(f"  p50 {outcome['p50_ms']:.2f} / p95 "
              f"{outcome['p95_ms']:.2f} / p99 "
              f"{outcome['p99_ms']:.2f} ms")
    if scale:
        print(f"  autoscale: +{scale['scale_ups']}/"
              f"-{scale['scale_downs']} "
              f"({scale['replicas_start']} → "
              f"{scale['replicas_final']} replicas)")
    if args.check:
        total = (outcome["completed"] + outcome["shed"]
                 + outcome["failed"])
        if total != outcome["accepted"]:
            raise SystemExit(
                f"resolution accounting broken: completed+shed+failed "
                f"{total} != accepted {outcome['accepted']}")
        if rstats["failed"] != outcome["failed"]:
            raise SystemExit(
                f"router failed count {rstats['failed']} != observed "
                f"{outcome['failed']}")
    return row


def fleet_families(rng, n_families: int, n_requests: int, zipf_a: float,
                   prefix_pages: int, page_size: int, vocab: int,
                   suffix_max: int = 3):
    """Shared-prefix request families: ``n_families`` fixed prefixes of
    ``prefix_pages`` full pages each, requests drawing their family
    Zipf(``zipf_a``)-distributed (family 0 hottest) with a short random
    suffix — the system-prompt traffic shape affinity routing and the
    host tier exist for.  Returns ``(seeds, family_ids)``."""
    plen = prefix_pages * page_size
    prefixes = [rng.randint(1, vocab, plen).tolist()
                for _ in range(n_families)]
    w = 1.0 / np.power(np.arange(1, n_families + 1), zipf_a)
    w /= w.sum()
    fams = rng.choice(n_families, size=n_requests, p=w)
    seeds = [prefixes[f] + rng.randint(1, vocab,
                                       1 + rng.randint(suffix_max)).tolist()
             for f in fams]
    return seeds, [int(f) for f in fams]


def fleet_row(impl, replicas, prefill_replicas, families, zipf_a,
              requests, tokens, wall_s, router_stats,
              replica_stats, transport: str = "inproc",
              ship_bytes_per_s: float = 0.0) -> dict:
    """The pinned JSON contract for one ``--fleet-sweep`` point:
    fleet-aggregate throughput plus the affinity/prefill/host-tier
    counters that explain it and a per-replica breakdown (role-labelled
    — prefill replicas ride along with their ship counts).
    ``transport`` names the replica wire (inproc/stdio/tcp) and
    ``ship_bytes_per_s`` the prefill→decode KV-page payload rate over
    it (0.0 without prefill replicas) — both default-valued so parsers
    of the pre-transport contract keep working.
    ``tests/test_fleet.py::TestBenchFleetContract`` keeps this shape
    honest."""
    per_replica, hits, misses, readmitted = [], 0, 0, 0
    for s in replica_stats:
        entry = {"name": s.get("name", "?"), "role": s.get("role", "?"),
                 "alive": s.get("alive", True)}
        if s.get("role") == "decode":
            pfx = s.get("prefix") or {}
            entry.update(admitted=s.get("admitted", 0),
                         prefix_hits=pfx.get("hits", 0),
                         prefix_misses=pfx.get("misses", 0))
            hits += pfx.get("hits", 0)
            misses += pfx.get("misses", 0)
            readmitted += (s.get("kv_host") or {}).get("readmitted", 0)
        else:
            entry.update(prefills=s.get("prefills", 0),
                         pages_shipped=s.get("pages_shipped", 0))
        per_replica.append(entry)
    rate = tokens / wall_s if wall_s else 0.0
    return {"model": "transformer", "mode": "fleet_sweep", "impl": impl,
            "replicas": replicas, "prefill_replicas": prefill_replicas,
            "families": families, "zipf_a": zipf_a,
            "requests": requests, "tokens": tokens, "wall_s": wall_s,
            "tok_per_s": rate,
            "hit_rate": hits / max(1, hits + misses),
            "affinity_hits": router_stats.get("affinity_hits", 0),
            "affinity_misses": router_stats.get("affinity_misses", 0),
            "prefill_shipped": router_stats.get("prefill_shipped", 0),
            "prefill_fallback": router_stats.get("prefill_fallback", 0),
            "prefill_skipped": router_stats.get("prefill_skipped", 0),
            "kv_host_readmitted": readmitted,
            "transport": transport,
            "ship_bytes_per_s": float(ship_bytes_per_s),
            "per_replica": per_replica}


def bench_fleet(args):
    """``--fleet-sweep``: the same Zipf shared-prefix family stream
    through a least-loaded fleet and an affinity-routed fleet — the
    per-replica prefix hit-rate recovery (and, with
    ``--prefill-replicas`` / ``--host-mb``, the prefill offload and
    host-tier re-admits) is the headline."""
    from bigdl_tpu.models.transformer import TransformerLM, lm_decode
    from bigdl_tpu.serve.fleet import DecodeFleet
    from bigdl_tpu.utils.random import set_seed
    set_seed(1)
    model = TransformerLM(vocab_size=128, d_model=64, n_heads=4,
                          n_layers=2, hidden=128)
    rng = np.random.RandomState(0)
    ps, n_words = args.page_size, args.decode_words
    seeds, _fams = fleet_families(
        rng, args.families, args.requests, args.zipf_a,
        args.prefix_pages, ps, 128)
    n_pos = max(len(s) for s in seeds) + n_words - 1
    toks = len(seeds) * n_words

    for length in sorted({len(s) for s in seeds}):
        lm_decode(model, [1] * length, n_words)
    oracle = [lm_decode(model, s, n_words) for s in seeds]

    transport = getattr(args, "transport", "inproc")

    def ship_bytes_total():
        from bigdl_tpu.obs import metrics as obs_metrics
        fam = obs_metrics.get().snapshot().get("fleet_ship_bytes_total")
        return sum(r.get("value", 0.0) for r in (fam or {}).get(
            "series", []))

    def run_point(impl, affinity):
        kw = {}
        agents = []
        if transport == "stdio":
            kw["process"] = True
        elif transport == "tcp":
            from bigdl_tpu.serve.remote import spawn_agent
            agents = [spawn_agent(token="bench")
                      for _ in range(args.replicas
                                     + args.prefill_replicas)]
            kw.update(hosts=[a.addr for a in agents], token="bench")
        try:
            fleet = DecodeFleet(
                model, n_decode=args.replicas,
                n_prefill=args.prefill_replicas, affinity=affinity,
                host_mb=args.host_mb or None,
                max_slots=args.decode_slots,
                n_pos=n_pos, page_size=ps,
                sync_interval=args.decode_sync,
                kv_quant=args.kv_quant, **kw)
        except Exception:
            for a in agents:
                a.close()
            raise
        ship0 = ship_bytes_total()
        t0 = time.perf_counter()
        futs = fleet.submit_many(seeds, n_words)
        rows = [f.result(timeout=600) for f in futs]
        wall = time.perf_counter() - t0
        shipped_b = ship_bytes_total() - ship0
        st = fleet.stats()
        row = fleet_row(impl, args.replicas, args.prefill_replicas,
                        args.families, args.zipf_a, len(seeds), toks,
                        wall, st["router"], st["replicas"],
                        transport=transport,
                        ship_bytes_per_s=(shipped_b / wall if wall
                                          else 0.0))
        row["parity"] = rows == oracle if args.kv_quant == "off" else None
        row["agreement"] = float(np.mean([
            np.mean(np.asarray(r[len(s):]) == np.asarray(o[len(s):]))
            for r, o, s in zip(rows, oracle, seeds)]))
        fleet.close()
        for a in agents:
            a.close()
        print(f"bench_serve: {json.dumps(row)}")
        return row

    base = run_point("least_loaded", affinity=False)
    aff = run_point("affinity", affinity=True)

    print(f"\ntransformer fleet sweep ({args.replicas} decode + "
          f"{args.prefill_replicas} prefill over {transport}; "
          f"{args.families} families, "
          f"zipf {args.zipf_a}, {len(seeds)} requests):")
    for pt in (base, aff):
        ship = (f", ship {pt['ship_bytes_per_s'] / 1e6:.2f} MB/s"
                if pt["ship_bytes_per_s"] else "")
        print(f"  {pt['impl']:<13} {pt['tok_per_s']:8.1f} tok/s, "
              f"prefix hit-rate {pt['hit_rate']:.0%}, affinity "
              f"{pt['affinity_hits']}/{pt['affinity_hits'] + pt['affinity_misses']}, "
              f"shipped {pt['prefill_shipped']}, agreement "
              f"{pt['agreement']:.3f}{ship}")
    if args.prefill_replicas:
        # shipped pages equalize the ADMISSION hit rate (every request
        # adopts its chain), so affinity's win shows as prefill work
        # SHED instead: hops skipped because the pick already cached it
        print(f"  affinity skipped {aff['prefill_skipped']} prefill "
              f"hops (least-loaded skipped "
              f"{base['prefill_skipped']})")
    else:
        ratio = (aff["hit_rate"] / base["hit_rate"]
                 if base["hit_rate"] else float("inf"))
        print(f"  affinity recovers {ratio:.2f}x the least-loaded "
              f"prefix hit rate")
    if args.check:
        if args.kv_quant == "off" and not (base["parity"]
                                           and aff["parity"]):
            raise SystemExit("fleet sweep lost token parity")
        if args.prefill_replicas:
            if aff["prefill_skipped"] <= base["prefill_skipped"]:
                raise SystemExit(
                    f"affinity skipped {aff['prefill_skipped']} "
                    f"prefill hops vs least-loaded "
                    f"{base['prefill_skipped']} — no offload win")
        elif aff["hit_rate"] <= base["hit_rate"]:
            raise SystemExit(
                f"affinity hit rate {aff['hit_rate']:.2f} did not beat "
                f"least-loaded {base['hit_rate']:.2f}")
    return [base, aff]


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--model", default="lenet",
                    choices=("lenet", "inception", "transformer"))
    ap.add_argument("--requests", type=int, default=200)
    ap.add_argument("--loads", default="inf,500,100",
                    help="offered loads in req/s (comma list; inf = "
                         "closed loop)")
    ap.add_argument("--max-batch", type=int, default=32)
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--decode-words", type=int, default=16)
    ap.add_argument("--decode-slots", type=int, default=4)
    ap.add_argument("--decode-sync", type=int, default=8)
    ap.add_argument("--decode-sweep", action="store_true",
                    help="paged-vs-slab concurrency-scaling sweep at a "
                         "fixed pooled-token budget, plus a zero-cold-"
                         "compile speculative stream")
    ap.add_argument("--decode-npos", type=int, default=48,
                    help="per-request position capacity for the sweep "
                         "(slab rows reserve ALL of it)")
    ap.add_argument("--page-size", type=int, default=8,
                    help="KV page size (tokens) for the sweep")
    ap.add_argument("--spec-k", type=int, default=4,
                    help="draft length for the speculative sweep point")
    ap.add_argument("--attn-kernel", default="off",
                    choices=("off", "paged", "spec", "paged+spec"),
                    help="run the sweep's paged points through the "
                         "Mosaic paged-attention / spec-verify kernels "
                         "(transformer._PALLAS_PAGED_ATTN / "
                         "_PALLAS_SPEC_VERIFY; interpreter off-TPU) — "
                         "the rows' attn_kernel column records what "
                         "was active")
    ap.add_argument("--temperature", type=float, default=0.7,
                    help="sampling temperature for the sweep's "
                         "sampled/mixed points")
    ap.add_argument("--top-k", type=int, default=0,
                    help="top-k filter for the sweep's sampled point "
                         "(0 = off)")
    ap.add_argument("--top-p", type=float, default=0.0,
                    help="nucleus filter for the sweep's sampled "
                         "point (0 = off)")
    ap.add_argument("--stop-len", type=int, default=2,
                    help="stop-sequence length for the sweep's "
                         "early-retirement point (cut from each "
                         "request's own greedy oracle)")
    ap.add_argument("--quant", default=None,
                    choices=("off", "int8", "fp8"),
                    help="weight quantization for the scoring/router "
                         "engines (default: BIGDL_SERVE_QUANT)")
    ap.add_argument("--kv-quant", default=None, choices=("off", "int8"),
                    help="KV-page quantization for the decode sweep "
                         "(default: BIGDL_SERVE_KV_QUANT)")
    ap.add_argument("--fleet-sweep", action="store_true",
                    help="shared-prefix family stream through a "
                         "least-loaded vs an affinity-routed decode "
                         "fleet (docs/serving.md 'Disaggregated "
                         "fleet')")
    ap.add_argument("--families", type=int, default=6,
                    help="shared-prefix request families for the fleet "
                         "sweep")
    ap.add_argument("--zipf-a", type=float, default=1.1,
                    help="Zipf exponent over the request families")
    ap.add_argument("--prefix-pages", type=int, default=2,
                    help="full KV pages per family prefix")
    ap.add_argument("--prefill-replicas", type=int, default=0,
                    help="dedicated prefill replicas for the fleet "
                         "sweep")
    ap.add_argument("--host-mb", type=int, default=0,
                    help="per-replica host-RAM KV tier budget (MiB) "
                         "for the fleet sweep (0 = off)")
    ap.add_argument("--transport", default="inproc",
                    choices=("inproc", "stdio", "tcp"),
                    help="fleet replica wire for the fleet sweep: "
                         "in-process threads, stdio subprocess "
                         "workers, or TCP-loopback replica agents "
                         "(docs/serving.md 'Cross-host fleet')")
    ap.add_argument("--traffic", action="store_true",
                    help="open-loop bursty/diurnal traffic run: seeded "
                         "Poisson arrivals with a declared burst "
                         "window, mixed priority classes and (for "
                         "--model transformer) shared-prefix families "
                         "(docs/serving.md 'Autoscaling')")
    ap.add_argument("--base-rps", type=float, default=50.0,
                    help="traffic: baseline offered rate (req/s)")
    ap.add_argument("--burst-factor", type=float, default=8.0,
                    help="traffic: rate multiplier inside the burst "
                         "window")
    ap.add_argument("--burst-start-s", type=float, default=1.0,
                    help="traffic: burst window start offset (s)")
    ap.add_argument("--burst-len-s", type=float, default=1.0,
                    help="traffic: burst window length (s; 0 = none)")
    ap.add_argument("--burst-margin-s", type=float, default=1.0,
                    help="traffic: drain margin appended to the "
                         "declared overload window when splitting "
                         "sheds into in/out of window")
    ap.add_argument("--diurnal-amp", type=float, default=0.0,
                    help="traffic: sinusoidal diurnal amplitude in "
                         "[0, 1) over the base rate")
    ap.add_argument("--diurnal-period-s", type=float, default=60.0,
                    help="traffic: diurnal period (s)")
    ap.add_argument("--priority-mix", default="0:0.2,2:0.8",
                    help="traffic: 'class:weight,...' request mix "
                         "(lower class = more urgent)")
    ap.add_argument("--traffic-seed", type=int, default=0,
                    help="traffic: RNG seed (arrivals, priorities and "
                         "families replay byte-identically)")
    ap.add_argument("--autoscale", action="store_true",
                    help="traffic: arm the SLO-driven autoscaler over "
                         "the pool/fleet (serve/autoscale.py)")
    ap.add_argument("--min-replicas", type=int, default=0,
                    help="autoscale lower bound (0 = --replicas)")
    ap.add_argument("--max-replicas", type=int, default=8,
                    help="autoscale upper bound")
    ap.add_argument("--scale-interval", type=float, default=0.5,
                    help="autoscale cadence seconds for the traffic run")
    ap.add_argument("--replicas", type=int, default=1,
                    help="> 1 sweeps a ReplicaPool behind the SLO "
                         "router instead of one engine (also the fleet "
                         "sweep's decode-replica count)")
    ap.add_argument("--slo-ms", type=float, default=0.0,
                    help="per-request deadline for the router sweep "
                         "(0 = none; arms the shed policy)")
    ap.add_argument("--check", action="store_true",
                    help="fail unless batched >= 2x serial throughput")
    args = ap.parse_args()
    args.loads = [float(tok) for tok in str(args.loads).split(",") if tok]
    from bigdl_tpu import quant as _quant
    if args.quant is None:
        args.quant = _quant.weight_mode_default()
    if args.kv_quant is None:
        args.kv_quant = _quant.kv_mode_default()

    if args.traffic:
        args.replicas = max(2, args.replicas)
        bench_traffic(args)
    elif args.fleet_sweep:
        args.replicas = max(2, args.replicas)
        bench_fleet(args)
    elif args.decode_sweep:
        bench_decode_sweep(args)
    elif args.model == "transformer":
        bench_decode(args)
    elif args.replicas > 1:
        bench_router(args)
    else:
        bench_scoring(args)


if __name__ == "__main__":
    main()
