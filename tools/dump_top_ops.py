"""Dump top device ops of a bench chunk-step variant (round-5 tooling)."""
import os as _os, sys as _sys
_REPO = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
_sys.path.insert(0, _REPO); _sys.path.insert(0, _os.path.join(_REPO, "tools"))

def main():
    import jax
    from bigdl_tpu import tensor as bt
    import bench
    from ab_device_clock import build_chunk, device_us_per_step
    bench._enable_compile_cache()
    bt.set_policy(getattr(bt, _os.environ.get("BIGDL_POLICY", "BF16_COMPUTE")))
    model_name = _sys.argv[1] if len(_sys.argv) > 1 else "vgg_cifar"
    batch = int(_sys.argv[2]) if len(_sys.argv) > 2 else 128
    impl = _sys.argv[3] if len(_sys.argv) > 3 else "rbg"
    topn = int(_sys.argv[4]) if len(_sys.argv) > 4 else 25
    jax.config.update("jax_default_prng_impl", impl)
    step, st = build_chunk(model_name, batch, impl)
    us, per_op = device_us_per_step(step, st)
    print(f"{model_name} bs{batch} {impl}: device-busy {us/1e3:.3f} ms/step")
    total = sum(per_op.values())
    for name, t in per_op.most_common(topn):
        print(f"  {t/32/1e3:8.4f} ms/step {100*t/total:5.1f}%  {name}")

if __name__ == "__main__":
    main()
