"""Replica agent: host one serve replica behind a TCP port
(docs/serving.md "Cross-host fleet").

The cross-host counterpart of the stdio replica worker: one agent per
host leases out ONE replica slot, speaking the same hardened frame
codec (``serve/frames.py``) and running the same
:class:`~bigdl_tpu.serve.cluster.WorkerOps` op set the subprocess
workers run — engine, decode, or prefill role, chosen by the client's
init frame.  ``python -m tools.replica_agent --port 7070`` on each
host, then ``BIGDL_SERVE_HOSTS=h1:7070,h2:7070`` on the pool side.

Session protocol (what TCP adds over a pipe):

- **hello/welcome handshake**: the first client bytes are a ``hello``
  in a FIXED pickle-free layout (``frames.read_hello`` — the op
  frames are pickle, and unpickling an unauthenticated peer's bytes
  would be remote code execution, so nothing is deserialized before
  the shared token (``BIGDL_SERVE_TOKEN``, compared constant-time)
  checks out).  A null session id opens a fresh session (superseding
  any previous one — an agent is one replica slot), a non-null one
  re-attaches after a blip.  The ``welcome`` carries the session id +
  epoch; a bad token or unknown session gets a typed refusal and a
  closed connection.  The agent binds 127.0.0.1 by default and
  REFUSES to listen on a non-loopback interface with an empty token.
- **sequenced outbox**: every session frame the agent sends (ready,
  events, token chunks, replies) carries a contiguous ``seq`` and is
  retained until the client acks it (the ``acked`` watermark
  piggybacked on hello/ping frames).  A re-attach replays everything
  un-acked, in order — the client dedups by ``seq``, so a reply the
  blip swallowed is re-delivered exactly once.
- **request dedup**: the client replays its un-answered requests on
  re-attach; the agent drops request ids it already executed, so a
  request is never run twice no matter where the cut fell.
- **liveness**: a session whose connection stays gone past
  ``BIGDL_SERVE_SESSION_TTL_S`` (default 30) is reaped — its replica
  closed, its host lease effectively returned.

Chaos: ``BIGDL_FAULTS=serve_partition@at=N[,len_s=S]`` black-holes the
agent at the Nth submit — the triggering request is processed FIRST
(its reply waits in the outbox), then the connection drops and new
connections are refused for S seconds.  A blip under the client's
liveness budget must re-attach with zero requeues; a longer one
converts to the normal death path.  ``serve_kill`` works here too
(``os._exit`` inside the shared WorkerOps) and kills the whole agent —
real death, not a blip.
"""
from __future__ import annotations

import argparse
import hmac
import itertools
import os
import pickle
import socket
import struct
import sys
import threading
import time
from collections import deque

from bigdl_tpu.serve.frames import (FrameProtocolError, read_frame,
                                    read_hello, write_frame,
                                    write_refusal, write_welcome)

ENV_SESSION_TTL = "BIGDL_SERVE_SESSION_TTL_S"
DEFAULT_SESSION_TTL_S = 30.0
ENV_TOKEN = "BIGDL_SERVE_TOKEN"


def _loopback(host: str) -> bool:
    return host in ("localhost", "::1", "") or host.startswith("127.")


def session_ttl_default() -> float:
    try:
        return float(os.environ.get(ENV_SESSION_TTL, "")
                     or DEFAULT_SESSION_TTL_S)
    except ValueError:
        return DEFAULT_SESSION_TTL_S


class _PartitionDrop(Exception):
    """Internal: unwind a connection for the serve_partition chaos
    site (the session survives; the socket does not)."""


class _Conn:
    __slots__ = ("sock", "rfile", "wfile")

    def __init__(self, sock):
        self.sock = sock
        self.rfile = sock.makefile("rb")
        self.wfile = sock.makefile("wb")

    def close(self):
        for f in (self.wfile, self.rfile):
            try:
                f.close()
            except (OSError, ValueError):
                pass
        try:
            self.sock.close()
        except OSError:
            pass


class Session:
    """One client's replica slot: the ops handler plus the sequenced
    replay outbox that makes a re-attach lossless.  ``send`` is handed
    to WorkerOps as its reply channel — every outbound frame gets a
    ``seq``, lands in the outbox, and goes out on whatever connection
    is currently attached (write failures are silently absorbed: the
    frame replays on the next attach)."""

    def __init__(self, sid: str, epoch: int):
        self.sid = sid
        self.epoch = epoch
        #: one lock serializes seq assignment AND the socket writes, so
        #: frames leave in seq order even when an attach's replay races
        #: a live reply callback
        self.lock = threading.RLock()
        self.next_seq = 1
        self.outbox = deque()       # (seq, frame), pruned by client acks
        #: executed request ids (replay dedup).  Grows with request
        #: count — acceptable for a slot that lives as long as one
        #: replica lease.  Pings are exempt (idempotent, never
        #: replayed), so the keepalive cadence does not leak into it
        self.seen_rids = set()
        self.ops = None
        self.conn = None
        self.detached_at = time.monotonic()
        self.closed = False

    def send(self, msg):
        with self.lock:
            if self.closed:
                return
            msg = dict(msg)
            msg["seq"] = self.next_seq
            self.next_seq += 1
            self.outbox.append((msg["seq"], msg))
            if self.conn is not None:
                try:
                    write_frame(self.conn.wfile, msg)
                except Exception:
                    # a dying connection mid-write: detach, replay later
                    self.conn = None
                    self.detached_at = time.monotonic()

    def ack(self, acked: int):
        with self.lock:
            while self.outbox and self.outbox[0][0] <= acked:
                self.outbox.popleft()

    def attach(self, conn, acked: int):
        """Install a (re)connected socket and replay the un-acked
        outbox in order.  Raises on a write failure — the caller drops
        the connection and the client retries."""
        with self.lock:
            self.ack(acked)
            self.conn = conn
            self.detached_at = None
            for _, msg in list(self.outbox):
                write_frame(conn.wfile, msg)

    def detach(self, conn):
        with self.lock:
            if self.conn is conn:
                self.conn = None
                self.detached_at = time.monotonic()

    def close(self):
        with self.lock:
            if self.closed:
                return
            self.closed = True
            self.conn = None
        if self.ops is not None:
            try:
                self.ops.close_abrupt()
            except Exception:   # pragma: no cover - replica teardown
                pass


class ReplicaAgent:
    """The TCP listener.  Usable in-process (tests:
    ``ReplicaAgent(port=0).start()`` on a loopback ephemeral port) or
    as a standalone process via :func:`main`."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 token=None, session_ttl_s: float | None = None,
                 once: bool = False, forward_events: bool = False):
        from bigdl_tpu.serve import remote as remote_mod
        self.host = host
        self.port = int(port)
        self.token = (token if token is not None
                      else remote_mod.token_default())
        self.session_ttl_s = (session_ttl_default() if session_ttl_s is None
                              else float(session_ttl_s))
        self.once = once
        self.forward_events = forward_events
        self._sessions: dict = {}
        self._seq = itertools.count(1)
        self._lock = threading.Lock()
        self._blackhole_until = 0.0
        self._closed = threading.Event()
        self.done = threading.Event()
        self._sock = None

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        if not self.token and not _loopback(self.host):
            raise ValueError(
                f"refusing to listen on non-loopback {self.host!r} "
                f"with an empty token: any peer that can reach the "
                f"port could lease the replica slot.  Set {ENV_TOKEN} "
                f"(or --token), or bind 127.0.0.1")
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind((self.host, self.port))
        sock.listen(16)
        self.port = sock.getsockname()[1]
        self._sock = sock
        threading.Thread(target=self._accept_loop, daemon=True,
                         name=f"bigdl-agent-{self.port}-accept").start()
        threading.Thread(target=self._reap_loop, daemon=True,
                         name=f"bigdl-agent-{self.port}-reaper").start()
        if self.forward_events:
            # stream this process's obs events to the attached client
            # (the ProcessReplica `op: event` contract over TCP); only
            # the standalone agent does this — an in-process agent's
            # events already live in the host log
            from bigdl_tpu.obs import events as obs_events
            log = obs_events.get()
            if log is not None:
                log.add_sink(self._forward_event)
        return self

    def close(self):
        if self._closed.is_set():
            return
        self._closed.set()
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:   # pragma: no cover - teardown
            pass
        with self._lock:
            sessions = list(self._sessions.values())
            self._sessions.clear()
        for s in sessions:
            s.close()
        self.done.set()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- event forwarding (standalone agents) -------------------------------
    def _forward_event(self, ev):
        with self._lock:
            sessions = list(self._sessions.values())
        for s in sessions:
            s.send({"op": "event", "event": ev})

    # -- accept / handshake -------------------------------------------------
    def _accept_loop(self):
        while not self._closed.is_set():
            try:
                sock, _addr = self._sock.accept()
            except OSError:
                return
            if time.monotonic() < self._blackhole_until:
                # partitioned: the network "drops" every packet — a new
                # connection attempt just dies
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            threading.Thread(
                target=self._serve_conn, args=(sock,), daemon=True,
                name=f"bigdl-agent-{self.port}-conn").start()

    def _serve_conn(self, sock):
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # bounded sends: Session.send/attach write while holding
        # session.lock, and a black-holed peer (packets dropped, no
        # RST) would otherwise block a full kernel send buffer for the
        # TCP timeout — stalling rid dedup, close() and the TTL reaper
        # behind that lock.  A timed-out write just detaches this
        # connection; the frame replays on the next attach.
        send_s = max(1.0, min(10.0, self.session_ttl_s / 4.0))
        sock.setsockopt(
            socket.SOL_SOCKET, socket.SO_SNDTIMEO,
            struct.pack("ll", int(send_s), int((send_s % 1.0) * 1e6)))
        conn = _Conn(sock)
        session = None
        try:
            session = self._handshake(conn)
            if session is None:
                return
            self._read_loop(session, conn)
        except _PartitionDrop:
            pass
        except FrameProtocolError as e:
            # garbage/corrupt/oversized bytes never reach pickle: name
            # the violation on the ring and drop the connection
            print(f"agent {self.host}:{self.port}: frame protocol "
                  f"violation: {e}; dropping connection",
                  file=sys.stderr, flush=True)
        except (OSError, ValueError, EOFError, pickle.PickleError):
            pass
        finally:
            if session is not None:
                session.detach(conn)
            conn.close()

    def _handshake(self, conn):
        """Authenticate BEFORE deserializing anything: the hello is a
        fixed pickle-free layout (``frames.read_hello``), so an
        unauthenticated peer's bytes never reach ``pickle.loads`` —
        garbage fails typed on magic/version/field bounds, and only a
        token-bearing client gets the pickled op stream."""
        hello = read_hello(conn.rfile)
        if hello is None:
            return None
        if not hmac.compare_digest(
                str(hello.get("token") or "").encode("utf-8"),
                str(self.token or "").encode("utf-8")):
            print(f"agent {self.host}:{self.port}: rejected connection "
                  f"(bad token)", file=sys.stderr, flush=True)
            write_refusal(conn.wfile, "bad token: agent and client "
                          "must share BIGDL_SERVE_TOKEN")
            return None
        sid = hello.get("session")
        if sid is None:
            session = self._new_session()
            resumed = False
        else:
            with self._lock:
                session = self._sessions.get(sid)
            if session is None or session.closed:
                write_refusal(
                    conn.wfile,
                    f"unknown session {sid!r}: agent restarted "
                    f"or the session expired "
                    f"({ENV_SESSION_TTL}={self.session_ttl_s})")
                return None
            resumed = True
        write_welcome(conn.wfile, session.sid, session.epoch, resumed,
                      os.getpid())
        session.attach(conn, int(hello.get("acked") or 0))
        return session

    def _new_session(self) -> Session:
        n = next(self._seq)
        session = Session(f"s{n}", epoch=n)
        with self._lock:
            # ONE replica slot per agent: a fresh hello supersedes any
            # previous session (its replica is torn down, the host is
            # re-leasable)
            old = list(self._sessions.values())
            self._sessions = {session.sid: session}
        for s in old:
            s.close()
        return session

    # -- op loop ------------------------------------------------------------
    def _read_loop(self, session, conn):
        from bigdl_tpu.resilience import faults
        from bigdl_tpu.serve import cluster
        injector = faults.get()
        while not self._closed.is_set():
            msg = read_frame(conn.rfile)
            if msg is None:
                return
            if not isinstance(msg, dict):
                continue
            if "acked" in msg:
                session.ack(int(msg["acked"]))
            op = msg.get("op")
            if op in ("hello", "ack"):
                continue
            rid = msg.get("id")
            if rid is not None and op != "ping":
                # pings skip the dedup set: they are idempotent and the
                # client never replays them, and at the liveness/4
                # cadence they would otherwise leak an rid entry every
                # ~0.5s for the whole session lifetime
                with session.lock:
                    if rid in session.seen_rids:
                        # a replayed request this slot already executed:
                        # its reply is (or will be) in the outbox
                        continue
                    session.seen_rids.add(rid)
            if op == "init":
                if session.ops is None:
                    session.ops = cluster.build_worker_ops(
                        msg, session.send)
                    session.send({"op": "ready", "pid": os.getpid()})
                continue
            if session.ops is None:
                session.send({"id": rid, "ok": False,
                              "etype": "RuntimeError",
                              "error": "no init frame yet"})
                continue
            if (op == "submit" and injector is not None
                    and injector.armed("serve_partition")):
                spec = injector.fires("serve_partition")
                if spec is not None:
                    # the triggering request is processed FIRST — its
                    # reply/chunks land in the outbox, so a re-attach
                    # inside the liveness budget replays them and the
                    # blip costs zero requeues
                    session.ops.handle(msg)
                    self._partition(spec.len_s)
            if not session.ops.handle(msg):
                self._end_session(session)
                return

    def _partition(self, len_s: float):
        from bigdl_tpu.obs import events as obs_events
        print(f"serve_partition chaos fired: black-holing agent "
              f"{self.host}:{self.port} for {len_s}s",
              file=sys.stderr, flush=True)
        obs_events.emit("remote", kind="partition", len_s=float(len_s))
        self._blackhole_until = time.monotonic() + float(len_s)
        raise _PartitionDrop()

    def _end_session(self, session):
        with self._lock:
            self._sessions.pop(session.sid, None)
        session.close()
        if self.once:
            self.close()

    # -- session TTL reaper -------------------------------------------------
    def _reap_loop(self):
        period = max(0.05, min(1.0, self.session_ttl_s / 4.0))
        while not self._closed.wait(period):
            now = time.monotonic()
            stale = []
            with self._lock:
                for sid, s in list(self._sessions.items()):
                    da = s.detached_at
                    if da is not None and now - da > self.session_ttl_s:
                        stale.append(s)
                        self._sessions.pop(sid, None)
            for s in stale:
                print(f"agent {self.host}:{self.port}: session {s.sid} "
                      f"detached > {self.session_ttl_s}s; reaping",
                      file=sys.stderr, flush=True)
                s.close()
                if self.once:
                    self.close()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="bigdl_tpu replica agent: lease this host's "
                    "replica slot over TCP")
    parser.add_argument("--host", default="127.0.0.1",
                        help="bind interface (default loopback; a "
                             "non-loopback bind requires a token)")
    parser.add_argument("--port", type=int, default=0,
                        help="0 = ephemeral (printed as AGENT_PORT=)")
    parser.add_argument("--token", default=None,
                        help="shared handshake secret (default: "
                             "BIGDL_SERVE_TOKEN)")
    parser.add_argument("--once", action="store_true",
                        help="exit after the first session closes")
    args = parser.parse_args(argv)

    import jax
    platform = os.environ.get("BIGDL_SERVE_WORKER_PLATFORM", "cpu")
    jax.config.update("jax_platforms", platform)
    if platform == "cpu":
        from bigdl_tpu.utils.engine import set_cpu_device_count
        set_cpu_device_count(
            int(os.environ.get("BIGDL_SERVE_WORKER_DEVICES", "1")))
        jax.config.update("jax_default_matmul_precision", "highest")
    os.environ.setdefault("BIGDL_CHECK_SINGLETON", "0")

    try:
        agent = ReplicaAgent(host=args.host, port=args.port,
                             token=args.token, once=args.once,
                             forward_events=True).start()
    except ValueError as e:
        print(f"replica agent: {e}", file=sys.stderr, flush=True)
        return 2
    # the machine-readable banner spawn_agent() waits for
    print(f"AGENT_PORT={agent.port}", flush=True)
    print(f"replica agent listening on {args.host}:{agent.port} "
          f"(pid {os.getpid()})", file=sys.stderr, flush=True)
    try:
        agent.done.wait()
    except KeyboardInterrupt:
        pass
    finally:
        agent.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
