"""Same-process A/B of full train-step variants.

The relay-attached chip's clock varies >10% run to run, so only
within-process comparisons are trustworthy.  This builds the bench train
step under each flag combination and times them in interleaved windows
(A B A B A B), reporting the per-variant minimum.

Usage: python tools/ab_step.py [model] [batch]
"""
from __future__ import annotations

import os as _os
import sys as _sys

_REPO_ROOT = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
if _REPO_ROOT not in _sys.path:
    _sys.path.insert(0, _REPO_ROOT)  # run without an installed package

import sys
import time


def build(model_name, batch, s2d, lrn_stencil, sqrt_pow=True):
    import bigdl_tpu.nn.conv as convmod
    from bigdl_tpu.nn.normalization import SpatialCrossMapLRN
    convmod._S2D_STEM = s2d
    SpatialCrossMapLRN._STENCIL = lrn_stencil
    SpatialCrossMapLRN._SQRT_POW = sqrt_pow
    sys.path.insert(0, "tools")
    from profile_step import build_step
    return build_step(model_name, batch)


def time_window(step, state, iters=10):
    t0 = time.perf_counter()
    params, net_state, opt_state, x, y, key = state
    for _ in range(iters):
        params, net_state, opt_state, loss = step(
            params, net_state, opt_state, x, y, key)
    float(loss)
    return (time.perf_counter() - t0) / iters * 1e3, (
        params, net_state, opt_state, x, y, key)


def main():
    model_name = sys.argv[1] if len(sys.argv) > 1 else "inception"
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 128
    variants = {}
    for s2d in (False, True):
        for st in (False, True):
            for sq in (False, True):
                variants["s2d=%d stencil=%d sqrt=%d" % (s2d, st, sq)] = dict(
                    s2d=s2d, lrn_stencil=st, sqrt_pow=sq)
    steps = {}
    for name, flags in variants.items():
        step, args = build(model_name, batch, **flags)
        params, net_state, opt_state, x, y, key = args
        for _ in range(3):
            params, net_state, opt_state, loss = step(
                params, net_state, opt_state, x, y, key)
        float(loss)
        steps[name] = (step, (params, net_state, opt_state, x, y, key))

    best = {name: float("inf") for name in variants}
    for _ in range(3):
        for name in variants:
            step, state = steps[name]
            dt, state = time_window(step, state)
            steps[name] = (step, state)
            best[name] = min(best[name], dt)
    for name, ms in best.items():
        print("%-28s %8.2f ms/step  %8.1f img/s" % (name, ms, batch / ms * 1e3))


if __name__ == "__main__":
    main()
