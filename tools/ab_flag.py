"""Device-clock A/B of a module flag on a full bench chunk step.

Usage: python tools/ab_flag.py MODEL BATCH MODULE ATTR
e.g.:  python tools/ab_flag.py resnet50 64 bigdl_tpu.nn.conv _DOT_1X1
"""
import os as _os, sys as _sys, importlib, time
_REPO = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
_sys.path.insert(0, _REPO); _sys.path.insert(0, _os.path.join(_REPO, "tools"))


def main():
    from bigdl_tpu import tensor as bt
    import bench
    from ab_device_clock import build_chunk, device_us_per_step
    bench._enable_compile_cache()
    bt.set_policy(getattr(bt, _os.environ.get("BIGDL_POLICY", "BF16_COMPUTE")))
    model_name, batch = _sys.argv[1], int(_sys.argv[2])
    mod, attr = importlib.import_module(_sys.argv[3]), _sys.argv[4]
    import jax
    impl = _os.environ.get("BIGDL_PRNG", "rbg") or "threefry2x32"
    jax.config.update("jax_default_prng_impl", impl)
    for value in (False, True, False, True):
        setattr(mod, attr, value)
        t0 = time.perf_counter()
        step, st = build_chunk(model_name, batch, impl)
        us, per_op = device_us_per_step(step, st)
        print(f"{model_name} bs{batch} {attr}={value}: device-busy "
              f"{us/1e3:.3f} ms/step (setup {time.perf_counter()-t0:.0f}s)",
              flush=True)


if __name__ == "__main__":
    main()
