"""Round-5 long-context measurements on the real chip (VERDICT item 6):
1) single-chip causal-LM train step at T=8192 (full softmax) —
   tokens/sec + HBM in use;
2) KV-cached lm_decode at long T — tokens/sec for a full one-dispatch
   decode at the longest tested length.

Usage: python tools/longctx_probe.py [train|decode] ...
"""
import os as _os, sys as _sys, time
_REPO = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
_sys.path.insert(0, _REPO)

import numpy as np


def train_probe(t_len=8192, vocab=256, d_model=256, heads=4, layers=4):
    import jax
    import jax.numpy as jnp
    import bigdl_tpu.nn as nn
    from bigdl_tpu import tensor as bt
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.nn.module import Context
    from bigdl_tpu.optim.optim_method import SGD
    from bigdl_tpu.utils.random import set_seed

    bt.set_policy(bt.BF16_COMPUTE)
    set_seed(1)
    m = TransformerLM(vocab_size=vocab, d_model=d_model, n_heads=heads,
                      n_layers=layers, hidden=4 * d_model, dropout=0.1)
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                       size_average=True)
    rs = np.random.RandomState(0)
    ids = rs.randint(0, vocab, (1, t_len))
    x = jnp.asarray(np.eye(vocab, dtype=np.float32)[ids])
    y = jnp.asarray(rs.randint(1, vocab + 1, (1, t_len)), jnp.float32)
    method = SGD()
    params, net_state = m.params(), m.state()
    opt_state = method.init_state(params)
    hyper = {"lr": 0.01, "momentum": 0.9, "dampening": 0.0,
             "weight_decay": 0.0, "nesterov": False}

    def step(params, net_state, opt_state, x, y, key):
        def loss_fn(p):
            out, ns = m.apply(p, x, net_state, Context(True, key))
            return crit.apply_loss(out, y), ns
        (loss, ns), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        p2, o2 = method.update(grads, opt_state, params, hyper)
        return p2, ns, o2, loss

    jstep = jax.jit(step, donate_argnums=(0, 1, 2))
    key = jax.random.PRNGKey(0)
    t0 = time.time()
    for _ in range(2):
        params, net_state, opt_state, loss = jstep(params, net_state,
                                                   opt_state, x, y, key)
    print(f"T={t_len} compile+2: {time.time()-t0:.1f}s loss "
          f"{float(loss):.3f}", flush=True)
    best = 9e9
    for _ in range(3):
        t0 = time.time()
        for _ in range(5):
            params, net_state, opt_state, loss = jstep(
                params, net_state, opt_state, x, y, key)
        float(loss)
        best = min(best, (time.time() - t0) / 5)
    stats = jax.devices()[0].memory_stats() or {}
    print(f"train T={t_len} d{d_model} L{layers}: {best*1e3:.1f} ms/step "
          f"{t_len/best:,.0f} tokens/sec  hbm_in_use "
          f"{stats.get('bytes_in_use', 0)/2**30:.2f} GiB", flush=True)


def decode_probe(t_len=16384, vocab=2048, d_model=256, heads=4, layers=4):
    import jax
    from bigdl_tpu.models.transformer import TransformerLM, lm_decode
    from bigdl_tpu.utils.random import set_seed

    set_seed(1)
    m = TransformerLM(vocab_size=vocab, d_model=d_model, n_heads=heads,
                      n_layers=layers, hidden=4 * d_model, dropout=0.0)
    seed_ids = list(range(1, 17))
    n_words = t_len - len(seed_ids) + 1
    t0 = time.time()
    out = lm_decode(m, seed_ids, n_words)
    cold = time.time() - t0
    t0 = time.time()
    out = lm_decode(m, seed_ids, n_words)
    warm = time.time() - t0
    stats = jax.devices()[0].memory_stats() or {}
    print(f"decode T={t_len} d{d_model} L{layers}: one-dispatch full "
          f"decode cold {cold:.1f}s warm {warm:.1f}s = "
          f"{n_words/warm:,.0f} tokens/sec  hbm_in_use "
          f"{stats.get('bytes_in_use', 0)/2**30:.2f} GiB "
          f"(len(out)={len(out)})", flush=True)


if __name__ == "__main__":
    mode = _sys.argv[1] if len(_sys.argv) > 1 else "train"
    if mode == "train":
        train_probe(*(int(a) for a in _sys.argv[2:]))
    else:
        decode_probe(*(int(a) for a in _sys.argv[2:]))
