"""Hadoop SequenceFile ingestion — the reference's ImageNet wire format.

The reference packs ImageNet into Hadoop SequenceFiles of Text->Text
records (models/utils/ImageNetSeqFileGenerator.scala via
dataset/image/BGRImgToLocalSeqFile.scala:57-76) and trains from them
(dataset/DataSet.SeqFileFolder DataSet.scala:384-455,
dataset/image/LocalSeqFileToBytes.scala).  This module implements the
actual SequenceFile version-6 wire format in pure Python so data
produced by the reference toolchain can be ingested directly (and data
written here is readable by Hadoop):

  header:  b"SEQ" 0x06 | vint-str keyClass | vint-str valueClass |
           bool compress | bool blockCompress | u32-BE metadata count
           (+ Text pairs) | 16-byte sync marker
  record:  i32-BE recordLen | i32-BE keyLen | key | value
           (key/value each serialized as Hadoop Text: vint len + bytes)
  sync escape: i32-BE -1 | 16-byte sync marker, inserted by writers at
           least every SYNC_INTERVAL (2000) bytes so readers can seek.

Per-record payload layout (BGRImgToLocalSeqFile.scala:62-71):
  key   = Text("<label>") or Text("<name>\n<label>") when hasName
  value = i32-BE width | i32-BE height | H*W*3 bytes, interleaved BGR,
          each byte = (float_pixel * 255).toByte

Only uncompressed record-oriented files are supported (the layout the
reference writes: SequenceFile.createWriter with a default Configuration
— compression NONE); compressed files raise.
"""
from __future__ import annotations

import hashlib
import io
import os
import struct

import numpy as np

from bigdl_tpu.dataset.dataset import LocalDataSet
from bigdl_tpu.dataset.sample import ByteRecord
from bigdl_tpu.dataset.transformer import Transformer

TEXT_CLASS = "org.apache.hadoop.io.Text"
SYNC_SIZE = 16
SYNC_INTERVAL = 100 * (SYNC_SIZE + 4)  # Hadoop SequenceFile.SYNC_INTERVAL


# ---------------------------------------------------------------------------
# Hadoop WritableUtils variable-length ints (writeVInt/readVInt)
# ---------------------------------------------------------------------------

def write_vint(value: int) -> bytes:
    """Hadoop WritableUtils.writeVLong encoding."""
    if -112 <= value <= 127:
        return struct.pack("b", value)
    length = -112
    v = value
    if v < 0:
        v = ~v
        length = -120
    tmp = v
    while tmp != 0:
        tmp >>= 8
        length -= 1
    out = [struct.pack("b", length)]
    n_bytes = -(length + 112) if length >= -120 else -(length + 120)
    for shift in range(8 * (n_bytes - 1), -1, -8):
        out.append(struct.pack("B", (v >> shift) & 0xFF))
    return b"".join(out)


def read_vint(f) -> int:
    first = struct.unpack("b", f.read(1))[0]
    if first >= -112:
        return first
    negative = first < -120
    n_bytes = -(first + 120) if negative else -(first + 112)
    v = 0
    for _ in range(n_bytes):
        v = (v << 8) | f.read(1)[0]
    return ~v if negative else v


def _text(data: bytes) -> bytes:
    """Hadoop Text serialization: vint byte-length + raw bytes."""
    return write_vint(len(data)) + data


def _read_text(f) -> bytes:
    return f.read(read_vint(f))


def _read_exact(f, n: int, path: str, offset: int, what: str) -> bytes:
    """``f.read(n)`` that REFUSES short reads: a truncated or corrupt
    .seq file must raise, naming file and offset, instead of yielding
    silently wrong records (ADVICE r5 #1)."""
    data = f.read(n)
    if len(data) != n:
        raise ValueError(
            f"{path}: truncated {what} at offset {offset}: wanted {n} "
            f"bytes, got {len(data)} — file is corrupt or was cut short")
    return data


# ---------------------------------------------------------------------------
# File-level reader / writer
# ---------------------------------------------------------------------------

class SequenceFileWriter:
    """Uncompressed Text->Text SequenceFile writer (version 6 layout,
    what ``SequenceFile.createWriter(new Configuration, ...)`` emits)."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "wb")
        # Deterministic per-path marker: any 16 bytes work — readers
        # learn it from the header (Hadoop uses an MD5 of class+time).
        self.sync = hashlib.md5(b"bigdl_tpu.seqfile:" + path.encode()).digest()
        hdr = io.BytesIO()
        hdr.write(b"SEQ\x06")
        hdr.write(_text(TEXT_CLASS.encode()))
        hdr.write(_text(TEXT_CLASS.encode()))
        hdr.write(b"\x00\x00")  # compress, blockCompress: false
        hdr.write(struct.pack(">i", 0))  # metadata: 0 entries
        hdr.write(self.sync)
        self._f.write(hdr.getvalue())
        self._last_sync = self._f.tell()
        self.n = 0

    def append(self, key: bytes, value: bytes):
        if self._f.tell() >= self._last_sync + SYNC_INTERVAL:
            self._f.write(struct.pack(">i", -1))
            self._f.write(self.sync)
            self._last_sync = self._f.tell()
        k, v = _text(key), _text(value)
        self._f.write(struct.pack(">ii", len(k) + len(v), len(k)))
        self._f.write(k)
        self._f.write(v)
        self.n += 1

    def close(self):
        if not self._f.closed:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_sequence_file(path: str):
    """Yield (key_bytes, value_bytes) from one SequenceFile.

    Accepts any uncompressed record-layout file (the key/value classes
    are not restricted to Text — bytes come back as serialized by the
    writer minus the Text length prefix when the class IS Text)."""
    with open(path, "rb") as f:
        magic = f.read(3)
        if magic != b"SEQ":
            raise ValueError(f"{path}: not a Hadoop SequenceFile")
        version = f.read(1)[0]
        if version < 6:
            raise NotImplementedError(
                f"{path}: SequenceFile version {version} (< 6) unsupported")
        key_cls = _read_text(f).decode()
        val_cls = _read_text(f).decode()
        compress, block_compress = f.read(1)[0], f.read(1)[0]
        if compress or block_compress:
            raise NotImplementedError(
                f"{path}: compressed SequenceFiles unsupported "
                "(the reference generator writes uncompressed)")
        (n_meta,) = struct.unpack(">i", f.read(4))
        for _ in range(n_meta):
            _read_text(f), _read_text(f)
        sync = f.read(SYNC_SIZE)
        is_text = (key_cls == TEXT_CLASS, val_cls == TEXT_CLASS)
        from bigdl_tpu.resilience import faults
        inj = faults.get()
        rec_index = 0
        while True:
            off = f.tell()
            raw = f.read(4)
            if not raw:
                return
            if len(raw) < 4:
                raise ValueError(
                    f"{path}: truncated record length at offset {off}: "
                    f"got {len(raw)}/4 bytes — file was cut short")
            (rec_len,) = struct.unpack(">i", raw)
            if rec_len == -1:  # sync escape
                marker = _read_exact(f, SYNC_SIZE, path, off + 4,
                                     "sync marker")
                if marker != sync:
                    raise ValueError(f"{path}: corrupt sync marker")
                continue
            (key_len,) = struct.unpack(
                ">i", _read_exact(f, 4, path, off + 4, "key length"))
            if key_len < 0 or rec_len < key_len:
                raise ValueError(
                    f"{path}: corrupt record header at offset {off}: "
                    f"rec_len {rec_len}, key_len {key_len} (need "
                    "rec_len >= key_len >= 0)")
            key = _read_exact(f, key_len, path, off + 8, "record key")
            vlen = rec_len - key_len
            value = f.read(vlen)
            if inj is not None:
                spec = inj.fires("record_truncate", step=rec_index)
                if spec is not None:  # simulated short read, caught below
                    value = faults.truncate(value)
            if len(value) != vlen:
                raise ValueError(
                    f"{path}: truncated record value at offset "
                    f"{off + 8 + key_len}: wanted {vlen} bytes, got "
                    f"{len(value)} — file is corrupt or was cut short")
            if inj is not None:
                spec = inj.fires("record_corrupt", step=rec_index)
                if spec is not None:  # silent payload damage (bit rot)
                    value = faults.flip_bit(value, spec, rec_index)
            rec_index += 1
            if is_text[0]:
                key = _read_text(io.BytesIO(key))
            if is_text[1]:
                value = _read_text(io.BytesIO(value))
            yield key, value


# ---------------------------------------------------------------------------
# The reference's image record layer
# ---------------------------------------------------------------------------

def read_label(key_bytes: bytes) -> str:
    """(ref DataSet.SeqFileFolder.readLabel DataSet.scala:409-416)"""
    parts = key_bytes.decode().split("\n")
    return parts[0] if len(parts) == 1 else parts[1]


def read_name(key_bytes: bytes) -> str:
    """(ref DataSet.SeqFileFolder.readName DataSet.scala:424-428)"""
    parts = key_bytes.decode().split("\n")
    if len(parts) < 2:
        raise ValueError("key in seq file only contains label, no name")
    return parts[0]


def encode_image_value(data, width: int, height: int,
                       normalize: float = 255.0) -> bytes:
    """float HWC image -> the value payload BGRImgToLocalSeqFile writes:
    i32-BE width | i32-BE height | (pixel * normalize).toByte stream."""
    arr = np.asarray(data, np.float32).reshape(-1)
    raw = (arr * normalize).astype(np.int32).astype(np.uint8).tobytes()
    return struct.pack(">ii", width, height) + raw


def decode_image_value(value: bytes, normalize: float = 255.0):
    """Value payload -> (HWC float array scaled by 1/normalize, w, h)."""
    w, h = struct.unpack(">ii", value[:8])
    arr = np.frombuffer(value, np.uint8, offset=8).astype(np.float32)
    return arr.reshape(h, w, 3) / normalize, w, h


class BGRImgToLocalSeqFile(Transformer):
    """LabeledImage stream -> numbered ``.seq`` files of blockSize records
    (ref BGRImgToLocalSeqFile.scala:41-81).  Input items are LabeledImage
    or (LabeledImage, name) pairs; yields each generated file name.

    ``normalize`` mirrors convertToByte's multiplier: 255.0 for images
    scaled to [0,1] (the reference's layout), 1.0 for [0,255] pipelines.
    RGB-ordered images are flipped to the on-disk BGR interleave."""

    def __init__(self, block_size: int, base_file_name: str,
                 has_name: bool = False, normalize: float = 255.0):
        self.block_size = block_size
        self.base = str(base_file_name)
        self.has_name = has_name
        self.normalize = normalize

    def __call__(self, iterator):
        it = iter(iterator)
        index = 0
        done = False
        while not done:
            done = True
            writer = None
            for item in it:
                img, name = item if isinstance(item, tuple) else (item, "")
                if writer is None:  # open lazily: no empty trailing file
                    writer = SequenceFileWriter(f"{self.base}_{index}.seq")
                d = img.data
                if getattr(img, "order", "bgr") == "rgb":
                    d = d[..., ::-1]
                h, w = d.shape[:2]
                key = (f"{name}\n{int(img.label)}" if self.has_name
                       else f"{int(img.label)}")
                writer.append(key.encode(),
                              encode_image_value(d, w, h, self.normalize))
                if writer.n >= self.block_size:
                    done = False
                    break
            if writer is not None:
                writer.close()
                index += 1
                yield f"{self.base}_{index - 1}.seq"


class LocalSeqFileToBytes(Transformer):
    """``.seq`` path stream -> ByteRecord stream (ref
    LocalSeqFileToBytes.scala:34-80): the record's value bytes (width/
    height prefix included) labeled by readLabel(key)."""

    def __call__(self, iterator):
        for path in iterator:
            for key, value in read_sequence_file(str(path)):
                yield ByteRecord(value, float(read_label(key)))


class SeqBytesToBGRImg(Transformer):
    """ByteRecord (prefixed raw BGR bytes from a seq file) -> LabeledImage
    in BGR channel order, pixels scaled by 1/normalize (the role of the
    reference's BytesToBGRImg over SeqFileFolder records)."""

    def __init__(self, normalize: float = 255.0):
        self.normalize = normalize

    def __call__(self, iterator):
        from bigdl_tpu.dataset.image import LabeledImage
        for rec in iterator:
            arr, _, _ = decode_image_value(rec.data, self.normalize)
            yield LabeledImage(arr, rec.label, order="bgr")


def folder_listing(path: str):
    """Entry names of a local folder or fsspec URL; [] when the path is
    not a listable directory.  Shared by the wire-format dispatch
    (``DataSet.seq_file_folder``) and ``find_seq_files`` so one listing
    (one RPC on remote stores) answers both questions."""
    from bigdl_tpu.utils import fs
    if not fs.is_url(path) and not os.path.isdir(path):
        return []
    try:
        return fs.listdir(path)
    except (FileNotFoundError, OSError):
        return []


def find_seq_files(path: str, names=None):
    """Sorted ``*.seq`` under a local folder or fsspec URL
    (ref DataSet.scala:449-455).  ``names`` short-circuits the listing
    when the caller already holds one (``folder_listing``)."""
    from bigdl_tpu.utils import fs
    if names is None:
        names = folder_listing(path)
    return sorted(fs.join(path, f) for f in names if f.endswith(".seq"))


def iter_record_keys(path: str):
    """Yield only the Text keys of a SequenceFile, seeking past the value
    payloads — an O(metadata) pass for counting/label scans that never
    reads the (multi-KB) image bytes."""
    from bigdl_tpu.utils import fs
    with fs.open_file(path, "rb") as f:
        if f.read(4) != b"SEQ\x06":
            raise ValueError(f"{path}: not a version-6 SequenceFile")
        key_cls = _read_text(f).decode()
        _read_text(f)
        if f.read(1)[0] or f.read(1)[0]:
            raise NotImplementedError(f"{path}: compressed file unsupported")
        (n_meta,) = struct.unpack(">i", f.read(4))
        for _ in range(n_meta):
            _read_text(f), _read_text(f)
        f.read(SYNC_SIZE)
        # seeking skips the value payloads, so a file cut short mid-value
        # is only detectable against the real size — grab it up front
        here = f.tell()
        f.seek(0, 2)
        file_size = f.tell()
        f.seek(here)
        while True:
            off = f.tell()
            raw = f.read(4)
            if not raw:
                return
            if len(raw) < 4:
                raise ValueError(
                    f"{path}: truncated record length at offset {off}: "
                    f"got {len(raw)}/4 bytes — file was cut short")
            (rec_len,) = struct.unpack(">i", raw)
            if rec_len == -1:
                _read_exact(f, SYNC_SIZE, path, off + 4, "sync marker")
                continue
            (key_len,) = struct.unpack(
                ">i", _read_exact(f, 4, path, off + 4, "key length"))
            if key_len < 0 or rec_len < key_len:
                raise ValueError(
                    f"{path}: corrupt record header at offset {off}: "
                    f"rec_len {rec_len}, key_len {key_len} (need "
                    "rec_len >= key_len >= 0)")
            if off + 8 + rec_len > file_size:
                raise ValueError(
                    f"{path}: truncated record value at offset "
                    f"{off + 8 + key_len}: record ends at "
                    f"{off + 8 + rec_len} but the file holds only "
                    f"{file_size} bytes")
            key = _read_exact(f, key_len, path, off + 8, "record key")
            f.seek(rec_len - key_len, 1)
            yield (_read_text(io.BytesIO(key))
                   if key_cls == TEXT_CLASS else key)


class SeqFileDataSet(LocalDataSet):
    """Folder of Hadoop SequenceFiles as a ByteRecord dataset (ref
    DataSet.SeqFileFolder.files DataSet.scala:436-446).  ``class_num``
    drops records whose label exceeds it, like the reference's filter.
    Files are streamed (never fully in memory); ``train=True`` loops with
    the file order shuffled per epoch."""

    def __init__(self, path: str, class_num: int = None,
                 distributed: bool = False, files=None):
        import jax
        self.files = find_seq_files(path) if files is None else list(files)
        if not self.files:
            raise ValueError(f"Can't find any sequence files under {path}")
        self.class_num = class_num
        self.distributed = distributed
        if distributed:
            # whole files per process, like ShardFolder / the reference's
            # partition-per-node sequence-file splits
            idx, nproc = jax.process_index(), jax.process_count()
            self.local_files = self.files[idx::nproc]
            if not self.local_files:
                raise ValueError(
                    f"process {idx}/{nproc} got no sequence files: "
                    f"{len(self.files)} .seq files under {path} < process "
                    f"count; regenerate with more output files")
        else:
            self.local_files = list(self.files)
        self._size = None

    def _records(self, files):
        for rec in LocalSeqFileToBytes()(iter(files)):
            if self.class_num is None or rec.label <= self.class_num:
                yield rec

    def size(self):
        """GLOBAL record count (all files, post class filter) — a
        keys-only scan that seeks past image payloads; cached."""
        if self._size is None:
            self._size = sum(
                1 for f in self.files for key in iter_record_keys(f)
                if self.class_num is None
                or float(read_label(key)) <= self.class_num)
        return self._size

    def shuffle(self):
        # Streaming dataset: shuffling happens at file granularity per
        # epoch inside data(train=True) (the reference likewise shuffles
        # sequence-file splits, not records — DataSet.scala:436-446).
        pass

    def data(self, train: bool = False):
        if not train:
            return self._records(self.local_files)

        def looped():
            from bigdl_tpu.utils.random import RNG
            while True:
                files = list(self.local_files)
                RNG.np_rng().shuffle(files)
                yield from self._records(files)
        return looped()
