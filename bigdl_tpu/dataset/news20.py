"""20 Newsgroups + GloVe ingestion (ref dl/src/main/python/dataset/news20.py:
download_news20 :12, download_glove_w2v :24, get_news20 :38,
get_glove_w2v).

The reference downloads archives at call time; here ingestion reads
already-extracted local copies (air-gapped TPU pods don't have egress from
the trainer), with the same directory layouts:

- ``20_newsgroups/<group>/<doc-id>`` — one file per post, label = 1-based
  group index in sorted order (matching get_news20's ordering);
- ``glove.6B/glove.6B.<dim>d.txt`` — space-separated word vectors.

``embed_samples`` turns (text, label) pairs into padded embedded Samples
the TextClassifier model consumes — the analyze/tokenize/normalize path
of the reference's example/textclassification prepare_data.
"""
from __future__ import annotations

import os
import re

import numpy as np


def get_news20(source_dir):
    """[(text, 1-based label)] from an extracted 20_newsgroups tree
    (ref news20.py get_news20 :38-52)."""
    news_dir = os.path.join(source_dir, "20_newsgroups")
    if not os.path.isdir(news_dir):
        news_dir = source_dir  # already pointing at the class folders
    texts = []
    # a co-located glove.6B/ dir must not be mistaken for a class folder
    groups = sorted(d for d in os.listdir(news_dir)
                    if os.path.isdir(os.path.join(news_dir, d))
                    and not d.startswith((".", "glove")))
    if not groups:
        raise FileNotFoundError(
            f"no newsgroup class folders under {news_dir}; extract "
            f"20news-19997.tar.gz there (the reference downloads it from "
            f"qwone.com — this loader is offline by design)")
    for label, name in enumerate(groups, start=1):
        d = os.path.join(news_dir, name)
        for fn in sorted(os.listdir(d)):
            path = os.path.join(d, fn)
            if os.path.isfile(path):
                with open(path, "rb") as f:
                    texts.append((f.read().decode("latin-1"), float(label)))
    if not texts:
        raise FileNotFoundError(
            f"newsgroup folders under {news_dir} contain no documents "
            f"({', '.join(groups[:3])}...) — incomplete extraction?")
    return texts


def get_glove_w2v(source_dir, dim: int = 100):
    """{word: np.float32[dim]} from an extracted glove.6B directory
    (ref news20.py get_glove_w2v)."""
    path = os.path.join(source_dir, f"glove.6B.{dim}d.txt")
    if not os.path.isfile(path):
        alt = os.path.join(source_dir, "glove.6B", f"glove.6B.{dim}d.txt")
        if os.path.isfile(alt):
            path = alt
        else:
            raise FileNotFoundError(
                f"no glove.6B.{dim}d.txt under {source_dir}; extract "
                f"glove.6B.zip there (offline by design)")
    w2v = {}
    with open(path, encoding="utf-8") as f:
        for line in f:
            parts = line.rstrip().split(" ")
            w2v[parts[0]] = np.asarray(parts[1:], np.float32)
    return w2v


_TOKEN = re.compile(r"[a-z]+")


def tokenize(text: str):
    """Lowercase word tokens (the reference's analyzer: text_to_words)."""
    return _TOKEN.findall(text.lower())


def embed_samples(texts, w2v, seq_len: int = 1000, embed_dim: int = 100):
    """(text, label) pairs -> Samples of (seq_len, embed_dim) float32
    features with zero padding/truncation (ref prepare_data in
    example/textclassification: tokens -> glove vectors -> pad)."""
    from bigdl_tpu.dataset.sample import Sample
    samples = []
    for text, label in texts:
        vecs = [w2v[t] for t in tokenize(text) if t in w2v][:seq_len]
        feat = np.zeros((seq_len, embed_dim), np.float32)
        if vecs:
            feat[:len(vecs)] = np.stack(vecs)
        samples.append(Sample(feat, np.asarray([label], np.float32)))
    return samples
