"""MNIST idx-ubyte reader (ref models/lenet/Utils.scala raw idx reader).

Reads the standard idx files if present; ``synthetic()`` generates a
deterministic stand-in with the same shapes for perf runs and CI (the
DistriOptimizerPerf role of training on synthetic data,
models/utils/DistriOptimizerPerf.scala).
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from bigdl_tpu.dataset.image import LabeledImage

TRAIN_MEAN = 0.13066047740239506 * 255
TRAIN_STD = 0.3081078 * 255
TEST_MEAN = 0.13251460696903547 * 255
TEST_STD = 0.31048024 * 255


def _open(path):
    return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")


def load_images(path):
    with _open(path) as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051, f"bad idx image magic {magic}"
        data = np.frombuffer(f.read(n * rows * cols), np.uint8)
        return data.reshape(n, rows, cols).astype(np.float32)


def load_labels(path):
    with _open(path) as f:
        magic, n = struct.unpack(">II", f.read(8))
        assert magic == 2049, f"bad idx label magic {magic}"
        return np.frombuffer(f.read(n), np.uint8).astype(np.float32)


def load(folder, training: bool = True):
    """Returns a list of LabeledImage (grey HxW), labels 1-based."""
    prefix = "train" if training else "t10k"
    imgs = labels = None
    for suffix in ("", ".gz"):
        ip = os.path.join(folder, f"{prefix}-images-idx3-ubyte{suffix}")
        lp = os.path.join(folder, f"{prefix}-labels-idx1-ubyte{suffix}")
        if os.path.exists(ip) and os.path.exists(lp):
            imgs, labels = load_images(ip), load_labels(lp)
            break
    if imgs is None:
        raise FileNotFoundError(f"no MNIST idx files under {folder}")
    return [LabeledImage(img, lbl + 1) for img, lbl in zip(imgs, labels)]


def synthetic(n: int = 1024, seed: int = 0):
    """Deterministic synthetic MNIST-shaped data."""
    rng = np.random.RandomState(seed)
    imgs = rng.uniform(0, 255, (n, 28, 28)).astype(np.float32)
    labels = rng.randint(0, 10, n).astype(np.float32)
    return [LabeledImage(img, lbl + 1) for img, lbl in zip(imgs, labels)]
