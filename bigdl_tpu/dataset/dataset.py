"""DataSet abstractions (ref dataset/DataSet.scala).

Two worlds, as in the reference (DataSet.scala:111/164):

- ``LocalDataSet``: host-local iterator source.
- ``ShardedDataSet`` (the ``DistributedDataSet`` role): each JAX process
  holds its shard of the data; ``Utils.getBatchSize`` semantics
  (global batch ÷ node count, must divide evenly — ref Utils.scala:26-48)
  decide the per-host slice, and the distributed optimizer forms global
  device arrays from per-host batches.

``transform``/``>>`` composition matches DataSet.scala:74-88.
"""
from __future__ import annotations

import numpy as np

from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import Transformer
from bigdl_tpu.utils.random import RNG


def get_batch_size(total_batch: int, node_number: int) -> int:
    """Global batch ÷ nodes with divisibility check (ref Utils.scala:26-48)."""
    if total_batch % node_number != 0:
        raise ValueError(
            f"total batch size {total_batch} cannot be divided by node number "
            f"{node_number}; adjust the batch size (ref dataset/Utils.scala:26)")
    return total_batch // node_number


class AbstractDataSet:
    """(ref DataSet.scala:47)"""

    def data(self, train: bool):
        """An iterator over records. ``train=True`` loops forever (shuffled);
        ``train=False`` makes one pass."""
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    def shuffle(self):
        raise NotImplementedError

    def transform(self, transformer: Transformer) -> "AbstractDataSet":
        return TransformedDataSet(self, transformer)

    def __rshift__(self, transformer: Transformer):
        """``ds >> transformer`` == reference's ``ds -> transformer``."""
        return self.transform(transformer)


class LocalDataSet(AbstractDataSet):
    """Iterator-based local dataset (ref DataSet.scala:111)."""


class LocalArrayDataSet(LocalDataSet):
    """In-memory array dataset with looped shuffled iteration
    (ref DataSet.scala:128)."""

    def __init__(self, data):
        self._data = list(data)

    def size(self):
        return len(self._data)

    def shuffle(self):
        RNG.shuffle(self._data)
        return self

    def data(self, train: bool):
        if train:
            def looped():
                while True:
                    idx = RNG.np_rng().permutation(len(self._data))
                    for i in idx:
                        yield self._data[i]
            return looped()
        # index-based view, no per-call copy of the backing list (an
        # ImageNet-scale list is ~1M pointers per validation pass); a
        # shuffle between passes is visible to the NEXT iterator
        return (self._data[i] for i in range(len(self._data)))


class TransformedDataSet(AbstractDataSet):
    def __init__(self, base: AbstractDataSet, transformer: Transformer):
        self.base = base
        self.transformer = transformer

    def size(self):
        return self.base.size()

    def shuffle(self):
        self.base.shuffle()
        return self

    def data(self, train: bool):
        return self.transformer(self.base.data(train))


class ShardedDataSet(AbstractDataSet):
    """Per-process shard of a global dataset (the DistributedDataSet role,
    ref DataSet.scala:164 + CachedDistriDataSet:203).

    The reference coalesces the RDD to one partition per node and iterates
    with a random offset per partition; here each JAX process takes the
    ``process_index``-th strided shard and iterates it shuffled.
    """

    def __init__(self, data, n_shards: int = None, shard_index: int = None):
        import jax
        self.n_shards = n_shards if n_shards is not None else jax.process_count()
        self.shard_index = shard_index if shard_index is not None else jax.process_index()
        data = list(data)
        self._global_size = len(data)
        self._shard = data[self.shard_index::self.n_shards]
        # elastic runs keep the FULL record list so recovery can
        # re-partition it when the process world shrinks (reshard) —
        # without it, a dead process takes its records' only owner with
        # it.  Fail-fast runs (the default) drop the other shards as
        # before: N-times resident memory is a price only recovery pays.
        from bigdl_tpu.resilience import elastic
        self._data = data if elastic.enabled() else None

    def size(self):
        return self._global_size

    def shard_size(self):
        return len(self._shard)

    def reshard(self, n_shards: int = None, shard_index: int = None):
        """Re-partition over a changed process world (elastic recovery,
        docs/resilience.md): defaults re-read the LIVE jax topology, so
        after a re-form each survivor picks up its new strided shard of
        the ORIGINAL record order — every record keeps exactly one owner
        and the global size is unchanged.  In-place shuffles of the old
        shard are discarded by design: the recovery protocol rewinds the
        RNG stream to its anchor, so iteration order is re-derived from
        the restored stream, not inherited from a half-dead epoch."""
        import jax
        if self._data is None:
            raise RuntimeError(
                "ShardedDataSet.reshard needs the full record list, "
                "which is only retained under BIGDL_ELASTIC=1 (set the "
                "flag before constructing the dataset)")
        self.n_shards = (n_shards if n_shards is not None
                         else jax.process_count())
        self.shard_index = (shard_index if shard_index is not None
                            else jax.process_index())
        self._shard = self._data[self.shard_index::self.n_shards]
        return self

    def shuffle(self):
        RNG.shuffle(self._shard)
        return self

    def data(self, train: bool):
        if train:
            def looped():
                while True:
                    idx = RNG.np_rng().permutation(len(self._shard))
                    for i in idx:
                        yield self._shard[i]
            return looped()
        # same snapshot-free view as LocalArrayDataSet.data(train=False)
        return (self._shard[i] for i in range(len(self._shard)))


# DistributedDataSet is the reference's name for the concept; ShardedDataSet
# is the implementation.  Alias for API parity.
DistributedDataSet = ShardedDataSet


class DataSet:
    """Factory namespace (ref object DataSet, DataSet.scala:271-455)."""

    @staticmethod
    def array(data, distributed: bool = False):
        """(ref DataSet.array :271-294)"""
        if distributed:
            return ShardedDataSet(data)
        return LocalArrayDataSet(data)

    @staticmethod
    def image_folder(path, distributed: bool = False):
        """Class-per-subfolder image dataset (ref DataSet.ImageFolder
        :322-379).  Returns paths + 1-based float labels as Samples of
        (path, label); decode happens in the transformer pipeline."""
        import os
        classes = sorted(d for d in os.listdir(path)
                         if os.path.isdir(os.path.join(path, d)))
        records = []
        for li, cls in enumerate(classes):
            d = os.path.join(path, cls)
            for f in sorted(os.listdir(d)):
                records.append((os.path.join(d, f), float(li + 1)))
        return DataSet.array(records, distributed)

    @staticmethod
    def seq_file_folder(path, distributed: bool = False, class_num=None):
        """Streaming packed-record dataset (ref DataSet.SeqFileFolder
        DataSet.scala:384-455).  A folder of ``*.seq`` files is read as
        actual Hadoop SequenceFiles — the reference toolchain's ImageNet
        wire format (``bigdl_tpu.dataset.seqfile``); otherwise the folder
        is this framework's own packed-shard format written by
        ``bigdl_tpu.dataset.shardfile.write_shards`` / ``imagenet_tools``."""
        from bigdl_tpu.dataset import seqfile
        # one listing decides the wire format (a remote listdir is an RPC;
        # two listings could also disagree under concurrent writes)
        names = seqfile.folder_listing(path)
        seq_files = seqfile.find_seq_files(path, names=names)
        if seq_files:
            bdts = [n for n in names if n.endswith(".bdts")]
            if bdts:
                # dispatching on "any .seq present" would silently pick a
                # wire format; a folder holding both is ambiguous
                raise ValueError(
                    f"{path} holds BOTH Hadoop SequenceFiles "
                    f"({len(seq_files)} *.seq) and packed shards "
                    f"({len(bdts)} *.bdts) — format selection would be "
                    "silent and order-dependent; split the folder (or "
                    "remove the stray files) so it holds exactly one "
                    "wire format")
            return seqfile.SeqFileDataSet(path, class_num=class_num,
                                          distributed=distributed,
                                          files=seq_files)
        if class_num is not None:
            raise ValueError(
                f"class_num is only supported for Hadoop SequenceFile "
                f"folders; {path} holds no .seq files")
        from bigdl_tpu.dataset.shardfile import ShardFolder
        return ShardFolder(path, distributed=distributed)
