"""CIFAR-10 binary reader (ref models/vgg/Utils.scala CIFAR loader).

Binary format: per record 1 label byte + 3072 pixel bytes (RGB planes).
``synthetic()`` provides shape-identical stand-in data.
"""
from __future__ import annotations

import os

import numpy as np

from bigdl_tpu.dataset.image import LabeledImage

# per-channel BGR means/stds used by the reference's vgg pipeline
TRAIN_MEAN = (0.4913996898739353 * 255, 0.4821584196221302 * 255, 0.44653092422369434 * 255)
TRAIN_STD = (0.24703223517429462 * 255, 0.2434851308749409 * 255, 0.26158784442034005 * 255)


def load_bin(path):
    from bigdl_tpu import native
    raw = np.fromfile(path, np.uint8)
    labels1, imgs = native.cifar_decode(raw)  # native or numpy fallback
    return imgs, labels1 - 1.0


def load(folder, training: bool = True):
    files = ([f"data_batch_{i}.bin" for i in range(1, 6)] if training
             else ["test_batch.bin"])
    records = []
    for fn in files:
        p = os.path.join(folder, fn)
        if not os.path.exists(p):
            raise FileNotFoundError(p)
        imgs, labels = load_bin(p)
        records += [LabeledImage(i, l + 1) for i, l in zip(imgs, labels)]
    return records


def synthetic(n: int = 1024, seed: int = 0):
    rng = np.random.RandomState(seed)
    imgs = rng.uniform(0, 255, (n, 32, 32, 3)).astype(np.float32)
    labels = rng.randint(0, 10, n).astype(np.float32)
    return [LabeledImage(i, l + 1) for i, l in zip(imgs, labels)]
