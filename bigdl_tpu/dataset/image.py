"""Image pipeline (ref dataset/image/, 22 files — SURVEY.md §2.4).

Records are HWC float32 numpy arrays ("BGRImage"/"GreyImage" roles, ref
image/Types.scala:127/246/278) paired with a 1-based float label:
``LabeledImage``.  All augmentation runs on host numpy (the reference runs
it on executor JVM threads); batches cross to the device once assembled.

Decode uses Pillow when available (the javax.imageio role), else raw
numpy codecs for the formats the bundled readers produce.
"""
from __future__ import annotations

import numpy as np

from bigdl_tpu import native
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import Transformer, FuncTransformer
from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.utils.random import RNG


class LabeledImage:
    """HWC float image + label (ref LabeledBGRImage image/Types.scala:246).

    ``order`` records the channel layout ("rgb" or "bgr") so layout-sensitive
    transformers (ColorJitter, Lighting) pick correct per-channel weights
    without the caller having to thread it through the pipeline."""

    __slots__ = ("data", "label", "order")

    def __init__(self, data, label, order: str = "rgb"):
        self.data = np.asarray(data, np.float32)
        self.label = float(label)
        self.order = order

    @property
    def height(self):
        return self.data.shape[0]

    @property
    def width(self):
        return self.data.shape[1]


def _decode_bytes(raw: bytes):
    try:
        import io
        from PIL import Image as PILImage
        img = PILImage.open(io.BytesIO(raw)).convert("RGB")
        return np.asarray(img, np.float32)
    except ImportError as e:  # pragma: no cover
        raise RuntimeError("Pillow unavailable for image decode") from e


class BytesToImg(Transformer):
    """Decode ByteRecord bytes to LabeledImage in RGB channel order,
    optional resize to (scale_to, scale_to) (ref BytesToBGRImg;
    BGRImage.resize image/Types.scala:278).  ``to_bgr=True`` flips channel
    order to the reference's BGR so reference-ordered per-channel
    constants (normalizer means/stds, jitter weights) apply unchanged."""

    pure_per_record = True   # decode: 1-to-1, no RNG (prefetch fan-out ok)

    def __init__(self, scale_to: int = None, to_bgr: bool = False):
        self.scale_to = scale_to
        self.to_bgr = to_bgr

    def __call__(self, iterator):
        for rec in iterator:
            arr = _decode_bytes(rec.data)
            if self.scale_to is not None:
                arr = _resize(arr, self.scale_to, self.scale_to)
            if self.to_bgr:
                arr = arr[..., ::-1].copy()
            yield LabeledImage(arr, rec.label,
                               order="bgr" if self.to_bgr else "rgb")


class BytesToBGRImg(BytesToImg):
    """Decode to BGR channel order exactly like the reference's
    BytesToBGRImg (image/Types.scala:278 stores pixels BGR), so pipelines
    ported with reference BGR mean/std tuples stay channel-correct."""

    def __init__(self, scale_to: int = None):
        super().__init__(scale_to=scale_to, to_bgr=True)


def _resize(arr, h, w):
    """Bilinear resize, pure numpy on float32 (no uint8 round-trip, so
    normalized/negative pixel values survive).  Works on HW and HWC."""
    arr = np.asarray(arr, np.float32)
    H, W = arr.shape[:2]
    if (H, W) == (h, w):
        return arr
    ys = np.linspace(0, H - 1, h, dtype=np.float32)
    xs = np.linspace(0, W - 1, w, dtype=np.float32)
    y0 = np.floor(ys).astype(np.intp)
    x0 = np.floor(xs).astype(np.intp)
    y1 = np.minimum(y0 + 1, H - 1)
    x1 = np.minimum(x0 + 1, W - 1)
    wy = (ys - y0)[:, None]
    wx = (xs - x0)[None, :]
    if arr.ndim == 3:
        wy = wy[..., None]
        wx = wx[..., None]
    top = arr[y0][:, x0] * (1 - wx) + arr[y0][:, x1] * wx
    bot = arr[y1][:, x0] * (1 - wx) + arr[y1][:, x1] * wx
    return (top * (1 - wy) + bot * wy).astype(np.float32)


class BytesToGreyImg(Transformer):
    """Decode ByteRecord bytes to greyscale LabeledImage
    (ref BytesToGreyImg.scala); ``row x col`` raw-u8 records."""

    pure_per_record = True

    def __init__(self, row: int, col: int):
        self.row = row
        self.col = col

    def __call__(self, iterator):
        for rec in iterator:
            arr = np.frombuffer(rec.data, np.uint8).astype(np.float32)
            yield LabeledImage(arr.reshape(self.row, self.col), rec.label)


class ImgNormalizer(Transformer):
    """Subtract mean, divide std, per channel (ref BGRImgNormalizer /
    GreyImgNormalizer).  Means/stds are scalars or per-channel tuples.
    Routes through the native hostops kernel when built (numpy fallback)."""

    pure_per_record = True

    def __init__(self, mean, std):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def __call__(self, iterator):
        from bigdl_tpu import native
        use_native = native.is_loaded()
        for img in iterator:
            if use_native and img.data.ndim == 3 and self.mean.ndim <= 1:
                img.data = native.normalize(img.data, self.mean, self.std)
            else:
                img.data = (img.data - self.mean) / self.std
            yield img

    @staticmethod
    def from_dataset(dataset, max_samples: int = 10000):
        """Estimate mean/std from data (ref GreyImgNormalizer dataset ctor)."""
        n, s, s2 = 0, 0.0, 0.0
        it = dataset.data(train=False)
        for i, img in enumerate(it):
            if i >= max_samples:
                break
            d = img.data if isinstance(img, LabeledImage) else img
            s += d.mean()
            s2 += (d ** 2).mean()
            n += 1
        mean = s / n
        std = float(np.sqrt(max(s2 / n - mean ** 2, 1e-12)))
        return ImgNormalizer(mean, std)


class ImgPixelNormalizer(Transformer):
    """Subtract a full per-pixel mean image (ref BGRImgPixelNormalizer)."""

    pure_per_record = True

    def __init__(self, mean_image):
        self.mean_image = np.asarray(mean_image, np.float32)

    def __call__(self, iterator):
        for img in iterator:
            img.data = img.data - self.mean_image
            yield img


class ImgCropper(Transformer):
    """Positioned crop (ref BGRImgCropper.scala).  ``cropper_method`` is
    ``"center"`` or ``"random"``; this spelling defaults to center (the
    validation-pipeline choice), while the reference-named ``BGRImgCropper``
    subclass defaults to random, matching the reference's
    ``cropperMethod: CropperMethod = CropRandom`` default."""

    def __init__(self, crop_width: int, crop_height: int,
                 cropper_method: str = "center"):
        if cropper_method not in ("center", "random"):
            raise ValueError(
                f"cropper_method must be center|random, got {cropper_method}")
        self.cw, self.ch = crop_width, crop_height
        self.cropper_method = cropper_method
        # center crops are pure 1-to-1 maps; random crops draw RNG and
        # must stay on the prefetch producer (dataset/prefetch.py)
        self.stochastic = cropper_method == "random"
        self.pure_per_record = not self.stochastic

    def __call__(self, iterator):
        for img in iterator:
            h, w = img.data.shape[:2]
            if self.cropper_method == "random":
                y0 = RNG.np_rng().randint(0, h - self.ch + 1)
                x0 = RNG.np_rng().randint(0, w - self.cw + 1)
            else:
                y0 = (h - self.ch) // 2
                x0 = (w - self.cw) // 2
            img.data = img.data[y0:y0 + self.ch, x0:x0 + self.cw]
            yield img


class BGRImgCropper(ImgCropper):
    """Reference-named cropper: defaults to random position like
    BGRImgCropper.scala (CropRandom); pass ``cropper_method="center"``
    for validation pipelines."""

    def __init__(self, crop_width: int, crop_height: int,
                 cropper_method: str = "random"):
        super().__init__(crop_width, crop_height, cropper_method)


class ImgRdmCropper(Transformer):
    """Random-position crop with optional zero padding
    (ref BGRImgRdmCropper / GreyImgCropper)."""

    stochastic = True        # RNG draws: stays on the prefetch producer

    def __init__(self, crop_width: int, crop_height: int, padding: int = 0):
        self.cw, self.ch = crop_width, crop_height
        self.padding = padding

    def __call__(self, iterator):
        for img in iterator:
            d = img.data
            if self.padding > 0:
                p = self.padding
                pads = ((p, p), (p, p)) + ((0, 0),) * (d.ndim - 2)
                d = np.pad(d, pads)
            h, w = d.shape[:2]
            y0 = RNG.np_rng().randint(0, h - self.ch + 1)
            x0 = RNG.np_rng().randint(0, w - self.cw + 1)
            img.data = d[y0:y0 + self.ch, x0:x0 + self.cw]
            yield img


class HFlip(Transformer):
    """Random horizontal flip (ref HFlip.scala)."""

    stochastic = True

    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold

    def __call__(self, iterator):
        for img in iterator:
            if RNG.np_rng().uniform() < self.threshold:
                img.data = img.data[:, ::-1].copy()
            yield img


class ColorJitter(Transformer):
    """Random brightness/contrast/saturation in random order
    (ref ColoJitter.scala).  Channel layout is read from each image's
    ``order`` (set by the decoders); pass ``channel_order`` only to
    override it."""

    stochastic = True

    def __init__(self, brightness: float = 0.4, contrast: float = 0.4,
                 saturation: float = 0.4, channel_order: str = None):
        if channel_order not in (None, "bgr", "rgb"):
            raise ValueError(f"channel_order must be bgr|rgb, got {channel_order}")
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation
        self.channel_order = channel_order

    def _grayscale(self, d, order):
        # ITU-R 601 luma; weight per channel position depends on layout
        r, g_, b = ((2, 1, 0) if order == "bgr" else (0, 1, 2))
        g = 0.299 * d[..., r] + 0.587 * d[..., g_] + 0.114 * d[..., b]
        return g[..., None]

    def __call__(self, iterator):
        rng = RNG.np_rng()
        for img in iterator:
            order = self.channel_order or getattr(img, "order", "rgb")
            ops = [self._do_brightness, self._do_contrast, self._do_saturation]
            rng.shuffle(ops)
            for op in ops:
                img.data = op(img.data, rng, order)
            yield img

    def _do_brightness(self, d, rng, order):
        alpha = 1.0 + rng.uniform(-self.brightness, self.brightness)
        return d * alpha

    def _do_contrast(self, d, rng, order):
        alpha = 1.0 + rng.uniform(-self.contrast, self.contrast)
        mean = self._grayscale(d, order).mean()
        return d * alpha + mean * (1 - alpha)

    def _do_saturation(self, d, rng, order):
        alpha = 1.0 + rng.uniform(-self.saturation, self.saturation)
        return d * alpha + self._grayscale(d, order) * (1 - alpha)


class Lighting(Transformer):
    """PCA lighting noise with ImageNet eigen-decomposition
    (ref Lighting.scala; values originate from fb.resnet.torch where rows
    are RGB-ordered).

    Two intentional divergences from the reference (also noted in
    PARITY.md), chosen to match fb.resnet.torch's original semantics
    rather than reproduce reference quirks:

    - alpha is drawn from ``normal(0, alphastd)`` (fb.resnet.torch), while
      Lighting.scala:41 draws ``uniform(0, alphastd)``;
    - the RGB-ordered shift row is flipped for BGR-decoded images so each
      eigen-component lands on its own channel, while the reference applies
      the RGB rows to BGR pixels unflipped."""

    stochastic = True

    alphastd = 0.1
    eig_val = np.asarray([0.2175, 0.0188, 0.0045], np.float32)
    eig_vec = np.asarray([  # rows: R, G, B
        [-0.5675, 0.7192, 0.4009],
        [-0.5808, -0.0045, -0.8140],
        [-0.5836, -0.6948, 0.4203]], np.float32)

    def __init__(self, channel_order: str = None):
        if channel_order not in (None, "bgr", "rgb"):
            raise ValueError(f"channel_order must be bgr|rgb, got {channel_order}")
        self.channel_order = channel_order

    def __call__(self, iterator):
        rng = RNG.np_rng()
        for img in iterator:
            order = self.channel_order or getattr(img, "order", "rgb")
            alpha = rng.normal(0, self.alphastd, 3).astype(np.float32)
            shift = (self.eig_vec * alpha * self.eig_val).sum(axis=1)
            if order == "bgr":
                shift = shift[::-1]
            img.data = img.data + shift
            yield img


def _img_to_nchw(data, to_chw):
    """One LabeledImage array -> CHW (grey gets a singleton channel)."""
    if data.ndim == 2:
        return data[None]  # grey -> (1, H, W)
    if to_chw:
        return native.hwc_to_chw(data)
    return data


def _stack_batch(imgs, to_chw):
    """LabeledImages -> one MiniBatch (shared by serial + MT batchers)."""
    xs = [_img_to_nchw(img.data, to_chw) for img in imgs]
    ys = [img.label for img in imgs]
    return MiniBatch(np.stack(xs), np.asarray(ys, np.float32))


class ImgToBatch(Transformer):
    """LabeledImage -> MiniBatch in NCHW (ref BGRImgToBatch/GreyImgToBatch)."""

    def __init__(self, batch_size: int, to_chw: bool = True):
        self.batch_size = batch_size
        self.to_chw = to_chw

    def __call__(self, iterator):
        buf = []
        for img in iterator:
            buf.append(img)
            if len(buf) == self.batch_size:
                yield _stack_batch(buf, self.to_chw)
                buf = []
        if buf:
            yield _stack_batch(buf, self.to_chw)


class MTLabeledImgToBatch(Transformer):
    """Multi-threaded record->image->MiniBatch assembly (ref
    MTLabeledBGRImgToBatch.scala:47: coreNumber cloned sub-pipelines feeding
    a PreFetch queue).  ``transformer`` maps one upstream record to a
    LabeledImage; it is applied concurrently across ``num_threads`` host
    threads per batch, and finished batches are prefetched one deep so host
    decode/augment overlaps device compute.  ``width``/``height`` fix the
    batch buffer dims as in the reference: any image arriving at another
    size is resized before stacking."""

    def __init__(self, width: int, height: int, batch_size: int,
                 transformer: Transformer, num_threads: int = None,
                 to_chw: bool = True):
        import os
        import threading
        self.width = width
        self.height = height
        self.batch_size = batch_size
        self.transformer = transformer
        self.num_threads = num_threads or min(8, os.cpu_count() or 1)
        self.to_chw = to_chw
        self._tls = threading.local()

    def _thread_transformer(self):
        # one cloned sub-pipeline per worker thread, as the reference does
        # (MTLabeledBGRImgToBatch.scala:47): transformers with mutable
        # instance state (preallocated buffers etc.) must not be shared
        import copy
        tls = self._tls
        if getattr(tls, "transformer", None) is None:
            tls.transformer = copy.deepcopy(self.transformer)
        return tls.transformer

    def _apply_one(self, rec):
        out = list(self._thread_transformer()(iter([rec])))
        if len(out) != 1:
            raise ValueError(
                "MTLabeledImgToBatch transformer must be 1-to-1 per record")
        img = out[0]
        h, w = img.data.shape[:2]
        if (h, w) != (self.height, self.width):
            img.data = _resize(img.data, self.height, self.width)
        return img

    def __call__(self, iterator):
        from concurrent.futures import ThreadPoolExecutor

        def batches():
            buf = []
            for rec in iterator:
                buf.append(rec)
                if len(buf) == self.batch_size:
                    yield buf
                    buf = []
            if buf:
                yield buf

        def build(pool, raw):
            return _stack_batch(list(pool.map(self._apply_one, raw)),
                                self.to_chw)

        # +2 threads run whole-batch assembly (at most 2 in flight) so all
        # num_threads decode workers stay available — a blocked assembly
        # task must never starve the per-record tasks it is waiting on.
        with ThreadPoolExecutor(max_workers=self.num_threads + 2) as pool:
            from collections import deque
            futures = deque()
            it = batches()
            for raw in it:
                futures.append(pool.submit(build, pool, raw))
                if len(futures) >= 2:
                    yield futures.popleft().result()
            while futures:
                yield futures.popleft().result()


class ImgToSample(Transformer):
    """LabeledImage -> Sample (for RDD-of-Sample style ingestion)."""

    pure_per_record = True

    def __init__(self, to_chw: bool = True):
        self.to_chw = to_chw

    def __call__(self, iterator):
        for img in iterator:
            d = img.data
            if d.ndim == 2:
                d = d[None]
            elif self.to_chw:
                d = np.transpose(d, (2, 0, 1))
            yield Sample(d, np.asarray([img.label], np.float32))


class ImgToImageVector(Transformer):
    """LabeledImage -> flat float vector Sample
    (ref BGRImgToImageVector.scala: the MLlib DenseVector bridge feeding
    DLClassifier pipelines — here the "DataFrame" is any columnar store of
    flat vectors).  The reference's ``copyTo(..., toRGB=true)``
    (image/Types.scala:154-164) writes a *planar CHW* vector with the BGR
    interleaved channels flipped to RGB plane order (plane 0 = R, 1 = G,
    2 = B); this transformer emits exactly that layout for 3-channel
    images.  Greyscale (2-D) images flatten as-is."""

    pure_per_record = True

    def __call__(self, iterator):
        for img in iterator:
            d = np.asarray(img.data, np.float32)
            if d.ndim == 3 and d.shape[2] == 3:
                # HWC BGR -> CHW (B,G,R planes) -> reverse planes -> RGB
                d = np.transpose(d, (2, 0, 1))[::-1]
            vec = np.ascontiguousarray(d, np.float32).reshape(-1)
            yield Sample(vec, np.asarray([img.label], np.float32))
