"""Image pipeline (ref dataset/image/, 22 files — SURVEY.md §2.4).

Records are HWC float32 numpy arrays ("BGRImage"/"GreyImage" roles, ref
image/Types.scala:127/246/278) paired with a 1-based float label:
``LabeledImage``.  All augmentation runs on host numpy (the reference runs
it on executor JVM threads); batches cross to the device once assembled.

Decode uses Pillow when available (the javax.imageio role), else raw
numpy codecs for the formats the bundled readers produce.
"""
from __future__ import annotations

import numpy as np

from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import Transformer, FuncTransformer
from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.utils.random import RNG


class LabeledImage:
    """HWC float image + label (ref LabeledBGRImage image/Types.scala:246)."""

    __slots__ = ("data", "label")

    def __init__(self, data, label):
        self.data = np.asarray(data, np.float32)
        self.label = float(label)

    @property
    def height(self):
        return self.data.shape[0]

    @property
    def width(self):
        return self.data.shape[1]


def _decode_bytes(raw: bytes):
    try:
        import io
        from PIL import Image as PILImage
        img = PILImage.open(io.BytesIO(raw)).convert("RGB")
        return np.asarray(img, np.float32)
    except ImportError as e:  # pragma: no cover
        raise RuntimeError("Pillow unavailable for image decode") from e


class BytesToImg(Transformer):
    """Decode ByteRecord bytes to LabeledImage, optional resize to
    (scale_to, scale_to) (ref BytesToBGRImg; BGRImage.resize
    image/Types.scala:278)."""

    def __init__(self, scale_to: int = None):
        self.scale_to = scale_to

    def __call__(self, iterator):
        for rec in iterator:
            arr = _decode_bytes(rec.data)
            if self.scale_to is not None:
                arr = _resize(arr, self.scale_to, self.scale_to)
            yield LabeledImage(arr, rec.label)


def _resize(arr, h, w):
    """Bilinear resize via PIL if present, else nearest with numpy."""
    try:
        from PIL import Image as PILImage
        img = PILImage.fromarray(arr.astype(np.uint8))
        return np.asarray(img.resize((w, h), PILImage.BILINEAR), np.float32)
    except ImportError:  # pragma: no cover
        ys = (np.arange(h) * arr.shape[0] / h).astype(int)
        xs = (np.arange(w) * arr.shape[1] / w).astype(int)
        return arr[ys][:, xs]


class BytesToGreyImg(Transformer):
    """Decode ByteRecord bytes to greyscale LabeledImage
    (ref BytesToGreyImg.scala); ``row x col`` raw-u8 records."""

    def __init__(self, row: int, col: int):
        self.row = row
        self.col = col

    def __call__(self, iterator):
        for rec in iterator:
            arr = np.frombuffer(rec.data, np.uint8).astype(np.float32)
            yield LabeledImage(arr.reshape(self.row, self.col), rec.label)


class ImgNormalizer(Transformer):
    """Subtract mean, divide std, per channel (ref BGRImgNormalizer /
    GreyImgNormalizer).  Means/stds are scalars or per-channel tuples.
    Routes through the native hostops kernel when built (numpy fallback)."""

    def __init__(self, mean, std):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def __call__(self, iterator):
        from bigdl_tpu import native
        use_native = native.is_loaded()
        for img in iterator:
            if use_native and img.data.ndim == 3 and self.mean.ndim <= 1:
                img.data = native.normalize(img.data, self.mean, self.std)
            else:
                img.data = (img.data - self.mean) / self.std
            yield img

    @staticmethod
    def from_dataset(dataset, max_samples: int = 10000):
        """Estimate mean/std from data (ref GreyImgNormalizer dataset ctor)."""
        n, s, s2 = 0, 0.0, 0.0
        it = dataset.data(train=False)
        for i, img in enumerate(it):
            if i >= max_samples:
                break
            d = img.data if isinstance(img, LabeledImage) else img
            s += d.mean()
            s2 += (d ** 2).mean()
            n += 1
        mean = s / n
        std = float(np.sqrt(max(s2 / n - mean ** 2, 1e-12)))
        return ImgNormalizer(mean, std)


class ImgPixelNormalizer(Transformer):
    """Subtract a full per-pixel mean image (ref BGRImgPixelNormalizer)."""

    def __init__(self, mean_image):
        self.mean_image = np.asarray(mean_image, np.float32)

    def __call__(self, iterator):
        for img in iterator:
            img.data = img.data - self.mean_image
            yield img


class ImgCropper(Transformer):
    """Center crop (ref BGRImgCropper with CropCenter)."""

    def __init__(self, crop_width: int, crop_height: int):
        self.cw, self.ch = crop_width, crop_height

    def __call__(self, iterator):
        for img in iterator:
            h, w = img.data.shape[:2]
            y0 = (h - self.ch) // 2
            x0 = (w - self.cw) // 2
            img.data = img.data[y0:y0 + self.ch, x0:x0 + self.cw]
            yield img


class ImgRdmCropper(Transformer):
    """Random-position crop with optional zero padding
    (ref BGRImgRdmCropper / GreyImgCropper)."""

    def __init__(self, crop_width: int, crop_height: int, padding: int = 0):
        self.cw, self.ch = crop_width, crop_height
        self.padding = padding

    def __call__(self, iterator):
        for img in iterator:
            d = img.data
            if self.padding > 0:
                p = self.padding
                pads = ((p, p), (p, p)) + ((0, 0),) * (d.ndim - 2)
                d = np.pad(d, pads)
            h, w = d.shape[:2]
            y0 = RNG.np_rng().randint(0, h - self.ch + 1)
            x0 = RNG.np_rng().randint(0, w - self.cw + 1)
            img.data = d[y0:y0 + self.ch, x0:x0 + self.cw]
            yield img


class HFlip(Transformer):
    """Random horizontal flip (ref HFlip.scala)."""

    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold

    def __call__(self, iterator):
        for img in iterator:
            if RNG.np_rng().uniform() < self.threshold:
                img.data = img.data[:, ::-1].copy()
            yield img


class ColorJitter(Transformer):
    """Random brightness/contrast/saturation in random order
    (ref ColoJitter.scala)."""

    def __init__(self, brightness: float = 0.4, contrast: float = 0.4,
                 saturation: float = 0.4):
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation

    def _grayscale(self, d):
        # BGR weights as in the reference
        g = 0.114 * d[..., 0] + 0.587 * d[..., 1] + 0.299 * d[..., 2]
        return g[..., None]

    def __call__(self, iterator):
        rng = RNG.np_rng()
        for img in iterator:
            ops = [self._do_brightness, self._do_contrast, self._do_saturation]
            rng.shuffle(ops)
            for op in ops:
                img.data = op(img.data, rng)
            yield img

    def _do_brightness(self, d, rng):
        alpha = 1.0 + rng.uniform(-self.brightness, self.brightness)
        return d * alpha

    def _do_contrast(self, d, rng):
        alpha = 1.0 + rng.uniform(-self.contrast, self.contrast)
        mean = self._grayscale(d).mean()
        return d * alpha + mean * (1 - alpha)

    def _do_saturation(self, d, rng):
        alpha = 1.0 + rng.uniform(-self.saturation, self.saturation)
        return d * alpha + self._grayscale(d) * (1 - alpha)


class Lighting(Transformer):
    """PCA lighting noise with ImageNet eigen-decomposition
    (ref Lighting.scala)."""

    alphastd = 0.1
    eig_val = np.asarray([0.2175, 0.0188, 0.0045], np.float32)
    eig_vec = np.asarray([
        [-0.5675, 0.7192, 0.4009],
        [-0.5808, -0.0045, -0.8140],
        [-0.5836, -0.6948, 0.4203]], np.float32)

    def __call__(self, iterator):
        rng = RNG.np_rng()
        for img in iterator:
            alpha = rng.normal(0, self.alphastd, 3).astype(np.float32)
            shift = (self.eig_vec * alpha * self.eig_val).sum(axis=1)
            img.data = img.data + shift
            yield img


class ImgToBatch(Transformer):
    """LabeledImage -> MiniBatch in NCHW (ref BGRImgToBatch/GreyImgToBatch)."""

    def __init__(self, batch_size: int, to_chw: bool = True):
        self.batch_size = batch_size
        self.to_chw = to_chw

    def __call__(self, iterator):
        from bigdl_tpu import native
        buf_x, buf_y = [], []
        for img in iterator:
            d = img.data
            if d.ndim == 2:
                d = d[None]  # grey -> (1, H, W)
            elif self.to_chw:
                d = native.hwc_to_chw(d)
            buf_x.append(d)
            buf_y.append(img.label)
            if len(buf_x) == self.batch_size:
                yield MiniBatch(np.stack(buf_x), np.asarray(buf_y, np.float32))
                buf_x, buf_y = [], []
        if buf_x:
            yield MiniBatch(np.stack(buf_x), np.asarray(buf_y, np.float32))


class ImgToSample(Transformer):
    """LabeledImage -> Sample (for RDD-of-Sample style ingestion)."""

    def __init__(self, to_chw: bool = True):
        self.to_chw = to_chw

    def __call__(self, iterator):
        for img in iterator:
            d = img.data
            if d.ndim == 2:
                d = d[None]
            elif self.to_chw:
                d = np.transpose(d, (2, 0, 1))
            yield Sample(d, np.asarray([img.label], np.float32))
