"""Transformer pipeline (ref dataset/Transformer.scala:40-55).

A ``Transformer[A, B]`` is ``Iterator[A] -> Iterator[B]``, composed with
``->`` — here the ``>>`` operator (and ``.chain()``).  SampleToBatch
(Transformer.scala:99-241) assembles fixed-shape padded MiniBatches, the
contact point with jit's static-shape requirement (SURVEY.md §7 hard parts:
variable-length batching must pad to fixed shapes).
"""
from __future__ import annotations

import numpy as np

from bigdl_tpu.dataset.sample import Sample, MiniBatch


class Transformer:
    """Iterator-to-iterator stage. Subclasses override __call__."""

    def __call__(self, iterator):
        raise NotImplementedError

    def __rshift__(self, other: "Transformer") -> "ChainedTransformer":
        """``a >> b`` == reference's ``a -> b``."""
        return ChainedTransformer(self, other)

    def chain(self, other):
        return self.__rshift__(other)

    def clone_transformer(self):
        import copy
        return copy.deepcopy(self)


class ChainedTransformer(Transformer):
    def __init__(self, first, last):
        self.first = first
        self.last = last

    def __call__(self, iterator):
        return self.last(self.first(iterator))


class Identity(Transformer):
    def __call__(self, iterator):
        return iterator


class FuncTransformer(Transformer):
    """Wrap a per-record function into a Transformer."""

    def __init__(self, fn):
        self.fn = fn

    def __call__(self, iterator):
        return (self.fn(x) for x in iterator)


class SampleToBatch(Transformer):
    """Sample -> MiniBatch with optional fixed-length padding
    (ref Transformer.scala:99-241).

    ``feature_padding``/``label_padding``: pad value for variable-length
    features/labels.  ``fixed_length``: pad every batch to this length
    (keeps one static shape for jit instead of per-batch max).
    ``partition_num``: drop the tail so every partition yields whole batches.
    """

    def __init__(self, batch_size: int, feature_padding=None, label_padding=None,
                 fixed_length: int = None, drop_last: bool = False):
        self.batch_size = batch_size
        self.feature_padding = feature_padding
        self.label_padding = label_padding
        self.fixed_length = fixed_length
        self.drop_last = drop_last

    def _assemble(self, samples):
        feats = [s.feature for s in samples]
        labels = [s.label for s in samples]
        if self.feature_padding is not None:
            feats = _pad_stack(feats, self.feature_padding, self.fixed_length)
        else:
            feats = np.stack(feats)
        if self.label_padding is not None:
            labels = _pad_stack(labels, self.label_padding, self.fixed_length)
        else:
            labels = np.stack(labels)
        return MiniBatch(feats, labels)

    def __call__(self, iterator):
        buf = []
        for s in iterator:
            buf.append(s)
            if len(buf) == self.batch_size:
                yield self._assemble(buf)
                buf = []
        if buf and not self.drop_last:
            yield self._assemble(buf)


def _pad_stack(arrays, pad_value, fixed_length=None):
    """Stack 1..nD arrays, padding dim 0 to max (or fixed) length."""
    max_len = fixed_length if fixed_length is not None else max(a.shape[0] for a in arrays)
    out_shape = (len(arrays), max_len) + arrays[0].shape[1:]
    out = np.full(out_shape, pad_value, dtype=arrays[0].dtype)
    for i, a in enumerate(arrays):
        n = min(a.shape[0], max_len)
        out[i, :n] = a[:n]
    return out


class PreFetch(Transformer):
    """Background-thread prefetch (the capability of the reference's
    MTLabeledBGRImgToBatch + PreFetch, MTLabeledBGRImgToBatch.scala:47,106:
    overlap host-side decode/augment with device compute)."""

    def __init__(self, depth: int = 2):
        self.depth = depth

    def __call__(self, iterator):
        import queue
        import threading

        q = queue.Queue(maxsize=self.depth)
        _END = object()
        stop = threading.Event()

        class _Error:
            # private sentinel so a pipeline that legitimately yields
            # exception *objects* as data items is not confused with a
            # worker failure
            def __init__(self, exc):
                self.exc = exc

        def put(item):
            # bounded put that gives up when the consumer is gone, so an
            # abandoned iterator can't leave this thread blocked forever
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                for item in iterator:
                    if not put(item):
                        return
                put(_END)
            except BaseException as e:  # propagate to the consumer
                put(_Error(e))

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    break
                if isinstance(item, _Error):
                    raise item.exc
                yield item
        finally:
            stop.set()
