"""Transformer pipeline (ref dataset/Transformer.scala:40-55).

A ``Transformer[A, B]`` is ``Iterator[A] -> Iterator[B]``, composed with
``->`` — here the ``>>`` operator (and ``.chain()``).  SampleToBatch
(Transformer.scala:99-241) assembles fixed-shape padded MiniBatches, the
contact point with jit's static-shape requirement (SURVEY.md §7 hard parts:
variable-length batching must pad to fixed shapes).
"""
from __future__ import annotations

import numpy as np

from bigdl_tpu.dataset.sample import Sample, MiniBatch


class Transformer:
    """Iterator-to-iterator stage. Subclasses override __call__."""

    #: exactly one output record per input record, no RNG draws, no
    #: cross-record state — eligible for ordered worker fan-out in the
    #: prefetch pipeline (``dataset/prefetch.py``); decode/normalize
    #: stages set this
    pure_per_record = False
    #: draws from the framework RNG (``utils.random.RNG``) — must run on
    #: the prefetch producer thread (the seed-stream owner) so the draw
    #: sequence stays bit-identical to the serial path
    stochastic = False

    def __call__(self, iterator):
        raise NotImplementedError

    def __rshift__(self, other: "Transformer") -> "ChainedTransformer":
        """``a >> b`` == reference's ``a -> b``."""
        return ChainedTransformer(self, other)

    def chain(self, other):
        return self.__rshift__(other)

    def clone_transformer(self):
        import copy
        return copy.deepcopy(self)


class ChainedTransformer(Transformer):
    def __init__(self, first, last):
        self.first = first
        self.last = last

    def __call__(self, iterator):
        return self.last(self.first(iterator))


class Identity(Transformer):
    def __call__(self, iterator):
        return iterator


class FuncTransformer(Transformer):
    """Wrap a per-record function into a Transformer."""

    def __init__(self, fn):
        self.fn = fn

    def __call__(self, iterator):
        return (self.fn(x) for x in iterator)


class SampleToBatch(Transformer):
    """Sample -> MiniBatch with optional fixed-length padding
    (ref Transformer.scala:99-241).

    ``feature_padding``/``label_padding``: pad value for variable-length
    features/labels.  ``fixed_length``: pad every batch to this length
    (keeps one static shape for jit instead of per-batch max).
    ``partition_num``: drop the tail so every partition yields whole batches.

    ``reuse_buffers=N`` (N >= 2) assembles batches into a ring of N
    preallocated arrays instead of a fresh ``np.stack`` allocation per
    batch — sample rows are copied straight into the slot.  A yielded
    MiniBatch is then only valid until N-1 more batches have been drawn:
    use it with consumers that copy promptly (the training loops convert
    to device arrays immediately; a prefetch pipeline of depth d needs
    ``N >= d + 2`` to cover queued + in-flight batches).  Off (0) by
    default because collecting batches into a list is a valid use of the
    default path.
    """

    def __init__(self, batch_size: int = None, feature_padding=None,
                 label_padding=None, fixed_length: int = None,
                 drop_last: bool = False, reuse_buffers: int = 0,
                 global_batch_size: int = None):
        if reuse_buffers and reuse_buffers < 2:
            raise ValueError(
                f"reuse_buffers needs a ring of >= 2 slots, got "
                f"{reuse_buffers} (the consumer still holds the previous "
                "batch while the next is assembled)")
        if (batch_size is None) == (global_batch_size is None):
            raise ValueError("pass exactly one of batch_size (per-process)"
                             " or global_batch_size (divided over the live"
                             " process world)")
        # global_batch_size is the reference's Utils.getBatchSize contract
        # (global batch ÷ node count, Utils.scala:26-48) resolved at
        # ITERATION time from the live jax topology instead of once at
        # construction — so an elastic re-form (docs/resilience.md) that
        # shrinks the world automatically grows each survivor's local
        # batch and the GLOBAL batch stays fixed.
        self.global_batch_size = (int(global_batch_size)
                                  if global_batch_size is not None else None)
        self.batch_size = batch_size
        self.feature_padding = feature_padding
        self.label_padding = label_padding
        self.fixed_length = fixed_length
        self.drop_last = drop_last
        self.reuse_buffers = int(reuse_buffers)
        self._ring = None
        self._ring_i = 0

    def _ring_slot(self, feats, labels):
        """The next preallocated (feature, label) buffer pair, or None
        when the batch doesn't fit the ring (partial tail batch, shape
        drift) — those fall back to a fresh allocation."""
        if not self.reuse_buffers:
            return None
        if self._ring is None:
            f0, l0 = np.asarray(feats[0]), np.asarray(labels[0])
            # global mode: batch_size is None; size the ring from the
            # batch being assembled (== the resolved local batch)
            rows = (self.batch_size if self.batch_size is not None
                    else len(feats))
            # padded sides have data-dependent dim 1 unless pinned
            if self.feature_padding is not None:
                if self.fixed_length is None:
                    return None
                fshape = (rows, self.fixed_length) + f0.shape[1:]
            else:
                fshape = (rows,) + f0.shape
            if self.label_padding is not None:
                if self.fixed_length is None:
                    return None
                lshape = (rows, self.fixed_length) + l0.shape[1:]
            else:
                lshape = (rows,) + l0.shape
            self._ring = [
                (np.empty(fshape, f0.dtype), np.empty(lshape, l0.dtype))
                for _ in range(self.reuse_buffers)]
        fbuf, lbuf = self._ring[self._ring_i]
        if len(feats) != fbuf.shape[0] \
                or not self._rows_fit(fbuf, feats, self.feature_padding) \
                or not self._rows_fit(lbuf, labels, self.label_padding):
            return None
        self._ring_i = (self._ring_i + 1) % len(self._ring)
        return fbuf, lbuf

    @staticmethod
    def _rows_fit(buf, rows, pad_value):
        """Every row must match the buffer's row shape exactly (padded
        sides: the trailing dims; dim 0 is clipped/padded) — a drifting
        shape falls back to fresh allocation instead of crashing on the
        copy or, worse, broadcasting silently into wrong data."""
        if pad_value is None:
            want = buf.shape[1:]
            return all(np.shape(r) == want for r in rows)
        want = buf.shape[2:]
        return all(np.shape(r)[1:] == want for r in rows)

    @staticmethod
    def _fill(buf, arrays, pad_value):
        """Copy sample rows into a preallocated batch buffer (the padded
        path pre-fills with the pad value, then writes each prefix)."""
        if pad_value is None:
            for i, a in enumerate(arrays):
                buf[i] = a
            return buf
        buf.fill(pad_value)
        max_len = buf.shape[1]
        for i, a in enumerate(arrays):
            n = min(np.shape(a)[0], max_len)
            buf[i, :n] = a[:n]
        return buf

    def _assemble(self, samples):
        feats = [s.feature for s in samples]
        labels = [s.label for s in samples]
        slot = self._ring_slot(feats, labels)
        if slot is not None:
            return MiniBatch(self._fill(slot[0], feats, self.feature_padding),
                             self._fill(slot[1], labels, self.label_padding))
        if self.feature_padding is not None:
            feats = _pad_stack(feats, self.feature_padding, self.fixed_length)
        else:
            feats = np.stack(feats)
        if self.label_padding is not None:
            labels = _pad_stack(labels, self.label_padding, self.fixed_length)
        else:
            labels = np.stack(labels)
        return MiniBatch(feats, labels)

    def _local_batch(self) -> int:
        if self.global_batch_size is None:
            return self.batch_size
        import jax
        from bigdl_tpu.dataset.dataset import get_batch_size
        return get_batch_size(self.global_batch_size, jax.process_count())

    def __call__(self, iterator):
        batch = self._local_batch()
        if self.reuse_buffers and self.global_batch_size is not None \
                and self._ring is not None \
                and self._ring[0][0].shape[0] != batch:
            self._ring = None  # world changed: old slots have stale rows
        buf = []
        for s in iterator:
            buf.append(s)
            if len(buf) == batch:
                yield self._assemble(buf)
                buf = []
        if buf and not self.drop_last:
            yield self._assemble(buf)


def _pad_stack(arrays, pad_value, fixed_length=None):
    """Stack 1..nD arrays, padding dim 0 to max (or fixed) length."""
    max_len = fixed_length if fixed_length is not None else max(a.shape[0] for a in arrays)
    out_shape = (len(arrays), max_len) + arrays[0].shape[1:]
    out = np.full(out_shape, pad_value, dtype=arrays[0].dtype)
    for i, a in enumerate(arrays):
        n = min(a.shape[0], max_len)
        out[i, :n] = a[:n]
    return out


class PreFetch(Transformer):
    """Background-thread prefetch (the capability of the reference's
    MTLabeledBGRImgToBatch + PreFetch, MTLabeledBGRImgToBatch.scala:47,106:
    overlap host-side decode/augment with device compute)."""

    def __init__(self, depth: int = 2):
        self.depth = depth

    def __call__(self, iterator):
        import queue
        import threading

        q = queue.Queue(maxsize=self.depth)
        _END = object()
        stop = threading.Event()

        class _Error:
            # private sentinel so a pipeline that legitimately yields
            # exception *objects* as data items is not confused with a
            # worker failure
            def __init__(self, exc):
                self.exc = exc

        def put(item):
            # bounded put that gives up when the consumer is gone, so an
            # abandoned iterator can't leave this thread blocked forever
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                for item in iterator:
                    if not put(item):
                        return
                put(_END)
            except BaseException as e:  # propagate to the consumer
                put(_Error(e))

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    break
                if isinstance(item, _Error):
                    raise item.exc
                yield item
        finally:
            stop.set()
