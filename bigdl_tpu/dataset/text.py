"""Text pipeline (ref dataset/text/: LabeledSentence types,
LabeledSentenceToSample.scala:43; models/rnn/Utils.scala Dictionary :144,
WordTokenizer :207, readSentence :132).
"""
from __future__ import annotations

import re

import numpy as np

from bigdl_tpu.dataset.sample import Sample, LabeledSentence
from bigdl_tpu.dataset.transformer import Transformer


class Dictionary:
    """Vocabulary built from tokenized sentences (ref rnn/Utils.Dictionary
    :144): most-frequent ``vocab_size`` words, the rest map to an
    out-of-vocabulary bucket."""

    def __init__(self, sentences=None, vocab_size: int = None):
        self.word2index = {}
        self.index2word = []
        if sentences is not None:
            from collections import Counter
            counts = Counter(w for s in sentences for w in s)
            words = [w for w, _ in counts.most_common(vocab_size)]
            for w in words:
                self.add_word(w)

    def add_word(self, word):
        if word not in self.word2index:
            self.word2index[word] = len(self.index2word)
            self.index2word.append(word)
        return self.word2index[word]

    def vocab_size(self):
        return len(self.index2word)

    def index(self, word):
        """0-based index; unknown words map to vocab_size (OOV bucket)."""
        return self.word2index.get(word, len(self.index2word))

    def word(self, index):
        """Reverse lookup (ref Dictionary.getWord): the OOV bucket and
        out-of-range indices render as ``<unk>``."""
        if 0 <= int(index) < len(self.index2word):
            return self.index2word[int(index)]
        return "<unk>"


class WordTokenizer(Transformer):
    """Lower-case word tokenizer (ref rnn/Utils.WordTokenizer :207)."""

    def __call__(self, iterator):
        for line in iterator:
            tokens = re.findall(r"[\w']+", line.lower())
            if tokens:
                yield tokens


class SentenceToLabeledSentence(Transformer):
    """Language-model pairs: data = w_0..w_{n-2}, label = w_1..w_{n-1}
    (the reference rnn Train pipeline's shift-by-one)."""

    def __init__(self, dictionary: Dictionary):
        self.dictionary = dictionary

    def __call__(self, iterator):
        for tokens in iterator:
            ids = np.asarray([self.dictionary.index(w) for w in tokens], np.int64)
            if len(ids) < 2:
                continue
            yield LabeledSentence(ids[:-1], ids[1:])


class LabeledSentenceToSample(Transformer):
    """LabeledSentence -> Sample with one-hot or index encoding and padding
    (ref text/LabeledSentenceToSample.scala:43).

    one-hot when ``n_input_dims`` (vocab size) is given (reference's
    SimpleRNN input format); labels are 1-based class indices.
    """

    def __init__(self, n_input_dims: int = None, fixed_length: int = None,
                 pad_value: int = 0, label_pad_class: int = 1):
        self.n_input_dims = n_input_dims
        self.fixed_length = fixed_length
        self.pad_value = pad_value
        # labels are 1-based class targets: pad positions must still carry a
        # valid class id (ref LabeledSentenceToSample padding semantics)
        self.label_pad_class = label_pad_class

    def __call__(self, iterator):
        for s in iterator:
            length = self.fixed_length if self.fixed_length is not None else s.data_length()
            data_ids = s.data[:length]
            label_ids = s.label[:length]
            if self.n_input_dims is not None:
                feat = np.zeros((length, self.n_input_dims), np.float32)
                feat[np.arange(len(data_ids)), data_ids] = 1.0
            else:
                feat = np.full((length,), self.pad_value, np.float32)
                feat[:len(data_ids)] = data_ids
            label = np.full((length,), self.label_pad_class, np.float32)
            label[:len(label_ids)] = label_ids + 1  # 1-based class targets
            yield Sample(feat, label)
