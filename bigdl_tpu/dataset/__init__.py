from bigdl_tpu.dataset.sample import Sample, MiniBatch, ByteRecord, LabeledSentence
from bigdl_tpu.dataset.transformer import (
    Transformer, ChainedTransformer, Identity, SampleToBatch, PreFetch,
)
from bigdl_tpu.dataset.dataset import (
    DataSet, LocalDataSet, LocalArrayDataSet, DistributedDataSet,
    ShardedDataSet,
)
from bigdl_tpu.dataset.prefetch import PipelineRunner
from bigdl_tpu.dataset.image import (
    LabeledImage, BytesToImg, BytesToBGRImg, BytesToGreyImg, ImgNormalizer,
    ImgPixelNormalizer, ImgCropper, BGRImgCropper, ImgRdmCropper, HFlip,
    ColorJitter, Lighting, ImgToBatch, ImgToSample, ImgToImageVector,
    MTLabeledImgToBatch,
)
from bigdl_tpu.dataset.text import (
    Dictionary, WordTokenizer, SentenceToLabeledSentence,
    LabeledSentenceToSample,
)

# Reference-name aliases (ref dataset/image/*.scala).  BytesToBGRImg above
# is a real BGR decoder; the remaining layout-agnostic transformers (crop,
# flip, normalize with caller-supplied per-channel constants) share one
# implementation for BGR/RGB/grey arrays.
GreyImgNormalizer = ImgNormalizer
BGRImgNormalizer = ImgNormalizer
BGRImgPixelNormalizer = ImgPixelNormalizer
BGRImgRdmCropper = ImgRdmCropper
GreyImgCropper = ImgRdmCropper  # the reference's grey cropper is random-position
BGRImgToBatch = ImgToBatch
GreyImgToBatch = ImgToBatch
BGRImgToSample = ImgToSample
BGRImgToImageVector = ImgToImageVector  # MLlib DenseVector role: planar CHW, RGB plane order
MTLabeledBGRImgToBatch = MTLabeledImgToBatch
ColoJitter = ColorJitter  # reference spelling (dataset/image/ColoJitter.scala)

__all__ = [
    "Sample", "MiniBatch", "ByteRecord", "LabeledSentence",
    "Transformer", "ChainedTransformer", "Identity", "SampleToBatch",
    "PreFetch",
    "DataSet", "LocalDataSet", "LocalArrayDataSet", "DistributedDataSet",
    "ShardedDataSet", "PipelineRunner",
    "LabeledImage", "BytesToImg", "BytesToGreyImg", "ImgNormalizer",
    "ImgPixelNormalizer", "ImgCropper", "ImgRdmCropper", "HFlip",
    "ColorJitter", "Lighting", "ImgToBatch", "ImgToSample",
    "MTLabeledImgToBatch",
    "BytesToBGRImg", "GreyImgNormalizer", "BGRImgNormalizer",
    "BGRImgPixelNormalizer", "BGRImgCropper", "GreyImgCropper",
    "BGRImgRdmCropper", "BGRImgToBatch", "GreyImgToBatch", "BGRImgToSample",
    "BGRImgToImageVector", "ImgToImageVector", "MTLabeledBGRImgToBatch", "ColoJitter",
    "Dictionary", "WordTokenizer", "SentenceToLabeledSentence",
    "LabeledSentenceToSample",
]
