from bigdl_tpu.dataset.sample import Sample, MiniBatch, ByteRecord, LabeledSentence
from bigdl_tpu.dataset.transformer import (
    Transformer, ChainedTransformer, Identity, SampleToBatch,
)
from bigdl_tpu.dataset.dataset import (
    DataSet, LocalDataSet, LocalArrayDataSet, DistributedDataSet,
    ShardedDataSet,
)

__all__ = [
    "Sample", "MiniBatch", "ByteRecord", "LabeledSentence",
    "Transformer", "ChainedTransformer", "Identity", "SampleToBatch",
    "DataSet", "LocalDataSet", "LocalArrayDataSet", "DistributedDataSet",
    "ShardedDataSet",
]
