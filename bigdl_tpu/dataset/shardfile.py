"""Packed record shards — the Hadoop SequenceFile role
(ref dataset/DataSet.SeqFileFolder :384-455 and
models/utils/ImageNetSeqFileGenerator.scala: pre-pack many small image files
into large sequential shards so training reads streams, not inodes).

Format (little-endian):
  header: magic b"BDTS" | u32 version | u64 record count
  record: u32 label_len | label bytes (utf-8, e.g. "1012") |
          u32 data_len  | data bytes (encoded image or raw array)

``write_shards`` packs (key, bytes) pairs into N shards round-robin;
``ShardFolder`` reads a directory of shards as a ByteRecord dataset
(shardable across processes).
"""
from __future__ import annotations

import io
import os
import struct

import numpy as np

from bigdl_tpu.dataset.sample import ByteRecord
from bigdl_tpu.utils import fs
from bigdl_tpu.dataset.dataset import LocalDataSet, ShardedDataSet, DataSet

MAGIC = b"BDTS"
VERSION = 1


def write_shard(records, path):
    """records: iterable of (label: float|str, data: bytes).  ``path`` may
    be a local path or any fsspec URL (remote stores get a full-buffer
    upload; seek-back patching of the count happens in memory)."""
    buf = io.BytesIO()
    n = 0
    buf.write(MAGIC + struct.pack("<IQ", VERSION, 0))
    for label, data in records:
        key = str(label).encode()
        buf.write(struct.pack("<I", len(key)) + key)
        buf.write(struct.pack("<I", len(data)) + data)
        n += 1
    buf.seek(len(MAGIC) + 4)
    buf.write(struct.pack("<Q", n))
    fs.write_bytes_atomic(path, buf.getvalue())
    return n


def write_shards(records, out_dir, n_shards: int = 8, prefix: str = "shard"):
    """Round-robin pack records into ``n_shards`` files
    (the ImageNetSeqFileGenerator role)."""
    fs.makedirs(out_dir)
    buckets = [[] for _ in range(n_shards)]
    for i, rec in enumerate(records):
        buckets[i % n_shards].append(rec)
    paths = []
    for i, bucket in enumerate(buckets):
        p = fs.join(out_dir, f"{prefix}-{i:05d}.bdts")
        write_shard(bucket, p)
        paths.append(p)
    return paths


def read_shard(path):
    """Yield ByteRecord from one shard file (local or fsspec URL)."""
    with fs.open_file(path, "rb") as f:
        head = f.read(len(MAGIC) + 12)
        assert head[:4] == MAGIC, f"bad shard magic in {path}"
        version, count = struct.unpack("<IQ", head[4:])
        assert version == VERSION
        for _ in range(count):
            (klen,) = struct.unpack("<I", f.read(4))
            key = f.read(klen).decode()
            (dlen,) = struct.unpack("<I", f.read(4))
            data = f.read(dlen)
            try:
                label = float(key)
            except ValueError:
                label = key
            yield ByteRecord(data, label)


class ShardFolder(LocalDataSet):
    """Dataset over a directory of .bdts shards.  ``distributed=True``
    assigns whole shards to processes (the partition-per-node layout of
    CachedDistriDataSet)."""

    def __init__(self, folder, distributed: bool = False):
        import jax
        self.distributed = distributed  # Optimizer factory dispatch hint
        self.paths = sorted(
            fs.join(folder, f) for f in fs.listdir(folder)
            if f.endswith(".bdts"))
        if not self.paths:
            raise FileNotFoundError(f"no .bdts shards under {folder}")
        self._counts = []
        for p in self.paths:
            with fs.open_file(p, "rb") as f:
                head = f.read(len(MAGIC) + 12)
                self._counts.append(struct.unpack("<IQ", head[4:])[1])
        if distributed:
            idx, nproc = jax.process_index(), jax.process_count()
            self.local_paths = self.paths[idx::nproc]
            if not self.local_paths:
                # an empty local slice would make the train iterator spin
                # forever yielding nothing while peers wait at the collective
                raise ValueError(
                    f"process {idx}/{nproc} got no shards: {len(self.paths)} "
                    f"shard files under {folder} < process count; repack with "
                    f"write_shards(n_shards >= {nproc})")
        else:
            self.local_paths = list(self.paths)
        self._order = list(range(len(self.local_paths)))

    def size(self):
        return sum(self._counts)

    def shuffle(self):
        from bigdl_tpu.utils.random import RNG
        RNG.shuffle(self._order)
        return self

    def data(self, train: bool):
        if train:
            def looped():
                while True:
                    self.shuffle()
                    for i in self._order:
                        yield from read_shard(self.local_paths[i])
            return looped()

        def once():
            for p in self.local_paths:
                yield from read_shard(p)
        return once()
