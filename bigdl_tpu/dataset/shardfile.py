"""Packed record shards — the Hadoop SequenceFile role
(ref dataset/DataSet.SeqFileFolder :384-455 and
models/utils/ImageNetSeqFileGenerator.scala: pre-pack many small image files
into large sequential shards so training reads streams, not inodes).

Format (little-endian):
  header: magic b"BDTS" | u32 version | u64 record count
  record: u32 label_len | label bytes (utf-8, e.g. "1012") |
          u32 data_len  | data bytes (encoded image or raw array)

``write_shards`` packs (key, bytes) pairs into N shards round-robin;
``ShardFolder`` reads a directory of shards as a ByteRecord dataset
(shardable across processes).
"""
from __future__ import annotations

import io
import os
import struct

import numpy as np

from bigdl_tpu.dataset.sample import ByteRecord
from bigdl_tpu.utils import fs
from bigdl_tpu.dataset.dataset import LocalDataSet, ShardedDataSet, DataSet

MAGIC = b"BDTS"
VERSION = 1


class _ShardWriter:
    """Incremental single-shard writer.  Local paths stream to a ``.tmp``
    file (GB-scale shards never live in memory) finished by an atomic
    rename; fsspec URLs buffer in memory (object stores need whole-object
    upload) and go out through ``fs.write_bytes_atomic``."""

    def __init__(self, path):
        self.path = path
        self.n = 0
        self.closed = False
        self._local = not fs.is_url(path)
        if self._local:
            fs.makedirs(os.path.dirname(os.path.abspath(path)))
            self._tmp = path + ".tmp"
            self._f = open(self._tmp, "w+b")
        else:
            self._f = io.BytesIO()
        self._f.write(MAGIC + struct.pack("<IQ", VERSION, 0))

    def append(self, label, data):
        key = str(label).encode()
        self._f.write(struct.pack("<I", len(key)) + key)
        self._f.write(struct.pack("<I", len(data)) + data)
        self.n += 1

    def close(self):
        self._f.seek(len(MAGIC) + 4)
        self._f.write(struct.pack("<Q", self.n))
        if self._local:
            self._f.close()
            os.replace(self._tmp, self.path)
        else:
            fs.write_bytes_atomic(self.path, self._f.getvalue())
        self.closed = True
        return self.n

    def abort(self):
        """Drop the partial shard (no stale .tmp survives a failed run)."""
        self._f.close()
        if self._local:
            try:
                os.unlink(self._tmp)
            except OSError:
                pass


def write_shard(records, path):
    """records: iterable of (label: float|str, data: bytes).  ``path`` may
    be a local path or any fsspec URL; see _ShardWriter for the two
    streaming strategies."""
    w = _ShardWriter(path)
    try:
        for label, data in records:
            w.append(label, data)
    except BaseException:
        w.abort()
        raise
    return w.close()


def write_shards(records, out_dir, n_shards: int = 8, prefix: str = "shard"):
    """Round-robin pack records into ``n_shards`` files
    (the ImageNetSeqFileGenerator role).  Streams: each record goes
    straight to its shard writer, so the full dataset is never resident
    in memory."""
    fs.makedirs(out_dir)
    writers = []
    try:
        for i in range(n_shards):
            writers.append(
                _ShardWriter(fs.join(out_dir, f"{prefix}-{i:05d}.bdts")))
        for i, (label, data) in enumerate(records):
            writers[i % n_shards].append(label, data)
        for w in writers:
            w.close()
    except BaseException:
        for w in writers:
            if not w.closed:
                w.abort()
        raise
    return [w.path for w in writers]


def read_shard(path):
    """Yield ByteRecord from one shard file (local or fsspec URL)."""
    with fs.open_file(path, "rb") as f:
        head = f.read(len(MAGIC) + 12)
        assert head[:4] == MAGIC, f"bad shard magic in {path}"
        version, count = struct.unpack("<IQ", head[4:])
        assert version == VERSION
        for _ in range(count):
            (klen,) = struct.unpack("<I", f.read(4))
            key = f.read(klen).decode()
            (dlen,) = struct.unpack("<I", f.read(4))
            data = f.read(dlen)
            try:
                label = float(key)
            except ValueError:
                label = key
            yield ByteRecord(data, label)


class ShardFolder(LocalDataSet):
    """Dataset over a directory of .bdts shards.  ``distributed=True``
    assigns whole shards to processes (the partition-per-node layout of
    CachedDistriDataSet)."""

    def __init__(self, folder, distributed: bool = False):
        import jax
        self.distributed = distributed  # Optimizer factory dispatch hint
        self.paths = sorted(
            fs.join(folder, f) for f in fs.listdir(folder)
            if f.endswith(".bdts"))
        if not self.paths:
            raise FileNotFoundError(f"no .bdts shards under {folder}")
        self._counts = []
        for p in self.paths:
            with fs.open_file(p, "rb") as f:
                head = f.read(len(MAGIC) + 12)
                self._counts.append(struct.unpack("<IQ", head[4:])[1])
        if distributed:
            idx, nproc = jax.process_index(), jax.process_count()
            self.local_paths = self.paths[idx::nproc]
            if not self.local_paths:
                # an empty local slice would make the train iterator spin
                # forever yielding nothing while peers wait at the collective
                raise ValueError(
                    f"process {idx}/{nproc} got no shards: {len(self.paths)} "
                    f"shard files under {folder} < process count; repack with "
                    f"write_shards(n_shards >= {nproc})")
        else:
            self.local_paths = list(self.paths)
        self._order = list(range(len(self.local_paths)))

    def size(self):
        return sum(self._counts)

    def shuffle(self):
        from bigdl_tpu.utils.random import RNG
        RNG.shuffle(self._order)
        return self

    def data(self, train: bool):
        if train:
            def looped():
                while True:
                    self.shuffle()
                    for i in self._order:
                        yield from read_shard(self.local_paths[i])
            return looped()

        def once():
            for p in self.local_paths:
                yield from read_shard(p)
        return once()
