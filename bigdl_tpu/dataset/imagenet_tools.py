"""ImageNet shard generator (ref models/utils/ImageNetSeqFileGenerator.scala:
convert a class-per-folder ImageNet tree into packed sequential shards so
distributed training streams large files).

  python -m bigdl_tpu.dataset.imagenet_tools -f ./imagenet/train -o ./shards -p 64
"""
from __future__ import annotations

import argparse
import os

from bigdl_tpu.dataset import shardfile


def generate(folder: str, output: str, n_shards: int = 64,
             has_name: bool = False):
    classes = sorted(d for d in os.listdir(folder)
                     if os.path.isdir(os.path.join(folder, d)))

    def records():
        for li, cls in enumerate(classes):
            d = os.path.join(folder, cls)
            for fn in sorted(os.listdir(d)):
                with open(os.path.join(d, fn), "rb") as f:
                    data = f.read()
                key = f"{li + 1}" if not has_name else f"{li + 1}:{fn}"
                yield (key, data)

    paths = shardfile.write_shards(records(), output, n_shards)
    return paths, len(classes)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-f", "--folder", required=True)
    p.add_argument("-o", "--output", required=True)
    p.add_argument("-p", "--parallel", type=int, default=64,
                   help="number of shards (the reference's parallel count)")
    p.add_argument("--hasName", action="store_true")
    args = p.parse_args(argv)
    paths, n_classes = generate(args.folder, args.output, args.parallel,
                                args.hasName)
    print(f"wrote {len(paths)} shards for {n_classes} classes to {args.output}")


if __name__ == "__main__":
    main()
