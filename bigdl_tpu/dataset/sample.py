"""Record types (ref dataset/Sample.scala:33, Types.scala:27-81).

``Sample`` = (feature, label) numpy pair on host; ``MiniBatch`` = batched
device-ready pair.  Host data stays numpy until batch assembly — only full
minibatches cross to HBM (the reference's analogous rule: records stay in
the RDD until SampleToBatch).
"""
from __future__ import annotations

import numpy as np


class Sample:
    __slots__ = ("feature", "label")

    def __init__(self, feature, label):
        self.feature = np.asarray(feature)
        self.label = np.asarray(label)

    def feature_size(self):
        return self.feature.shape

    def label_size(self):
        return self.label.shape

    def clone(self):
        return Sample(self.feature.copy(), self.label.copy())

    def __eq__(self, other):
        return (isinstance(other, Sample)
                and np.array_equal(self.feature, other.feature)
                and np.array_equal(self.label, other.label))

    def __repr__(self):
        return f"Sample(feature{self.feature.shape}, label{self.label.shape})"


class MiniBatch:
    """(ref Types.scala:74) — ``data`` (B, ...) and ``labels`` (B, ...)."""

    __slots__ = ("data", "labels")

    def __init__(self, data, labels):
        self.data = data
        self.labels = labels

    def size(self):
        return int(self.data.shape[0])

    def __iter__(self):  # tuple-unpack convenience
        yield self.data
        yield self.labels

    def __repr__(self):
        return f"MiniBatch(data{tuple(self.data.shape)}, labels{tuple(self.labels.shape)})"


class ByteRecord:
    """Raw bytes + label (ref Types.scala:81), pre-decode image records."""

    __slots__ = ("data", "label")

    def __init__(self, data: bytes, label: float):
        self.data = data
        self.label = label


class LabeledSentence:
    """Token-id sequence + per-position labels (ref text/Types.scala:33)."""

    __slots__ = ("data", "label")

    def __init__(self, data, label):
        self.data = np.asarray(data)
        self.label = np.asarray(label)

    def data_length(self):
        return len(self.data)

    def label_length(self):
        return len(self.label)
