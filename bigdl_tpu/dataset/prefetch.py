"""Asynchronous host input pipeline: bounded background fetch +
prefetch-to-device (docs/performance.md "host pipeline",
docs/observability.md "host pipeline" spans/events).

The reference hides data loading behind compute — Spark executors
materialize the next partition's mini-batches while the current
super-step trains (MTLabeledBGRImgToBatch + PreFetch) — while our serial
loop ran the whole Transformer chain on the main thread inside the
``data-load`` span.  This module moves that work off the critical path:

- :class:`PipelineRunner` executes the dataset's transformer chain on ONE
  background producer thread feeding a bounded queue.  The producer
  *owns the process seed stream* (``RNG.own_seed_stream``), so shuffle
  permutations and RNG-bearing transforms (random crop/flip/jitter) draw
  the exact values, in the exact order, the serial loop would have drawn
  — the loss trajectory is bit-identical with prefetch on or off
  (asserted by ``tests/test_prefetch.py``).  Pure per-record stages
  (decode, normalize — ``Transformer.pure_per_record``) may additionally
  fan out across a thread pool (``BIGDL_PREFETCH_WORKERS``) with order
  preserved; stochastic stages always stay on the single producer.
- Epoch semantics move WITH the draws: the producer mirrors the
  optimizer's rollover arithmetic (count/reset for single-step,
  count/subtract for chunked dispatch) and performs the epoch-boundary
  ``dataset.shuffle()`` + iterator rebuild itself, so the stream sees the
  identical draw sequence.  The consuming loop only advances its epoch
  counters.
- ``to_device`` adds a second stage: a transfer thread double-buffers
  batches onto the device (the optimizer passes its own
  ``_device_put_batch``, so local, sharded and multi-host layouts all
  overlap H2D with compute).  Its wall time is credited to the ``h2d``
  span via :meth:`PipelineRunner.take_h2d_seconds`.
- Checkpoint/resume: every produced item carries the stream snapshot
  taken right after its draws.  :meth:`rng_snapshot` splices the snapshot
  of the last CONSUMED item with the live device-key counter, so a resume
  replays exactly the batches the interrupted run had consumed — not the
  ones it had merely prefetched.  :meth:`close` restores that state, so a
  finished run leaves the stream exactly where a serial run would.

Flags: ``BIGDL_PREFETCH`` (default on; ``0`` disables, ``N>=2`` sets the
queue depth), ``BIGDL_SYNC_EVERY_STEP=1`` (escape hatch: the training
loops also sync the loss every step, for debugging/chaos drills),
``BIGDL_PREFETCH_WORKERS`` (pure-stage fan-out width, default 0).

Chaos: the optimizers do NOT hand ``to_device`` to the runner while a
``FaultInjector`` is installed — batches then stay on host until consume
time so ``_chaos_prestep`` keys every site by the *consuming* step and
``BIGDL_FAULTS`` drills are unchanged (docs/resilience.md).
"""
from __future__ import annotations

import logging
import os
import queue
import threading
import time
from collections import deque

import numpy as np

from bigdl_tpu.utils.random import RNG

logger = logging.getLogger("bigdl_tpu.dataset")

ENV_PREFETCH = "BIGDL_PREFETCH"
ENV_SYNC_EVERY_STEP = "BIGDL_SYNC_EVERY_STEP"
ENV_WORKERS = "BIGDL_PREFETCH_WORKERS"

DEFAULT_DEPTH = 2


def enabled() -> bool:
    """Master switch: ``BIGDL_PREFETCH`` (default on)."""
    return os.environ.get(ENV_PREFETCH, "1").strip() != "0"


def depth() -> int:
    """Queue depth per stage.  ``BIGDL_PREFETCH=N`` with N >= 2 sets the
    depth; any other truthy value keeps the default double-buffer."""
    raw = os.environ.get(ENV_PREFETCH, "").strip()
    try:
        n = int(raw)
    except ValueError:
        return DEFAULT_DEPTH
    return n if n >= 2 else DEFAULT_DEPTH


def sync_every_step() -> bool:
    """``BIGDL_SYNC_EVERY_STEP=1``: the loops materialize loss/finite on
    the host every iteration (the pre-cadence behavior)."""
    return os.environ.get(ENV_SYNC_EVERY_STEP, "0").strip() == "1"


def workers() -> int:
    try:
        return max(0, int(os.environ.get(ENV_WORKERS, "0")))
    except ValueError:
        return 0


def stack_chunk(batches):
    """Stack n uniform-shape MiniBatches into (n, B, ...) host arrays.

    Each batch is converted ONCE — the converted arrays serve both the
    shape check and the stack (the old ``_next_chunk`` converted every
    batch twice: ``np.asarray`` for the check, ``np.stack`` again)."""
    xs = [np.asarray(b.data) for b in batches]
    ys = [np.asarray(b.labels) for b in batches]
    shapes = {a.shape for a in xs}
    if len(shapes) != 1:
        raise ValueError(
            "iterations_per_dispatch needs uniform batch shapes "
            f"within a chunk, got {shapes}")
    return np.stack(xs), np.stack(ys)


def background(iterator, depth: int = DEFAULT_DEPTH):
    """Plain bounded background prefetch of an iterator (no RNG
    ownership, no epoch machinery) — what validation batches ride."""
    from bigdl_tpu.dataset.transformer import PreFetch
    return PreFetch(depth)(iterator)


def has_stochastic_stage(dataset) -> bool:
    """True when the dataset's transformer chain contains an RNG-bearing
    stage.  ``validate`` keeps such (unconventional) eval pipelines on
    the calling thread instead of a background one, so their draws at
    least come from a deterministic per-thread stream rather than a
    fresh derived stream per validation pass."""
    return any(getattr(s, "stochastic", False)
               for s in _decompose(dataset)[1])


class Item:
    """One produced batch: host arrays, optional device arrays, the
    stream snapshot taken after its draws, and fetch-side telemetry."""

    __slots__ = ("x", "y", "device", "rng", "seq", "fetch_wall",
                 "queue_depth")

    def __init__(self, x, y, rng=None, seq=0, fetch_wall=0.0):
        self.x = x
        self.y = y
        self.device = None
        self.rng = rng
        self.seq = seq
        self.fetch_wall = fetch_wall
        self.queue_depth = 0


class _End:
    pass


class _Error:
    # private wrapper so a pipeline legitimately yielding exception
    # objects as data is never confused with a worker failure
    def __init__(self, exc):
        self.exc = exc


_END = _End()


def _decompose(dataset):
    """Peel a TransformedDataSet chain into (base_dataset, [stages]),
    flattening ChainedTransformer trees into stage order."""
    from bigdl_tpu.dataset.dataset import TransformedDataSet
    from bigdl_tpu.dataset.transformer import ChainedTransformer

    def flatten(t):
        if isinstance(t, ChainedTransformer):
            return flatten(t.first) + flatten(t.last)
        return [t]

    stages = []
    while isinstance(dataset, TransformedDataSet):
        stages = flatten(dataset.transformer) + stages
        dataset = dataset.base
    return dataset, stages


def _is_pure_map(stage) -> bool:
    """A stage eligible for worker fan-out: declared 1-to-1 per record
    (``pure_per_record``) and free of RNG draws (not ``stochastic``)."""
    return bool(getattr(stage, "pure_per_record", False)) and \
        not bool(getattr(stage, "stochastic", False))


class PipelineRunner:
    """Bounded background input pipeline over one dataset.

    ``chunk > 1`` assembles stacked (n, B, ...) chunks for the device-side
    scanned loop (``set_iterations_per_dispatch``).  ``epoch_size``
    enables producer-side epoch rollover (training); ``records_scale``
    converts a local host batch to the GLOBAL record count the consuming
    loop's epoch arithmetic uses (multi-host data sharding).

    ``to_device(xh, yh) -> (x, y)`` arms the second stage: a transfer
    thread that double-buffers batches onto the device ahead of
    consumption.  ``own_rng`` (default: ``train``) moves the process seed
    stream onto the producer — see the module docstring.
    """

    def __init__(self, dataset, *, train: bool = True, chunk: int = 1,
                 epoch_size: int | None = None, depth: int | None = None,
                 to_device=None, records_scale: int = 1,
                 own_rng: bool | None = None, n_workers: int | None = None):
        self._dataset = dataset
        self._train = train
        self._chunk = max(1, int(chunk))
        self._epoch_size = int(epoch_size) if epoch_size else None
        self.depth = int(depth) if depth else globals()["depth"]()
        self._records_scale = max(1, int(records_scale))
        self._own_rng = train if own_rng is None else bool(own_rng)
        self._n_workers = workers() if n_workers is None else int(n_workers)
        self._to_device = to_device

        self._host_q = queue.Queue(maxsize=self.depth)
        self._out_q = (self._host_q if to_device is None
                       else queue.Queue(maxsize=self.depth))
        self._stop = threading.Event()
        self._pause = threading.Event()
        # held by the producer for the whole of one draw (transform chain
        # + epoch rollover); pause() acquires it to wait out an in-flight
        # draw — an Event-flag handshake alone would race (the producer
        # could pass the pause check right before the flag is set)
        self._work_lock = threading.Lock()
        self._stats_lock = threading.Lock()
        self._closed = False
        self._count = 0          # records into the current epoch
        self._pool = None
        self._split = None       # (base, pure_prefix, rest) when fanning out
        if self._train and self._n_workers > 0:
            base, stages = _decompose(dataset)
            prefix = []
            i = 0
            while i < len(stages) and _is_pure_map(stages[i]):
                prefix.append(stages[i])
                i += 1
            # records per base-iterator cycle: the looped iterator draws
            # its shuffle permutation at each cycle start, so the
            # fan-out window must drain before crossing a boundary or
            # that draw lands early in the stream.  Only the list-backed
            # datasets have a knowable cycle (ShardedDataSet loops its
            # LOCAL shard — size() would be the global count; streaming
            # sets like ShardFolder reshuffle on their own schedule):
            # everything else keeps the single producer, preserving the
            # bit-parity guarantee over a fan-out speedup.
            from bigdl_tpu.dataset.dataset import (LocalArrayDataSet,
                                                   ShardedDataSet)
            if isinstance(base, ShardedDataSet):
                cycle = base.shard_size()
            elif isinstance(base, LocalArrayDataSet):
                cycle = base.size()
            else:
                cycle = None
                if prefix:
                    logger.info(
                        "prefetch worker fan-out disabled: %s has no "
                        "knowable shuffle-cycle length, so read-ahead "
                        "could reorder its RNG draws",
                        type(base).__name__)
            if prefix and cycle:
                from concurrent.futures import ThreadPoolExecutor
                self._pool = ThreadPoolExecutor(
                    max_workers=self._n_workers,
                    thread_name_prefix="bigdl-prefetch-worker")
                self._cycle = cycle
                self._split = (base, prefix, stages[i:])

        # telemetry drained by the consuming loop
        self.consumed = 0
        self.produced = 0
        self.epochs_rolled = 0
        self.stall_seconds = 0.0
        self._h2d_seconds = 0.0
        self._h2d_count = 0
        self._fetch_seconds = 0.0
        self._fetch_count = 0

        self._start_snap = RNG.snapshot() if self._own_rng else None
        self._last_rng = None    # snapshot of the last CONSUMED item

        self._producer = threading.Thread(
            target=self._produce, daemon=True,
            name="bigdl-prefetch-producer")
        self._transfer = None
        if to_device is not None:
            self._transfer = threading.Thread(
                target=self._transfer_loop, daemon=True,
                name="bigdl-prefetch-h2d")
        self._producer.start()
        if self._transfer is not None:
            self._transfer.start()

    # -- producer side -----------------------------------------------------
    def _make_iter(self):
        if self._split is None:
            return self._dataset.data(train=self._train)
        base, prefix, rest = self._split
        it = self._parallel_map(base.data(train=self._train), prefix)
        for stage in rest:
            it = stage(it)
        return it

    def _parallel_map(self, records, prefix):
        """Ordered fan-out of the pure per-record stage prefix across the
        worker pool (a bounded window of in-flight futures)."""
        pool, window = self._pool, self._n_workers * 2

        def apply(rec):
            out = rec
            for stage in prefix:
                res = list(stage(iter([out])))
                if len(res) != 1:
                    raise ValueError(
                        f"{type(stage).__name__} declared pure_per_record "
                        f"but produced {len(res)} records from 1")
                out = res[0]
            return out

        cycle = self._cycle if self._train else None

        def gen():
            """Bounded in-flight window, record order preserved.  The
            stream's draw interleaving must match the serial chain:
            stochastic downstream stages draw per YIELDED record, and
            pulling the base iterator across a cycle boundary draws the
            next shuffle permutation — so the window drains fully before
            the first pull of a new cycle."""
            futs = deque()
            pulled = 0
            it = iter(records)
            while True:
                if cycle and pulled and pulled % cycle == 0 and futs:
                    while futs:
                        yield futs.popleft().result()
                try:
                    rec = next(it)
                except StopIteration:
                    break
                futs.append(pool.submit(apply, rec))
                pulled += 1
                if len(futs) >= window:
                    yield futs.popleft().result()
            while futs:
                yield futs.popleft().result()

        return gen()

    def _advance_epoch(self, records: int):
        """Mirror of the optimizers' ``_advance_epochs`` arithmetic, run
        at PRODUCE time so the epoch-boundary shuffle + permutation draws
        land at the same point of the stream as in the serial loop."""
        if not self._epoch_size or not self._train:
            return
        self._count += records
        if self._chunk <= 1:
            if self._count >= self._epoch_size:
                self._count = 0
                self._rollover()
        else:
            while self._count >= self._epoch_size:
                self._count -= self._epoch_size
                self._rollover()

    def _rollover(self):
        self._dataset.shuffle()
        self._it = self._make_iter()
        self.epochs_rolled += 1

    def _produce(self):
        try:
            if self._own_rng:
                RNG.own_seed_stream()
            self._it = self._make_iter()
            seq = 0
            while not self._stop.is_set():
                if self._pause.is_set():
                    time.sleep(0.002)
                    continue
                with self._work_lock:
                    if self._pause.is_set():  # re-check under the lock
                        continue
                    t0 = time.perf_counter()
                    if self._chunk <= 1:
                        try:
                            b = next(self._it)
                        except StopIteration:
                            self._put(self._host_q, _END)
                            return
                        x, y = b.data, b.labels
                        records = int(np.asarray(x).shape[0])
                    else:
                        x, y = stack_chunk(
                            [next(self._it) for _ in range(self._chunk)])
                        records = int(x.shape[0] * x.shape[1])
                    self._advance_epoch(records * self._records_scale)
                    snap = RNG.snapshot() if self._own_rng else None
                    wall = time.perf_counter() - t0
                item = Item(x, y, rng=snap, seq=seq, fetch_wall=wall)
                with self._stats_lock:
                    self._fetch_seconds += wall
                    self._fetch_count += 1
                if not self._put(self._host_q, item):
                    return
                self.produced += 1
                seq += 1
        except BaseException as e:  # surface on the consumer thread
            self._put(self._host_q, _Error(e))

    def _put(self, q, item) -> bool:
        """Bounded put that gives up once the consumer is gone, so an
        abandoned runner never leaves its threads blocked forever."""
        while not self._stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _transfer_loop(self):
        while not self._stop.is_set():
            try:
                item = self._host_q.get(timeout=0.1)
            except queue.Empty:
                continue
            if isinstance(item, (_End, _Error)):
                self._put(self._out_q, item)
                return
            try:
                t0 = time.perf_counter()
                item.device = self._to_device(item.x, item.y)
                dt = time.perf_counter() - t0
                with self._stats_lock:
                    self._h2d_seconds += dt
                    self._h2d_count += 1
            except BaseException as e:
                self._put(self._out_q, _Error(e))
                return
            if not self._put(self._out_q, item):
                return

    # -- consumer side -----------------------------------------------------
    def get(self):
        """Next item, blocking.  Returns ``(item, waited_seconds)``;
        raises StopIteration when a one-pass (eval) stream is exhausted
        and re-raises any producer/transfer failure."""
        t0 = time.perf_counter()
        item = self._out_q.get()
        waited = time.perf_counter() - t0
        if isinstance(item, _End):
            raise StopIteration
        if isinstance(item, _Error):
            raise item.exc
        self.stall_seconds += waited
        self.consumed += 1
        item.queue_depth = self._out_q.qsize()
        if item.rng is not None:
            self._last_rng = item.rng
        return item, waited

    def __iter__(self):
        while True:
            try:
                yield self.get()[0]
            except StopIteration:
                return

    def take_h2d(self):
        """Drain the transfer thread's accumulated (seconds, batches) —
        credited to the ``h2d`` span by the consuming loop."""
        with self._stats_lock:
            out = (self._h2d_seconds, self._h2d_count)
            self._h2d_seconds, self._h2d_count = 0.0, 0
        return out

    def take_fetch(self):
        """Drain the producer's accumulated (seconds, batches) of
        transform-chain wall — the ``data-load/fetch`` span."""
        with self._stats_lock:
            out = (self._fetch_seconds, self._fetch_count)
            self._fetch_seconds, self._fetch_count = 0.0, 0
        return out

    def rng_snapshot(self) -> dict:
        """Host-stream state as of the last CONSUMED batch, with the
        LIVE device-key counter spliced in — the checkpoint payload that
        makes a resumed run replay the serial trajectory (keys are
        minted at consume time on the loop thread, np draws at fetch
        time on the producer)."""
        base = self._last_rng or self._start_snap
        if base is None:
            return RNG.snapshot()
        snap = dict(base)
        snap["key_counter"] = RNG.key_counter()
        return snap

    def pause(self):
        """Hold the producer before its next draw (validation borrows the
        dataset's backing store; an epoch shuffle must not interleave).
        Acquiring the work lock waits out a draw already in flight."""
        self._pause.set()
        with self._work_lock:
            pass
        return self

    def resume(self):
        self._pause.clear()
        return self

    def close(self, restore_rng: bool = True):
        """Stop both threads, then (training runners) hand the seed
        stream back to the calling thread restored to the last-consumed
        state — erasing the ahead-draws of merely-prefetched batches so
        the process RNG ends exactly where a serial run would."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        for q in {id(self._host_q): self._host_q,
                  id(self._out_q): self._out_q}.values():
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
        self._producer.join(timeout=5.0)
        if self._transfer is not None:
            self._transfer.join(timeout=5.0)
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        if self._producer.is_alive():  # pragma: no cover - defensive
            logger.warning("prefetch producer did not stop within 5s")
        if self._own_rng and restore_rng:
            RNG.restore(self.rng_snapshot())
