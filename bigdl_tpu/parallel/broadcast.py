"""Model/parameter broadcast (ref models/utils/ModelBroadcast.scala:33).

The reference broadcasts model structure and flattened weights separately
to cut Spark broadcast time.  On TPU, "broadcast" = placing a replicated
``NamedSharding`` on the params pytree: XLA materializes one copy per
device over ICI.  For multi-host, ``broadcast_from_host0`` makes every
process agree on host 0's values (the driver->executor broadcast role).
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def replicate_to_mesh(params, mesh: Mesh):
    """Place every leaf replicated across the mesh (ICI broadcast)."""
    sharding = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda v: jax.device_put(v, sharding), params)


def broadcast_from_host0(params):
    """Multi-host: all processes take process 0's values.

    Uses a psum over a trivial mesh where only process 0 contributes —
    the standard multihost broadcast; no-op with one process."""
    if jax.process_count() == 1:
        return params
    from jax.experimental import multihost_utils
    return multihost_utils.broadcast_one_to_all(params)


def model_broadcast(model, mesh: Mesh):
    """Broadcast a module's parameters to every device of the mesh and
    load them back (the ModelBroadcast.value() role)."""
    params = broadcast_from_host0(model.params())
    model.load_params(replicate_to_mesh(params, mesh))
    return model
