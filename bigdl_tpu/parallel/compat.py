"""jax API compatibility shims for the parallel layer.

``shard_map`` graduated from ``jax.experimental.shard_map`` to
``jax.shard_map`` (and its ``check_rep`` flag became ``check_vma``); the
baked toolchains this framework runs on span both sides of that move.
Every internal call site routes through here so the rest of the codebase
is written against the new spelling only.
"""
from __future__ import annotations

import jax


def shard_map(f, mesh=None, in_specs=None, out_specs=None,
              check_vma=None, **kwargs):
    """``jax.shard_map`` when this jax has it, else the experimental one
    with ``check_vma`` translated to its old ``check_rep`` name."""
    if hasattr(jax, "shard_map"):
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kwargs)
    from jax.experimental.shard_map import shard_map as _shard_map
    # the old check_rep inferencer predates pvary/vma marks and raises
    # false positives on ring/pipeline carries written for the new
    # checker — off unless explicitly requested
    kwargs["check_rep"] = bool(check_vma) if check_vma is not None else False
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kwargs)


def typeof(x):
    """``jax.typeof`` (new) or ``jax.core.get_aval`` (old).  Call sites
    only probe optional attrs (``vma``) via getattr-with-default, so the
    old aval — which lacks them — degrades exactly like the new API's
    no-varying-axes case."""
    if hasattr(jax, "typeof"):
        return jax.typeof(x)
    from jax import core
    return core.get_aval(x)


def axis_size(axis_name):
    """Static size of a named mesh axis from inside shard_map.
    ``lax.axis_size`` is the new spelling; the old idiom ``psum(1, axis)``
    constant-folds to the same static int on older jax."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def grad_psum_is_explicit():
    """True when this jax's shard_map AD does NOT auto-psum cotangents
    of replicated operands — the old ``jax.experimental.shard_map``
    path, which this compat layer runs with ``check_rep=False`` (the
    flag that also carried the efficient-transpose rewrite).  Callers
    that accumulate parameter gradients against data-replicated params
    inside shard_map must then reduce the accumulator over the data
    axis themselves; on new jax the vjp already delivers the
    cross-replica sum and an extra psum would double-count."""
    return not hasattr(jax, "shard_map")
