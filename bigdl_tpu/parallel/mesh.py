"""Device-mesh construction.

Replaces the reference's cluster-topology bookkeeping (Engine.nodeNumber /
partition-per-node, Engine.scala:254) with ``jax.sharding.Mesh`` axes:

- ``data``  — data parallelism (the reference's only inter-node axis),
- ``model`` — tensor parallelism (absent in the reference, SURVEY.md §2.9),
- ``seq``   — sequence/context parallelism for ring attention.

Collectives over ``data``/``model`` within a slice ride ICI; multi-slice
spans DCN.  Axis sizes multiply to the device count.
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh


def make_mesh(axis_sizes: dict, devices=None) -> Mesh:
    """Build a mesh from ``{axis_name: size}``; a single ``-1`` size is
    inferred from the device count."""
    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices)
    names = tuple(axis_sizes.keys())
    sizes = list(axis_sizes.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = len(devices) // known
    total = int(np.prod(sizes))
    if total != len(devices):
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total} devices, "
                         f"have {len(devices)}")
    return Mesh(devices.reshape(sizes), names)


def data_parallel_mesh(devices=None) -> Mesh:
    """Pure-DP mesh — the reference's DistriOptimizer topology."""
    return make_mesh({"data": -1}, devices)


def hybrid_mesh(dp: int = -1, mp: int = 1, devices=None) -> Mesh:
    """(data, model) mesh for DP x TP hybrid sharding."""
    return make_mesh({"data": dp, "model": mp}, devices)
