"""Ring attention — sequence-parallel exact attention over a mesh axis.

The reference's only sequence machinery is a serial truncated-BPTT loop
(Recurrent.scala, SURVEY.md §5.7 — no attention, no context parallelism).
For a TPU-native framework, long-context is first-class: this module
implements blockwise ring attention (Liu et al. ring-attention pattern):

- Q/K/V are sharded over a ``seq`` mesh axis: each device holds a
  contiguous sequence block of length T/P.
- Each device computes blockwise attention against its local K/V block,
  then rotates K/V around the ring with ``lax.ppermute`` (P-1 hops over
  ICI), maintaining a numerically-stable online softmax (running max m and
  normalizer l), so the full T x T attention is exact while HBM holds only
  T/P-sized blocks and communication overlaps compute around the ring.

``ring_attention`` is the shard_map-able collective function;
``ring_self_attention`` wraps it under a Mesh for (B, T, H, D) inputs.
Causal masking uses global block offsets derived from ``axis_index``.
"""
from __future__ import annotations

from functools import partial

import jax

from bigdl_tpu.parallel.compat import typeof as _compat_typeof

from bigdl_tpu.parallel.compat import shard_map
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.parallel.collectives import pvary
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _block_attn(q, k, v, q_off, k_off, causal, scale):
    """Scores for one (q-block, k-block) pair with online-softmax stats.

    q: (B, Tq, H, D), k/v: (B, Tk, H, D).  Returns (s_max, p_sum, pv)
    where p = exp(s - s_max) and masking is applied pre-softmax.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        qi = q_off + jnp.arange(tq)[:, None]
        ki = k_off + jnp.arange(tk)[None, :]
        s = jnp.where(qi >= ki, s, -jnp.inf)
    # the running max is a numerical shift only — softmax is invariant to
    # it, so it must be fully non-differentiable or the shift's gradient
    # paths (here vs the alpha/beta rescales in the ring step) would have
    # to cancel exactly; stop_gradient everywhere makes the grad exact
    m = lax.stop_gradient(s.max(axis=-1))       # (B, H, Tq)
    p = jnp.exp(s - m[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)      # fully-masked rows stay 0
    l = p.sum(axis=-1)                          # (B, H, Tq)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    return m, l, pv


def ring_attention(q, k, v, axis_name: str = "seq", causal: bool = False):
    """Collective ring attention: call inside shard_map with q/k/v sequence-
    sharded over ``axis_name``.  Shapes per device: (B, T_local, H, D)."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    from bigdl_tpu.parallel.compat import axis_size as _axis_size
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    t_local = q.shape[1]
    q_off = idx * t_local

    fwd = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, hop):
        k_blk, v_blk, m, l, o = carry
        # k-block currently held came from rank (idx - hop) mod n
        src = (idx - hop) % n
        bm, bl, bpv = _block_attn(q, k_blk, v_blk, q_off, src * t_local,
                                  causal, scale)
        m_new = jnp.maximum(m, bm)
        alpha = jnp.exp(m - m_new)          # rescale old accumulators
        beta = jnp.exp(bm - m_new)          # rescale new block
        l = l * alpha + bl * beta
        o = o * alpha.transpose(0, 2, 1)[..., None] \
            + bpv * beta.transpose(0, 2, 1)[..., None]
        k_blk = lax.ppermute(k_blk, axis_name, fwd)
        v_blk = lax.ppermute(v_blk, axis_name, fwd)
        return (k_blk, v_blk, m_new, l, o), None

    b, _, h, d = q.shape
    # pvary: initial accumulators must carry the same varying type as the
    # operands (the ring axis, plus a batch axis under hybrid dp x sp)
    vary_axes = tuple(getattr(_compat_typeof(q), "vma", None) or (axis_name,))
    m0 = pvary(jnp.full((b, h, t_local), -jnp.inf, jnp.float32), vary_axes)
    l0 = pvary(jnp.zeros((b, h, t_local), jnp.float32), vary_axes)
    o0 = pvary(jnp.zeros((b, t_local, h, d), jnp.float32), vary_axes)
    (k_f, v_f, m, l, o), _ = lax.scan(
        step, (k, v, m0, l0, o0), jnp.arange(n))
    l = jnp.maximum(l, 1e-20)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_self_attention(q, k, v, mesh: Mesh, axis_name: str = "seq",
                        causal: bool = False, batch_axis: str = None):
    """Host-level wrapper: shard (B, T, H, D) over ``axis_name`` and run the
    ring.  The jitted result composes with surrounding pjit computation.

    ``batch_axis``: also shard the batch dim (hybrid dp x sp) — each
    data-parallel group runs its own seq ring; without it a mesh that
    HAS a data axis would replicate (all-gather) the batch into every
    data slice."""
    spec = P(batch_axis, axis_name)
    f = shard_map(
        partial(ring_attention, axis_name=axis_name, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return f(q, k, v)


def full_attention(q, k, v, causal: bool = False):
    """Single-device reference implementation (for tests)."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
