"""Collective primitives over mesh axes.

The TPU-native equivalent of the reference's hand-built collective on Spark
BlockManager (parameters/AllReduceParameter.scala, SURVEY.md §2.5): its
putGradients+aggregrateGradientPartition = reduce-scatter, its
sendWeightPartition+getWeights = all-gather.  Here each is one XLA op over
ICI.  For use inside ``shard_map``-ped functions.
"""
from __future__ import annotations

import jax
from jax import lax


def all_reduce(x, axis_name: str = "data"):
    """Sum across the axis (= the reference's full AllReduceParameter cycle)."""
    return lax.psum(x, axis_name)


def all_reduce_mean(x, axis_name: str = "data"):
    return lax.pmean(x, axis_name)


def reduce_scatter(x, axis_name: str = "data", scatter_dimension: int = 0,
                   tiled: bool = True):
    """Sum + shard: each participant keeps its slice
    (= putGradients + aggregrateGradientPartition, AllReduceParameter.scala:202/162)."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dimension,
                            tiled=tiled)


def all_gather(x, axis_name: str = "data", axis: int = 0, tiled: bool = True):
    """Collect every participant's slice
    (= sendWeightPartition + getWeights, AllReduceParameter.scala:218/135)."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def ppermute(x, axis_name: str, perm):
    """Point-to-point ring shifts (building block of ring attention)."""
    return lax.ppermute(x, axis_name, perm)


def pvary(x, axis_names):
    """Mark ``x`` device-varying over ``axis_names`` (shard_map scan carries
    must keep a consistent varying type).  ``lax.pvary`` is deprecated in
    jax>=0.9 in favor of ``lax.pcast(..., to='varying')``."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axis_names, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, axis_names)
    return x  # pre-vma jax (check_rep model): nothing to mark


def ring_shift(x, axis_name: str, shift: int = 1):
    """Shift values around the axis ring by ``shift`` positions."""
    from bigdl_tpu.parallel.compat import axis_size as _axis_size
    n = _axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name: str, split_axis: int, concat_axis: int,
               tiled: bool = True):
    """Ulysses-style sequence<->head reshard primitive."""
    return lax.all_to_all(x, axis_name, split_axis, concat_axis, tiled=tiled)


def axis_index(axis_name: str):
    return lax.axis_index(axis_name)


def axis_size(axis_name: str):
    from bigdl_tpu.parallel.compat import axis_size as _axis_size
    return _axis_size(axis_name)
