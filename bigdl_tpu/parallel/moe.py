"""Expert parallelism — distributed mixture-of-experts over a mesh axis.

The reference's ``MixtureTable`` (nn/MixtureTable.scala:221) is a
single-device soft mixture; distributed EP (experts sharded across chips,
tokens routed with all-to-all over ICI) is absent (SURVEY.md §2.9).  This
module provides both pieces TPU-first:

- ``top1_gating``: softmax router with capacity-bounded top-1 dispatch
  (tokens over capacity are dropped, combine weights renormalized);
- ``moe_apply``: shard_map'd expert layer — each rank holds ``experts/P``
  expert MLPs; dispatched tokens travel rank->rank with ``lax.all_to_all``
  (the EP all-to-all), experts run batched on the MXU, results return with
  the inverse all-to-all and are combined by gate weight.

Dense-dispatch formulation (one-hot matmuls) keeps shapes static for XLA.
"""
from __future__ import annotations

from functools import partial

import jax

from bigdl_tpu.parallel.compat import shard_map
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def expert_capacity(n_tokens: int, n_experts: int,
                    capacity_factor: float) -> int:
    """Per-expert token capacity — ONE formula shared by every MoE front
    door (moe_apply, moe_apply_sharded_tokens, nn.MoE), so the same
    capacity_factor drops the same tokens everywhere."""
    return max(int(capacity_factor * n_tokens / n_experts), 1)


def top1_gating(logits, n_experts: int, capacity: int):
    """logits: (T, E). Returns (dispatch (T, E, C) one-hot, combine
    (T, E, C) weights): token t goes to expert e at slot c."""
    gates = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(gates, axis=-1)             # (T,)
    gate_val = jnp.max(gates, axis=-1)                  # (T,)
    onehot = jax.nn.one_hot(expert_idx, n_experts)      # (T, E)
    # position of each token within its expert's queue
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot   # (T, E)
    in_cap = (pos < capacity) & (onehot > 0)
    slot = jnp.asarray(pos, jnp.int32)
    dispatch = (jax.nn.one_hot(slot, capacity) *
                in_cap[..., None].astype(jnp.float32))  # (T, E, C)
    combine = dispatch * gate_val[:, None, None]
    return dispatch, combine


def moe_apply(router_w, expert_w1, expert_b1, expert_w2, expert_b2, x,
              mesh: Mesh, axis: str = "expert", capacity_factor: float = 1.25):
    """Distributed top-1 MoE FFN.

    x: (T, D) tokens (replicated across the expert axis for routing; the
       data axis, if any, composes outside).
    expert_w1: (E, D, H), expert_b1: (E, H), expert_w2: (E, H, D),
    expert_b2: (E, D) — sharded over ``axis`` on dim 0.
    Returns (T, D).
    """
    n_expert = expert_w1.shape[0]
    n_rank = mesh.shape[axis]
    assert n_expert % n_rank == 0
    e_local = n_expert // n_rank
    t = x.shape[0]
    capacity = expert_capacity(t, n_expert, capacity_factor)

    def ranked(router_w, w1, b1, w2, b2, x):
        logits = x @ router_w                           # (T, E)
        dispatch, combine = top1_gating(logits, n_expert, capacity)
        # gather expert inputs: (E, C, D); every rank computes the full
        # dispatch (router replicated) then keeps its local experts
        expert_in = jnp.einsum("td,tec->ecd", x, dispatch)
        # reshape to (n_rank, e_local, C, D) and all-to-all is unnecessary
        # here because x is replicated across the axis — each rank slices
        # its experts directly (the all-to-all formulation matters when
        # tokens are data-sharded; see moe_apply_sharded_tokens)
        rank = lax.axis_index(axis)
        local_in = lax.dynamic_slice_in_dim(expert_in, rank * e_local,
                                            e_local, axis=0)  # (e_local, C, D)
        h = jax.nn.relu(jnp.einsum("ecd,edh->ech", local_in, w1) + b1[:, None])
        local_out = jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None]  # (e_local, C, D)
        # scatter back: all experts' outputs = all_gather over the axis
        all_out = lax.all_gather(local_out, axis, axis=0, tiled=True)  # (E, C, D)
        return jnp.einsum("ecd,tec->td", all_out, combine)

    pspec_e = P(axis)
    f = shard_map(
        ranked, mesh=mesh,
        in_specs=(P(), pspec_e, pspec_e, pspec_e, pspec_e, P()),
        out_specs=P(), check_vma=False)  # replication holds post-all_gather
    return f(router_w, expert_w1, expert_b1, expert_w2, expert_b2, x)


def moe_apply_sharded_tokens(router_w, expert_w1, expert_b1, expert_w2,
                             expert_b2, x, mesh: Mesh,
                             data_axis: str = "data",
                             expert_axis: str = "expert",
                             capacity_factor: float = 1.25):
    """MoE with tokens sharded over ``data_axis`` AND experts over
    ``expert_axis``: the full EP pattern — local routing, then
    ``all_to_all`` over the expert axis carries each rank's dispatched
    tokens to the expert owners and back."""
    n_expert = expert_w1.shape[0]
    n_rank = mesh.shape[expert_axis]
    e_local = n_expert // n_rank

    def ranked(router_w, w1, b1, w2, b2, x_local):
        t_local = x_local.shape[0]
        capacity = expert_capacity(t_local, n_expert, capacity_factor)
        logits = x_local @ router_w
        dispatch, combine = top1_gating(logits, n_expert, capacity)
        expert_in = jnp.einsum("td,tec->ecd", x_local, dispatch)  # (E, C, D)
        # (n_rank, e_local, C, D) --all_to_all--> each rank receives the
        # chunks destined for ITS experts from every peer:
        # result (n_rank_src, e_local, C, D)
        grouped = expert_in.reshape(n_rank, e_local, capacity, -1)
        received = lax.all_to_all(grouped, expert_axis, split_axis=0,
                                  concat_axis=0, tiled=False)
        recv = received.reshape(n_rank * e_local, capacity, -1)  # src-major
        h = jax.nn.relu(jnp.einsum("scd,edh->sch",
                                   recv.reshape(n_rank, e_local, capacity, -1)
                                   .transpose(1, 0, 2, 3)
                                   .reshape(e_local, n_rank * capacity, -1),
                                   w1) + b1[:, None])
        out = jnp.einsum("sch,ehd->scd", h, w2) + b2[:, None]
        # undo: (e_local, n_rank*C, D) -> (n_rank, e_local, C, D) -> a2a back
        back = (out.reshape(e_local, n_rank, capacity, -1)
                .transpose(1, 0, 2, 3))
        returned = lax.all_to_all(back, expert_axis, split_axis=0,
                                  concat_axis=0, tiled=False)
        expert_out = returned.reshape(n_expert, capacity, -1)
        return jnp.einsum("ecd,tec->td", expert_out, combine)

    pspec_e = P(expert_axis)
    f = shard_map(
        ranked, mesh=mesh,
        in_specs=(P(), pspec_e, pspec_e, pspec_e, pspec_e, P(data_axis)),
        out_specs=P(data_axis), check_vma=False)
    return f(router_w, expert_w1, expert_b1, expert_w2, expert_b2, x)
