"""Sharding rules for parameters and batches.

The reference shards the flat parameter vector across Spark partitions
(AllReduceParameter.init :100-117); here sharding is per-tensor
``NamedSharding`` over the mesh, chosen by rule:

- default: replicate params, shard batch dim over ``data``;
- ``shard_params_rule``: tensor-parallel layout for Linear/Conv weights over
  the ``model`` axis (row/col split by tensor rank), the hybrid layout the
  dryrun exercises;
- optimizer-state sharding (the ZeRO-1 analogue of the reference's
  owner-partition update, DistriOptimizer.scala:232 "update on MY slice
  only") via ``zero1_rule``.
"""
from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def replicated(mesh: Mesh):
    return NamedSharding(mesh, P())


def batch_sharded(mesh: Mesh, axis: str = "data", ndim: int = None):
    """Shard dim 0 (batch) over ``axis``, replicate the rest."""
    return NamedSharding(mesh, P(axis))


def constrain(x, mesh: Mesh, spec: P):
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def shard_params_rule(mesh: Mesh, model_axis: str = "model"):
    """Pytree-mapped rule: 2D weights (out, in) split ``out`` over the model
    axis; 4D conv kernels (O, I, H, W) split ``O``; 1D (bias) replicated.
    Returns a fn param_array -> NamedSharding."""
    if model_axis not in mesh.axis_names or mesh.shape[model_axis] == 1:
        return lambda x: NamedSharding(mesh, P())
    size = mesh.shape[model_axis]

    def rule(x):
        if x.ndim >= 2 and x.shape[0] % size == 0:
            return NamedSharding(mesh, P(model_axis))
        return NamedSharding(mesh, P())

    return rule


def zero1_rule(mesh: Mesh, data_axis: str = "data"):
    """Shard optimizer-state leaves (velocity/variance mirrors of params)
    over the data axis where divisible — ZeRO-1: each data-parallel rank
    owns the update state for its parameter slice."""
    size = mesh.shape[data_axis]

    def rule(x):
        if x.ndim >= 1 and x.shape[0] % size == 0:
            return NamedSharding(mesh, P(data_axis))
        return NamedSharding(mesh, P())

    return rule


def zero1_tp_rule(mesh: Mesh, data_axis: str = "data",
                  model_axis: str = "model"):
    """ZeRO-1 composed with tensor parallelism: optimizer-state leaves keep
    the TP layout of their parameter (dim 0 over ``model`` where eligible)
    and are additionally sharded over ``data`` — dim 1 for TP'd leaves,
    dim 0 otherwise — where divisible."""
    tp = shard_params_rule(mesh, model_axis)
    dsize = mesh.shape[data_axis]

    def rule(x):
        s = tp(x)
        if len(s.spec) and s.spec[0] == model_axis:
            if x.ndim >= 2 and x.shape[1] % dsize == 0:
                return NamedSharding(mesh, P(model_axis, data_axis))
            return s
        if x.ndim >= 1 and x.shape[0] % dsize == 0:
            return NamedSharding(mesh, P(data_axis))
        return s

    return rule
