"""Pipeline parallelism — GPipe-style microbatch pipelining over a mesh axis.

Absent in the reference (SURVEY.md §2.9: PP = NO); first-class here because
pipeline schedules are a core TPU scaling strategy when a model exceeds one
chip's HBM.

Design (the shard_map ring formulation):
- the repeated-block model is expressed as ONE stage function applied P
  times (scan-over-layers), with each pipeline rank holding its stage's
  parameters (stacked pytree sharded on the ``pipe`` axis, leading dim P);
- microbatches stream through ranks with ``ppermute`` hops: at tick t,
  rank r computes its stage on the activation it received at t-1 and
  forwards the result around the ring — the classic GPipe fill/steady/drain
  schedule, total ticks = n_micro + P - 1;
- everything is one compiled region: XLA overlaps the ppermute hop with
  the next microbatch's compute.

``pipeline_apply`` returns the final-stage outputs for all microbatches in
order.  Differentiable end-to-end (ppermute has a transpose rule), so the
same function trains under ``jax.grad``.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.parallel.collectives import pvary
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_apply(stage_fn, stage_params, x_micro, mesh: Mesh,
                   axis: str = "pipe", remat: bool = False):
    """Run a P-stage pipeline over microbatches.

    stage_fn(params_slice, x) -> y          (one stage's computation;
                                             activation shapes preserved)
    stage_params: pytree with leading dim P (stage-stacked), will be
                  sharded over ``axis``.
    x_micro: (M, micro_batch, ...) microbatched input (replicated).
    Returns (M, micro_batch, ...) outputs of the last stage.

    ``remat=True`` wraps the stage in ``jax.checkpoint``: only the
    pipeline-boundary activations (the scan carry, one microbatch
    activation per tick) stay live for the backward; each stage's
    *internal* activations are recomputed.  Measured on the 8-device CPU
    mesh (tests/test_pipeline_moe.py::test_pipeline_remat_memory):
    compiled temp memory for a 4-stage x 3-layer-MLP pipeline drops 2.4x.
    GPipe liveness caveat: even with remat, boundary activations for all
    in-flight microbatches are saved per tick — a 1F1B schedule (not
    implemented) would cap that at n_stage instead of n_micro + P - 1;
    docs/distributed.md records the cost model.
    """
    n_stage = mesh.shape[axis]
    if remat:
        stage_fn = jax.checkpoint(stage_fn)

    def ranked(params, x_all):
        # inside shard_map: params has leading dim 1 (my stage), x_all is
        # the full microbatch stack (replicated)
        my_params = jax.tree_util.tree_map(lambda v: v[0], params)
        rank = lax.axis_index(axis)
        n_micro = x_all.shape[0]
        n_ticks = n_micro + n_stage - 1
        fwd = [(i, (i + 1) % n_stage) for i in range(n_stage)]

        micro_shape = x_all.shape[1:]
        # pvary: scan carries must be device-varying over the pipe axis
        buf = pvary(jnp.zeros(micro_shape, x_all.dtype), (axis,))
        outs = pvary(jnp.zeros((n_micro,) + micro_shape, x_all.dtype),
                     (axis,))

        def tick(carry, t):
            buf, outs = carry
            # rank 0 injects microbatch t (when available)
            inject = x_all[jnp.clip(t, 0, n_micro - 1)]
            cur = jnp.where(rank == 0,
                            jnp.where(t < n_micro, inject, jnp.zeros_like(inject)),
                            buf)
            y = stage_fn(my_params, cur)
            # last rank emits microbatch (t - (P-1)) at tick t
            out_idx = t - (n_stage - 1)
            emit = (rank == n_stage - 1) & (out_idx >= 0)
            upd = lax.dynamic_update_index_in_dim(
                outs, y, jnp.maximum(out_idx, 0), 0)
            outs = jnp.where(emit, upd, outs)
            buf = lax.ppermute(y, axis, fwd)
            return (buf, outs), None

        (buf, outs), _ = lax.scan(tick, (buf, outs), jnp.arange(n_ticks))
        # every rank holds `outs`, but only the last rank's is real;
        # broadcast it (max works since others are zero-initialized only if
        # last rank wrote) — use psum of masked value for correctness
        mask = (rank == n_stage - 1).astype(outs.dtype)
        outs = lax.psum(outs * mask, axis)
        return outs

    pspec = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    f = jax.shard_map(ranked, mesh=mesh,
                      in_specs=(pspec, P()), out_specs=P())
    return f(stage_params, x_micro)


def stack_stage_params(per_stage_params):
    """[stage0_tree, stage1_tree, ...] -> one tree with leading dim P."""
    return jax.tree_util.tree_map(lambda *vs: jnp.stack(vs), *per_stage_params)
