"""Pipeline parallelism — GPipe-style microbatch pipelining over a mesh axis.

Absent in the reference (SURVEY.md §2.9: PP = NO); first-class here because
pipeline schedules are a core TPU scaling strategy when a model exceeds one
chip's HBM.

Design (the shard_map ring formulation):
- the repeated-block model is expressed as ONE stage function applied P
  times (scan-over-layers), with each pipeline rank holding its stage's
  parameters (stacked pytree sharded on the ``pipe`` axis, leading dim P);
- microbatches stream through ranks with ``ppermute`` hops: at tick t,
  rank r computes its stage on the activation it received at t-1 and
  forwards the result around the ring — the classic GPipe fill/steady/drain
  schedule, total ticks = n_micro + P - 1;
- everything is one compiled region: XLA overlaps the ppermute hop with
  the next microbatch's compute.

``pipeline_apply`` returns the final-stage outputs for all microbatches in
order.  Differentiable end-to-end (ppermute has a transpose rule), so the
same function trains under ``jax.grad``.
"""
from __future__ import annotations

from functools import partial

import jax

from bigdl_tpu.parallel.compat import shard_map, grad_psum_is_explicit
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.parallel.collectives import pvary
from jax.sharding import Mesh, PartitionSpec as P


def _merge_state_over(state, data_axis):
    """Replica-merge per-stage carried state: float leaves (BN running
    stats) average, non-float leaves take the max (rank-identical by
    construction).  Shared by both schedules."""
    return jax.tree_util.tree_map(
        lambda s: lax.pmean(s, data_axis)
        if jnp.issubdtype(s.dtype, jnp.floating)
        else lax.pmax(s, data_axis), state)


def pipeline_apply(stage_fn, stage_params, x_micro, mesh: Mesh,
                   axis: str = "pipe", remat: bool = False,
                   stage_state=None, data_axis: str = None):
    """Run a P-stage pipeline over microbatches.

    stage_fn(params_slice, x) -> y          (one stage's computation;
                                             activation shapes preserved)
    stage_params: pytree with leading dim P (stage-stacked), will be
                  sharded over ``axis``.
    x_micro: (M, micro_batch, ...) microbatched input (replicated).
    Returns (M, micro_batch, ...) outputs of the last stage.

    ``stage_state`` (optional): a stage-stacked pytree of per-stage carried
    state (e.g. BatchNorm running stats), sharded over ``axis`` like the
    params.  When given, the stage function takes the extended signature
    ``stage_fn(params_slice, state_slice, x, micro_idx) -> (y, new_state)``
    — ``micro_idx`` is the (traced) global microbatch index, for deriving
    per-microbatch RNG keys — state updates apply only on valid (non-fill/
    drain) ticks, sequentially per microbatch (the reference's per-clone
    running-stat updates on sub-batches, BatchNormalization.scala under
    _subModelNumber), and the return value becomes
    ``(outputs, new_stage_state)``.

    ``data_axis`` composes with data parallelism: x_micro is sharded
    over it on the per-microbatch batch dim and the outputs come back
    likewise sharded; float state pmeans across replicas.

    ``remat=True`` wraps the stage in ``jax.checkpoint``: only the
    pipeline-boundary activations (the scan carry, one microbatch
    activation per tick) stay live for the backward; each stage's
    *internal* activations are recomputed.  Measured on the 8-device CPU
    mesh (tests/test_pipeline_moe.py::test_pipeline_remat_memory):
    compiled temp memory for a 4-stage x 3-layer-MLP pipeline drops 2.4x.
    GPipe liveness caveat: even with remat, boundary activations for all
    in-flight microbatches are saved per tick — O(n_micro + P - 1) per
    stage.  ``pipeline_train_1f1b`` below implements the 1F1B schedule,
    which bounds that at ~2(P-1)+1 independent of n_micro;
    docs/distributed.md records both cost models.
    """
    n_stage = mesh.shape[axis]
    stateful = stage_state is not None
    if stateful:
        fn = stage_fn
    else:
        # legacy stateless signature; dummy state rides along untouched
        fn = lambda p, s, x, m: (stage_fn(p, x), s)
        stage_state = jnp.zeros((n_stage, 1), jnp.float32)
    if remat:
        fn = jax.checkpoint(fn)
    vary_axes = (axis,) if data_axis is None else (axis, data_axis)

    def ranked(params, st, x_all):
        # inside shard_map: params has leading dim 1 (my stage), x_all is
        # the full microbatch stack (replicated over the pipe axis)
        my_params = jax.tree_util.tree_map(lambda v: v[0], params)
        my_state = jax.tree_util.tree_map(lambda v: v[0], st)
        if data_axis is not None:
            my_state = jax.tree_util.tree_map(
                lambda v: pvary(v, (data_axis,)), my_state)
        rank = lax.axis_index(axis)
        n_micro = x_all.shape[0]
        n_ticks = n_micro + n_stage - 1
        fwd = [(i, (i + 1) % n_stage) for i in range(n_stage)]

        micro_shape = x_all.shape[1:]
        # pvary: scan carries must be device-varying over the pipe axis
        buf = pvary(jnp.zeros(micro_shape, x_all.dtype), vary_axes)
        outs = pvary(jnp.zeros((n_micro,) + micro_shape, x_all.dtype),
                     vary_axes)

        def tick(carry, t):
            buf, outs, my_state = carry
            # rank r processes the microbatch rank 0 injected at t - r
            m = t - rank
            valid = (m >= 0) & (m < n_micro)
            # rank 0 injects microbatch t (when available)
            inject = x_all[jnp.clip(t, 0, n_micro - 1)]
            cur = jnp.where(rank == 0,
                            jnp.where(t < n_micro, inject, jnp.zeros_like(inject)),
                            buf)
            y, ns = fn(my_params, my_state, cur, m)
            # state advances only on valid ticks (fill/drain run on zeros)
            my_state = jax.tree_util.tree_map(
                lambda old, new: jnp.where(valid, new, old), my_state, ns)
            # last rank emits microbatch (t - (P-1)) at tick t
            out_idx = t - (n_stage - 1)
            emit = (rank == n_stage - 1) & (out_idx >= 0)
            upd = lax.dynamic_update_index_in_dim(
                outs, y, jnp.maximum(out_idx, 0), 0)
            outs = jnp.where(emit, upd, outs)
            buf = lax.ppermute(y, axis, fwd)
            return (buf, outs, my_state), None

        (buf, outs, my_state), _ = lax.scan(
            tick, (buf, outs, my_state), jnp.arange(n_ticks))
        # every rank holds `outs`, but only the last rank's is real;
        # broadcast it (max works since others are zero-initialized only if
        # last rank wrote) — use psum of masked value for correctness
        mask = (rank == n_stage - 1).astype(outs.dtype)
        outs = lax.psum(outs * mask, axis)
        if data_axis is not None:
            my_state = _merge_state_over(my_state, data_axis)
        return outs, jax.tree_util.tree_map(lambda v: v[None], my_state)

    pspec = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    sspec = jax.tree_util.tree_map(lambda _: P(axis), stage_state)
    xspec = P(None, data_axis) if data_axis is not None else P()
    f = shard_map(ranked, mesh=mesh,
                      in_specs=(pspec, sspec, xspec),
                      out_specs=(xspec, sspec))
    outs, new_state = f(stage_params, stage_state, x_micro)
    return (outs, new_state) if stateful else outs


def stack_stage_params(per_stage_params):
    """[stage0_tree, stage1_tree, ...] -> one tree with leading dim P."""
    return jax.tree_util.tree_map(lambda *vs: jnp.stack(vs), *per_stage_params)


def pipeline_train_1f1b(stage_fn, loss_fn, stage_params, x_micro, t_micro,
                        mesh: Mesh, axis: str = "pipe",
                        shard_inputs: bool = False, stage_state=None,
                        data_axis: str = None):
    """1F1B pipeline schedule: forward and backward interleaved so each
    stage keeps at most ~2*(P-1)+1 in-flight microbatch activations —
    independent of the microbatch count — where GPipe's autodiff keeps
    n_micro + P - 1 per stage (pipeline_apply docstring).

    Schedule (combined tick k, stage r, P = n_stage):
      - forward of microbatch  mf = k - r
      - backward of microbatch mb = k - (2*(P-1) - r)
    so the last stage backwards a microbatch the same tick it forwards
    it (loss cotangent computed in place), and stage r's backward runs
    one tick before stage r-1's — the activation gradient rides the
    reverse ring.  Total ticks = n_micro + 2*(P-1).

    Residuals: only each stage's INPUT activation per in-flight
    microbatch is buffered (circular buffer, depth 2*P); the stage is
    recomputed inside ``jax.vjp`` at backward time — the same
    fwd+recompute+bwd = 3 stage evaluations per microbatch per stage
    that GPipe-with-remat costs, but with the bounded buffer.

    stage_fn(params_slice, x) -> y   (activation shapes preserved)
    loss_fn(y_last, target) -> scalar (per microbatch; mean over
    microbatches is applied here)
    Returns (mean_loss, grads) with grads shaped like ``stage_params``
    (leading dim P, stage-sharded like the input).

    Operand memory: by default x_micro / t_micro are REPLICATED onto
    every rank (in_specs P()), so per-device input+target memory is
    O(n_micro) even though live activations are bounded.
    ``shard_inputs=True`` shards both over the pipe axis instead
    (n_micro must divide by P): each rank stores n_micro/P microbatches
    and the owner delivers the tick's microbatch with ONE masked psum
    (same for the target on the backward side) — O(n_micro/P) operand
    memory for two extra microbatch-sized collectives per tick.

    ``data_axis`` (optional): composes the pipeline with data
    parallelism over a second mesh axis — each data-parallel replica
    group runs the SAME 1F1B schedule on its microbatch shard (x/t
    sharded over ``data_axis`` on the per-microbatch batch dim), and
    gradients / loss / float state pmean across replicas before
    returning, exactly the plain-DP contract.  Incompatible with
    ``shard_inputs`` (one sharding per operand dim).

    ``stage_state`` (optional): stage-stacked carried state (BN running
    stats), sharded over ``axis``; switches the stage function to the
    extended signature ``stage_fn(params_slice, state_slice, x, micro_idx)
    -> (y, new_state)`` and the return value to ``(loss, grads,
    new_stage_state)``.  Contract: a stage's TRAINING-mode output must not
    depend on the carried state (true of BatchNorm, which normalizes by
    batch statistics in training — running stats are eval-only), because
    the backward-time recompute runs against a later state than the
    forward half; stochastic layers must key off ``micro_idx`` so the
    recompute draws the same mask.  State advances once per valid forward
    tick — per-microbatch sequential EMA, the reference's per-clone
    sub-batch updates (BatchNormalization.scala under _subModelNumber).
    """
    n_stage = mesh.shape[axis]
    stateful = stage_state is not None
    if stateful:
        fn = stage_fn
    else:
        fn = lambda p, s, x, m: (stage_fn(p, x), s)
        stage_state = jnp.zeros((n_stage, 1), jnp.float32)
    n_micro = x_micro.shape[0]
    depth = 2 * n_stage  # circular residual buffer, >= max in-flight + 1
    if shard_inputs and data_axis is not None:
        raise ValueError("shard_inputs and data_axis are mutually "
                         "exclusive (one sharding per operand dim)")
    if shard_inputs and n_micro % n_stage:
        raise ValueError(f"shard_inputs requires n_micro ({n_micro}) "
                         f"divisible by the pipe axis ({n_stage})")
    per = n_micro // n_stage if shard_inputs else n_micro
    vary_axes = (axis,) if data_axis is None else (axis, data_axis)
    dscale = mesh.shape[data_axis] if data_axis is not None else 1

    def ranked(params, st, x_all, t_all):
        my_params = jax.tree_util.tree_map(lambda v: v[0], params)
        my_state0 = jax.tree_util.tree_map(lambda v: v[0], st)
        if data_axis is not None:
            # state updates derive from the data-sharded x, so the carry
            # must start data-varying
            my_state0 = jax.tree_util.tree_map(
                lambda v: pvary(v, (data_axis,)), my_state0)
        rank = lax.axis_index(axis)

        def fetch(arr, m):
            # microbatch m of a possibly pipe-sharded (per, mb, ...)
            # array.  m MUST be a global (rank-independent) index: with
            # shard_inputs the owning rank contributes its slice and the
            # psum delivers it everywhere — a rank-dependent m would make
            # each rank contribute for a DIFFERENT microbatch and the sum
            # would be garbage.
            if not shard_inputs:
                return arr[jnp.clip(m, 0, n_micro - 1)]
            local = arr[jnp.clip(m - rank * per, 0, per - 1)]
            mine = (m // per == rank) & (m >= 0) & (m < n_micro)
            return lax.psum(local * mine.astype(local.dtype), axis)
        n_ticks = n_micro + 2 * (n_stage - 1)
        fwd_ring = [(i, (i + 1) % n_stage) for i in range(n_stage)]
        bwd_ring = [(i, (i - 1) % n_stage) for i in range(n_stage)]

        micro_shape = x_all.shape[1:]
        zeros_micro = jnp.zeros(micro_shape, x_all.dtype)
        buf_fwd = pvary(zeros_micro, vary_axes)        # fwd ring carry
        buf_bwd = pvary(zeros_micro, vary_axes)        # bwd ring carry
        resid = pvary(jnp.zeros((depth,) + micro_shape, x_all.dtype),
                      vary_axes)                       # saved stage inputs
        # my_params are already device-varying (stage-sharded), so zeros
        # derived from them are too — no pvary needed (pcast would reject)
        # grad_acc stays data-INVARIANT: inside shard_map, jax.vjp w.r.t.
        # the data-replicated my_params already psums each cotangent over
        # the data axis (vma-aware AD), so the per-tick gp arrives as the
        # cross-replica SUM — the 1/dscale in the loss closure turns that
        # into the mean, and no explicit grad collective is needed
        grad_acc = jax.tree_util.tree_map(jnp.zeros_like, my_params)
        loss_acc = pvary(jnp.zeros((), jnp.float32), vary_axes)

        def tick(carry, k):
            buf_fwd, buf_bwd, resid, grad_acc, loss_acc, my_state = carry

            # ---------------- forward half ----------------
            mf = k - rank
            f_valid = (mf >= 0) & (mf < n_micro)
            # global index: rank 0 is the only consumer and its mf == k
            inject = fetch(x_all, k)
            cur = jnp.where(rank == 0, inject, buf_fwd)
            y, ns = fn(my_params, my_state, cur, mf)
            my_state = jax.tree_util.tree_map(
                lambda old, new: jnp.where(f_valid, new, old), my_state, ns)
            resid = lax.dynamic_update_index_in_dim(
                resid, jnp.where(f_valid, cur, zeros_micro),
                jnp.maximum(mf, 0) % depth, 0)
            buf_fwd_next = lax.ppermute(
                jnp.where(f_valid, y, jnp.zeros_like(y)), axis, fwd_ring)

            # ---------------- backward half ----------------
            mb = k - (2 * (n_stage - 1) - rank)
            b_valid = (mb >= 0) & (mb < n_micro)
            x_saved = resid[jnp.maximum(mb, 0) % depth]
            # global index: the last rank is the only consumer of the
            # target and its mb == k - (P-1)
            tgt = fetch(t_all, k - (n_stage - 1))
            is_last = rank == n_stage - 1

            # ONE stage vjp per tick: recompute the stage forward, then
            # pick the cotangent — the loss gradient (last stage; from a
            # cheap vjp of loss_fn alone on the recomputed y) or the
            # incoming activation gradient off the reverse ring.  Static
            # structure on every rank/tick, 3 stage evals per microbatch
            # total (fwd half + recompute + bwd) as documented.  The
            # carried state is a non-diff constant here (see the stateful
            # contract in the docstring).
            y_re, stage_vjp = jax.vjp(
                lambda p, xx: fn(p, my_state, xx, mb)[0],
                my_params, x_saved)
            loss_val, loss_vjp = jax.vjp(
                lambda yy: loss_fn(yy, tgt) / (n_micro * dscale), y_re)
            one = pvary(jnp.ones((), loss_val.dtype), vary_axes)
            (dy,) = loss_vjp(one)
            cot = jnp.where(is_last, dy, buf_bwd)
            gp, gx = stage_vjp(cot)

            # jnp.where masking (NOT multiply-by-mask): a vjp evaluated
            # on the zeroed residual of a fill/drain tick may be
            # non-finite, and NaN * 0 would poison the accumulator
            grad_acc = jax.tree_util.tree_map(
                lambda acc, g: acc + jnp.where(b_valid, g,
                                               jnp.zeros_like(g)),
                grad_acc, gp)
            loss_acc = loss_acc + jnp.where(
                is_last & b_valid, loss_val.astype(jnp.float32), 0.0)
            buf_bwd_next = lax.ppermute(
                jnp.where(b_valid, gx, jnp.zeros_like(gx)), axis, bwd_ring)

            return (buf_fwd_next, buf_bwd_next, resid, grad_acc,
                    loss_acc, my_state), None

        carry = (buf_fwd, buf_bwd, resid, grad_acc, loss_acc, my_state0)
        carry, _ = lax.scan(tick, carry, jnp.arange(n_ticks))
        _, _, _, grad_acc, loss_acc, my_state = carry
        if data_axis is not None and grad_psum_is_explicit():
            # old-jax shard_map (check_rep=False) does NOT auto-psum the
            # cotangent of the data-replicated my_params, so grad_acc is
            # each replica's PARTIAL sum here — reduce it once after the
            # scan (psum is linear, so one reduce == per-tick reduces).
            # On vma-aware jax the per-tick gp already arrives summed
            # and this branch must stay off or grads double-count.
            grad_acc = jax.tree_util.tree_map(
                lambda v: lax.psum(v, data_axis), grad_acc)
        loss = lax.psum(loss_acc, axis)  # only last rank contributed
        if data_axis is not None:
            # loss_acc already carries the 1/dscale factor: psum over the
            # replicas completes the global mean
            loss = lax.psum(loss, data_axis)
            my_state = _merge_state_over(my_state, data_axis)
        grads = jax.tree_util.tree_map(lambda g: g[None], grad_acc)
        return loss, grads, jax.tree_util.tree_map(lambda v: v[None],
                                                   my_state)

    pspec = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    sspec = jax.tree_util.tree_map(lambda _: P(axis), stage_state)
    if shard_inputs:
        xspec = P(axis)
    elif data_axis is not None:
        xspec = P(None, data_axis)   # (M, mb, ...): shard the batch dim
    else:
        xspec = P()
    f = shard_map(ranked, mesh=mesh,
                      in_specs=(pspec, sspec, xspec, xspec),
                      out_specs=(P(), pspec, sspec))
    loss, grads, new_state = f(stage_params, stage_state, x_micro, t_micro)
    return (loss, grads, new_state) if stateful else (loss, grads)
