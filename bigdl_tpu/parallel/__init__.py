from bigdl_tpu.parallel.mesh import make_mesh, data_parallel_mesh, hybrid_mesh
from bigdl_tpu.parallel import collectives
from bigdl_tpu.parallel.sharding import (
    replicated, batch_sharded, shard_params_rule, constrain,
)

__all__ = [
    "make_mesh", "data_parallel_mesh", "hybrid_mesh", "collectives",
    "replicated", "batch_sharded", "shard_params_rule", "constrain",
]
