"""Stage-partitioning Sequential models for pipeline training through the
Optimizer API.

The reference hides ALL distribution behind the Optimizer factory
(ref optim/Optimizer.scala:151-186: the caller never touches the transport);
``DistriOptimizer(pipeline_stages=P)`` gives pipeline parallelism the same
front door.  This module turns an arbitrary ``Sequential`` model into the
homogeneous stage representation the shard_map pipeline engines
(``parallel/pipeline.py``) require:

- **partition**: top-level modules are split into P contiguous stages
  balanced by an analytic FLOP estimate (conv/linear ≈ 2·|W|·spatial_out·mb,
  else output bytes) via the classic linear-partition DP;
- **homogenize**: per-stage parameter/state pytrees are raveled
  (``jax.flatten_util.ravel_pytree``), zero-padded to the max stage size,
  and stacked into one ``(P, maxlen)`` array sharded over the ``pipe``
  axis — boundary activations likewise ride the ring as per-sample
  flattened ``(mb, max_act)`` buffers, so every stage has identical
  operand shapes;
- **dispatch**: one stage function selects its stage's computation with
  ``lax.switch(rank, ...)`` — each rank executes only its branch at
  runtime; the compiled program is the same SPMD executable everywhere.

RNG contract: stochastic layers (Dropout) derive their key from
``fold_in(fold_in(base_key, micro_idx), stage)`` so the 1F1B backward-time
recompute draws the identical mask.  This stream intentionally differs
from the DP step's stream (per-microbatch masks vs one full-batch mask) —
the same divergence the reference has between a single model and its
per-clone thread RNGs (Dropout.scala threads over Engine.model).
"""
from __future__ import annotations

import jax

from bigdl_tpu.parallel.compat import typeof as _compat_typeof
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.flatten_util import ravel_pytree

from bigdl_tpu.nn.module import Context


def _flat_size(tree):
    return int(sum(np.prod(l.shape) for l in jax.tree_util.tree_leaves(tree)))


def _module_cost(module, mb, out_shape):
    """Analytic per-module cost for stage balancing: matmul/conv-style
    modules cost ~2·|W|·spatial_out·mb FLOPs (exact for SpatialConvolution
    and Linear; a same-spatial approximation for container blocks like an
    Inception mixed unit), everything else is bandwidth — counted as output
    elements.  Only relative magnitudes matter here."""
    psize = _flat_size(module.params())
    spatial = int(np.prod(out_shape[2:])) if len(out_shape) > 2 else 1
    out_elems = int(np.prod(out_shape))
    return 2.0 * psize * spatial * mb + out_elems


def _linear_partition(costs, n_stages):
    """Split ``costs`` into ``n_stages`` contiguous non-empty groups
    minimizing the max group sum (O(n² P) DP; n is the module count)."""
    n = len(costs)
    prefix = np.concatenate([[0.0], np.cumsum(costs)])
    INF = float("inf")
    dp = np.full((n + 1, n_stages + 1), INF)
    par = np.zeros((n + 1, n_stages + 1), np.int64)
    dp[0, 0] = 0.0
    for p in range(1, n_stages + 1):
        for i in range(p, n + 1):
            for j in range(p - 1, i):
                c = max(dp[j, p - 1], prefix[i] - prefix[j])
                if c < dp[i, p]:
                    dp[i, p] = c
                    par[i, p] = j
    ranges = []
    i = n
    for p in range(n_stages, 0, -1):
        j = int(par[i, p])
        ranges.append((j, i))
        i = j
    return ranges[::-1]


class StagePlan:
    """Everything needed to run a partitioned Sequential through the
    pipeline engines: stage ranges, boundary shapes, ravel/unravel
    templates, and the pack/unpack/stage-fn builders.  Built once per
    training run by :func:`partition_sequential`."""

    def __init__(self, model, n_stages, ranges, in_shapes, out_shape,
                 axis="pipe"):
        self.model = model
        self.modules = model.modules
        self.n_stages = n_stages
        self.ranges = ranges
        self.in_shapes = in_shapes        # per-stage input shape, incl. mb
        self.out_shape = out_shape        # final output shape, incl. mb
        self.mb = in_shapes[0][0]
        self.axis = axis

        self.act_sizes = [int(np.prod(s[1:])) for s in in_shapes]
        self.out_size = int(np.prod(out_shape[1:]))
        self.max_act = max(self.act_sizes + [self.out_size])

        self.unravel_p, self.p_sizes = [], []
        self.unravel_s, self.s_sizes = [], []
        for (a, b) in ranges:
            pt = [self.modules[j].params() for j in range(a, b)]
            st = [self.modules[j].state() for j in range(a, b)]
            fp, up = ravel_pytree(pt)
            fs, us = ravel_pytree(st)
            self.unravel_p.append(up)
            self.p_sizes.append(int(fp.size))
            self.unravel_s.append(us)
            self.s_sizes.append(int(fs.size))
        self.max_p = max(self.p_sizes)
        # width >= 1 so fully stateless models still carry a well-formed
        # (P, 1) array through the scan
        self.max_s = max(self.s_sizes + [1])

    # -- packing -----------------------------------------------------------
    def _pack(self, tree, width):
        rows = []
        for (a, b) in self.ranges:
            flat, _ = ravel_pytree([tree[str(j)] for j in range(a, b)])
            flat = flat.astype(jnp.float32) if flat.size == 0 else flat
            rows.append(jnp.pad(flat, (0, width - flat.size)))
        return jnp.stack(rows)

    def pack_params(self, tree):
        """Module-tree params pytree -> (P, max_p) stage-stacked array."""
        return self._pack(tree, self.max_p)

    def pack_state(self, tree):
        return self._pack(tree, self.max_s)

    @staticmethod
    def _gather_stacked(stacked):
        """Host copy of a (P, width) stage-stacked array.  Single-process:
        a plain device_get.  Multi-host (stages span processes over DCN):
        rows are placed by their GLOBAL dim-0 index and de-duplicated —
        under a hybrid dp x pp mesh each stage row is replicated across
        the data axis, so a naive concat of addressable shards would
        duplicate or misplace rows.  The assembly is a COLLECTIVE
        (process_allgather), so every process must reach the call site
        together (checkpoint/validation unpacks run on all processes
        before any process-0 gating)."""
        if jax.process_count() == 1 or getattr(
                stacked, "is_fully_addressable", True):
            return np.asarray(jax.device_get(stacked))
        # row ownership is GLOBAL sharding metadata — every process
        # computes the identical map, so no ownership collective is
        # needed and the short-circuit below is process-consistent
        n_rows = stacked.shape[0]
        owner = {}                       # global row -> owning process
        rows_of = {}                     # process -> set of rows
        for dev, idx in stacked.sharding.devices_indices_map(
                stacked.shape).items():
            sl = idx[0]
            for r in range(sl.start or 0, sl.stop if sl.stop is not None
                           else n_rows):
                owner.setdefault(r, dev.process_index)
                rows_of.setdefault(dev.process_index, set()).add(r)
        assert len(owner) == n_rows, "stage rows with no owner"
        if all(len(rows_of.get(p, ())) == n_rows
               for p in range(jax.process_count())):
            # e.g. hybrid dp x pp data-major layouts: every process holds
            # every stage row — purely local assembly, no collective
            local = np.zeros(stacked.shape, stacked.dtype)
            for s in stacked.addressable_shards:
                start = s.index[0].start or 0
                data = np.asarray(s.data)
                local[start:start + data.shape[0]] = data
            return local
        from jax.experimental import multihost_utils
        local = np.zeros(stacked.shape, stacked.dtype)
        for s in stacked.addressable_shards:
            start = s.index[0].start or 0
            data = np.asarray(s.data)
            local[start:start + data.shape[0]] = data
        g_rows = np.asarray(multihost_utils.process_allgather(
            local, tiled=False))          # (nproc, P, width)
        out = np.zeros(stacked.shape, stacked.dtype)
        for r in range(n_rows):
            out[r] = g_rows[owner[r], r]
        return out

    def _unpack(self, stacked, sizes, unravels):
        stacked = self._gather_stacked(stacked)
        tree = {"~": {}}
        for i, (a, b) in enumerate(self.ranges):
            stage = unravels[i](jnp.asarray(stacked[i, :sizes[i]]))
            for k, j in enumerate(range(a, b)):
                tree[str(j)] = stage[k]
        return tree

    def unpack_params(self, stacked):
        """(P, max_p) stage-stacked array -> module-tree params pytree
        (host-side: gathers the stage shards)."""
        return self._unpack(stacked, self.p_sizes, self.unravel_p)

    def unpack_state(self, stacked):
        return self._unpack(stacked, self.s_sizes, self.unravel_s)

    # -- the stage function ------------------------------------------------
    def make_branches(self, base_key, training=True):
        """Per-stage computation functions ``run(flat_p, flat_s, flat_x, m)
        -> (flat_y, flat_s')`` — the switch targets of
        :meth:`make_stage_fn`, also usable directly as a sequential
        single-device oracle (tests compare the pipeline against exactly
        these branches run in order)."""
        mb = self.mb

        def branch(i):
            a, b = self.ranges[i]
            in_shape, in_size = self.in_shapes[i], self.act_sizes[i]
            p_size, s_size = self.p_sizes[i], self.s_sizes[i]

            def run(flat_p, flat_s, flat_x, m):
                p_list = self.unravel_p[i](flat_p[:p_size])
                s_list = self.unravel_s[i](flat_s[:s_size])
                # batch dim stays -1: under a hybrid dp x pp mesh the
                # stage sees the LOCAL microbatch shard, not plan.mb
                x = flat_x[:, :in_size].reshape((-1,) + in_shape[1:])
                key = jax.random.fold_in(
                    jax.random.fold_in(base_key, jnp.maximum(m, 0)), i)
                ctx = Context(training=training, key=key)
                new_s = []
                for k, j in enumerate(range(a, b)):
                    x, ns = self.modules[j].apply(p_list[k], x, s_list[k], ctx)
                    new_s.append(ns)
                y = x.reshape(x.shape[0], -1).astype(jnp.float32)
                y = jnp.pad(y, ((0, 0), (0, self.max_act - y.shape[1])))
                fs, _ = ravel_pytree(new_s)
                fs = (fs.astype(jnp.float32) if fs.size else
                      jnp.zeros((0,), jnp.float32))
                fs = jnp.pad(fs, (0, self.max_s - fs.size))
                return y, fs

            return run

        return [branch(i) for i in range(self.n_stages)]

    def make_stage_fn(self, base_key, training=True, fold_axis=None):
        """Build the engine-facing ``stage_fn(flat_p, flat_s, flat_x, m)
        -> (flat_y, flat_s')`` dispatching on the pipe rank.
        ``fold_axis`` decorrelates stochastic layers per data-parallel
        replica (the DP step's per-replica key fold)."""
        axis = self.axis

        def varying(v, target_vma):
            # a stateless stage emits its (empty-padded) state as a
            # CONSTANT, so its vma lacks axes that stateful branches'
            # outputs carry (pipe, and data under hybrid dp x pp) —
            # switch requires equal output types, so promote every
            # branch output to the operands' varying axes
            from bigdl_tpu.parallel.collectives import pvary
            vma = getattr(_compat_typeof(v), "vma", None)
            if vma is None:
                return v
            missing = tuple(a for a in target_vma if a not in vma)
            return pvary(v, missing) if missing else v

        # without a per-replica key fold the branch closures are key-
        # independent: build them ONCE (stage_fn is retraced many times —
        # fwd + vjp per 1F1B tick)
        static_branches = (self.make_branches(base_key, training)
                           if fold_axis is None else None)

        def stage_fn(flat_p, flat_s, flat_x, m):
            if static_branches is not None:
                branches = static_branches
            else:
                key = jax.random.fold_in(base_key,
                                         lax.axis_index(fold_axis))
                branches = self.make_branches(key, training)
            target = set(getattr(_compat_typeof(flat_x), "vma", ()) or ())
            target |= set(getattr(_compat_typeof(flat_p), "vma", ()) or ())
            target |= {axis}
            wrapped = [
                (lambda p, s, x, mm, b=b:
                 jax.tree_util.tree_map(
                     lambda v: varying(v, sorted(target)), b(p, s, x, mm)))
                for b in branches
            ]
            rank = lax.axis_index(axis)
            return lax.switch(rank, wrapped, flat_p, flat_s, flat_x, m)

        return stage_fn

    def make_loss_fn(self, criterion):
        def loss_fn(y_flat, tgt):
            # -1 batch dim: the local microbatch under dp x pp, the
            # global one in the GPipe outside-shard_map loss
            out = y_flat[:, :self.out_size].reshape(
                (-1,) + self.out_shape[1:])
            return criterion.apply_loss(out, tgt)
        return loss_fn

    def pack_input(self, x_micro):
        """(M, mb, ...) microbatched input -> (M, mb, max_act) flat-padded
        ring buffers."""
        m, mb = x_micro.shape[0], x_micro.shape[1]
        xf = x_micro.reshape(m, mb, -1).astype(jnp.float32)
        return jnp.pad(xf, ((0, 0), (0, 0), (0, self.max_act - xf.shape[2])))

    def describe(self):
        lines = []
        for i, (a, b) in enumerate(self.ranges):
            names = [type(self.modules[j]).__name__ for j in range(a, b)]
            lines.append(f"stage {i}: modules [{a}:{b}) "
                         f"({self.p_sizes[i]:,} params) {names}")
        return "\n".join(lines)


def partition_sequential(model, n_stages, micro_shape, axis="pipe",
                         training=True):
    """Partition a ``Sequential`` model into ``n_stages`` pipeline stages.

    ``micro_shape`` is the shape of ONE microbatch including its batch dim
    ``(mb, ...)``.  Boundary shapes come from an ``eval_shape`` sweep (no
    FLOPs spent); stages are balanced by the analytic cost model.  Every
    stage boundary must be a single array (true of the Sequential model
    zoo; Table-valued boundaries would need a table-flattening hop).
    """
    from bigdl_tpu.nn.containers import Sequential
    if not isinstance(model, Sequential):
        raise ValueError(
            f"pipeline_stages requires a Sequential model, got "
            f"{type(model).__name__}")
    modules = model.modules
    if len(modules) < n_stages:
        raise ValueError(f"model has {len(modules)} top-level modules, "
                         f"cannot make {n_stages} stages")

    key = jax.random.PRNGKey(0)
    cur = jax.ShapeDtypeStruct(tuple(micro_shape), jnp.float32)
    shapes = [cur.shape]
    costs = []
    for m in modules:
        p, s = m.params(), m.state()

        def one(x, m=m, p=p, s=s):
            return m.apply(p, x, s, Context(training=training, key=key))[0]

        cur = jax.eval_shape(one, cur)
        if not hasattr(cur, "shape"):
            raise ValueError(
                f"stage boundary after {type(m).__name__} is not a single "
                "array; pipeline partitioning needs tensor boundaries")
        shapes.append(cur.shape)
        costs.append(_module_cost(m, micro_shape[0], cur.shape))

    ranges = _linear_partition(costs, n_stages)
    in_shapes = [shapes[a] for a, _ in ranges]
    return StagePlan(model, n_stages, ranges, in_shapes, shapes[-1],
                     axis=axis)
