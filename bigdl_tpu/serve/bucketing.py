"""Shape-bucketed batch padding (docs/serving.md).

XLA compiles one executable per input shape, so a serving path that
forwards whatever batch size the queue happened to close on would pay a
cold compile for every distinct size it ever sees — tens of seconds on a
TPU, in the latency path of live requests.  The fix is the standard one:
quantize batch sizes to a small fixed set of power-of-two **buckets**,
zero-pad each assembled batch up to its bucket, and trim the pad rows
off the outputs.  Every bucket's executable is built once (ahead of
time, at engine start — `ServeEngine.warmup`), so after warmup a mixed
request stream touches ZERO cold compiles no matter how sizes arrive.

The same helper serves validation: the last partial batch of an eval
pass used to compile a second program for its odd shape
(`optim/local_optimizer.validate` now pads the tail back to the full
batch shape and trims — one compiled shape per pass).

Rows are padded with ZEROS, not repeats of the last row: a repeated real
row costs the same FLOPs but means a poisoned/non-finite final row is
forwarded multiple times, and it makes the pad rows indistinguishable
from data in a crash dump.  Pad rows never reach a caller either way —
`trim` drops them before futures resolve.
"""
from __future__ import annotations

import numpy as np


def bucket_sizes(max_batch: int) -> tuple:
    """The bucket ladder for ``max_batch``: powers of two up to and
    including ``max_batch`` (with ``max_batch`` itself appended when it
    is not a power of two, so a full batch pads by zero rows)."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    out = []
    b = 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return tuple(out)


def bucket_for(n: int, max_batch: int) -> int:
    """Smallest bucket >= ``n`` on the ``max_batch`` ladder."""
    if n < 1:
        raise ValueError(f"batch of {n} rows has no bucket")
    if n > max_batch:
        raise ValueError(f"{n} rows exceeds max_batch={max_batch}")
    for b in bucket_sizes(max_batch):
        if b >= n:
            return b
    raise AssertionError("unreachable: ladder ends at max_batch")


def pad_rows(x, target: int):
    """Zero-pad ``x`` (n, ...) up to ``target`` rows.

    Returns ``(padded, n)`` where ``padded`` shares no rows with any
    real record beyond the first ``n``.  ``n == target`` returns ``x``
    unchanged (no copy)."""
    x = np.asarray(x)
    n = x.shape[0]
    if n == 0:
        # 0-row input: nothing to serve — hand back the empty batch
        # unchanged instead of manufacturing an all-pad batch (or
        # raising mid-pipeline); callers skip dispatch on n == 0
        return x, 0
    if n == target:
        return x, n
    if n > target:
        raise ValueError(f"cannot pad {n} rows down to {target}")
    pad = np.zeros((target - n,) + x.shape[1:], dtype=x.dtype)
    return np.concatenate([x, pad]), n


def valid_mask(n: int, target: int) -> np.ndarray:
    """Boolean (target,) mask of the real rows of a padded batch."""
    m = np.zeros((target,), dtype=bool)
    m[:n] = True
    return m


def trim(out, n: int):
    """Drop the pad rows of a bucketed output (no-op when full;
    ``n == 0`` returns the empty slice rather than the pad rows)."""
    if n == 0:
        return out[:0]
    return out if out.shape[0] == n else out[:n]
