"""Streaming decode futures: incremental per-token delivery with
callback safety (docs/observability.md "Streaming telemetry").

The ContinuousDecoder historically resolved one future per request with
the whole token row at retire, which makes the two SLOs production LM
serving is judged on — time-to-first-token (TTFT) and inter-token
latency (ITL) — unmeasurable anywhere in the stack.  This module grows
decode futures into :class:`StreamFuture`\\ s:

- :meth:`StreamFuture.on_tokens` registers an incremental consumer fed
  at each existing ``BIGDL_SERVE_SYNC`` boundary — the decoder's token
  slab is materialized at the boundary anyway, so delivery adds zero
  extra device syncs and never happens per token;
- chunks carry an absolute **start index**, so a requeued request
  (replica death) re-delivering its deterministic greedy stream from a
  survivor is deduplicated instead of duplicated — consumers see every
  token exactly once, byte-identical to the all-at-once result;
- consumer callbacks run on a dedicated delivery thread
  (:class:`TokenDelivery`) or a frame-forwarding thread — NEVER the
  decode step loop — so a slow or raising consumer can not stall the
  device;
- a raising consumer (``on_tokens`` or ``add_done_callback``) fails
  only its own registration: it is dropped with an obs ``serve`` error
  event, and the stream, its future, and the delivery/dispatch threads
  all keep running (:class:`SafeFuture` is the ``add_done_callback``
  half of that contract — ``ServeEngine`` futures use it too).

Per-token SLO class (``serve/router.py``): ``BIGDL_SERVE_SLO_TTFT_MS``
/ ``BIGDL_SERVE_SLO_ITL_MS`` declare first-token and inter-token
budgets for streaming requests; the router's EDF deadline and
shed-before-miss projection then run against the projected FIRST-token
completion, not end-to-end retire (a stream that starts late is already
failing its users even if it retires on time).

Do not block inside an ``on_tokens`` callback waiting on the same
future's ``result()`` — the result is resolved on the delivery thread
the callback occupies.
"""
from __future__ import annotations

import logging
import os
import queue
import threading
import time
from concurrent.futures import Future

logger = logging.getLogger("bigdl_tpu.serve")

ENV_TTFT_MS = "BIGDL_SERVE_SLO_TTFT_MS"
ENV_ITL_MS = "BIGDL_SERVE_SLO_ITL_MS"


def ttft_ms_default() -> float:
    """Default first-token SLO budget (ms; 0 = no per-token class)."""
    try:
        return max(0.0, float(os.environ.get(ENV_TTFT_MS, "0") or 0))
    except ValueError:
        return 0.0


def itl_ms_default() -> float:
    """Declared inter-token SLO budget (ms; 0 = none).  A positive
    budget arms the absolute ``itl_burn`` alert default — windowed ITL
    p95 above it — next to the always-on relative ``itl_regression``
    rule (obs/alerts.py ``default_rules``)."""
    try:
        return max(0.0, float(os.environ.get(ENV_ITL_MS, "0") or 0))
    except ValueError:
        return 0.0


def _consumer_error(where: str, exc: BaseException):
    """One obs ``serve`` error event per raising user callback — the
    callback is the failure, never the stream machinery around it."""
    logger.warning("serve %s callback raised: %s: %s", where,
                   type(exc).__name__, exc)
    try:
        from bigdl_tpu.obs import events
        events.emit("serve", kind="error",
                    error=f"{type(exc).__name__}: {exc}", callback=where)
    except Exception:  # pragma: no cover - telemetry must not mask
        pass


class SafeFuture(Future):
    """A Future whose user callbacks can never kill the resolving
    thread: every ``add_done_callback`` invocation — at set-time on the
    engine compute / decoder delivery thread, or inline when the future
    is already done — is guarded, and a raise is converted into an obs
    ``serve`` error event instead of propagating.  (CPython already
    swallows ``Exception`` from set-time callbacks into a logger; this
    widens the guard to ``BaseException``, covers the already-done
    inline path, and lands the failure in the event stream where a
    postmortem can see it.)"""

    def add_done_callback(self, fn):
        # mirror CPython's implementation so the inline already-done
        # call path raises into OUR guard (the stdlib's own guard logs
        # to a stdlib logger the obs stream never sees)
        try:
            with self._condition:
                if self._state not in ("CANCELLED",
                                       "CANCELLED_AND_NOTIFIED",
                                       "FINISHED"):
                    self._done_callbacks.append(fn)
                    return
        except AttributeError:   # pragma: no cover - exotic runtime
            super().add_done_callback(fn)
            return
        try:
            fn(self)
        except BaseException as e:
            _consumer_error("done_callback", e)

    def _invoke_callbacks(self):
        callbacks, self._done_callbacks = self._done_callbacks, []
        for fn in callbacks:
            try:
                fn(self)
            except BaseException as e:
                _consumer_error("done_callback", e)


class StreamFuture(SafeFuture):
    """A decode future that can ALSO deliver its generated tokens
    incrementally.

    Producers call :meth:`feed` with each boundary's new tokens and the
    chunk's absolute start index; consumers register with
    :meth:`on_tokens` (``cb(tokens)`` — a list of fresh token ids) and
    are replayed the backlog on registration, so a consumer attached a
    moment after the first boundary still sees every token exactly
    once.  :meth:`pipe_to` chains futures (decoder → replica proxy →
    router future → client) preserving the start-index dedup, which is
    what makes requeue-after-replica-death re-delivery idempotent: the
    retried request regenerates the same greedy prefix, and the overlap
    is dropped here.

    ``streaming`` is the producer's signal to start per-boundary
    delivery: true once any consumer is registered, or after
    :meth:`request_stream` (the fleet payload's ``stream`` flag —
    intent can cross a process boundary before the consumer pipe is
    attached).  The future still resolves with the full token row
    either way."""

    def __init__(self):
        super().__init__()
        self._slock = threading.Lock()
        self._stream_tokens: list = []
        #: consumer entries [cb, indexed, sent, draining] — ``sent`` is
        #: how many tokens this consumer has been handed, ``draining``
        #: marks the one thread currently delivering to it
        self._consumers: list = []
        self._want_stream = False
        self.t_create = time.perf_counter()
        self.t_first_token: float | None = None
        self.stream_chunks = 0

    # -- consumer side ------------------------------------------------------
    @property
    def streaming(self) -> bool:
        # lock-free: two atomic attribute reads — the decode step loop
        # polls this per live request per boundary and must never wait
        # behind a consumer callback
        return self._want_stream or bool(self._consumers)

    def request_stream(self) -> "StreamFuture":
        """Mark this future as wanting per-boundary delivery even
        before a consumer is attached (chunks buffer and replay)."""
        with self._slock:
            self._want_stream = True
        return self

    def on_tokens(self, cb) -> "StreamFuture":
        """Register ``cb(tokens)`` for every delivered chunk; the
        backlog already delivered is replayed first (under the stream
        lock, so no chunk can race between replay and registration).  A
        raising ``cb`` is dropped with an obs error event — it fails
        only its own registration, never the stream or the delivery
        thread."""
        return self._register(cb, indexed=False)

    def on_tokens_indexed(self, cb) -> "StreamFuture":
        """Like :meth:`on_tokens` but ``cb(tokens, start)`` — the
        chunk's absolute index in the generated stream.  Forwarders
        (frame protocol, :meth:`pipe_to`) use this so dedup survives
        process hops."""
        return self._register(cb, indexed=True)

    def _register(self, cb, indexed: bool):
        entry = [cb, indexed, 0, False]
        with self._slock:
            self._consumers.append(entry)
        self._drain(entry)          # replay any backlog (outside lock)
        return self

    def pipe_to(self, dst: "StreamFuture") -> "StreamFuture":
        """Forward every chunk into ``dst`` (index-preserving)."""
        dst.request_stream()
        return self.on_tokens_indexed(dst.feed)

    # -- producer side ------------------------------------------------------
    def feed(self, tokens, start: int | None = None,
             ts: float | None = None) -> int:
        """Deliver a chunk.  ``start`` is the chunk's absolute index in
        the generated stream (``None`` = append at the current end);
        already-delivered overlap — a requeued request re-streaming
        from a fresh replica — is trimmed, so consumers see each index
        exactly once.  Returns the number of NEW tokens delivered.

        Consumer callbacks are invoked OUTSIDE the stream lock (a slow
        consumer can block its delivery thread, never a thread that
        merely checks :attr:`streaming` or feeds a sibling)."""
        tokens = [int(t) for t in tokens]
        with self._slock:
            n = len(self._stream_tokens)
            if start is None:
                start = n
            if start > n:   # a gap would silently corrupt the stream
                raise ValueError(
                    f"stream chunk starts at {start} but only {n} "
                    f"tokens were delivered")
            tokens = tokens[n - start:]
            if not tokens:
                return 0
            if self.t_first_token is None:
                self.t_first_token = (time.perf_counter() if ts is None
                                      else float(ts))
            self.stream_chunks += 1
            self._stream_tokens.extend(tokens)
            consumers = list(self._consumers)
        for entry in consumers:
            self._drain(entry)
        return len(tokens)

    def _drain(self, entry):
        """Hand ``entry`` everything past its ``sent`` watermark, one
        drainer at a time per consumer (``draining`` flag), callbacks
        outside the lock.  The empty-check and flag-clear share one
        lock acquisition, so a chunk fed concurrently with the last
        iteration either lands in this loop or finds the flag already
        cleared and drains it itself — nothing strands."""
        cb, indexed = entry[0], entry[1]
        with self._slock:
            if entry[3] or entry not in self._consumers:
                return              # another thread is delivering
            entry[3] = True
        while True:
            with self._slock:
                sent = entry[2]
                pending = list(self._stream_tokens[sent:])
                if not pending:
                    entry[3] = False
                    return
                entry[2] = sent + len(pending)
            try:
                if indexed:
                    cb(pending, sent)
                else:
                    cb(pending)
            except BaseException as e:
                # fail ONLY this registration: drop it so one broken
                # consumer cannot re-raise on every later boundary
                with self._slock:
                    try:
                        self._consumers.remove(entry)
                    except ValueError:  # pragma: no cover - raced drop
                        pass
                    entry[3] = False
                _consumer_error("on_tokens", e)
                return

    # -- introspection ------------------------------------------------------
    def tokens_streamed(self) -> int:
        with self._slock:
            return len(self._stream_tokens)

    def streamed(self) -> list:
        """Every token delivered so far (a copy, in order)."""
        with self._slock:
            return list(self._stream_tokens)

    @property
    def ttft_s(self) -> float | None:
        """Seconds from this future's creation to its first streamed
        token (None until the first chunk lands) — the router's
        first-token service estimate reads this."""
        t = self.t_first_token
        return None if t is None else t - self.t_create


class TokenDelivery:
    """The decoder's dedicated delivery thread: a FIFO of chunk feeds
    and final resolutions, so user callbacks (and ``set_result``'s
    done-callback fan-out for streaming futures) run HERE and the step
    loop never blocks on a consumer.  FIFO order guarantees a stream's
    final chunk is delivered before its future resolves — a client that
    waits on ``result()`` has, by then, seen the full stream."""

    def __init__(self, name: str = "stream"):
        self._q: "queue.Queue" = queue.Queue()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"bigdl-serve-{name}-delivery")
        self._thread.start()

    def enqueue(self, fut: StreamFuture, tokens, start: int, ts: float):
        self._q.put(("feed", fut, tokens, start, ts))

    def resolve(self, fut: Future, value):
        self._q.put(("result", fut, value, None, None))

    def _loop(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            try:
                if item[0] == "feed":
                    _, fut, tokens, start, ts = item
                    fut.feed(tokens, start=start, ts=ts)
                else:
                    fut = item[1]
                    if not fut.done():
                        fut.set_result(item[2])
            except BaseException as e:  # pragma: no cover - defensive
                logger.warning("token delivery failed: %s: %s",
                               type(e).__name__, e)

    def close(self, timeout: float = 10.0):
        """Drain everything already queued, then stop (FIFO: the
        sentinel lands after every pending chunk/resolution)."""
        self._q.put(None)
        self._thread.join(timeout=timeout)
