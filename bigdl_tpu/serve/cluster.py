"""Replica pool: N serve engines behind one SLO router, with versioned
hot weight rollout (docs/serving.md "Control plane").

The ServeEngine (PR 5) maximizes ONE process/chip slice; production
traffic needs the layer above it — the role the reference delegated to
Spark's driver + task scheduler (Engine.nodeNumber executors behind one
job queue).  Here that layer is explicit and TPU-shaped:

- :class:`LocalReplica` — an in-process ServeEngine (one per chip slice
  of this host; on the CPU CI mesh, N replicas share the virtual
  devices).
- :class:`ProcessReplica` — a subprocess running :func:`replica_main`
  with its OWN jax runtime (the production shape: each replica owns its
  slice; a replica crash is a process death, not a pool death),
  speaking a length-prefixed pickle protocol over stdin/stdout.  Killed
  replicas fail their outstanding futures with
  :class:`~bigdl_tpu.serve.router.DeadReplicaError`, which the router
  requeues onto survivors — the 4-replica chaos drill
  (``tests/test_serve_cluster.py``, ``BIGDL_FAULTS=serve_kill@...``)
  proves zero lost futures.  The child is NOT a telemetry black hole:
  its obs events stream to the parent's event log over the same frame
  protocol (``op: event``), its metrics registry snapshots are pulled
  on demand (``op: telemetry``) and merged into the fleet view, its
  stderr is captured into a bounded ring whose tail rides
  :class:`DeadReplicaError` messages and the crash bundle an unexpected
  death dumps, and sampled request traces (``obs/trace.py``) cross the
  boundary on the submit/reply frames with their hop stamps intact
  (``CLOCK_MONOTONIC`` is host-wide, so parent+child hops stay
  subtractable).
- :class:`ReplicaPool` — replicas + :class:`~bigdl_tpu.serve.router.Router`
  + :class:`WeightStore`, with the two-phase rollout protocol::

      rollout(params, state)
        │ 1. STAGE on all   — every replica pins version v+1 next to v;
        │                     serving continues on v (costs HBM only)
        │ 2. COMMIT (flip)  — each replica's flip is ONE tuple swap
        │                     between batches: in-flight batches finish
        │                     on v, every later batch serves v+1
        └─ on ANY failure  — staged-only replicas drop the pair;
                             already-committed replicas revert (one-deep
                             history), the fleet converges back to v,
                             zero in-flight futures dropped

  Every phase emits an obs ``serve`` event (rollout_begin /
  rollout_commit / rollout_rollback) so a postmortem can reconstruct
  which versions served when.

Flags: ``BIGDL_SERVE_REPLICAS`` (pool size default),
``BIGDL_SERVE_SLO_MS`` / ``BIGDL_SERVE_SHED`` (router admission —
serve/router.py), ``BIGDL_SERVE_HOSTS`` / ``BIGDL_SERVE_TOKEN`` /
``BIGDL_SERVE_LIVENESS_S`` (cross-host fleet over TCP replica agents —
serve/remote.py), ``BIGDL_SERVE_MAX_FRAME_MB`` (frame-size bound —
serve/frames.py).
"""
from __future__ import annotations

import itertools
import logging
import os
import pickle
import subprocess
import sys
import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as np

from bigdl_tpu.serve.engine import (PoisonedRequestError, ServeEngine,
                                    SheddedError)
from bigdl_tpu.serve.frames import FrameProtocolError
from bigdl_tpu.serve.frames import read_frame as _read_frame
from bigdl_tpu.serve.frames import write_frame as _write_frame
from bigdl_tpu.serve.paging import RequestTooLongError
from bigdl_tpu.serve.router import (DeadReplicaError, Router,
                                    replicas_default)
from bigdl_tpu.serve.streaming import StreamFuture, TokenDelivery

logger = logging.getLogger("bigdl_tpu.serve")

_POOL_SEQ = itertools.count()

#: bounded per-replica stderr ring (lines); the tail is what a
#: postmortem actually needs — the jax traceback right before death
_STDERR_LINES = 256

#: exception names a worker may report, mapped back to real types so
#: router retry logic and caller except-clauses behave identically for
#: local and subprocess replicas
_EXC_TYPES = {
    "PoisonedRequestError": PoisonedRequestError,
    "SheddedError": SheddedError,
    "DeadReplicaError": DeadReplicaError,
    "RequestTooLongError": RequestTooLongError,
    "FrameProtocolError": FrameProtocolError,
    "ValueError": ValueError,
    "RuntimeError": RuntimeError,
    "OSError": OSError,
}


class RolloutError(RuntimeError):
    """A two-phase weight rollout failed and was rolled back; every
    replica is serving the PREVIOUS version."""


class ReplicaSpawnError(RuntimeError):
    """A replica child died (or timed out) during the spawn/warmup
    handshake — before it ever took traffic.  Carries the child's
    stderr ring tail (``stderr_tail``) so the jax traceback that killed
    the warmup is IN the exception, not lost to a raw frame error.
    The autoscaler's retry/backoff + circuit breaker key on this type
    (``serve/autoscale.py``)."""

    def __init__(self, message: str, stderr_tail=None):
        super().__init__(message)
        self.stderr_tail = list(stderr_tail or [])


#: deterministic spawn-failure chaos knob: a replica worker started
#: with BIGDL_SERVE_SPAWN_FAIL=1 in its env exits during the warmup
#: handshake (after the init frame, before `ready`) — the drill site
#: behind the ReplicaSpawnError and circuit-breaker regression tests
ENV_SPAWN_FAIL = "BIGDL_SERVE_SPAWN_FAIL"


# ---------------------------------------------------------------------------
# weight store
# ---------------------------------------------------------------------------

class WeightStore:
    """Monotonically versioned in-memory checkpoint store for rollouts.

    ``put`` snapshots (params, state) as HOST numpy copies — the
    training loop's donated device buffers are dead after the next
    step, so a rollout must never alias them.  Versions only grow;
    ``get`` of any retained version supports rollback to it."""

    def __init__(self, keep: int = 4):
        self._lock = threading.Lock()
        self._versions: dict = {}
        self._next = 1
        self.keep = max(2, int(keep))

    def _snapshot(self, tree):
        import jax
        return jax.tree_util.tree_map(lambda l: np.array(l), tree)

    def put(self, params, state) -> int:
        snap = (self._snapshot(params), self._snapshot(state))
        with self._lock:
            version = self._next
            self._next += 1
            self._versions[version] = snap
            while len(self._versions) > self.keep:
                del self._versions[min(self._versions)]
            retained = list(self._versions.values())
        # host-RAM tenant truth: every retained snapshot's bytes (the
        # store is host numpy, not HBM — the breakdown table labels it)
        from bigdl_tpu.obs import ledger as obs_ledger
        obs_ledger.note_tenant(
            "weight_store_host",
            sum(obs_ledger.tree_nbytes(s) for s in retained))
        return version

    def put_model(self, model) -> int:
        return self.put(model.params(), model.state())

    def get(self, version: int):
        with self._lock:
            if version not in self._versions:
                raise KeyError(f"weight version {version} not in store "
                               f"(have {sorted(self._versions)})")
            return self._versions[version]

    def latest(self) -> int | None:
        with self._lock:
            return max(self._versions) if self._versions else None

    def versions(self) -> list:
        with self._lock:
            return sorted(self._versions)


# ---------------------------------------------------------------------------
# replicas
# ---------------------------------------------------------------------------

class LocalReplica:
    """One in-process ServeEngine wearing the replica surface the
    router expects (submit/inflight/alive/stats + the rollout verbs)."""

    #: flight-recorder transport attribution (obs/recorder.py)
    transport = "inproc"

    def __init__(self, engine: ServeEngine, name: str = "local"):
        self.engine = engine
        self.name = name

    def submit(self, x, trace=None) -> Future:
        return self.engine.submit(x, trace=trace)

    def registry_snapshot(self) -> dict | None:
        """None: a local replica's engine instruments already live in
        THIS process's registry — the pool's merge would double-count
        them if we returned a copy here."""
        return None

    def inflight(self) -> int:
        return self.engine.inflight()

    def alive(self) -> bool:
        e = self.engine
        return (not e._closed and e._assembler.is_alive()
                and e._compute.is_alive())

    def stats(self) -> dict:
        return self.engine.stats()

    def weights_version(self) -> int:
        return self.engine.weights_version

    def stage_weights(self, params, state, version=None):
        self.engine.stage_weights(params, state, version)

    def commit_weights(self) -> int:
        return self.engine.commit_weights()

    def rollback_weights(self):
        self.engine.rollback_weights()

    def revert_weights(self) -> int:
        return self.engine.revert_weights()

    def close(self, drain: bool = True):
        self.engine.close(drain=drain)


class ProcessReplica:
    """A serve replica in its own OS process (its own jax runtime /
    chip slice).  The parent ships the model once at spawn; requests and
    rollout verbs ride length-prefixed pickle frames over stdin/stdout.
    Process death — including a ``BIGDL_FAULTS=serve_kill@...`` chaos
    kill — fails every outstanding future with :class:`DeadReplicaError`
    so the router can requeue them on a surviving replica.

    Subclasses repoint ``_WORKER_MODULE`` / override :meth:`_init_frame`
    to spawn a different worker over the SAME frame transport — the
    disaggregated fleet's prefill/decode replicas (``serve/fleet.py``)
    ride this class unchanged below the init handshake."""

    #: ``python -m <module>`` entry point of the child worker
    _WORKER_MODULE = "bigdl_tpu.serve.cluster"

    #: flight-recorder transport attribution (obs/recorder.py)
    transport = "stdio"

    def _init_frame(self, model, worker_kwargs) -> dict:
        """The first frame shipped to the child (the spawn handshake)."""
        return {"op": "init", "model": model, "engine": worker_kwargs}

    def __init__(self, model, name: str = "proc", env=None,
                 spawn_timeout: float = 120.0, **engine_kwargs):
        self.name = name
        self._lock = threading.Lock()
        self._wlock = threading.Lock()
        self._futures: dict = {}   # rid -> (future, trace-or-None)
        self._ids = iter(range(1, 1 << 62))
        self._dead = False
        self._closing = False
        self._stderr_ring = deque(maxlen=_STDERR_LINES)
        #: lazy parent-side delivery thread for incremental token
        #: frames (streaming decode replicas) — user callbacks must
        #: never run on, or block, the frame-reader thread
        self._delivery = None

        child_env = dict(os.environ)
        # the child must NOT inherit the parent's event-log dir: its
        # events reach the parent's log over `op: event` frames
        # (append_foreign, attributed replica=<name>); an inherited
        # BIGDL_OBS_DIR would make the child open the same
        # events.p0.jsonl and double-write every event.  An explicit
        # env={...} override below can still opt a child into its own
        # file sink.
        from bigdl_tpu.obs import events as obs_events
        child_env.pop(obs_events.ENV_DIR, None)
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        child_env["PYTHONPATH"] = (repo_root + os.pathsep
                                   + child_env.get("PYTHONPATH", ""))
        if env:
            child_env.update(env)
        # the child engine's registry series must not collide with a
        # same-named engine in another replica once snapshots merge
        engine_kwargs = dict(engine_kwargs)
        engine_kwargs.setdefault("name", name)
        # stderr CAPTURED, not discarded: the ring tail is the first
        # thing a dead-replica postmortem needs (the old DEVNULL made
        # every child crash an unexplained DeadReplicaError)
        self.proc = subprocess.Popen(
            [sys.executable, "-m", self._WORKER_MODULE],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, env=child_env)
        self._stderr_reader = threading.Thread(
            target=self._stderr_loop, daemon=True,
            name=f"bigdl-serve-{name}-stderr")
        self._stderr_reader.start()
        try:
            _write_frame(self.proc.stdin,
                         self._init_frame(model, engine_kwargs),
                         self._wlock)
        except (OSError, ValueError) as e:
            # the child died before reading its init frame (EPIPE): a
            # raw pipe error carries nothing — raise the typed spawn
            # error with whatever the child said on stderr
            raise self._spawn_error(
                f"replica {name} rejected the init frame: "
                f"{type(e).__name__}: {e}") from e
        self._reader = threading.Thread(target=self._read_loop,
                                        daemon=True,
                                        name=f"bigdl-serve-{name}-reader")
        self._ready = threading.Event()
        self._reader.start()
        if not self._ready.wait(spawn_timeout):
            raise self._spawn_error(
                f"replica {name} did not come up in {spawn_timeout}s")
        if self._dead:
            raise self._spawn_error(
                f"replica {name} died during startup (exit code "
                f"{self.proc.poll()})")

    # -- wire ---------------------------------------------------------------
    def _read_loop(self):
        while True:
            try:
                msg = _read_frame(self.proc.stdout)
            except FrameProtocolError as e:
                # a malformed/corrupt/desynced frame from the child is
                # indistinguishable from death for recovery purposes,
                # but the POSTMORTEM must name the protocol violation
                logger.warning("replica %s: %s; treating as death",
                               self.name, e)
                msg = None
            except (OSError, ValueError, EOFError, pickle.PickleError):
                msg = None
            if msg is None:
                self._on_death()
                return
            op = msg.get("op")
            if op == "ready":
                self._ready.set()
                continue
            if op == "event":
                # a child obs event forwarded over the frame protocol:
                # land it in the PARENT's event log, attributed
                self._forward_event(msg.get("event"))
                continue
            if op == "tokens":
                # an incremental token chunk from a streaming decode
                # request (serve/fleet.py fleet_main): feed the rpc
                # future WITHOUT popping it — the terminal reply frame
                # still resolves it.  The chunk's absolute start index
                # rides the frame so the StreamFuture dedup survives
                # the process hop.  Fed through a parent-side delivery
                # thread, NOT inline: user on_tokens callbacks hang off
                # the piped chain, and a slow (or cross-request
                # blocking) consumer must never park the reader thread
                # that every reply frame from this replica rides.
                with self._lock:
                    entry = self._futures.get(msg.get("id"))
                if entry is not None:
                    self._ensure_delivery().enqueue(
                        entry[0], msg.get("tokens") or [],
                        msg.get("start"), None)
                continue
            with self._lock:
                entry = self._futures.pop(msg.get("id"), None)
            if entry is None:
                continue
            fut, tr = entry
            if msg.get("ok"):
                if tr is not None:
                    # hops the child stamped after the wire crossing
                    tr.extend(msg.get("hops") or ())
                    if msg.get("rec"):
                        # the child's flight-recorder notes merge into
                        # the parent's record (same frame as the hops)
                        from bigdl_tpu.obs import recorder as obs_rec
                        obs_rec.note(tr.trace_id, **msg["rec"])
                if fut.streaming and self._delivery is not None:
                    # streaming submits resolve through the delivery
                    # FIFO so the final token chunk always lands before
                    # result() unblocks (the decoder-side contract)
                    self._delivery.resolve(fut, msg.get("out"))
                else:
                    fut.set_result(msg.get("out"))
            else:
                cls = _EXC_TYPES.get(msg.get("etype"), RuntimeError)
                fut.set_exception(cls(msg.get("error", "replica error")))

    def _stderr_loop(self):
        try:
            for raw in self.proc.stderr:
                self._stderr_ring.append(
                    raw.decode("utf-8", errors="replace").rstrip("\n"))
        except (OSError, ValueError):  # pragma: no cover - pipe teardown
            pass

    def stderr_tail(self, n: int | None = None) -> list:
        """Last captured stderr lines (newest last)."""
        tail = list(self._stderr_ring)
        return tail if n is None else tail[-n:]

    def _tail_suffix(self, n: int = 8) -> str:
        tail = self.stderr_tail(n)
        if not tail:
            return ""
        return "; stderr tail:\n  " + "\n  ".join(tail)

    def _dead_error(self) -> DeadReplicaError:
        return DeadReplicaError(
            f"replica {self.name} (pid {self.proc.pid}) died"
            f"{self._tail_suffix()}")

    def _spawn_error(self, message: str) -> ReplicaSpawnError:
        """Constructor-failure epilogue: kill the child (idempotent),
        drain its stderr to EOF so the tail is complete, and return the
        typed error with the tail attached — a spawn failure must leak
        neither the subprocess nor the reason it died."""
        self._closing = True     # death past this point is expected
        try:
            self.proc.kill()
        except OSError:   # pragma: no cover - already gone
            pass
        try:
            self.proc.wait(timeout=5.0)
        except Exception:   # pragma: no cover - still exiting
            pass
        self._stderr_reader.join(timeout=2.0)
        return ReplicaSpawnError(message + self._tail_suffix(),
                                 stderr_tail=self.stderr_tail())

    def _forward_event(self, event):
        if not isinstance(event, dict):
            return
        try:
            from bigdl_tpu.obs import events as obs_events
            log = obs_events.get()
            if log is not None:
                log.append_foreign(event, replica=self.name)
        except Exception:  # pragma: no cover - telemetry must not kill IO
            logger.warning("replica %s: event forward failed", self.name)

    def _on_death(self):
        with self._lock:
            if self._dead:
                return
            self._dead = True
            orphans = [f for f, _ in self._futures.values()]
            self._futures.clear()
        # release a constructor stuck waiting for the ready frame — a
        # child that crashes during startup must fail fast, not after
        # the full spawn timeout (__init__ re-checks _dead)
        self._ready.set()
        # drain the stderr pipe to EOF before freezing the tail: the
        # stdout EOF that got us here can beat the child's last stderr
        # line by a scheduling quantum
        if threading.current_thread() is not self._stderr_reader:
            self._stderr_reader.join(timeout=2.0)
        # poll only AFTER the drain: a crashing child closes stdout
        # before it finishes dying, and a stale early poll() reading
        # None would skip the crash bundle below for idle-replica
        # deaths (no orphans to trip the other condition)
        exit_code = self.proc.poll()
        if exit_code is None and not self._closing:
            try:
                exit_code = self.proc.wait(timeout=2.0)
            except Exception:  # pragma: no cover - still exiting
                pass
        err = self._dead_error()
        for fut in orphans:
            if not fut.done():
                fut.set_exception(err)
        # an UNEXPECTED death (not close()) leaves a crash bundle with
        # the child's stderr tail — the blackout the old DEVNULL caused
        if not self._closing and (orphans or exit_code not in (0, None)):
            try:
                from bigdl_tpu.obs import diagnostics
                diagnostics.dump_crash_bundle(
                    f"replica-{self.name}",
                    extra={"replica": self.name, "pid": self.proc.pid,
                           "exit_code": exit_code,
                           "orphaned_requests": len(orphans)},
                    texts={"stderr.txt": "\n".join(self.stderr_tail())})
            except Exception:  # pragma: no cover - diagnostics bug
                pass

    def _ensure_delivery(self) -> TokenDelivery:
        if self._delivery is None:
            self._delivery = TokenDelivery(name=self.name)
        return self._delivery

    def _rpc(self, op: str, timeout: float | None = None, **fields):
        fut = self._send(op, **fields)
        return fut.result(timeout=timeout)

    def _send(self, op: str, _trace=None, **fields) -> Future:
        rid = next(self._ids)
        # StreamFuture so decode submits can receive incremental token
        # frames (op: tokens); every other rpc just resolves it
        fut = StreamFuture()
        with self._lock:
            if self._dead:
                fut.set_exception(self._dead_error())
                return fut
            self._futures[rid] = (fut, _trace)
        try:
            _write_frame(self.proc.stdin,
                         dict(fields, op=op, id=rid), self._wlock)
        except FrameProtocolError as e:
            # an over-bound payload fails ONLY this rpc — nothing was
            # written, the stream stays frame-aligned, the replica lives
            with self._lock:
                self._futures.pop(rid, None)
            fut.set_exception(e)
        except (OSError, ValueError):
            self._on_death()
        return fut

    # -- replica surface ----------------------------------------------------
    def submit(self, x, trace=None) -> Future:
        return self._send(
            "submit", _trace=trace, x=np.asarray(x),
            trace=None if trace is None else trace.to_wire())

    def inflight(self) -> int:
        with self._lock:
            return len(self._futures)

    def alive(self) -> bool:
        return not self._dead and self.proc.poll() is None

    def stats(self) -> dict:
        return self._rpc("stats", timeout=30.0)

    def telemetry(self) -> dict:
        """``{"stats": engine.stats(), "registry": <metrics snapshot>}``
        pulled from the child over the frame protocol."""
        return self._rpc("telemetry", timeout=30.0)

    def registry_snapshot(self) -> dict | None:
        """The child process's metrics-registry snapshot (obs/metrics
        wire format) for the pool's fleet merge."""
        return self.telemetry().get("registry")

    def weights_version(self) -> int:
        return self._rpc("version", timeout=30.0)

    def stage_weights(self, params, state, version=None):
        self._rpc("stage", timeout=120.0, params=params, state=state,
                  version=version)

    def commit_weights(self) -> int:
        return self._rpc("commit", timeout=30.0)

    def rollback_weights(self):
        self._rpc("rollback", timeout=30.0)

    def revert_weights(self) -> int:
        return self._rpc("revert", timeout=30.0)

    def close(self, drain: bool = True):
        self._closing = True    # death past this point is expected
        if self.alive():
            try:
                self._rpc("close", timeout=60.0, drain=drain)
            except Exception:
                pass
        try:
            self.proc.wait(timeout=10.0)
        except subprocess.TimeoutExpired:
            self.proc.kill()
        self._on_death()
        # an unexpected death dumps its crash bundle on the READER
        # thread; close() returning means death handling (bundle
        # included) is complete
        if threading.current_thread() is not self._reader:
            self._reader.join(timeout=10.0)
        if self._delivery is not None:
            # flush pending chunks/resolutions, then stop the thread
            self._delivery.close()
            self._delivery = None


def wait_drained(router, victim, timeout: float):
    """Block until a drain-marked replica's backlog (router-outstanding
    + its own inflight) resolves; a victim dying mid-drain counts as
    drained — its orphans ride the requeue-on-death path.  Raises
    TimeoutError (nothing dropped, victim left draining) on expiry.
    Shared by ``ReplicaPool.remove_replica`` and
    ``DecodeFleet.remove_replica``."""
    t0 = time.monotonic()
    while True:
        pending = router.pending_for(victim)
        try:
            if victim.alive():
                pending += victim.inflight()
        except Exception:   # pragma: no cover - racing a death
            pass
        if pending == 0:
            return
        if time.monotonic() - t0 > timeout:
            raise TimeoutError(
                f"replica {getattr(victim, 'name', victim)} did not "
                f"drain in {timeout}s ({pending} pending); left "
                f"draining, nothing dropped")
        time.sleep(0.005)


class DynamicMembership:
    """The shared dynamic-membership surface (docs/serving.md
    "Autoscaling"): membership gauges, the drain-to-zero
    ``remove_replica`` contract, and the autoscaler hookup —
    :class:`ReplicaPool` and :class:`~bigdl_tpu.serve.fleet.DecodeFleet`
    both mix this in so the drain/accounting logic cannot diverge.

    Host-class requirements: ``name``, ``replicas``, ``router``,
    ``_scale_lock`` (RLock) and ``_warming`` exist before
    :meth:`_init_membership` is called; ``add_replica(reason=)`` is
    host-specific (the warm bar differs: weight versions for engine
    pools, compile-only for decode fleets)."""

    def _init_membership(self):
        from bigdl_tpu.obs import metrics as obs_metrics
        self.autoscaler = None
        reg = obs_metrics.get()
        self._m_members = {
            state: reg.gauge(
                "fleet_replicas",
                "pool membership by state (live/warming/draining)",
                state=state, pool=self.name)
            for state in ("live", "warming", "draining")}
        self._m_scale = {
            d: reg.counter("fleet_scale_events_total",
                           "committed scale actions by direction",
                           direction=d, pool=self.name)
            for d in ("up", "down")}
        self._update_membership()

    def membership(self) -> dict:
        """``{"live": n, "warming": n, "draining": n}`` — the counts
        behind the ``fleet_replicas`` gauges and serve_top's ``fleet:``
        line (live excludes draining; dead replicas count nowhere)."""
        live = draining = 0
        for r in list(self.replicas):
            try:
                ok = r.alive()
            except Exception:
                ok = False
            if not ok:
                continue
            if self.router.is_draining(r):
                draining += 1
            else:
                live += 1
        with self._scale_lock:
            warming = self._warming
        return {"live": live, "warming": warming, "draining": draining}

    def _update_membership(self) -> dict:
        m = self.membership()
        try:
            for state, gauge in self._m_members.items():
                gauge.set(m[state])
        except Exception:   # pragma: no cover - registry mid-teardown
            pass
        return m

    def _resolve_victim(self, replica):
        """An instance, a name, or None (→ the newest non-draining
        live replica: scale-down unwinds scale-up, LIFO)."""
        if replica is None:
            for r in reversed(self.replicas):
                try:
                    if r.alive() and not self.router.is_draining(r):
                        return r
                except Exception:
                    continue
            return None
        if isinstance(replica, str):
            return next((r for r in self.replicas
                         if getattr(r, "name", None) == replica), None)
        return replica if replica in self.replicas else None

    def remove_replica(self, replica=None, reason: str = "manual",
                       timeout: float = 120.0):
        """Drain one replica out of the pool with ZERO dropped futures
        (the hot-swap bar): mark it drain-only in the router (dispatch
        skips it, its queued/in-flight requests still complete), wait
        for its backlog to resolve, then detach and close it.  A victim
        dying mid-drain rides the normal requeue-on-death path.
        ``replica`` may be an instance, a name, or None (newest live
        replica).  Raises TimeoutError — replica left draining, nothing
        dropped — if the backlog does not resolve in ``timeout``."""
        from bigdl_tpu.obs import events
        with self._scale_lock:
            victim = self._resolve_victim(replica)
            if victim is None:
                raise ValueError(f"no such live replica: {replica!r}")
            live = [r for r in self.replicas
                    if r is not victim and r.alive()
                    and not self.router.is_draining(r)]
            if not live:
                raise ValueError(
                    "refusing to drain the last live replica")
            self.router.mark_draining(victim)
        self._update_membership()
        try:
            wait_drained(self.router, victim, timeout)
        except TimeoutError:
            self._update_membership()
            raise
        with self._scale_lock:
            self.router.remove_replica(victim)
            if victim in self.replicas:
                self.replicas.remove(victim)
        try:
            victim.close(drain=True)
        except Exception:   # pragma: no cover - died mid-drain
            pass
        self._update_membership()
        self._m_scale["down"].inc()
        events.emit("scale", kind="down",
                    replica=getattr(victim, "name", repr(victim)),
                    reason=reason, replicas=len(self.replicas))
        return victim

    def start_autoscaler(self, **kwargs):
        """Start the SLO-driven autoscaler loop (``serve/autoscale.py``)
        over ``merged_registry()`` and the membership verbs
        (``BIGDL_SERVE_AUTOSCALE=1`` auto-starts one at construction).
        Closed with the pool; idempotent — but kwargs passed to an
        ALREADY-RUNNING autoscaler (e.g. one the env auto-started) are
        a config conflict and logged loudly rather than silently
        dropped."""
        if self.autoscaler is not None:
            if kwargs:
                logger.warning(
                    "start_autoscaler(%s): an autoscaler is already "
                    "running (BIGDL_SERVE_AUTOSCALE auto-start?); the "
                    "new settings are IGNORED — close() it first to "
                    "reconfigure", ", ".join(sorted(kwargs)))
            return self.autoscaler
        from bigdl_tpu.serve import autoscale as autoscale_mod
        self.autoscaler = autoscale_mod.Autoscaler(self, **kwargs).start()
        return self.autoscaler


# ---------------------------------------------------------------------------
# the pool
# ---------------------------------------------------------------------------

class ReplicaPool(DynamicMembership):
    """N replicas + router + weight store: the serving control plane.

    ``ReplicaPool(model, n_replicas=4)`` builds in-process replicas
    (each its own ServeEngine and executable set — all riding the
    shared xcache, so N replicas of one architecture compile each
    bucket ONCE); ``process=True`` spawns subprocess replicas instead.
    ``replicas=[...]`` injects pre-built replicas (tests, heterogeneous
    pools) and ``replica_factory=fn(name)`` overrides how NEW replicas
    are built (tests, custom spawn env).  Requests flow
    ``pool.submit(x, priority=, slo_ms=)`` → router admission →
    least-loaded replica.

    Membership is DYNAMIC (docs/serving.md "Autoscaling"):
    :meth:`add_replica` spawns and warms a replica — through the xcache
    and the fleet's COMMITTED weight version — before the router may
    dispatch to it, and :meth:`remove_replica` drains a victim to zero
    backlog before closing it.  ``BIGDL_SERVE_AUTOSCALE=1`` arms the
    closed loop (``serve/autoscale.py``) over these verbs."""

    def __init__(self, model=None, n_replicas: int | None = None,
                 process: bool = False, replicas=None,
                 slo_ms: float | None = None, shed: bool | None = None,
                 est_ms: float = 50.0, store: WeightStore | None = None,
                 trace_sample: float | None = None,
                 name: str | None = None, replica_factory=None,
                 remote: bool | None = None, hosts=None, token=None,
                 **engine_kwargs):
        self.name = name or f"pool{next(_POOL_SEQ)}"
        self._model = model
        self._process = bool(process)
        self._engine_kwargs = dict(engine_kwargs)
        self._replica_factory = replica_factory
        # cross-host fleet (docs/serving.md "Cross-host fleet"):
        # remote=True (or hosts=/BIGDL_SERVE_HOSTS) leases replica-agent
        # addresses from a HostInventory and speaks TCP instead of
        # spawning local children — the autoscaler then scales across
        # the inventory, and exhaustion surfaces as ReplicaSpawnError
        # (the same circuit-breaker type as a local spawn failure)
        self._inventory = None
        if remote or (remote is None and hosts is not None):
            from bigdl_tpu.serve import remote as remote_mod
            self._inventory = remote_mod.HostInventory(hosts, token=token)
        elif remote is None and hosts is None and token is None:
            from bigdl_tpu.serve import remote as remote_mod
            if remote_mod.hosts_default():
                self._inventory = remote_mod.HostInventory()
        #: serializes membership changes against rollouts: a replica
        #: added mid-rollout must land on the COMMITTED version, never
        #: the staged one (the two-phase-rollout bar)
        self._scale_lock = threading.RLock()
        #: last version a rollout COMMITTED fleet-wide (None = the
        #: construction weights; a late spawn then captures the model's
        #: current weights, the documented engine semantic)
        self._served_version: int | None = None
        self._warming = 0
        self._next_replica = 0
        if replicas is None:
            if model is None and replica_factory is None:
                raise ValueError(
                    "ReplicaPool needs a model, replicas, or a "
                    "replica_factory")
            n = replicas_default() if n_replicas is None else int(n_replicas)
            replicas = []
            try:
                for _ in range(n):
                    replicas.append(self._spawn_replica(
                        self._next_name()))
            except Exception:
                # one bad replica fails construction CLEANLY: the
                # already-spawned good ones are closed, no subprocess
                # leaks past the raise (the ReplicaSpawnError contract)
                for r in replicas:
                    try:
                        r.close(drain=False)
                    except Exception:   # pragma: no cover - teardown
                        pass
                raise
        self.replicas = list(replicas)
        self._next_replica = max(self._next_replica, len(self.replicas))
        self.router = Router(self.replicas, slo_ms=slo_ms, shed=shed,
                             est_ms=est_ms, trace_sample=trace_sample)
        self.store = store if store is not None else WeightStore()
        self.exporter = None
        self.alerts = None
        self._init_membership()
        try:
            # BIGDL_OBS_HBM_SAMPLE=<s>: cadence HBM sampler for the
            # serving process (process-wide, started once)
            from bigdl_tpu.obs import ledger as obs_ledger
            obs_ledger.maybe_start_sampler_from_env()
        except Exception:   # pragma: no cover - obs layer unavailable
            pass
        from bigdl_tpu.obs import export as obs_export
        port = obs_export.export_port_default()
        if port is not None:
            try:
                self.start_exporter(port=port)
            except OSError as e:
                # e.g. a second pool in this process with a fixed
                # BIGDL_SERVE_EXPORT_PORT: the replicas are already
                # spawned, so a bind failure must not abort (and leak)
                # the pool — serve without the exporter instead
                logger.warning("exporter auto-start on port %d failed "
                               "(%s); pool runs without one", port, e)
        from bigdl_tpu.serve import autoscale as autoscale_mod
        if autoscale_mod.autoscale_default():
            # BIGDL_SERVE_AUTOSCALE=1: close the loop — the SLO-driven
            # autoscaler watches merged_registry() and drives
            # add_replica/remove_replica against the env-declared
            # min/max bounds and cadence
            self.start_autoscaler()

    # -- request path -------------------------------------------------------
    def submit(self, x, priority: int = 1,
               slo_ms: float | None = None) -> Future:
        return self.router.submit(x, priority=priority, slo_ms=slo_ms)

    def submit_many(self, rows, priority: int = 1,
                    slo_ms: float | None = None) -> list:
        return self.router.submit_many(rows, priority=priority,
                                       slo_ms=slo_ms)

    def predict(self, features) -> np.ndarray:
        futs = self.submit_many(np.asarray(features))
        return np.stack([f.result() for f in futs])

    # -- dynamic membership (docs/serving.md "Autoscaling") -----------------
    def _next_name(self) -> str:
        n = self._next_replica
        self._next_replica += 1
        if self._inventory is not None:
            return f"remote{n}"
        return f"{'proc' if self._process else 'local'}{n}"

    def _spawn_replica(self, name: str, env=None, **overrides):
        """Build one replica the way this pool was configured
        (``replica_factory`` > remote lease > subprocess > in-process
        engine).  Construction IS the xcache warmup: the engine
        compiles every bucket before this returns."""
        if self._replica_factory is not None:
            return self._replica_factory(name)
        if self._model is None:
            raise RuntimeError(
                "dynamic membership needs the pool's model (this pool "
                "was built from pre-built replicas; pass "
                "replica_factory= to scale it)")
        kw = dict(self._engine_kwargs)
        kw.update(overrides)
        if self._inventory is not None:
            from bigdl_tpu.serve import remote as remote_mod
            kw.pop("env", None)
            addr = self._inventory.lease()
            try:
                return remote_mod.RemoteReplica(
                    addr, self._model, name=name,
                    token=self._inventory.token,
                    on_release=self._inventory.release, **kw)
            except Exception:
                # failed spawns hand the host back: the autoscaler's
                # retry may succeed once the agent is reachable again
                self._inventory.release(addr)
                raise
        if self._process:
            # a pool-level env={...} (chaos plans, worker platform)
            # lives in engine_kwargs for back-compat with the old
            # inline-construction path; the per-call env= wins
            if env is None:
                env = kw.pop("env", None)
            else:
                kw.pop("env", None)
            return ProcessReplica(self._model, name=name, env=env, **kw)
        return LocalReplica(ServeEngine(self._model, name=name, **kw),
                            name=name)

    def add_replica(self, name: str | None = None,
                    reason: str = "manual", env=None, **overrides):
        """Spawn, WARM, then register one replica.  The warmup bar: the
        replica compiles its executables at construction (through the
        shared xcache — an identical architecture costs zero new
        compiles) and is rolled to the fleet's COMMITTED weight version
        before the router may dispatch to it.  A rollout racing this
        call wins: the warm loop re-stages until the version it warmed
        to is still the committed one at registration time, so a
        scale-up mid-rollout can never serve a staged-but-uncommitted
        version.  Emits a schema-validated ``scale``/``up`` event;
        spawn/warm failure closes the half-built replica and re-raises
        (the autoscaler's retry/backoff + circuit breaker sit above
        this)."""
        from bigdl_tpu.obs import events
        if name is None:
            with self._scale_lock:
                name = self._next_name()
        with self._scale_lock:
            self._warming += 1
        self._update_membership()
        try:
            replica = self._spawn_replica(name, env=env, **overrides)
        except Exception:
            with self._scale_lock:
                self._warming -= 1
            self._update_membership()
            raise
        try:
            while True:
                with self._scale_lock:
                    version = self._served_version
                if (version is not None
                        and replica.weights_version() != version):
                    params, state = self.store.get(version)
                    replica.stage_weights(params, state, version)
                    replica.commit_weights()
                with self._scale_lock:
                    if self._served_version == version:
                        # still the committed version: take traffic
                        self.replicas.append(replica)
                        self.router.add_replica(replica)
                        self._warming -= 1
                        break
                # a rollout committed while we warmed — re-warm to the
                # new served version before touching the dispatch set
        except Exception:
            with self._scale_lock:
                self._warming -= 1
            self._update_membership()
            try:
                replica.close(drain=False)
            except Exception:   # pragma: no cover - already dead
                pass
            raise
        self._update_membership()
        self._m_scale["up"].inc()
        events.emit("scale", kind="up", replica=name, reason=reason,
                    replicas=len(self.replicas))
        return replica

    # -- rollout ------------------------------------------------------------
    def rollout(self, params=None, state=None,
                version: int | None = None) -> int:
        """Two-phase hot swap: stage on every live replica, then flip.
        Pass (params, state) to publish new weights, or ``version`` to
        roll the fleet to/back to a stored version.  Returns the served
        version; raises :class:`RolloutError` (after converging every
        replica back to the prior version) when any replica fails.

        Serialized against dynamic membership (``_scale_lock``): a
        replica being ADDED during the stage→commit window warms to the
        version this rollout commits before it may take traffic, and a
        DRAINING replica is excluded from the target set — its backlog
        finishes on the version it already has, and its mid-drain close
        can never fail the commit."""
        with self._scale_lock:
            return self._rollout_locked(params, state, version)

    def _rollout_locked(self, params, state, version) -> int:
        from bigdl_tpu.obs import events

        if params is not None:
            version = self.store.put(params, state)
        elif version is None:
            version = self.store.latest()
            if version is None:
                raise ValueError("rollout with an empty WeightStore")
        params, state = self.store.get(version)
        reps = self.router.live_replicas(draining=False)
        if not reps:
            raise RolloutError("no live replica to roll out to")
        events.emit("serve", kind="rollout_begin", version=version,
                    replicas=len(reps))

        staged = []
        try:
            for r in reps:
                r.stage_weights(params, state, version)
                staged.append(r)
        except Exception as e:
            for r in staged:
                try:
                    r.rollback_weights()
                except Exception:  # pragma: no cover - replica died too
                    pass
            events.emit("serve", kind="rollout_rollback", version=version,
                        phase="stage", error=f"{type(e).__name__}: {e}")
            raise RolloutError(
                f"stage phase failed on replica "
                f"{getattr(reps[len(staged)], 'name', '?')}: {e}") from e

        committed = []
        try:
            for r in reps:
                r.commit_weights()
                committed.append(r)
        except Exception as e:
            # converge BACK: flip committed replicas to the previous
            # pair, drop the stage on the rest — no mixed-version fleet
            for r in committed:
                try:
                    r.revert_weights()
                except Exception:  # pragma: no cover
                    pass
            for r in reps[len(committed):]:
                try:
                    r.rollback_weights()   # no-op when already consumed
                except Exception:  # pragma: no cover
                    pass
            events.emit("serve", kind="rollout_rollback", version=version,
                        phase="commit", error=f"{type(e).__name__}: {e}")
            raise RolloutError(
                f"commit phase failed; fleet reverted: {e}") from e

        self._served_version = version
        events.emit("serve", kind="rollout_commit", version=version,
                    replicas=len(committed))
        return version

    @property
    def served_version(self) -> int | None:
        """The last version a rollout committed fleet-wide (None until
        the first rollout: replicas serve their construction capture).
        The warm bar :meth:`add_replica` rolls a new replica to."""
        with self._scale_lock:
            return self._served_version

    # -- telemetry / lifecycle ----------------------------------------------
    def merged_registry(self) -> dict:
        """One metrics snapshot covering the WHOLE fleet: this
        process's registry (the router + every LocalReplica engine +
        decoders + xcache) folded with each subprocess replica's
        registry snapshot, pulled over the frame protocol.  Histograms
        merge exactly (pinned bounds), counters/gauges per their agg —
        the fleet p99 this returns IS the pooled p99
        (``obs/metrics.merge``).

        Scope: the in-process half is the PROCESS-LIFETIME registry
        (Prometheus default-registry semantics), so series from earlier
        pools or engines in this process are included; counters stay
        monotonic across pool turnover.  Per-pool deltas come from
        rate-differencing two snapshots, not from a fresh-at-zero
        registry."""
        from bigdl_tpu.obs import metrics as obs_metrics
        snaps = [obs_metrics.get().snapshot()]
        for r in list(self.replicas):   # membership may change under us
            try:
                snaps.append(r.registry_snapshot())
            except Exception:  # pragma: no cover - racing a death
                logger.warning("telemetry pull failed for replica %s",
                               getattr(r, "name", r))
        return obs_metrics.merge(snaps)

    def prometheus(self) -> str:
        """The merged fleet registry in Prometheus text exposition
        format (what the exporter's ``/metrics`` serves)."""
        from bigdl_tpu.obs import metrics as obs_metrics
        return obs_metrics.render_prometheus(self.merged_registry())

    def start_exporter(self, port: int = 0, host: str = "127.0.0.1"):
        """Start the pull exporter over :meth:`merged_registry`
        (``BIGDL_SERVE_EXPORT_PORT`` auto-starts one at pool
        construction).  Returns the exporter; idempotent."""
        if self.exporter is None:
            from bigdl_tpu.obs import export as obs_export
            self.exporter = obs_export.MetricsExporter(
                self.merged_registry, port=port, host=host)
        return self.exporter

    def start_alerts(self, rules=None, interval: float = 5.0,
                     **rule_kwargs):
        """Start the declarative alert engine (``obs/alerts.py``) over
        :meth:`merged_registry` — the fleet-truth signal surface the
        autoscaler story consumes.  ``rules=None`` installs the default
        set (SLO burn, shed rate, queue depth, step-time regression,
        HBM headroom; ``rule_kwargs`` tune its bounds).  Fired/resolved
        transitions emit ``alert`` events and ``alert_active`` gauges,
        which ride THIS process's registry and therefore the exporter
        and ``serve_top``'s ``alerts:`` line.  Closed with the pool;
        idempotent."""
        if self.alerts is None:
            from bigdl_tpu.obs import alerts as obs_alerts
            if rules is None:
                rules = obs_alerts.default_rules(**rule_kwargs)
            self.alerts = obs_alerts.AlertEngine(
                self.merged_registry, rules,
                interval=interval).start()
        return self.alerts

    def stats(self) -> dict:
        """Fleet snapshot: the router's counters, one entry per replica
        (its ``engine.stats()`` view), and ``merged`` — the TRUE merge
        of every replica's metrics registry (fleet-pooled latency
        quantiles, summed admission counters), not a dict of dicts."""
        from bigdl_tpu.obs import metrics as obs_metrics
        out = {"router": self.router.stats(), "replicas": []}
        snaps = [obs_metrics.get().snapshot()]
        for r in list(self.replicas):
            entry = {"name": getattr(r, "name", repr(r)),
                     "alive": False}
            try:
                entry["alive"] = r.alive()
                if entry["alive"]:
                    tele = getattr(r, "telemetry", None)
                    if tele is not None:
                        # ONE frame round-trip per subprocess replica:
                        # telemetry() ships stats + registry together
                        t = tele()
                        entry.update(t["stats"])
                        if t.get("registry"):
                            snaps.append(t["registry"])
                    else:
                        entry.update(r.stats())
                        snap = r.registry_snapshot()
                        if snap:
                            snaps.append(snap)
            except Exception:  # pragma: no cover - racing a death
                pass
            out["replicas"].append(entry)
        out["merged"] = obs_metrics.serving_summary(obs_metrics.merge(snaps))
        return out

    def drain(self, timeout: float = 60.0):
        self.router.drain(timeout)
        return self

    def close(self, drain: bool = True):
        if self.autoscaler is not None:
            # first: a scale decision must not race the teardown
            self.autoscaler.close()
            self.autoscaler = None
        if drain:
            try:
                self.router.drain()
            except TimeoutError:  # pragma: no cover - shutdown path
                pass
        if self.alerts is not None:
            self.alerts.close()
            self.alerts = None
        if self.exporter is not None:
            self.exporter.close()
            self.exporter = None
        self.router.close()
        for r in list(self.replicas):
            try:
                r.close(drain=drain)
            except Exception:  # pragma: no cover
                pass
        try:
            # uniquely-labelled, possibly short-lived membership/scale
            # series die with the pool (the decoder/tier precedent)
            from bigdl_tpu.obs import metrics as obs_metrics
            obs_metrics.get().drop_series(pool=self.name)
        except Exception:   # pragma: no cover - registry mid-teardown
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# transport-agnostic worker op dispatch
# ---------------------------------------------------------------------------

class WorkerOps:
    """Transport-agnostic op dispatch for one replica worker.

    The SAME handler instance answers frames whether they arrived over
    a ProcessReplica's stdio pipe (:func:`worker_main`) or a
    :class:`~tools.replica_agent.ReplicaAgent` TCP session — the op-code
    set cannot diverge between transports because there is exactly one
    implementation of it.  ``send(msg)`` is the transport's reply
    channel (frame writer or session outbox); :meth:`handle` returns
    False when the worker should shut down (the ``close`` op).

    Subclasses own a ``target`` (engine / decode replica / prefill
    replica) and extend :meth:`_handle_role` with role-specific ops."""

    role = "worker"

    def __init__(self, send):
        from bigdl_tpu.resilience import faults
        self.send = send
        self.injector = faults.get()
        self.target = None

    # -- reply plumbing -----------------------------------------------------
    def _ok(self, rid, out):
        self.send({"id": rid, "ok": True, "out": out})

    def _err(self, rid, exc):
        self.send({"id": rid, "ok": False, "etype": type(exc).__name__,
                   "error": str(exc)})

    def _reply(self, rid, fut, tr=None):
        try:
            out = fut.result()
            msg = {"id": rid, "ok": True, "out": out}
            if tr is not None:
                # only the hops stamped on THIS side of the wire; the
                # parent extends its original context with them
                msg["hops"] = tr.new_hops()
                from bigdl_tpu.obs import recorder as obs_rec
                rec = obs_rec.export_notes(tr.trace_id)
                if rec:
                    # this side's flight-recorder notes (decode flags,
                    # committed row, page counters, weight version)
                    # ride the SAME reply frame as the hops
                    msg["rec"] = rec
            self.send(msg)
        except BaseException as e:
            self._err(rid, e)

    def _chaos_kill(self):
        """``BIGDL_FAULTS=serve_kill@at=N``: die at the Nth submitted
        request — the requeue-on-replica-death chaos site.  For a TCP
        agent this kills the whole agent process (real death, not a
        blip — ``serve_partition`` is the blip site)."""
        inj = self.injector
        if (inj is not None and inj.armed("serve_kill")
                and inj.fires("serve_kill")):
            # last words on stderr: the parent's ring captures them and
            # the kill drill asserts the tail survives into
            # DeadReplicaError + the crash bundle
            print(f"serve_kill chaos fired: {self.role} replica pid "
                  f"{os.getpid()} exiting", file=sys.stderr, flush=True)
            sys.stdout.flush()
            os._exit(1)   # induced replica death (chaos drill)

    # -- dispatch -----------------------------------------------------------
    def handle(self, msg) -> bool:
        """Answer one frame; False = close requested (worker exits)."""
        op, rid = msg.get("op"), msg.get("id")
        try:
            if op == "ping":
                # connection-liveness probe (RemoteReplica's heartbeat;
                # harmless no-op over stdio)
                self._ok(rid, {"pong": True, "role": self.role})
            elif op == "stats":
                self._ok(rid, self.target.stats())
            elif op == "telemetry":
                from bigdl_tpu.obs import metrics as obs_metrics
                self._ok(rid, {"stats": self.target.stats(),
                               "registry": obs_metrics.get().snapshot()})
            elif op == "close":
                self.target.close(drain=msg.get("drain", True))
                self._ok(rid, None)
                return False
            else:
                return self._handle_role(op, rid, msg)
        except BaseException as e:
            self._err(rid, e)
        return True

    def _handle_role(self, op, rid, msg) -> bool:
        self.send({"id": rid, "ok": False, "etype": "ValueError",
                   "error": f"unknown op {op!r} for role "
                            f"{self.role!r}"})
        return True

    def close_abrupt(self):
        """EOF/protocol-death epilogue: close the target undrained."""
        if self.target is not None:
            self.target.close(drain=False)


class EngineOps(WorkerOps):
    """The serve-engine worker ops (submit + stats/telemetry + the
    two-phase rollout verbs) — :func:`replica_main`'s historical op set,
    now shared verbatim with the TCP agent."""

    role = "engine"

    def __init__(self, init, send):
        super().__init__(send)
        self.target = ServeEngine(init["model"], **init.get("engine", {}))

    def _handle_role(self, op, rid, msg) -> bool:
        engine = self.target
        if op == "submit":
            self._chaos_kill()
            from bigdl_tpu.obs import trace as obs_trace
            tr = (obs_trace.Trace.from_wire(msg["trace"])
                  if msg.get("trace") else None)
            fut = engine.submit(msg["x"], trace=tr)
            fut.add_done_callback(
                lambda f, r=rid, t=tr: self._reply(r, f, t))
        elif op == "version":
            self._ok(rid, engine.weights_version)
        elif op == "stage":
            engine.stage_weights(msg["params"], msg["state"],
                                 msg.get("version"))
            self._ok(rid, None)
        elif op == "commit":
            self._ok(rid, engine.commit_weights())
        elif op == "rollback":
            engine.rollback_weights()
            self._ok(rid, None)
        elif op == "revert":
            self._ok(rid, engine.revert_weights())
        else:
            return super()._handle_role(op, rid, msg)
        return True


def build_worker_ops(init, send) -> WorkerOps:
    """The ops handler for one ``init`` frame: engine by default, the
    fleet roles (decode/prefill) when the frame names one.  Shared by
    :func:`worker_main` (stdio) and the TCP replica agent."""
    role = init.get("role", "engine")
    if role == "engine":
        return EngineOps(init, send)
    from bigdl_tpu.serve import fleet as fleet_mod
    return fleet_mod.build_fleet_ops(init, send)


# ---------------------------------------------------------------------------
# subprocess replica worker
# ---------------------------------------------------------------------------

def worker_main(stdin=None, stdout=None):
    """Entry point of a ProcessReplica child: build the ops handler the
    init frame names (engine / decode / prefill) and answer frames
    until EOF/close.  Runs with its own jax runtime (platform via
    ``BIGDL_SERVE_WORKER_PLATFORM``, default cpu — on a real fleet each
    replica process owns its accelerator slice).

    ``BIGDL_FAULTS=serve_kill@at=N[,proc=...]`` kills this process at
    the Nth submitted request (``os._exit``) — the chaos drill for the
    router's requeue-on-replica-death path.  A malformed frame on stdin
    (:class:`~bigdl_tpu.serve.frames.FrameProtocolError`) is fatal for
    the worker: it logs the violation to stderr and exits rather than
    resynchronizing against a corrupt stream."""
    stdin = stdin or sys.stdin.buffer
    stdout = stdout or sys.stdout.buffer

    import jax
    platform = os.environ.get("BIGDL_SERVE_WORKER_PLATFORM", "cpu")
    jax.config.update("jax_platforms", platform)
    if platform == "cpu":
        from bigdl_tpu.utils.engine import set_cpu_device_count
        set_cpu_device_count(
            int(os.environ.get("BIGDL_SERVE_WORKER_DEVICES", "1")))
        jax.config.update("jax_default_matmul_precision", "highest")
    os.environ.setdefault("BIGDL_CHECK_SINGLETON", "0")

    init = _read_frame(stdin)
    if init is None or init.get("op") != "init":
        return 2
    if os.environ.get(ENV_SPAWN_FAIL, "0") != "0":
        # deterministic spawn-failure chaos: die during the warmup
        # handshake (init consumed, `ready` never sent) — the parent
        # must surface a typed ReplicaSpawnError with this line in the
        # stderr tail, and the autoscaler's circuit breaker must trip
        # instead of crash-looping
        print(f"induced spawn failure ({ENV_SPAWN_FAIL}): replica pid "
              f"{os.getpid()} exiting", file=sys.stderr, flush=True)
        return 7
    from bigdl_tpu.obs import events as obs_events
    wlock = threading.Lock()

    def send(msg):
        _write_frame(stdout, msg, wlock)

    # stream THIS process's obs events to the parent as they happen —
    # the sink is registered before the engine exists so even its
    # `start` event crosses the boundary.  Write failures are swallowed
    # by add_sink's contract (a dying pipe must not kill the emitter).
    log = obs_events.get()
    if log is not None:
        log.add_sink(lambda ev: send({"op": "event", "event": ev}))

    ops = build_worker_ops(init, send)
    send({"op": "ready", "pid": os.getpid()})

    while True:
        try:
            msg = _read_frame(stdin)
        except FrameProtocolError as e:
            print(f"frame protocol error on stdin: {e}; worker exiting",
                  file=sys.stderr, flush=True)
            break
        if msg is None:
            break
        if not ops.handle(msg):
            return 0
    ops.close_abrupt()
    return 0


def replica_main(stdin=None, stdout=None):
    """Back-compat alias: the engine worker entry point (init frames
    without a ``role`` build an :class:`EngineOps`)."""
    return worker_main(stdin, stdout)


if __name__ == "__main__":
    sys.exit(worker_main())
