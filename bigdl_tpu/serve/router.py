"""SLO-aware admission router over a pool of serve replicas
(docs/serving.md "Control plane").

The single-engine queue (serve/engine.py) maximizes one chip; a fleet
needs the layer the reference delegated to Spark's scheduler: one
admission point in front of N replicas that decides *which* replica
serves a request, *when* a request is hopeless and must be shed instead
of served late, and *what* happens to requests parked on a replica that
died.  This router is that layer, in the Orca/continuous-batching
lineage (Yu et al., OSDI'22) reduced to the machinery the repo already
has:

- **Priority + deadline admission queue**: every request carries a
  priority class (lower = more urgent) and an absolute deadline derived
  from its SLO (``BIGDL_SERVE_SLO_MS`` default, per-request override).
  The dispatch order is (priority, deadline, arrival) — urgent classes
  drain first, EDF inside a class.
- **Least-loaded dispatch**: the next request goes to the live replica
  with the fewest outstanding requests (the ``engine.stats()``
  queue-depth/inflight signal, rate-differenced via the monotonic
  accepted/completed counters).
- **Shed-on-overload** (``BIGDL_SERVE_SHED``, default on): a request
  whose remaining deadline budget is smaller than the current service
  estimate is failed *now* with :class:`SheddedError` instead of being
  served past its deadline.  Because high-priority requests dispatch
  first, overload sheds the lowest classes first — the
  shed-before-deadline-miss ordering the overload test pins.
- **Requeue-on-replica-death**: a replica failing with
  :class:`DeadReplicaError` (or found dead by the health monitor — the
  watchdog-style liveness probe) has its outstanding requests pushed
  back into the admission queue and retried on a surviving replica, so
  a dead replica fails no future another replica can serve.  Genuine
  model errors (poisoned rows, shape mismatches) are NOT retried — they
  would fail identically anywhere.

The router never touches jax: replicas are anything with the small
``submit/stats/inflight/alive`` surface (``serve/cluster.py`` provides
in-process and subprocess implementations; ``serve/remote.py`` puts
the same surface on TCP).  Cross-host note: a ``RemoteReplica``
reports ``alive() == True`` through a network blip shorter than its
liveness budget — the health monitor therefore does NOT requeue on a
transient partition; requeue-exactly-once happens only when the blip
budget is spent and the replica fails typed with
:class:`DeadReplicaError` (docs/serving.md "Cross-host fleet").

Telemetry: the admission counters live in the mergeable metrics
registry (``obs/metrics.py``, labelled ``router=<name>``), and the
router is where request TRACES begin and end — admission mints a trace
context for every sampled request (``BIGDL_OBS_TRACE_SAMPLE``,
``obs/trace.py``), the dispatch path stamps queue/dispatch/shed/requeue
hops, and completion emits the finished chain as one ``trace`` obs
event.
"""
from __future__ import annotations

import heapq
import inspect
import itertools
import logging
import os
import threading
import time
from concurrent.futures import Future

from bigdl_tpu.obs import recorder as obs_recorder
from bigdl_tpu.obs import trace as obs_trace
from bigdl_tpu.serve.engine import SheddedError  # noqa: F401 (re-export)
from bigdl_tpu.serve.streaming import StreamFuture, ttft_ms_default

logger = logging.getLogger("bigdl_tpu.serve")

_ROUTER_SEQ = itertools.count()

ENV_REPLICAS = "BIGDL_SERVE_REPLICAS"
ENV_SLO_MS = "BIGDL_SERVE_SLO_MS"
ENV_SHED = "BIGDL_SERVE_SHED"

DEFAULT_REPLICAS = 2
DEFAULT_SLO_MS = 0.0       # 0 = no deadline unless the request sets one
#: EWMA weight for the service-time estimate the shed policy uses
_EST_ALPHA = 0.2


def replicas_default() -> int:
    try:
        return max(1, int(os.environ.get(ENV_REPLICAS, DEFAULT_REPLICAS)))
    except ValueError:
        return DEFAULT_REPLICAS


def slo_ms_default() -> float:
    try:
        return max(0.0, float(os.environ.get(ENV_SLO_MS, DEFAULT_SLO_MS)))
    except ValueError:
        return DEFAULT_SLO_MS


def shed_default() -> bool:
    return os.environ.get(ENV_SHED, "1") != "0"


class DeadReplicaError(RuntimeError):
    """The replica holding this request died before resolving it; the
    router requeues such requests onto a surviving replica."""


class _RouterReq:
    __slots__ = ("x", "future", "priority", "deadline", "ttft_deadline",
                 "t_submit", "attempts", "queued", "trace", "affinity",
                 "aff_note", "head")

    def __init__(self, x, priority, deadline, trace=None,
                 ttft_deadline=None, head=False):
        self.x = x
        # StreamFuture: decode replicas pipe incremental token chunks
        # into it (dedup by absolute index, so a requeue after replica
        # death re-delivers nothing twice); plain engine replicas just
        # resolve it like a Future
        self.future = StreamFuture()
        self.priority = int(priority)
        self.deadline = deadline          # absolute perf_counter, or None
        #: the per-token SLO class deadline: projected FIRST-token
        #: completion past this sheds the request (streaming classes)
        self.ttft_deadline = ttft_deadline
        self.t_submit = time.perf_counter()
        self.trace = trace                # obs.trace.Trace when sampled
        #: True when the HEAD sampler picked this request — its trace
        #: event is always emitted; tail retention (obs/recorder.py)
        #: additionally emits unsampled requests that end anomalous
        self.head = bool(head)
        #: pages the dispatcher predicts the chosen replica's prefix
        #: cache already holds (fleet affinity routing; None = unknown)
        self.affinity = None
        #: deferred affinity bookkeeping (name, keys, outcome) consumed
        #: at dispatch — a request shed BEFORE dispatch must pollute
        #: neither the index nor the hit/miss counters
        self.aff_note = None
        self.attempts = 0
        #: True while sitting in the admission heap — the idempotence
        #: guard for requeue-on-death (a dying replica's request can be
        #: seen BOTH by its failing future and by the orphan sweep)
        self.queued = False


class Router:
    """Admission queue + dispatcher + health monitor over ``replicas``.

    ``slo_ms``: default deadline for requests that don't set one (0 =
    none).  ``shed``: enable the overload policy.  ``est_ms`` seeds the
    service-time estimate before any completion has been observed.
    ``max_requeues``: attempts per request across replica deaths before
    the router gives up (a pool losing every replica must still fail
    futures, not hang them).
    """

    def __init__(self, replicas, slo_ms: float | None = None,
                 shed: bool | None = None, est_ms: float = 50.0,
                 max_requeues: int = 3, health_interval: float = 0.2,
                 name: str | None = None,
                 trace_sample: float | None = None,
                 ttft_ms: float | None = None):
        if not replicas:
            raise ValueError("Router needs at least one replica")
        self.replicas = list(replicas)
        self.name = name or f"router{next(_ROUTER_SEQ)}"
        self.slo_s = (slo_ms_default() if slo_ms is None
                      else max(0.0, float(slo_ms))) / 1e3
        #: default per-token SLO class: a first-token budget for
        #: streaming requests (``BIGDL_SERVE_SLO_TTFT_MS``; 0 = no
        #: class — requests only shed on their e2e deadline)
        self.ttft_slo_s = (ttft_ms_default() if ttft_ms is None
                           else max(0.0, float(ttft_ms))) / 1e3
        self.shed_enabled = shed_default() if shed is None else bool(shed)
        self.max_requeues = int(max_requeues)
        self._est_s = max(float(est_ms), 0.0) / 1e3
        #: EWMA of observed submit→first-token latency (streamed
        #: requests feed it) — the projection the TTFT shed check uses;
        #: seeded from the service estimate until a stream completes
        self._est_ttft_s = self._est_s
        self._seq = itertools.count()
        #: request tracing: deterministic sampler, default rate from
        #: BIGDL_OBS_TRACE_SAMPLE (0 = the hot path never stamps)
        self._sampler = obs_trace.Sampler(rate=trace_sample)
        self._trace_kwarg_ok: dict = {}   # id(replica) -> bool

        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._heap: list = []        # (priority, deadline, seq, req)
        self._outstanding: dict = {id(r): {} for r in self.replicas}
        self._dispatching = 0   # popped from the heap, not yet routed
        self._dead: set = set()
        #: drain-only replicas (scale-down victims): dispatch skips
        #: them, but their in-flight/queued requests still complete and
        #: requeue-on-death still covers them — the zero-dropped-futures
        #: drain contract (docs/serving.md "Autoscaling")
        self._draining: set = set()
        self._closed = False

        # monotonic counters (stats(); never reset — see engine.stats),
        # registry-backed so fleet dashboards read them merged
        from bigdl_tpu.obs import metrics as obs_metrics
        reg = obs_metrics.get()
        lab = {"router": self.name}
        self._m_req = {
            outcome: reg.counter("router_requests_total",
                                 "router admission counters by outcome",
                                 outcome=outcome, **lab)
            for outcome in ("accepted", "completed", "failed", "requeued")}
        # sheds split into DISJOINT stages: "admission" = pre-dispatch
        # SLO shed (the request never reached an engine, so NO engine
        # counter saw it) vs "replica" = an engine max_queue shed
        # bubbled up (already in that engine's serve_requests_total).
        # Fleet roll-ups (metrics.serving_summary, serve_top) add only
        # the admission stage on top of the engine counters — adding
        # both would double-count replica-stage sheds.
        self._m_shed = {
            stage: reg.counter("router_requests_total",
                               "router admission counters by outcome",
                               outcome="shed", stage=stage, **lab)
            for stage in ("admission", "replica")}
        self._m_qdepth = reg.gauge(
            "router_queue_depth", "admission-heap depth", **lab)
        self._m_est = reg.gauge(
            "router_est_ms", "EWMA service-time estimate (ms)",
            agg="max", **lab)
        self._m_est.set(self._est_s * 1e3)
        self._m_est_ttft = reg.gauge(
            "router_est_ttft_ms",
            "EWMA first-token latency estimate (ms)", agg="max", **lab)
        self._m_est_ttft.set(self._est_ttft_s * 1e3)

        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True,
            name="bigdl-serve-router")
        self._stop_health = threading.Event()
        self._health = threading.Thread(
            target=self._health_loop, args=(health_interval,),
            daemon=True, name="bigdl-serve-router-health")
        self._dispatcher.start()
        self._health.start()
        self._emit("router_start", replicas=len(self.replicas),
                   slo_ms=self.slo_s * 1e3, shed=self.shed_enabled)

    # -- registry-backed counter views (monotonic) --------------------------
    @property
    def accepted(self) -> int:
        return int(self._m_req["accepted"].value)

    @property
    def shed(self) -> int:
        return int(self._m_shed["admission"].value
                   + self._m_shed["replica"].value)

    @property
    def completed(self) -> int:
        return int(self._m_req["completed"].value)

    @property
    def failed(self) -> int:
        return int(self._m_req["failed"].value)

    @property
    def requeued(self) -> int:
        return int(self._m_req["requeued"].value)

    # -- submit -------------------------------------------------------------
    def submit(self, x, priority: int = 1, slo_ms: float | None = None,
               ttft_ms: float | None = None, on_tokens=None) -> Future:
        """Queue one row; returns a future resolving to its output.
        ``priority``: lower = more urgent (0 is the most urgent class).
        ``slo_ms`` overrides the router default; ``None``+default-0
        means no deadline (the request is never shed).

        Streaming (decode fleets): ``on_tokens`` registers an
        incremental token consumer on the returned
        :class:`~bigdl_tpu.serve.streaming.StreamFuture` (replica-side
        chunks are piped into it at dispatch), and ``ttft_ms`` arms the
        per-token SLO class — EDF orders by the FIRST-token deadline
        and the shed policy projects first-token completion, not
        end-to-end retire (``BIGDL_SERVE_SLO_TTFT_MS`` default)."""
        now = time.perf_counter()
        slo_s = self.slo_s if slo_ms is None else max(0.0, slo_ms) / 1e3
        deadline = (now + slo_s) if slo_s > 0 else None
        wants_stream = (on_tokens is not None
                        or (isinstance(x, dict) and x.get("stream")))
        ttft_s = (self.ttft_slo_s if ttft_ms is None
                  else max(0.0, ttft_ms) / 1e3)
        # the per-token class applies to STREAMING requests: a request
        # nobody consumes incrementally has no observable first token
        ttft_deadline = (now + ttft_s) if ttft_s > 0 and wants_stream \
            else None
        tr = self._sampler.next()
        head = tr is not None
        rec = obs_recorder.get()
        if tr is None and rec is not None:
            # tail-based retention: EVERY request gets a (cheap) trace
            # context; whether its hop chain is ever EMITTED is decided
            # at the terminal state (_finish_trace → recorder.finalize)
            tr = obs_trace.Trace()
        if tr is not None:
            tr.stamp("admit")
        req = _RouterReq(x, priority, deadline, trace=tr,
                         ttft_deadline=ttft_deadline, head=head)
        if rec is not None and tr is not None:
            fields = {"priority": int(priority),
                      "slo_ms": slo_s * 1e3 if slo_s > 0 else None,
                      "ttft_slo_ms": ttft_s * 1e3
                      if ttft_s > 0 and wants_stream else None,
                      "stream": True if wants_stream else None,
                      "head": True if head else None}
            if isinstance(x, dict) and "seed" in x:
                # decode payload: enough identity for request_replay
                # even when the replica-side notes never come back (a
                # death before the reply frame)
                seed = x["seed"]
                fields.update(seed_hash=obs_recorder.seed_hash(seed),
                              seed_len=len(seed),
                              n_words=x.get("n_words"))
                if x.get("sampling"):
                    # router-side copy of the (seed-resolved) sampling
                    # params — survives a replica death before the
                    # replica-side note comes back
                    fields["sampling"] = x["sampling"]
            rec.note(tr.trace_id, **fields)
        if wants_stream:
            req.future.request_stream()
        if on_tokens is not None:
            req.future.on_tokens(on_tokens)
        with self._cv:
            if self._closed:
                raise RuntimeError("Router is closed")
            self._m_req["accepted"].inc()
            self._push(req)
            self._m_qdepth.set(len(self._heap))
            self._cv.notify()
        return req.future

    def submit_many(self, rows, priority: int = 1,
                    slo_ms: float | None = None) -> list:
        return [self.submit(r, priority, slo_ms) for r in rows]

    def _push(self, req):
        """Queue (or re-queue) under the lock; no-ops on a request that
        is already queued or already resolved."""
        if req.queued or req.future.done():
            return False
        req.queued = True
        # EDF on the EARLIEST obligation: a streaming request's
        # first-token deadline (usually tighter than e2e) orders it;
        # None deadlines sort last inside their class
        dl = min(req.deadline if req.deadline is not None else float("inf"),
                 req.ttft_deadline if req.ttft_deadline is not None
                 else float("inf"))
        heapq.heappush(self._heap, (req.priority, dl, next(self._seq),
                                    req))
        return True

    # -- dispatch -----------------------------------------------------------
    def _dispatch_loop(self):
        while True:
            with self._cv:
                while not self._heap and not self._closed:
                    self._cv.wait(timeout=0.1)
                if self._closed and not self._heap:
                    return
                _, _, _, req = heapq.heappop(self._heap)
                req.queued = False
                # visible to drain() while between heap and outstanding
                self._dispatching += 1
                est = self._est_s
                self._m_qdepth.set(len(self._heap))
            if req.trace is not None:
                req.trace.stamp("queue")
            try:
                self._route(req, est)
            finally:
                with self._lock:
                    self._dispatching -= 1

    def _route(self, req, est):
        replica, load = self._pick_for(req)
        if replica is None:
            self._fail(req, RuntimeError("no live replica in the pool"))
            return
        # shed-before-deadline-miss: the projected completion (the
        # chosen replica's backlog + this request, at the EWMA service
        # estimate) landing past the deadline fails the future NOW —
        # the submitter can retry elsewhere — instead of burning
        # replica time to miss anyway.  High-priority classes dispatch
        # first, so overload drains budget from the LOWEST class first.
        # Streaming classes are judged on their FIRST-token projection
        # (backlog x the EWMA TTFT estimate): a stream that would start
        # past its TTFT budget is already failing its user even if it
        # could retire inside the e2e deadline.
        if self.shed_enabled:
            now = time.perf_counter()
            miss = reason = None
            if (req.deadline is not None
                    and now + est * (load + 1) > req.deadline):
                miss, reason = est, "completion past deadline"
            elif (req.ttft_deadline is not None
                    and req.future.t_first_token is None):
                # the first-token obligation only judges requests that
                # have not streamed yet: a requeue-after-replica-death
                # re-dispatch of a mid-stream request (its client HAS
                # tokens; re-delivery dedups by index) must not shed on
                # a deadline it already met
                with self._lock:
                    est_ttft = self._est_ttft_s
                if now + est_ttft * (load + 1) > req.ttft_deadline:
                    miss = est_ttft
                    reason = "first token past TTFT budget"
            if miss is not None:
                self._m_shed["admission"].inc()
                self._emit("shed", priority=req.priority,
                           wait_ms=(now - req.t_submit) * 1e3)
                self._finish_trace(req, "shed", hop="shed",
                                   shed_stage="admission")
                req.future.set_exception(SheddedError(
                    f"projected {reason} (priority {req.priority}, "
                    f"backlog {load}, est {miss * 1e3:.1f} ms)"))
                return
        with self._lock:
            self._outstanding[id(replica)][id(req)] = req
        if req.trace is not None:
            req.trace.stamp("dispatch")
        try:
            inner = self._submit_to(replica, req)
        except Exception as e:
            with self._lock:
                self._outstanding[id(replica)].pop(id(req), None)
            self._on_replica_error(replica, req, e)
            return
        if req.future.streaming and hasattr(inner, "pipe_to"):
            # incremental token chunks flow replica → client; the
            # absolute-index dedup makes a requeued request's
            # re-delivery (same greedy stream, fresh replica) a no-op
            # for tokens the client already has
            inner.pipe_to(req.future)
        inner.add_done_callback(
            lambda f, r=replica, q=req: self._on_done(r, q, f))

    def _pick_for(self, req):
        """Replica choice for one request — the base policy ignores the
        payload (least-loaded); :class:`~bigdl_tpu.serve.fleet.FleetRouter`
        overrides this with prefix-affinity dispatch."""
        return self._pick()

    def _submit_to(self, replica, req):
        """Hand ``req`` to the chosen replica, returning its inner
        future.  Subclass hook (the fleet router interposes the
        prefill-replica hop here); exceptions propagate to the caller's
        requeue/fail handling."""
        if req.trace is not None and self._accepts_trace(replica):
            return replica.submit(req.x, trace=req.trace)
        return replica.submit(req.x)

    def _accepts_trace(self, replica) -> bool:
        """Whether ``replica.submit`` takes the ``trace`` kwarg
        (replicas in this repo do; test fakes and minimal replicas may
        not).  Decided ONCE per replica by signature inspection, never
        by catching TypeError from the call — a submit that raises
        TypeError mid-flight (e.g. an unpicklable payload crossing the
        ProcessReplica frame boundary AFTER the future was registered)
        must surface, not be silently re-submitted untraced."""
        ok = self._trace_kwarg_ok.get(id(replica))
        if ok is None:
            try:
                params = inspect.signature(replica.submit).parameters
                ok = ("trace" in params
                      or any(p.kind is inspect.Parameter.VAR_KEYWORD
                             for p in params.values()))
            except (TypeError, ValueError):  # builtins, C callables
                ok = False
            self._trace_kwarg_ok[id(replica)] = ok
        return ok

    def _pick(self):
        """Least-loaded live replica (outstanding count through this
        router + the replica's own inflight signal); returns
        ``(replica, load)`` or ``(None, 0)``.  Drain-marked replicas
        are skipped while any other live replica exists — they only
        finish what they already hold — but remain the fallback when
        the whole pool is draining (a request must never fail while a
        live replica could serve it)."""
        best, best_load = None, None
        drain_best, drain_load = None, None
        with self._lock:
            dead = set(self._dead)
            draining = set(self._draining)
            outs = {k: len(v) for k, v in self._outstanding.items()}
        for r in list(self.replicas):
            if id(r) in dead:
                continue
            try:
                if not r.alive():
                    self._mark_dead(r)
                    continue
                load = outs.get(id(r), 0) + r.inflight()
            except Exception:
                self._mark_dead(r)
                continue
            if id(r) in draining:
                if drain_load is None or load < drain_load:
                    drain_best, drain_load = r, load
                continue
            if best_load is None or load < best_load:
                best, best_load = r, load
        if best is None and drain_best is not None:
            return drain_best, (drain_load or 0)
        return best, (best_load or 0)

    def _on_done(self, replica, req, inner):
        with self._lock:
            self._outstanding[id(replica)].pop(id(req), None)
        exc = inner.exception()
        if exc is None:
            lat = time.perf_counter() - req.t_submit
            ttft = getattr(req.future, "ttft_s", None)
            with self._lock:
                self._est_s += _EST_ALPHA * (lat - self._est_s)
                self._m_est.set(self._est_s * 1e3)
                if ttft is not None:
                    self._est_ttft_s += _EST_ALPHA * (ttft
                                                      - self._est_ttft_s)
                    self._m_est_ttft.set(self._est_ttft_s * 1e3)
            self._m_req["completed"].inc()
            self._finish_trace(req, "ok", hop="complete",
                               replica=getattr(replica, "name", None),
                               transport=getattr(replica, "transport",
                                                 None),
                               latency_ms=lat * 1e3)
            if not req.future.done():
                req.future.set_result(inner.result())
        else:
            self._on_replica_error(replica, req, exc)

    def _on_replica_error(self, replica, req, exc):
        """Requeue when the REPLICA was the problem; fail the future
        when the REQUEST was (a poisoned row fails identically on every
        replica — retrying it would serve nothing and hide the error)."""
        if isinstance(exc, SheddedError):
            # an engine-level admission shed (max_queue) is a SHED in
            # the router's taxonomy too, not a failure — the documented
            # counter contract keeps shed/failed disjoint
            self._m_shed["replica"].inc()
            self._finish_trace(req, "shed", hop="shed",
                               shed_stage="replica",
                               replica=getattr(replica, "name", None))
            if not req.future.done():
                req.future.set_exception(exc)
            return
        replica_died = isinstance(exc, DeadReplicaError)
        if not replica_died:
            try:
                replica_died = not replica.alive()
            except Exception:
                replica_died = True
        if replica_died:
            self._mark_dead(replica)
            if req.attempts < self.max_requeues:
                req.attempts += 1
                with self._cv:
                    if self._push(req):
                        self._m_req["requeued"].inc()
                        self._note_requeue(req, replica)
                        self._cv.notify()
                return
        self._fail(req, exc)

    def _fail(self, req, exc):
        self._m_req["failed"].inc()
        self._finish_trace(req, "failed",
                           error=f"{type(exc).__name__}: {exc}")
        if not req.future.done():
            req.future.set_exception(exc)

    def _note_requeue(self, req, replica=None):
        """Requeue bookkeeping: the hop stamp plus the flight-recorder
        involvement note (the dead replica that caused the requeue)."""
        if req.trace is None:
            return
        req.trace.stamp("requeue")
        name = getattr(replica, "name", None) if replica is not None \
            else None
        if name is not None:
            obs_recorder.note(req.trace.trace_id, death_replica=name)

    def _slo_verdict(self, req, status) -> str | None:
        """Which SLO budget a COMPLETED request blew (None = in
        budget): ``deadline`` = the future resolved past its e2e
        deadline, ``ttft`` = the first token streamed past its budget.
        Failed/shed requests are classified under their own forensic
        kinds, not here."""
        if status != "ok":
            return None
        if (req.deadline is not None
                and time.perf_counter() > req.deadline):
            return "deadline"
        t_first = getattr(req.future, "t_first_token", None)
        if (req.ttft_deadline is not None and t_first is not None
                and t_first > req.ttft_deadline):
            return "ttft"
        return None

    def _finish_trace(self, req, status, hop=None, **fields):
        """Terminal trace handling for EVERY request: the hop chain and
        last fields are absorbed into the flight recorder, which
        decides retention — a head-sampled request's trace event is
        always emitted, an unsampled one only when it ended anomalous
        (obs/recorder.py tail retention).  The trace object is detached
        afterwards so a double-resolution path (death sweep + failing
        future) cannot emit twice."""
        tr, req.trace = req.trace, None
        if tr is None:
            return
        if hop:
            tr.stamp(hop)
        fields = {k: v for k, v in fields.items() if v is not None}
        emit = obs_recorder.finalize(
            tr.trace_id, status, trace=tr, head_sampled=req.head,
            priority=req.priority,
            requeues=req.attempts if req.attempts else None,
            slo_miss=self._slo_verdict(req, status),
            e2e_ms=fields.get("latency_ms"), **fields)
        if emit:
            tr.emit(status=status, priority=req.priority, **fields)

    # -- health -------------------------------------------------------------
    def _mark_dead(self, replica):
        with self._lock:
            if id(replica) in self._dead:
                return
            self._dead.add(id(replica))
        self._emit("replica_dead",
                   replica=getattr(replica, "name", repr(replica)))
        logger.warning("serve router: replica %s marked dead",
                       getattr(replica, "name", replica))
        # orphans: requests dispatched to the replica whose futures will
        # never resolve (a clean DeadReplicaError failure goes through
        # _on_done instead and finds this dict already empty)
        with self._lock:
            orphans = list(self._outstanding.get(id(replica), {}).values())
            self._outstanding[id(replica)] = {}
        for req in orphans:
            if req.future.done() or req.queued:
                continue
            if req.attempts < self.max_requeues:
                req.attempts += 1
                with self._cv:
                    if self._push(req):
                        self._m_req["requeued"].inc()
                        self._note_requeue(req, replica)
                        self._cv.notify()
            else:
                self._fail(req, DeadReplicaError(
                    "replica died and requeue budget is exhausted"))

    def _health_loop(self, interval):
        """Watchdog-style liveness: probe every replica on a cadence so
        a silent death (no future ever resolves) still trips requeue."""
        while True:
            with self._lock:
                if self._closed:
                    return
            for r in self.replicas:
                with self._lock:
                    if id(r) in self._dead:
                        continue
                try:
                    ok = r.alive()
                except Exception:
                    ok = False
                if not ok:
                    self._mark_dead(r)
            # interruptible sleep: close() joins this thread, and an
            # orphaned daemon probe running into interpreter teardown
            # can abort the process inside the jax runtime's destructor
            if self._stop_health.wait(timeout=interval):
                return

    def live_replicas(self, draining: bool = True) -> list:
        """Replicas not marked dead; ``draining=False`` additionally
        excludes drain-only replicas (rollouts target this set — a
        scale-down victim finishes its backlog on the version it has)."""
        with self._lock:
            dead = set(self._dead)
            drain = set() if draining else set(self._draining)
        return [r for r in self.replicas
                if id(r) not in dead and id(r) not in drain]

    # -- dynamic membership (serve/autoscale.py, docs/serving.md) -----------
    def add_replica(self, replica):
        """Register a (warmed) replica with the dispatch set.  The
        caller owns the warmup contract: by the time a replica is added
        here it must already serve the fleet's committed weight version
        with its executables compiled (``ReplicaPool.add_replica``)."""
        with self._cv:
            if self._closed:
                raise RuntimeError("Router is closed")
            if replica in self.replicas:
                return replica
            self.replicas.append(replica)
            self._outstanding.setdefault(id(replica), {})
            # a replica object reused after a previous drain/removal
            # re-enters clean
            self._dead.discard(id(replica))
            self._draining.discard(id(replica))
            self._cv.notify()
        self._emit("replica_added",
                   replica=getattr(replica, "name", repr(replica)),
                   replicas=len(self.replicas))
        return replica

    def mark_draining(self, replica, draining: bool = True):
        """Flip a replica's drain-only state: dispatch skips it (while
        another live replica exists) but its queued/in-flight requests
        run to completion, and requeue-on-death still covers it."""
        with self._lock:
            if draining:
                self._draining.add(id(replica))
            else:
                self._draining.discard(id(replica))
        if draining:
            self._emit("replica_draining",
                       replica=getattr(replica, "name", repr(replica)))

    def is_draining(self, replica) -> bool:
        with self._lock:
            return id(replica) in self._draining

    def pending_for(self, replica) -> int:
        """Requests this router has dispatched to ``replica`` that have
        not resolved yet (the drain-wait signal)."""
        with self._lock:
            return len(self._outstanding.get(id(replica), {}))

    def remove_replica(self, replica):
        """Detach a replica from the router.  The caller must have
        drained it first (``mark_draining`` + wait on ``pending_for``);
        any request still outstanding is requeued like a death sweep —
        removal NEVER drops a future."""
        with self._lock:
            try:
                self.replicas.remove(replica)
            except ValueError:
                return
            orphans = list(
                self._outstanding.pop(id(replica), {}).values())
            self._dead.discard(id(replica))
            self._draining.discard(id(replica))
        for req in orphans:   # a caller that skipped the drain wait
            if req.future.done() or req.queued:
                continue
            # same budget as the death sweep: removal must not grant a
            # request more retries than a death would
            if req.attempts >= self.max_requeues:
                self._fail(req, DeadReplicaError(
                    "replica removed and requeue budget is exhausted"))
                continue
            req.attempts += 1
            with self._cv:
                if self._push(req):
                    self._m_req["requeued"].inc()
                    self._note_requeue(req, replica)
                    self._cv.notify()
        self._emit("replica_removed",
                   replica=getattr(replica, "name", repr(replica)),
                   replicas=len(self.replicas))

    # -- telemetry / lifecycle ----------------------------------------------
    def _emit(self, kind: str, **fields):
        from bigdl_tpu.obs import events
        events.emit("serve", kind=kind, **fields)

    def stats(self) -> dict:
        """Router counters (monotonic, never reset) + queue depth + the
        current service-time estimate — a view over the metrics
        registry, like ``engine.stats()``."""
        with self._lock:
            queue_depth = len(self._heap)
            est_ms = self._est_s * 1e3
            est_ttft_ms = self._est_ttft_s * 1e3
            dead = len(self._dead)
            draining = len(self._draining)
        return {
            "accepted": self.accepted,
            "shed": self.shed,
            "completed": self.completed,
            "failed": self.failed,
            "requeued": self.requeued,
            "queue_depth": queue_depth,
            "est_ms": est_ms,
            "est_ttft_ms": est_ttft_ms,
            "ttft_slo_ms": self.ttft_slo_s * 1e3,
            "replicas": len(self.replicas),
            "dead_replicas": dead,
            "draining_replicas": draining,
        }

    def drain(self, timeout: float = 60.0):
        """Block until every accepted request has resolved or been
        shed."""
        t0 = time.perf_counter()
        while True:
            with self._lock:
                pending = (len(self._heap) + self._dispatching
                           + sum(len(v)
                                 for v in self._outstanding.values()))
            if pending == 0:
                return self
            if time.perf_counter() - t0 > timeout:
                raise TimeoutError("router drain timed out")
            time.sleep(0.005)

    def close(self):
        with self._cv:
            if self._closed:
                return
            self._closed = True
            leftovers = [item[3] for item in self._heap]
            self._heap = []
            self._cv.notify_all()
        for req in leftovers:
            self._fail(req, RuntimeError("Router closed"))
        self._dispatcher.join(timeout=10.0)
        self._stop_health.set()
        self._health.join(timeout=10.0)
        self._emit("router_stop", **self.stats())

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
