"""Cross-host replicas over TCP: the stdio replica protocol on a
socket, with blip-tolerant reconnect (docs/serving.md "Cross-host
fleet").

A :class:`RemoteReplica` speaks to a replica agent
(``tools/replica_agent.py``) listening on ``host:port`` and wears the
EXACT :class:`~bigdl_tpu.serve.cluster.ProcessReplica` surface — the
router, the pool's rollout/membership machinery, the fleet's
page-shipping submit path and the autoscaler all take it unchanged.
The wire is the same hardened frame codec as the stdio pipes
(``serve/frames.py``), carrying the same op set; the agent hosts the
same :class:`~bigdl_tpu.serve.cluster.WorkerOps` the subprocess worker
runs, so the op vocabulary cannot diverge between transports.

What a socket adds over a pipe is a FAILURE MODE the pipe never had: a
pipe dies exactly when the replica dies, but a TCP connection can drop
while the replica is perfectly healthy.  The robustness core here is
telling those apart:

- **network blip** (connection lost < liveness budget): the client
  reconnects with backoff and re-attaches to the SAME agent session —
  session id + contiguous per-frame sequence numbers let the agent
  replay un-acked frames (replies, token chunks) and the client replay
  un-answered requests, each side deduplicating (``seq`` on the way
  down, request ids on the way up).  Zero requeues, zero duplicate
  token chunks (the StreamFuture's absolute-index dedup is the second
  belt), the session epoch unchanged.  During the blip ``alive()``
  stays True — the router keeps the replica in its dispatch set and
  its in-flight futures pending.
- **replica death / sustained partition** (budget exceeded, or the
  agent lost the session): the client converts to the existing
  :class:`~bigdl_tpu.serve.router.DeadReplicaError` path — every
  outstanding future fails typed, the router requeues each EXACTLY
  once on survivors, and the leased host returns to the inventory.

A silent black hole (packets dropped, socket not closed) is caught by
the keepalive: every ``liveness/4`` the client pings (measuring
``remote_rtt_seconds`` and piggybacking its ack watermark); a peer
quiet for a full budget gets its socket force-dropped so the reader
enters the reconnect path deterministically.

``HostInventory`` turns ``BIGDL_SERVE_HOSTS="h1:7070,h2:7070"`` into
the lease pool ReplicaPool/DecodeFleet spawn from — scale-up leases an
address, replica death or scale-down releases it, and an exhausted
inventory raises :class:`~bigdl_tpu.serve.cluster.ReplicaSpawnError`
(the type the autoscaler's circuit breaker already keys on).

Flags: ``BIGDL_SERVE_HOSTS`` (agent inventory), ``BIGDL_SERVE_TOKEN``
(shared-secret handshake), ``BIGDL_SERVE_LIVENESS_S`` (blip budget,
default 2.0).
"""
from __future__ import annotations

import logging
import os
import pickle
import socket
import subprocess
import sys
import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as np

from bigdl_tpu.serve.cluster import (_EXC_TYPES, _STDERR_LINES,
                                     ReplicaSpawnError)
from bigdl_tpu.serve.frames import FrameProtocolError
from bigdl_tpu.serve.frames import read_frame as _read_frame
from bigdl_tpu.serve.frames import read_welcome, write_hello
from bigdl_tpu.serve.frames import write_frame as _write_frame
from bigdl_tpu.serve.router import DeadReplicaError
from bigdl_tpu.serve.streaming import StreamFuture, TokenDelivery

logger = logging.getLogger("bigdl_tpu.serve")

ENV_HOSTS = "BIGDL_SERVE_HOSTS"
ENV_TOKEN = "BIGDL_SERVE_TOKEN"
ENV_LIVENESS = "BIGDL_SERVE_LIVENESS_S"

#: default blip budget (seconds): a connection loss shorter than this
#: is a network blip (reconnect + re-attach, zero requeues); longer is
#: a death (DeadReplicaError → requeue-exactly-once)
DEFAULT_LIVENESS_S = 2.0


def parse_hosts(spec) -> list:
    """``"h1:7070,h2:7071"`` (or an iterable of ``"h:p"`` /
    ``(h, p)``) → list of ``(host, port)`` tuples."""
    if spec is None:
        return []
    if isinstance(spec, str):
        items = [s for s in (p.strip() for p in spec.split(",")) if s]
    else:
        items = list(spec)
    out = []
    for item in items:
        if isinstance(item, (tuple, list)):
            host, port = item
        else:
            host, _, port = str(item).rpartition(":")
            if not host:
                raise ValueError(
                    f"bad host entry {item!r} (want host:port)")
        out.append((str(host), int(port)))
    return out


def hosts_default() -> list:
    return parse_hosts(os.environ.get(ENV_HOSTS, ""))


def token_default() -> str:
    return os.environ.get(ENV_TOKEN, "")


def liveness_default() -> float:
    try:
        return float(os.environ.get(ENV_LIVENESS, "")
                     or DEFAULT_LIVENESS_S)
    except ValueError:
        return DEFAULT_LIVENESS_S


class HostInventory:
    """The lease pool of replica-agent addresses a cross-host pool
    scales over.  ``lease()`` hands out a free address (exhaustion
    raises :class:`ReplicaSpawnError` — the autoscaler's circuit
    breaker trips instead of crash-looping) and ``release()`` returns
    one on replica death, scale-down, or spawn failure."""

    def __init__(self, hosts=None, token=None):
        hosts = parse_hosts(hosts) if hosts is not None else hosts_default()
        if not hosts:
            raise ValueError(
                f"cross-host pool needs agent addresses: pass hosts= "
                f"or set {ENV_HOSTS}=host:port[,host:port...]")
        self.token = token if token is not None else token_default()
        self._lock = threading.Lock()
        self._free = list(hosts)
        self._leased = []

    def lease(self):
        with self._lock:
            if not self._free:
                raise ReplicaSpawnError(
                    f"host inventory exhausted ({len(self._leased)} "
                    f"leased, 0 free): scale-up is capped by the "
                    f"{ENV_HOSTS} inventory")
            addr = self._free.pop(0)
            self._leased.append(addr)
            return addr

    def release(self, addr):
        with self._lock:
            if addr in self._leased:
                self._leased.remove(addr)
                self._free.append(addr)

    def stats(self) -> dict:
        with self._lock:
            return {"free": len(self._free), "leased": len(self._leased)}


class _Conn:
    """One TCP connection's socket + buffered file pair."""

    __slots__ = ("sock", "rfile", "wfile")

    def __init__(self, sock):
        self.sock = sock
        self.rfile = sock.makefile("rb")
        self.wfile = sock.makefile("wb")

    def force_drop(self):
        """Abort the connection from another thread: the reader's
        blocking read fails immediately (the keepalive's black-hole
        escape hatch)."""
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass

    def close(self):
        for f in (self.wfile, self.rfile):
            try:
                f.close()
            except (OSError, ValueError):
                pass
        try:
            self.sock.close()
        except OSError:
            pass


class _HandshakeRefused(RuntimeError):
    """The agent answered the hello with a typed refusal (bad token,
    unknown session) — permanent, retrying cannot help."""


class RemoteReplica:
    """A serve replica hosted by a TCP agent, wearing ProcessReplica's
    surface (submit/inflight/alive/stats/telemetry + the rollout verbs)
    with blip-tolerant reconnect.  See the module docstring for the
    blip-vs-death semantics; ``agent=`` optionally attaches a loopback
    :class:`AgentHandle` so death errors carry the agent's stderr
    tail."""

    #: flight-recorder transport attribution (obs/recorder.py)
    transport = "tcp"

    #: role the init frame declares; subclasses repoint it
    def _init_frame(self, model, worker_kwargs) -> dict:
        return {"op": "init", "model": model, "engine": worker_kwargs}

    def __init__(self, addr, model, name: str = "remote", token=None,
                 liveness_s: float | None = None, on_release=None,
                 spawn_timeout: float = 120.0, agent=None,
                 **engine_kwargs):
        self.addr = (str(addr[0]), int(addr[1]))
        self.name = name
        self.token = token if token is not None else token_default()
        self.liveness_s = (liveness_default() if liveness_s is None
                           else float(liveness_s))
        self._on_release = on_release
        self._agent = agent
        self._lock = threading.Lock()
        self._wlock = threading.Lock()
        self._futures: dict = {}    # rid -> (future, trace-or-None)
        self._pending: dict = {}    # rid -> frame (replayed on re-attach)
        self._ids = iter(range(1, 1 << 62))
        self._dead = False
        self._closing = False
        self._conn: _Conn | None = None
        self._session = None
        self._epoch = None
        self._acked = 0             # highest peer seq seen (dedup + ack)
        self._last_rx = time.monotonic()
        self._delivery = None
        self._ready = threading.Event()

        from bigdl_tpu.obs import metrics as obs_metrics
        reg = obs_metrics.get()
        lab = {"replica": self.name}
        self._m_reconnects = reg.counter(
            "remote_reconnects_total",
            "successful same-session re-attaches after a network blip",
            **lab)
        self._m_sessions = reg.gauge(
            "remote_sessions", "live agent sessions held by this client",
            **lab)
        self._m_rtt = reg.histogram(
            "remote_rtt_seconds",
            "keepalive ping round-trip to the replica agent", **lab)

        try:
            conn, welcome = self._dial(resume=False)
        except (_HandshakeRefused, FrameProtocolError, OSError,
                ValueError, EOFError, pickle.PickleError) as e:
            raise ReplicaSpawnError(
                f"replica {name}: agent {self.addr[0]}:{self.addr[1]} "
                f"refused the handshake: {type(e).__name__}: {e}"
                f"{self._agent_tail_suffix()}",
                stderr_tail=self._agent_stderr()) from e
        self._conn = conn
        self._session = welcome.get("session")
        self._epoch = welcome.get("epoch")
        self._m_sessions.set(1)
        from bigdl_tpu.obs import events as obs_events
        obs_events.emit("remote", kind="connect", replica=self.name,
                        address=f"{self.addr[0]}:{self.addr[1]}")

        engine_kwargs = dict(engine_kwargs)
        engine_kwargs.setdefault("name", name)
        # the init frame rides the session like any request (it has a
        # rid and sits in _pending), so a blip during the agent-side
        # model build replays it and the rid dedup makes that harmless
        rid = next(self._ids)
        self._init_rid = rid
        frame = dict(self._init_frame(model, engine_kwargs), id=rid)
        with self._lock:
            self._pending[rid] = frame
        try:
            _write_frame(conn.wfile, frame, self._wlock)
        except (OSError, ValueError) as e:
            self._teardown_conn()
            raise ReplicaSpawnError(
                f"replica {name}: init frame to "
                f"{self.addr[0]}:{self.addr[1]} failed: {e}"
                f"{self._agent_tail_suffix()}",
                stderr_tail=self._agent_stderr()) from e
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"bigdl-serve-{name}-reader")
        self._reader.start()
        self._keepalive = threading.Thread(
            target=self._keepalive_loop, daemon=True,
            name=f"bigdl-serve-{name}-keepalive")
        self._keepalive.start()
        if not self._ready.wait(spawn_timeout):
            self._teardown_conn()
            self._on_death()
            raise ReplicaSpawnError(
                f"replica {name} did not come up in {spawn_timeout}s"
                f"{self._agent_tail_suffix()}",
                stderr_tail=self._agent_stderr())
        if self._dead:
            raise ReplicaSpawnError(
                f"replica {name} died during startup"
                f"{self._agent_tail_suffix()}",
                stderr_tail=self._agent_stderr())

    # -- session surface ----------------------------------------------------
    @property
    def session_epoch(self):
        """The agent-side epoch of the session this client holds — the
        blip-vs-death witness: unchanged across a survived blip, new
        only with a new session (i.e. a new replica)."""
        return self._epoch

    # -- wire ---------------------------------------------------------------
    def _dial(self, resume: bool):
        """Connect + authenticate.  Returns ``(conn, welcome)``; raises
        OSError-family on transient failure (the partition may still
        heal) or :class:`_HandshakeRefused` on a typed refusal.  The
        hello/welcome exchange is the fixed pickle-free handshake
        layout (``serve/frames.py``) — neither peer unpickles anything
        before the token check passes."""
        timeout = max(2.0, self.liveness_s)
        sock = socket.create_connection(self.addr, timeout=timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn = _Conn(sock)
        try:
            write_hello(conn.wfile, token=self.token,
                        session=self._session if resume else None,
                        acked=self._acked, name=self.name)
            welcome = read_welcome(conn.rfile)
            if welcome is None:
                raise OSError("agent closed the connection mid-handshake")
            if welcome.get("op") == "error":
                raise _HandshakeRefused(
                    welcome.get("error", "agent refused the handshake"))
            if resume and not welcome.get("resumed"):
                raise _HandshakeRefused(
                    "agent did not resume the session")
        except BaseException:
            conn.close()
            raise
        sock.settimeout(None)
        self._last_rx = time.monotonic()
        return conn, welcome

    def _teardown_conn(self):
        conn, self._conn = self._conn, None
        if conn is not None:
            conn.close()

    def _read_loop(self):
        while True:
            conn = self._conn
            if conn is None:
                return
            try:
                msg = _read_frame(conn.rfile)
            except FrameProtocolError as e:
                # corrupt/desynced bytes: drop the connection — the
                # re-attach replay restores anything the cut lost
                logger.warning("replica %s: %s; dropping connection",
                               self.name, e)
                msg = None
            except (OSError, ValueError, EOFError, pickle.PickleError):
                msg = None
            if msg is None:
                if self._closing or self._dead:
                    self._on_death()
                    return
                if self._reconnect():
                    continue
                self._on_death()
                return
            self._last_rx = time.monotonic()
            seq = msg.get("seq")
            if seq is not None:
                if seq <= self._acked:
                    # a replayed frame this client already consumed
                    # before the blip — the downstream dedup belt
                    continue
                self._acked = seq
            try:
                self._handle(msg)
            except Exception:
                # a reply-handling bug (double-resolve, delivery
                # failure, ...) must not silently kill the only thread
                # that resolves futures — alive() would stay True and
                # the router would keep dispatching to a wedged
                # replica.  Convert it to the death path: orphans fail
                # typed and the router requeues.
                logger.exception(
                    "replica %s: reply handling failed; converting to "
                    "replica death", self.name)
                self._on_death()
                return

    def _handle(self, msg):
        op = msg.get("op")
        if op == "ready":
            with self._lock:
                self._pending.pop(self._init_rid, None)
                self._futures.pop(self._init_rid, None)
            self._ready.set()
            return
        if op == "event":
            self._forward_event(msg.get("event"))
            return
        if op == "tokens":
            with self._lock:
                entry = self._futures.get(msg.get("id"))
            if entry is not None:
                self._ensure_delivery().enqueue(
                    entry[0], msg.get("tokens") or [],
                    msg.get("start"), None)
            return
        with self._lock:
            entry = self._futures.pop(msg.get("id"), None)
            self._pending.pop(msg.get("id"), None)
        if entry is None:
            return
        fut, tr = entry
        if msg.get("ok"):
            if tr is not None:
                tr.extend(msg.get("hops") or ())
                if msg.get("rec"):
                    # the agent-side flight-recorder notes merge into
                    # this client's record (same frame as the hops)
                    from bigdl_tpu.obs import recorder as obs_recorder
                    obs_recorder.note(tr.trace_id, **msg["rec"])
            if fut.streaming and self._delivery is not None:
                self._delivery.resolve(fut, msg.get("out"))
            else:
                fut.set_result(msg.get("out"))
        else:
            cls = _EXC_TYPES.get(msg.get("etype"), RuntimeError)
            fut.set_exception(cls(msg.get("error", "replica error")))

    def _reconnect(self) -> bool:
        """The blip path: reconnect + re-attach to the same session
        within the liveness budget.  True = re-attached (reader
        continues, zero requeues); False = this replica is dead."""
        from bigdl_tpu.obs import events as obs_events
        t0 = time.monotonic()
        deadline = t0 + self.liveness_s
        self._teardown_conn()
        obs_events.emit("remote", kind="blip", replica=self.name)
        # requests in flight across the blip: note the partition
        # involvement so the recorder's terminal classification keeps
        # their full timeline even when they resolve healthy
        from bigdl_tpu.obs import recorder as obs_recorder
        with self._lock:
            blipped = [t for _, t in self._futures.values()
                       if t is not None]
        for t in blipped:
            obs_recorder.note(t.trace_id, blip_replica=self.name)
        logger.warning("replica %s: connection to %s:%d lost; "
                       "reconnecting (budget %.2fs)", self.name,
                       self.addr[0], self.addr[1], self.liveness_s)
        backoff = 0.02
        while time.monotonic() < deadline and not self._closing:
            try:
                conn, welcome = self._dial(resume=True)
            except _HandshakeRefused as e:
                # the agent lost the session (restart, TTL reap, a new
                # client superseded us): no amount of retrying re-attaches
                logger.warning("replica %s: re-attach refused: %s",
                               self.name, e)
                return False
            except (FrameProtocolError, OSError, ValueError, EOFError,
                    pickle.PickleError):
                time.sleep(min(backoff,
                               max(0.0, deadline - time.monotonic())))
                backoff = min(backoff * 2, 0.25)
                continue
            self._conn = conn
            # replay every un-answered request in rid order; the agent
            # dedups rids it already executed, and its outbox replay
            # (driven by our acked watermark in the hello) restores any
            # replies/chunks the cut swallowed
            with self._lock:
                replay = sorted(self._pending.items())
            try:
                for _, frame in replay:
                    _write_frame(conn.wfile, frame, self._wlock)
            except (FrameProtocolError, OSError, ValueError):
                # the link died again mid-replay: loop — budget allowing
                self._teardown_conn()
                continue
            blip_s = time.monotonic() - t0
            self._m_reconnects.inc()
            obs_events.emit("remote", kind="reattach", replica=self.name,
                            blip_s=round(blip_s, 4))
            logger.warning("replica %s: re-attached to session %s after "
                           "%.3fs blip (%d requests replayed)",
                           self.name, self._session, blip_s, len(replay))
            return True
        return False

    def _keepalive_loop(self):
        """Ping cadence ``liveness/4``: measures RTT, carries the ack
        watermark that lets the agent prune its outbox, and force-drops
        a silently black-holed socket after a full quiet budget so the
        reader reaches the reconnect path."""
        period = max(0.05, self.liveness_s / 4.0)
        while not (self._closing or self._dead):
            time.sleep(period)
            conn = self._conn
            if conn is None or self._closing or self._dead:
                continue
            if not self._ready.is_set():
                # the agent is still building the replica (the init
                # compile can legitimately exceed the blip budget);
                # spawn_timeout owns this window
                continue
            if time.monotonic() - self._last_rx > self.liveness_s:
                logger.warning(
                    "replica %s: no frames for %.2fs (silent black "
                    "hole); force-dropping the socket", self.name,
                    self.liveness_s)
                conn.force_drop()
                continue
            t0 = time.monotonic()
            fut = self._send("ping", _replay=False, acked=self._acked)
            try:
                fut.result(timeout=self.liveness_s)
                self._m_rtt.observe(time.monotonic() - t0)
            except Exception:
                # lost ping: the reader/liveness machinery owns the
                # consequence; just drop the orphaned future
                with self._lock:
                    self._futures.pop(getattr(fut, "_rid", None), None)

    def _forward_event(self, event):
        if not isinstance(event, dict):
            return
        try:
            from bigdl_tpu.obs import events as obs_events
            log = obs_events.get()
            if log is not None:
                log.append_foreign(event, replica=self.name)
        except Exception:  # pragma: no cover - telemetry must not kill IO
            logger.warning("replica %s: event forward failed", self.name)

    def _agent_stderr(self):
        return (self._agent.stderr_tail()
                if self._agent is not None else None)

    def _agent_tail_suffix(self, n: int = 8) -> str:
        tail = self._agent_stderr()
        if not tail:
            return ""
        return "; agent stderr tail:\n  " + "\n  ".join(tail[-n:])

    def _dead_error(self) -> DeadReplicaError:
        return DeadReplicaError(
            f"replica {self.name} (agent {self.addr[0]}:{self.addr[1]}) "
            f"died{self._agent_tail_suffix()}")

    def _on_death(self):
        with self._lock:
            if self._dead:
                return
            self._dead = True
            orphans = [f for f, _ in self._futures.values()]
            self._futures.clear()
            self._pending.clear()
        self._ready.set()
        self._teardown_conn()
        try:
            self._m_sessions.set(0)
        except Exception:   # pragma: no cover - registry mid-teardown
            pass
        err = self._dead_error()
        for fut in orphans:
            if not fut.done():
                fut.set_exception(err)
        if not self._closing:
            from bigdl_tpu.obs import events as obs_events
            obs_events.emit("remote", kind="death", replica=self.name,
                            orphaned_requests=len(orphans))
        if self._on_release is not None:
            try:
                self._on_release(self.addr)
            except Exception:   # pragma: no cover - inventory teardown
                pass
            self._on_release = None

    def _ensure_delivery(self) -> TokenDelivery:
        if self._delivery is None:
            self._delivery = TokenDelivery(name=self.name)
        return self._delivery

    def _rpc(self, op: str, timeout: float | None = None, **fields):
        fut = self._send(op, **fields)
        return fut.result(timeout=timeout)

    def _send(self, op: str, _trace=None, _replay=True, **fields) -> Future:
        rid = next(self._ids)
        fut = StreamFuture()
        fut._rid = rid
        frame = dict(fields, op=op, id=rid)
        with self._lock:
            if self._dead:
                fut.set_exception(self._dead_error())
                return fut
            self._futures[rid] = (fut, _trace)
            if _replay:
                self._pending[rid] = frame
        conn = self._conn
        try:
            if conn is not None:
                _write_frame(conn.wfile, frame, self._wlock)
        except FrameProtocolError as e:
            # over-bound payload: nothing was written, only this rpc
            # fails — the connection (and replica) live on
            with self._lock:
                self._futures.pop(rid, None)
                self._pending.pop(rid, None)
            fut.set_exception(e)
        except (OSError, ValueError):
            # mid-blip write: tolerated — the frame sits in _pending
            # and replays on re-attach (or orphans on death)
            pass
        return fut

    # -- replica surface (ProcessReplica parity) ----------------------------
    def submit(self, x, trace=None) -> Future:
        return self._send(
            "submit", _trace=trace, x=np.asarray(x),
            trace=None if trace is None else trace.to_wire())

    def inflight(self) -> int:
        with self._lock:
            return len(self._futures)

    def alive(self) -> bool:
        # True through a blip: the router must NOT requeue this
        # replica's work while a reconnect is still inside the budget
        return not self._dead

    def stats(self) -> dict:
        return self._rpc("stats", timeout=30.0)

    def telemetry(self) -> dict:
        return self._rpc("telemetry", timeout=30.0)

    def registry_snapshot(self) -> dict | None:
        return self.telemetry().get("registry")

    def weights_version(self) -> int:
        return self._rpc("version", timeout=30.0)

    def stage_weights(self, params, state, version=None):
        self._rpc("stage", timeout=120.0, params=params, state=state,
                  version=version)

    def commit_weights(self) -> int:
        return self._rpc("commit", timeout=30.0)

    def rollback_weights(self):
        self._rpc("rollback", timeout=30.0)

    def revert_weights(self) -> int:
        return self._rpc("revert", timeout=30.0)

    def close(self, drain: bool = True):
        self._closing = True
        if not self._dead and self._conn is not None:
            try:
                self._rpc("close", timeout=60.0, drain=drain)
            except Exception:
                pass
        self._on_death()
        if threading.current_thread() is not self._reader:
            self._reader.join(timeout=10.0)
        if self._delivery is not None:
            self._delivery.close()
            self._delivery = None
        try:
            from bigdl_tpu.obs import metrics as obs_metrics
            obs_metrics.get().drop_series(replica=self.name)
        except Exception:   # pragma: no cover - registry mid-teardown
            pass


class RemoteDecodeReplica(RemoteReplica):
    """A fleet decode replica behind a TCP agent: ProcessDecodeReplica's
    submit surface (shipped pages, streamed token chunks) on the
    blip-tolerant transport.  Shipped page bytes land on
    ``fleet_ship_bytes_total{transport="tcp"}``."""

    def _init_frame(self, model, worker_kwargs) -> dict:
        return {"op": "init", "role": "decode", "model": model,
                "decoder": worker_kwargs}

    def submit(self, x, trace=None) -> Future:
        from bigdl_tpu.serve.fleet import _note_ship_bytes
        _note_ship_bytes(self.name, "tcp", x.get("pages"))
        return self._send(
            "submit", _trace=trace,
            seed=[int(t) for t in x["seed"]],
            n_words=int(x["n_words"]), pages=x.get("pages"),
            stream=bool(x.get("stream")),
            sampling=x.get("sampling"),
            trace=None if trace is None else trace.to_wire())


class RemotePrefillReplica(RemoteReplica):
    """A fleet prefill replica behind a TCP agent — ``prefill_async``
    resolves to the shippable page payloads, death falls back to
    colocated prefill via the FleetRouter's existing path."""

    def _init_frame(self, model, worker_kwargs) -> dict:
        return {"op": "init", "role": "prefill", "model": model,
                "prefill": worker_kwargs}

    def prefill_async(self, seed) -> Future:
        return self._send("prefill", seed=[int(t) for t in seed])

    def prefill(self, seed, timeout: float = 120.0) -> list:
        return self.prefill_async(seed).result(timeout=timeout)


# ---------------------------------------------------------------------------
# loopback agent spawning (tests, single-host demos, bench)
# ---------------------------------------------------------------------------

class AgentHandle:
    """A locally spawned replica-agent subprocess: its address, its
    bounded stderr ring (the tail rides DeadReplicaError /
    ReplicaSpawnError messages), and kill/close for drills."""

    def __init__(self, proc, host: str, port: int):
        self.proc = proc
        self.host, self.port = host, port
        self._ring = deque(maxlen=_STDERR_LINES)
        self._stderr_reader = threading.Thread(
            target=self._stderr_loop, daemon=True,
            name=f"bigdl-agent-{port}-stderr")
        self._stderr_reader.start()

    @property
    def addr(self):
        return (self.host, self.port)

    def _stderr_loop(self):
        try:
            for raw in self.proc.stderr:
                self._ring.append(
                    raw.decode("utf-8", errors="replace").rstrip("\n"))
        except (OSError, ValueError):  # pragma: no cover - teardown
            pass

    def stderr_tail(self, n: int | None = None) -> list:
        tail = list(self._ring)
        return tail if n is None else tail[-n:]

    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self):
        """Induced agent death (the real-death drill)."""
        try:
            self.proc.kill()
        except OSError:   # pragma: no cover - already gone
            pass

    def close(self):
        self.kill()
        try:
            self.proc.wait(timeout=10.0)
        except Exception:   # pragma: no cover - still exiting
            pass
        self._stderr_reader.join(timeout=2.0)


def spawn_agent(host: str = "127.0.0.1", port: int = 0, token=None,
                env=None, spawn_timeout: float = 60.0) -> AgentHandle:
    """Spawn ``python -m tools.replica_agent`` on a loopback port and
    wait for its ``AGENT_PORT=<n>`` banner.  Returns the
    :class:`AgentHandle` whose ``.addr`` a RemoteReplica dials."""
    child_env = dict(os.environ)
    from bigdl_tpu.obs import events as obs_events
    child_env.pop(obs_events.ENV_DIR, None)
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    child_env["PYTHONPATH"] = (repo_root + os.pathsep
                               + child_env.get("PYTHONPATH", ""))
    if token is not None:
        child_env[ENV_TOKEN] = str(token)
    if env:
        child_env.update(env)
    proc = subprocess.Popen(
        [sys.executable, "-m", "tools.replica_agent",
         "--host", host, "--port", str(port)],
        stdin=subprocess.DEVNULL, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, env=child_env, cwd=repo_root)
    handle = AgentHandle(proc, host, port)
    deadline = time.monotonic() + spawn_timeout
    killer = threading.Timer(spawn_timeout, proc.kill)
    killer.daemon = True
    killer.start()
    try:
        while True:
            line = proc.stdout.readline()
            if not line:
                raise ReplicaSpawnError(
                    f"replica agent on {host}:{port} exited before "
                    f"announcing its port (exit {proc.poll()}); stderr "
                    f"tail:\n  " + "\n  ".join(handle.stderr_tail(8)),
                    stderr_tail=handle.stderr_tail())
            text = line.decode("utf-8", errors="replace").strip()
            if text.startswith("AGENT_PORT="):
                handle.port = int(text.split("=", 1)[1])
                return handle
            if time.monotonic() > deadline:
                raise ReplicaSpawnError(
                    f"replica agent on {host}:{port} did not announce "
                    f"its port in {spawn_timeout}s",
                    stderr_tail=handle.stderr_tail())
    except BaseException:
        handle.close()
        raise
    finally:
        killer.cancel()
