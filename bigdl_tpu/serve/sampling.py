"""Sampling for serving and offline decode (docs/serving.md "Sampled
decode").

ONE sampler for both decode paths: the offline ``lm_decode`` scan and
the served :class:`~bigdl_tpu.serve.decode.ContinuousDecoder` step
bodies call the same :func:`filter_logits` / :func:`sample_tokens`
math, so the two can never drift.  Everything here is traced-friendly
in BOTH regimes:

- **static scalars** (``lm_decode``'s keyword arguments): the filter
  reduces to exactly the historical temperature-scale + top-k-threshold
  ops, so pre-existing (temperature, top_k) draws stay byte-identical;
- **per-row traced vectors** (the served step): a ``(B,)`` float
  temperature, int top-k, float top-p and a ``(B, 2)`` uint32 PRNG-key
  row per slot ride the compiled step program as data — the vLLM-style
  traced-sampling-params trick — so a batch mixing greedy and any
  number of distinct sampling configs runs ONE compiled step with zero
  cold compiles.

**Key discipline (the replay contract).**  Served draws are keyed
``fold_in(request_key, DRAW_TAGS * gen_index + tag)`` — a pure function
of the request's own key and the GENERATED-TOKEN INDEX, never of slot,
batch composition, prefix-hit start position or sync cadence.  That
makes every sampled request bit-exactly replayable
(``tools/request_replay.py``) and its token stream invariant to where
and next to whom it was scheduled.  The tags separate the independent
draw streams one generated position can consume:

====================  ====================================================
``TAG_MAIN``          the non-speculative per-step draw
``TAG_DRAFT``         speculative draft proposal at this position
``TAG_ACCEPT``        the accept/reject uniform for that proposal
``TAG_FIX``           the residual (rejection) / bonus (all-accepted) draw
====================  ====================================================

**Lossless speculative sampling** (Leviathan et al.): accept the draft
token ``x`` with probability ``min(1, p(x)/q(x))`` — evaluated
division-free as ``u * q(x) < p(x)`` — and on rejection resample from
the normalized residual ``max(p - q, 0)`` (:func:`spec_residual`).
The committed marginal is exactly ``p``, so speculative decode keeps
its speedup at temperature > 0 while matching the non-speculative
sampling distribution; ``tests/test_sampling.py`` pins it with a
fixed-key χ² test.  :func:`spec_accept_one` is the single-position
reference chain the spec step body vectorizes.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field

import numpy as np

#: draw-stream tags: one generated position may consume up to
#: DRAW_TAGS independent subkeys (see the module docstring)
TAG_MAIN, TAG_DRAFT, TAG_ACCEPT, TAG_FIX = 0, 1, 2, 3
DRAW_TAGS = 4


@dataclass(frozen=True)
class SamplingParams:
    """Per-request decode sampling recipe.

    ``temperature <= 0`` is greedy (argmax — byte-identical to the
    pre-sampling decode stream); ``top_k``/``top_p`` truncate the
    scaled distribution (0 disables either; ``top_p`` in (0, 1));
    ``seed`` pins the request's PRNG key (resolved to a fresh random
    seed at submit when left None on a sampled request — the resolved
    value is what travels in fleet payloads and flight-recorder
    records, so requeue-after-death and replay redraw identically).
    ``stop`` is a tuple of token-id sequences: generation retires
    early at the sync boundary after any of them is produced, the
    resolved row truncated just past the match.  ``max_tokens`` caps
    ``n_words`` at submit when set."""

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0
    seed: int | None = None
    stop: tuple = ()
    max_tokens: int | None = None

    def __post_init__(self):
        if self.temperature < 0:
            raise ValueError("temperature must be >= 0 (0 = greedy)")
        if self.top_k < 0:
            raise ValueError("top_k must be >= 0 (0 = off)")
        if not 0.0 <= self.top_p <= 1.0:
            raise ValueError("top_p must be in [0, 1] (0 or 1 = off)")
        if self.max_tokens is not None and self.max_tokens < 1:
            raise ValueError("max_tokens must be >= 1 when set")
        stop = tuple(tuple(int(t) for t in s) for s in (self.stop or ()))
        if any(len(s) == 0 for s in stop):
            raise ValueError("stop sequences must be non-empty")
        object.__setattr__(self, "stop", stop)

    # -- derived -----------------------------------------------------------
    @property
    def greedy(self) -> bool:
        return self.temperature <= 0

    @property
    def is_default(self) -> bool:
        """True for the plain greedy request (nothing worth recording)."""
        return (self.greedy and not self.stop and not self.top_k
                and not self.top_p and self.max_tokens is None)

    # -- construction ------------------------------------------------------
    @classmethod
    def of(cls, val) -> "SamplingParams":
        """Coerce ``None`` (greedy default), a dict (fleet payloads,
        flight-recorder records) or an instance."""
        if val is None:
            return GREEDY
        if isinstance(val, cls):
            return val
        if isinstance(val, dict):
            known = ("temperature", "top_k", "top_p", "seed", "stop",
                     "max_tokens")
            kw = {k: val[k] for k in known if val.get(k) is not None}
            if "stop" in kw:
                kw["stop"] = tuple(tuple(s) for s in kw["stop"])
            return cls(**kw)
        raise TypeError(
            f"sampling must be SamplingParams, dict or None, "
            f"got {type(val).__name__}")

    def resolved(self) -> "SamplingParams":
        """Pin the PRNG seed: a sampled request with ``seed=None``
        gets a fresh random one HERE — before the params ever ride a
        fleet payload — so re-delivery after a replica death and
        offline replay both redraw the exact same stream."""
        if self.greedy or self.seed is not None:
            return self
        seed = int.from_bytes(os.urandom(4), "big")
        return SamplingParams(self.temperature, self.top_k, self.top_p,
                              seed, self.stop, self.max_tokens)

    def to_dict(self) -> dict:
        """Wire/record form (plain JSON types; ``of`` round-trips it)."""
        return {"temperature": self.temperature, "top_k": self.top_k,
                "top_p": self.top_p, "seed": self.seed,
                "stop": [list(s) for s in self.stop],
                "max_tokens": self.max_tokens}


GREEDY = SamplingParams()


def key_data(seed) -> np.ndarray:
    """The ``(2,)`` uint32 PRNG key row for one request seed — the
    threefry key layout ``jax.random.PRNGKey`` produces, computed
    host-side so admission never pays a device dispatch."""
    s = int(seed or 0) & 0xFFFFFFFFFFFFFFFF
    return np.array([(s >> 32) & 0xFFFFFFFF, s & 0xFFFFFFFF], np.uint32)


def _param(v, lp):
    """Broadcast a scalar or ``(B,)`` vector parameter against
    ``(..., V)`` logits: append singleton dims up to ``lp.ndim``."""
    import jax.numpy as jnp
    v = jnp.asarray(v)
    return v.reshape(v.shape + (1,) * (lp.ndim - v.ndim))


def filter_logits(logp, temperature=1.0, top_k=0, top_p=0.0):
    """Temperature-scale then top-k / top-p truncate log-probs.

    ``logp`` is ``(..., V)``; each parameter is a static scalar or a
    per-row vector broadcastable against the leading dims.  Rows with
    ``temperature <= 0`` pass through unscaled (the greedy lane takes
    the argmax and never reads the sampled draw); ``top_k`` keeps the
    k highest logits (0 or >= V disables — ties at the k-th value all
    survive, the historical ``lm_decode`` semantics); ``top_p`` keeps
    the smallest descending-probability prefix whose cumulative mass
    reaches p (0 or 1 disables; the top token always survives).
    """
    import jax
    import jax.numpy as jnp

    V = logp.shape[-1]
    t = _param(temperature, logp).astype(logp.dtype)
    lp = logp / jnp.where(t > 0, t, 1)
    kk = _param(top_k, logp)
    # k-th largest via one ascending sort (== lax.top_k's k-th value,
    # so the keep set matches the historical threshold exactly)
    srt = jnp.sort(lp, axis=-1)
    idx = jnp.broadcast_to(jnp.clip(V - kk, 0, V - 1),
                           lp.shape[:-1] + (1,))
    kth = jnp.take_along_axis(srt, idx, axis=-1)
    k_on = (kk > 0) & (kk < V)
    lp = jnp.where(k_on & (lp < kth), -jnp.inf, lp)
    pp = _param(top_p, logp).astype(logp.dtype)
    probs = jax.nn.softmax(lp, axis=-1)
    sp = jnp.flip(jnp.sort(probs, axis=-1), axis=-1)
    cum = jnp.cumsum(sp, axis=-1)
    keep = (cum - sp) < pp          # mass BEFORE this token still short
    thr = jnp.min(jnp.where(keep, sp, jnp.inf), axis=-1, keepdims=True)
    p_on = (pp > 0) & (pp < 1)
    return jnp.where(p_on & (probs < thr), -jnp.inf, lp)


def sample_tokens(logits, key, temperature=1.0, top_k=0, top_p=0.0):
    """One sampled token per row from filtered logits — the shared
    sampler both decode paths call.

    ``key`` is either one PRNG key (a single batch draw — the offline
    ``lm_decode`` scan, one split per step) or a ``(B, 2)`` uint32
    per-row key array (the served step — each row draws from its own
    request-keyed stream via :func:`fold_in_rows`)."""
    import jax

    lp = filter_logits(logits, temperature, top_k, top_p)
    if getattr(key, "ndim", 0) == 2:
        return jax.vmap(jax.random.categorical)(key, lp)
    return jax.random.categorical(key, lp)


def fold_in_rows(keys, data):
    """Per-row ``jax.random.fold_in``: ``(B, 2)`` uint32 keys x ``(B,)``
    int data -> ``(B, 2)`` subkeys.  The served step derives every draw
    key this way (``DRAW_TAGS * gen_index + tag``)."""
    import jax
    return jax.vmap(jax.random.fold_in)(keys, data)


def uniform_rows(keys):
    """One uniform [0, 1) draw per ``(B, 2)`` key row."""
    import jax
    return jax.vmap(lambda k: jax.random.uniform(k, ()))(keys)


def spec_residual(p, q):
    """The Leviathan rejection distribution: ``max(p - q, 0)``
    normalized, falling back to ``p`` where the residual has zero mass
    (draft == target).  ``p``/``q`` are probability rows ``(..., V)``."""
    import jax.numpy as jnp
    r = jnp.maximum(p - q, 0.0)
    z = r.sum(axis=-1, keepdims=True)
    return jnp.where(z > 0, r / jnp.where(z > 0, z, 1.0), p)


def spec_accept_one(key, p_logits, q_logits):
    """Single-position reference of the lossless accept/reject chain
    (what ``spec_step_body`` vectorizes across the window): draft
    ``x ~ q``, accept iff ``u * q(x) < p(x)``, else resample from the
    residual.  The committed marginal is exactly ``softmax(p_logits)``
    — the χ² pin in tests/test_sampling.py."""
    import jax
    import jax.numpy as jnp
    kd, ka, kr = jax.random.split(key, 3)
    x = jax.random.categorical(kd, q_logits)
    p = jax.nn.softmax(p_logits)
    q = jax.nn.softmax(q_logits)
    u = jax.random.uniform(ka, ())
    y = jax.random.categorical(kr, jnp.log(spec_residual(p, q)))
    return jnp.where(u * q[x] < p[x], x, y)
