"""Shared executable cache — one compile registry for every entry point
(docs/serving.md "Control plane", docs/performance.md).

PR 5 put the ahead-of-time ``jit(fwd).lower(...).compile()`` ladder
inside the ServeEngine, so only the serving path had the
zero-cold-compile property; the validators' pad-and-trim trick and the
train loop's jit cache were separate mechanisms with separate
accounting.  This module lifts that cache out into ONE process-wide
registry keyed by::

    (fn_key, leaf shapes/dtypes, mesh fingerprint, dtype-policy)

so that train dispatch, ``optim.validate`` and every serve replica ride
the same entries:

- ``optim.local_optimizer._eval_fn`` wraps its jitted forward in
  :class:`ShapedCallable` — each distinct batch shape resolves to one
  AOT-compiled executable here;
- ``ServeEngine.warmup`` asks this cache for each bucket's executable
  with the SAME ``fn_key`` (the model fingerprint), so a process that
  validates AND serves a common (model, shape) pair compiles it exactly
  once — the compile-counter audit ``tests/test_serve_cluster.py``
  holds both to;
- the train-step builders (``LocalOptimizer``/``DistriOptimizer``)
  register their jit dispatches through :func:`tracked_jit`, which
  keys on the batch operands only (a model-sized pytree walk per step
  would be host overhead the async pipeline just removed).

Two registration modes, one key space:

- **AOT** (:meth:`ExecutableCache.get_or_compile`): lower-and-compile
  now, return the executable; a later request for the same key gets
  the cached executable — zero new XLA work.
- **tracked jit** (:func:`tracked_jit`): the function stays a normal
  ``jax.jit`` dispatch (donation, sharding and weak-type semantics
  untouched — the train step donates its carried state), but the first
  dispatch of each key is recorded as a compile so ``stats()`` is a
  process-truthful compile counter across ALL entry points.

The cache never evicts (an executable is a few MB of device code; a
serving process wants them all resident); :func:`reset` exists for
tests and is wired into the suite's autouse fixture.
"""
from __future__ import annotations

import threading

import numpy as np

#: process-wide singleton (identity is stable across :func:`reset` so
#: closures built by ``tracked_jit``/``ShapedCallable`` never go stale)
_CACHE = None
_LOCK = threading.Lock()


def _policy_key():
    """Dtype-policy component of a cache key: the policy's three dtypes
    (stable across policy object identities)."""
    try:
        from bigdl_tpu import tensor as bt
        p = bt.policy()
        return (str(p.param_dtype), str(p.compute_dtype),
                str(p.output_dtype))
    except Exception:  # pragma: no cover - tensor layer absent
        return None


def _mesh_key(mesh):
    """Mesh component of a cache key: axis names/sizes + device ids (two
    meshes over different devices must not share executables)."""
    if mesh is None:
        return None
    return (tuple(mesh.axis_names), tuple(mesh.devices.shape),
            tuple(int(d.id) for d in mesh.devices.flat))


def _leaf_sharding(leaf):
    """Sharding component of one leaf's key: None for host numpy,
    ShapeDtypeStructs and single-device jax arrays (those interconvert
    freely — an AOT executable commits host inputs to its device), a
    distinguishing string for MULTI-device shardings (an executable
    lowered against mesh-sharded operands rejects differently-placed
    inputs, so those must never collide with the single-device entry)."""
    s = getattr(leaf, "sharding", None)
    if s is None:
        return None
    try:
        if len(s.device_set) <= 1:
            return None
        return str(s)
    except Exception:  # pragma: no cover - exotic sharding objects
        return None


def _shapes_key(args):
    """Leaf (shape, dtype, sharding) tuple of an argument pytree.
    Accepts real arrays, ShapeDtypeStructs, and python scalars."""
    import jax

    out = []
    for leaf in jax.tree_util.tree_leaves(args):
        dt = getattr(leaf, "dtype", None)
        if dt is None:
            dt = np.asarray(leaf).dtype
        out.append((tuple(np.shape(leaf)), str(dt),
                    _leaf_sharding(leaf)))
    return tuple(out)


_METRIC_HANDLES = (None, -1, {})   # (registry, generation, name->Counter)


def _note_metric(name: str):
    """Mirror a compile/hit tick into the obs metrics registry so the
    fleet exporter sees the process-truthful compile counter next to
    the serving numbers.  Counter handles are cached per (registry,
    generation) — this runs on every tracked_jit dispatch (once per
    train step), which must not pay a registry-lock resolution each
    time; a reset()/clear() bumps the generation and forces
    re-registration.  Best-effort by design: the executable cache must
    work even if the obs layer is mid-teardown."""
    global _METRIC_HANDLES
    try:
        from bigdl_tpu.obs import metrics
        reg = metrics.get()
        cache_reg, gen, handles = _METRIC_HANDLES
        if cache_reg is not reg or gen != reg.generation:
            handles = {}
            _METRIC_HANDLES = (reg, reg.generation, handles)
        c = handles.get(name)
        if c is None:
            c = handles[name] = reg.counter(name,
                                            "shared executable cache")
        c.inc()
    except Exception:  # pragma: no cover - obs layer unavailable
        pass


class ExecutableCache:
    """The process-wide registry.  Thread-safe: serve replicas warm
    concurrently with a validating training thread."""

    def __init__(self):
        self._lock = threading.RLock()
        self._exes = {}       # key -> AOT-compiled executable
        self._jit_keys = set()  # keys registered via tracked_jit
        self.compiles = 0     # fresh XLA builds (or first jit dispatches)
        self.hits = 0         # key re-resolutions that cost nothing

    def key_for(self, fn_key, args, mesh=None):
        return (fn_key, _shapes_key(args), _mesh_key(mesh), _policy_key())

    def get_or_compile(self, jitted, fn_key, args, mesh=None):
        """Resolve (or build) the AOT executable for ``jitted`` at the
        shapes of ``args`` (arrays or ShapeDtypeStructs).  Returns
        ``(executable, fresh)``."""
        key = self.key_for(fn_key, args, mesh)
        with self._lock:
            exe = self._exes.get(key)
            if exe is not None:
                self.hits += 1
                _note_metric("xcache_hits_total")
                return exe, False
        # compile outside the lock: tens of seconds cold on a chip, and
        # another thread may be resolving a different bucket meanwhile
        exe = jitted.lower(*args).compile()
        with self._lock:
            if key in self._exes:   # lost a benign race: count the hit
                self.hits += 1
                _note_metric("xcache_hits_total")
                return self._exes[key], False
            self._exes[key] = exe
            self.compiles += 1
        _note_metric("xcache_compiles_total")
        # cost/HBM ledger capture rides the compile, keyed by the SAME
        # cache key (obs/ledger.py); hits above never reach this line,
        # so the warm path stays ledger-free
        try:
            from bigdl_tpu.obs import ledger as obs_ledger
            obs_ledger.get().capture_compiled(fn_key, exe, key=key)
        except Exception:   # pragma: no cover - obs layer unavailable
            pass
        return exe, True

    def note_jit_dispatch(self, fn_key, key_args, mesh=None) -> bool:
        """Record one jit dispatch keyed by ``key_args`` shapes; returns
        True when this key is new (the dispatch that compiles)."""
        key = self.key_for(fn_key, key_args, mesh)
        with self._lock:
            if key in self._jit_keys:
                self.hits += 1
                fresh = False
            else:
                self._jit_keys.add(key)
                self.compiles += 1
                fresh = True
        _note_metric("xcache_compiles_total" if fresh
                     else "xcache_hits_total")
        return fresh

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._exes) + len(self._jit_keys),
                    "aot_entries": len(self._exes),
                    "compiles": self.compiles, "hits": self.hits}

    def clear(self):
        with self._lock:
            self._exes.clear()
            self._jit_keys.clear()
            self.compiles = 0
            self.hits = 0


def get() -> ExecutableCache:
    global _CACHE
    if _CACHE is None:
        with _LOCK:
            if _CACHE is None:
                _CACHE = ExecutableCache()
    return _CACHE


def reset():
    """Drop every entry and zero the counters (tests).  Executables
    already handed out keep working — the registry only forgets them."""
    get().clear()


class ShapedCallable:
    """A jitted function routed through the shared cache: each call
    resolves the AOT executable for its argument shapes and invokes it —
    after the first call per shape, the serving/eval path never touches
    ``jax.jit`` again.

    Key resolution walks the argument pytree (validate's per-batch
    cadence tolerates that; the ServeEngine's hot path does NOT go
    through here — it caches the resolved executable per bucket), with
    an identity fast path for the dominant eval pattern: the same
    (params, state) objects fed batch after batch skip the tree walk
    entirely.

    ``.jitted`` and ``.fn_key`` are public so the ServeEngine can warm
    buckets through the SAME key space this callable resolves from.
    """

    __slots__ = ("jitted", "fn_key", "mesh", "_fast")

    def __init__(self, jitted, fn_key, mesh=None):
        self.jitted = jitted
        self.fn_key = fn_key
        self.mesh = mesh
        #: (id-tuple of leading args, tail shape/dtype key, policy key,
        #: executable) — identity of the big operands is sufficient:
        #: same objects => same shapes/shardings, and values are
        #: executable ARGUMENTS, never baked in
        self._fast = None

    def __call__(self, *args):
        fast = self._fast
        if fast is not None:
            ids = tuple(id(a) for a in args[:-1])
            tail = args[-1]
            tkey = (tuple(np.shape(tail)),
                    str(getattr(tail, "dtype", "")))
            if (fast[0] == ids and fast[1] == tkey
                    and fast[2] == _policy_key()):
                return fast[3](*args)
        exe, _ = get().get_or_compile(self.jitted, self.fn_key, args,
                                      self.mesh)
        if len(args) > 1:
            tail = args[-1]
            self._fast = (tuple(id(a) for a in args[:-1]),
                          (tuple(np.shape(tail)),
                           str(getattr(tail, "dtype", ""))),
                          _policy_key(), exe)
        return exe(*args)

    def lower(self, *args):   # AOT escape hatch, parity with jit fns
        return self.jitted.lower(*args)


def tracked_jit(fn, fn_key, key_argnums=None, mesh=None, **jit_kwargs):
    """``jax.jit(fn, **jit_kwargs)`` with its dispatches registered in
    the shared cache.

    The wrapper keys on ``key_argnums`` (default: all args) — train
    steps pass the batch operand indices only, so the per-step cost is
    two shape probes, not a model-sized pytree walk.  Dispatch
    semantics (donation, shardings, weak types) are exactly jit's.
    """
    import jax

    jitted = jax.jit(fn, **jit_kwargs)
    cache = get()

    def wrapper(*args):
        sel = args if key_argnums is None else tuple(
            args[i] for i in key_argnums)
        fresh = cache.note_jit_dispatch(fn_key, sel, mesh)
        if fresh:
            # ledger capture on the dispatch that compiles, BEFORE the
            # dispatch runs — it may donate these argument buffers.
            # Cost comes from the lowering alone (one extra trace, no
            # second XLA compile); warm dispatches skip this entirely.
            try:
                from bigdl_tpu.obs import ledger as obs_ledger
                obs_ledger.get().capture_lowered(
                    fn_key, cache.key_for(fn_key, sel, mesh), jitted,
                    args)
            except Exception:  # pragma: no cover - obs layer unavailable
                pass
        return jitted(*args)

    wrapper.jitted = jitted
    wrapper.fn_key = fn_key
    return wrapper
