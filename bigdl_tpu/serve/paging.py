"""Block-paged KV-cache allocation for the continuous decoder
(docs/serving.md "Paged KV + speculative decode").

The PR-5 decoder reserved one fixed ``(B, n_pos)`` KV slab row per slot:
a 6-token request held the same HBM as a 64-token one, and concurrency
was hard-capped at the slab width B.  This module is the vLLM
PagedAttention idea applied to that slab: KV storage becomes a
``(n_pages, page_size, ...)`` pool, every request holds only the
fixed-size pages its own length needs, and a per-slot slot→page table
(traced state — admission never recompiles) maps logical positions to
pool pages.  Concurrency then scales with **total pooled tokens**, not
slab width.

:class:`PagePool` is the host-side allocator — pure bookkeeping, no
device arrays.  The device pool lives in the decoder; page ids handed
out here index its page dimension.  Pages are refcounted because the
prefix cache (``serve/prefix.py``) shares read-only pages across
requests: a shared page is released only when the last holder lets go.

:class:`RequestTooLongError` is the submit-time verdict for a request
whose ``n_seed + n_words - 1`` exceeds the decoder's position capacity.
It fails ONLY that request's future — the old behaviour silently held
the row at the slab edge (``pos`` clipped to ``n_pos - 1``), burning
steps while generating garbage tokens.
"""
from __future__ import annotations

from collections import deque


class RequestTooLongError(ValueError):
    """A decode request needs more positions than the decoder can ever
    hold (``len(seed) + n_words - 1 > n_pos``, or more pages than the
    whole pool).  Set on the request's OWN future at submit time; other
    requests are untouched."""


class PagePool:
    """Refcounted free-list allocator over ``n_pages`` fixed-size pages.

    Page ids are ``0 .. n_pages - 1`` — indices into the decoder's
    device pool arrays.  ``alloc_one`` hands out a page at refcount 1;
    :meth:`retain` / :meth:`release` move shared (prefix-cache) pages
    between holders; a page returns to the free list when its last
    reference drops.  Host-side only: nothing here touches jax.
    """

    def __init__(self, n_pages: int, page_size: int):
        if n_pages < 1 or page_size < 1:
            raise ValueError(
                f"PagePool needs n_pages >= 1 and page_size >= 1, got "
                f"{n_pages}/{page_size}")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self._free: "deque[int]" = deque(range(self.n_pages))
        self._ref: dict = {}          # page id -> refcount
        self.in_use_hwm = 0           # high-water mark of allocated pages

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return self.n_pages - len(self._free)

    def alloc_one(self) -> int:
        """One free page at refcount 1; raises when the pool is empty
        (callers check ``free_count`` / evict first)."""
        if not self._free:
            raise RuntimeError("page pool exhausted")
        pid = self._free.popleft()
        self._ref[pid] = 1
        if self.in_use > self.in_use_hwm:
            self.in_use_hwm = self.in_use
        return pid

    def retain(self, pid: int):
        """One more holder of an allocated page (a prefix-cache hit
        mapping a shared page into a new slot's table)."""
        self._ref[pid] += 1

    def release(self, pid: int):
        """Drop one reference; the page frees when nobody holds it."""
        n = self._ref[pid] - 1
        if n < 0:  # pragma: no cover - double-release guard
            raise RuntimeError(f"page {pid} released below zero")
        if n == 0:
            del self._ref[pid]
            self._free.append(pid)
        else:
            self._ref[pid] = n

    def refcount(self, pid: int) -> int:
        return self._ref.get(pid, 0)

    def stats(self) -> dict:
        return {"pages": self.n_pages, "page_size": self.page_size,
                "in_use": self.in_use, "free": self.free_count,
                "in_use_hwm": self.in_use_hwm}
