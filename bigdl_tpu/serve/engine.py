"""Throughput-oriented inference engine: request queue + dynamic
batching + shape-bucketed AOT executable cache (docs/serving.md).

The reference shipped batch scoring as a first-class subsystem
(DLClassifier / ``Module.predict`` over an RDD); this is the TPU-native
version, built on the same pipeline idioms the training path already
proved out (``dataset/prefetch.py`` double-buffering, the obs event
stream, ``BIGDL_FAULTS`` chaos sites):

- **Submit**: :meth:`ServeEngine.submit` / :meth:`submit_many` enqueue
  single rows and return ``concurrent.futures.Future`` objects — the
  async API a request handler calls.
- **Assemble**: a batcher thread closes a micro-batch on
  size-or-deadline (``BIGDL_SERVE_MAX_BATCH`` rows, or
  ``BIGDL_SERVE_MAX_WAIT_MS`` after the first queued row), rejects
  poisoned rows (non-finite values fail ONLY their own future, with an
  obs ``serve`` error event — the batch proceeds without them) and
  zero-pads to the power-of-two bucket (`serve/bucketing.py`).
- **Transfer**: a dedicated H2D thread double-buffers padded batches to
  the device (the ``prefetch.py`` transfer-thread pattern; bounded
  queues give backpressure).  This is a ``BIGDL_FAULTS`` site
  (``serve_h2d``) so the chaos matrix covers serving.
- **Execute**: a compute thread runs the bucket's ahead-of-time
  compiled executable (``jit(fwd).lower(...).compile()`` per bucket at
  warmup, riding the persistent XLA compilation cache) and resolves the
  futures with trimmed per-row outputs.  After warmup a mixed-size
  stream triggers ZERO new compiles — the single-compile invariant
  ``tests/test_serve.py`` audits.

Weights are captured and pinned to device ONCE at engine start
(``jax.device_put``); :meth:`refresh` re-captures them from the model
(same shapes/dtypes, so the executable cache survives).  An optional
``DTypePolicy`` (e.g. ``tensor.BF16_COMPUTE``) scopes bf16 MXU compute
to the serving forward without touching the process default.

Telemetry: every counter, gauge and the fixed-bucket latency histogram
live in the process-wide mergeable registry (``obs/metrics.py``,
labelled ``engine=<name>``) so per-replica numbers roll up exactly
across a fleet; :meth:`stats` is a thin view over the registry
(p50/p95/p99, queue depth, bucket hits, compile count), and ``serve``
events (start/stop/error) ride the obs stream (docs/observability.md).
Sampled requests carry a trace context (``obs/trace.py``) that the
H2D and compute stages stamp in passing.
"""
from __future__ import annotations

import itertools
import logging
import os
import queue
import threading
import time
from concurrent.futures import Future

import numpy as np

from bigdl_tpu.serve import bucketing
from bigdl_tpu.serve.streaming import SafeFuture

logger = logging.getLogger("bigdl_tpu.serve")

ENV_MAX_BATCH = "BIGDL_SERVE_MAX_BATCH"
ENV_MAX_WAIT_MS = "BIGDL_SERVE_MAX_WAIT_MS"

DEFAULT_MAX_BATCH = 64
DEFAULT_MAX_WAIT_MS = 2.0
#: bounded hand-off depth between assembler -> H2D -> compute (the
#: prefetch double-buffer: one batch in flight per stage, one queued)
_STAGE_DEPTH = 2
#: default engine names: unique per process so registry series never
#: collide between replicas that share one process
_ENGINE_SEQ = itertools.count()
#: count of pinned-policy warmups currently holding the process dtype
#: policy swapped (warmup() below): while > 0 the ambient policy is a
#: TRANSIENT trace-time state, not a drift — _check_policy_drift
#: suspends so a serving ambient-policy engine does not false-positive
#: against a sibling engine's compilation window
_PIN_LOCK = threading.Lock()
_PIN_DEPTH = 0


def max_batch_default() -> int:
    try:
        return max(1, int(os.environ.get(ENV_MAX_BATCH, DEFAULT_MAX_BATCH)))
    except ValueError:
        return DEFAULT_MAX_BATCH


def max_wait_ms_default() -> float:
    try:
        return max(0.0, float(os.environ.get(ENV_MAX_WAIT_MS,
                                             DEFAULT_MAX_WAIT_MS)))
    except ValueError:
        return DEFAULT_MAX_WAIT_MS


class _Request:
    __slots__ = ("x", "future", "t_submit", "trace")

    def __init__(self, x, trace=None):
        self.x = x
        # SafeFuture: a user add_done_callback that raises fails only
        # its own registration (obs error event) — it can never kill
        # the compute thread resolving the batch (serve/streaming.py)
        self.future = SafeFuture()
        self.t_submit = time.perf_counter()
        self.trace = trace       # obs.trace.Trace for sampled requests


class _End:
    pass


_END = _End()


class PoisonedRequestError(ValueError):
    """A submitted row contained non-finite values; only its own future
    fails — the rest of the micro-batch is served normally."""


class DTypePolicyDriftError(RuntimeError):
    """The process-global dtype policy changed between this engine's
    warmup and a submit.  The warmed executables were traced under the
    OLD policy (the policy is baked in at trace time — engine.__init__'s
    ``policy`` caveat), so serving on would silently answer with
    stale-precision outputs; failing the submit loudly makes the caller
    either restore the policy, pin one via ``ServeEngine(policy=...)``,
    or build a fresh engine under the new policy."""


class SheddedError(RuntimeError):
    """The request was rejected by admission control (engine queue bound
    or router overload policy) instead of being served past its
    deadline.  Carries no partial result; the caller may retry against
    a less-loaded endpoint."""


class ServeEngine:
    """Dynamic-batching inference engine over one model.

    ``ServeEngine(model)`` captures ``model.params()``/``state()`` once
    and pins them to device; call :meth:`refresh` after training updates
    the module tree.  ``input_shape``/``input_dtype`` (per-ROW shape,
    no batch dim) enable eager warmup at construction; otherwise every
    bucket compiles on the first batch (still one warmup moment — never
    per mixed size).

    ``policy`` caveat: the dtype policy is process-global at trace time
    (``tensor.set_policy`` is swapped around the warmup lowering and
    restored after), so when serving with a non-default policy NEXT TO
    concurrent training/tracing on other threads, pass ``input_shape``
    so the whole warmup happens synchronously at construction on the
    calling thread — lazy warmup would otherwise briefly apply the
    serving policy to traces racing it.  The converse drift — the
    PROCESS policy changing after an ambient-policy engine warmed — is
    caught at submit: :class:`DTypePolicyDriftError` instead of
    silently serving stale-precision executables.

    ``quant`` (default from ``BIGDL_SERVE_QUANT``: off/int8/fp8) serves
    per-channel quantized weights (docs/serving.md "Quantized
    serving"): the capture quantizes ``model.params()`` through a
    :class:`~bigdl_tpu.quant.WeightQuantizer` (pass ``calibration`` — a
    ``quant.calibrate.Calibration`` — to arm the activation-aware clip
    search), the executables take ``(qweights, scales)`` as ARGUMENTS
    and dequantize on the fly, and every staged rollout re-quantizes
    with the same recipe, so hot weight swaps never recompile.  The
    quant recipe rides the executable-cache function key — quantized
    and full-precision replicas of one architecture never collide.
    """

    def __init__(self, model, max_batch: int | None = None,
                 max_wait_ms: float | None = None, policy=None,
                 input_shape=None, input_dtype=np.float32,
                 max_queue: int | None = None, name: str | None = None,
                 quant: str | None = None, calibration=None):
        import jax

        self.model = model
        self.name = name or f"engine{next(_ENGINE_SEQ)}"
        self.max_batch = (max_batch_default() if max_batch is None
                          else max(1, int(max_batch)))
        self.max_wait_s = (max_wait_ms_default() if max_wait_ms is None
                           else max(0.0, float(max_wait_ms))) / 1e3
        #: admission bound: a submit seeing this many queued requests is
        #: shed (fails fast with SheddedError) instead of growing the
        #: backlog past any deadline.  None/0 = unbounded (the default;
        #: the router is the usual shedding layer — docs/serving.md).
        self.max_queue = int(max_queue) if max_queue else None
        self.buckets = bucketing.bucket_sizes(self.max_batch)
        self._policy = policy
        from bigdl_tpu import quant as quant_mod
        from bigdl_tpu.quant.weights import ON_MODES as _WEIGHT_MODES
        self.quant = (quant_mod.weight_mode_default() if quant is None
                      else quant_mod.normalize_mode(
                          quant, _WEIGHT_MODES, "quant"))
        #: maps fp params -> the {"q", "scale"} pack the executables
        #: take; None on the full-precision path.  May raise
        #: UnsupportedQuantError here (fp8 capability gate) — at
        #: construction, never from inside a trace.
        self._quantizer = None
        if self.quant != "off":
            self._quantizer = quant_mod.WeightQuantizer(
                model, self.quant, calibration=calibration)
        # (params, state) swap as ONE tuple so a refresh/commit racing
        # the compute thread can never pair new params with old state —
        # the half-swap audit tests/test_serve.py holds refresh() to
        self._weights = (jax.device_put(self._capture(model.params())),
                         jax.device_put(model.state()))
        self.weights_version = 0
        self._staged = None      # (version, (params, state)) or None
        self._prev_weights = None  # one-deep history for revert_weights
        # HBM tenant truth (obs/ledger.py): the pinned weight pack's
        # bytes — under weight quantization this is the int8/fp8 pack
        # size, i.e. the density the quantized-serving docs claim
        from bigdl_tpu.obs import ledger as obs_ledger
        obs_ledger.note_tenant(
            "serve_weights", obs_ledger.tree_nbytes(self._weights),
            engine=self.name, quant=self.quant)

        # ONE compiled-forward path per model: the same xcache-backed
        # eval fn the validators use (optim.local_optimizer._eval_fn) —
        # warmup resolves each bucket through the SHARED executable
        # cache (serve/xcache.py), so a process that validates AND
        # serves a common (model, shape) pair compiles it exactly once.
        # The quantized path gets its own fn (dequant-in-forward) under
        # a fn_key extended with the quant recipe: same cache, disjoint
        # keys.
        if self._quantizer is not None:
            from bigdl_tpu.quant.weights import quantized_eval_fn
            self._fwd = quantized_eval_fn(model, self._quantizer)
        else:
            from bigdl_tpu.optim.local_optimizer import _eval_fn
            self._fwd = _eval_fn(model)
        self._executables: dict = {}   # bucket -> compiled executable
        self._row_shape = None
        self._row_dtype = None
        #: the dtype-policy the warmed executables were traced under
        #: (None until the first warmup, or always when ``policy`` pins
        #: one): submit() refuses to serve across a process-policy
        #: drift (DTypePolicyDriftError)
        self._warm_policy_obj = None
        self._warm_policy_key = None

        self._lock = threading.Lock()
        self._closed = False
        self._queue: "queue.Queue" = queue.Queue()
        self._h2d_q: "queue.Queue" = queue.Queue(maxsize=_STAGE_DEPTH)
        self._exec_q: "queue.Queue" = queue.Queue(maxsize=_STAGE_DEPTH)

        # telemetry: every instrument lives in the process-wide
        # mergeable registry (obs/metrics.py) under engine=<name>, so a
        # replica fleet's numbers roll up exactly; the attribute
        # properties below and stats() are VIEWS over it.  The
        # accepted/shed/completed/failed counters are MONOTONIC from
        # construction and never reset — the router rate-differences
        # consecutive stats() snapshots, so a reset would read as a
        # huge negative rate.  completed+failed+inflight == accepted at
        # every instant; shed requests are counted in none of the other
        # three (their futures fail without entering the pipeline).
        from bigdl_tpu.obs import metrics as obs_metrics
        reg = obs_metrics.get()
        lab = {"engine": self.name}
        self._m_req = {
            outcome: reg.counter(
                "serve_requests_total",
                "engine admission counters by outcome", outcome=outcome,
                **lab)
            for outcome in ("accepted", "shed", "completed", "failed")}
        self._m_batches = reg.counter(
            "serve_batches_total", "micro-batches executed", **lab)
        self._m_compiles = reg.counter(
            "serve_compiles_total", "bucket executables installed", **lab)
        self._m_latency = reg.histogram(
            "serve_latency_seconds",
            "submit-to-resolve request latency", **lab)
        self._m_qdepth = reg.gauge(
            "serve_queue_depth", "requests waiting for a batch", **lab)
        self._m_qmax = reg.gauge(
            "serve_queue_depth_max", "queue-depth high-water mark",
            agg="max", **lab)
        self._m_inflight = reg.gauge(
            "serve_inflight", "accepted, not yet resolved", **lab)
        self._m_version = reg.gauge(
            "serve_weights_version", "committed weight version",
            agg="max", **lab)
        self._m_bucket = {
            b: reg.counter("serve_bucket_hits_total",
                           "batches served per pow2 bucket",
                           bucket=str(b), **lab)
            for b in self.buckets}
        self._inflight = 0       # submitted, future not yet resolved
        self._max_queue_depth = 0

        if input_shape is not None:
            self.warmup(tuple(input_shape), input_dtype)

        self._assembler = threading.Thread(
            target=self._assemble_loop, daemon=True,
            name="bigdl-serve-batcher")
        self._transfer = threading.Thread(
            target=self._h2d_loop, daemon=True, name="bigdl-serve-h2d")
        self._compute = threading.Thread(
            target=self._compute_loop, daemon=True,
            name="bigdl-serve-compute")
        self._assembler.start()
        self._transfer.start()
        self._compute.start()
        self._emit("start", max_batch=self.max_batch,
                   max_wait_ms=self.max_wait_s * 1e3,
                   buckets=list(self.buckets), quant=self.quant)

    # -- registry-backed counter views (monotonic; see __init__) ------------
    @property
    def accepted(self) -> int:
        return int(self._m_req["accepted"].value)

    @property
    def shed(self) -> int:
        return int(self._m_req["shed"].value)

    @property
    def served(self) -> int:
        """Rows completed OK (alias: completed)."""
        return int(self._m_req["completed"].value)

    @property
    def errors(self) -> int:
        """Rows failed (alias: failed)."""
        return int(self._m_req["failed"].value)

    @property
    def batches(self) -> int:
        return int(self._m_batches.value)

    @property
    def compiles(self) -> int:
        return int(self._m_compiles.value)

    def _capture(self, params):
        """Params as the executables expect them: quantized to the
        ``{"q", "scale"}`` pack when this engine serves quantized, the
        fp tree otherwise.  Capture, refresh and every staged rollout
        funnel through here, so a hot swap onto a quantized replica
        re-quantizes with the SAME recipe."""
        if self._quantizer is None:
            return params
        return self._quantizer.quantize(params)

    # -- compilation --------------------------------------------------------
    def warmup(self, row_shape: tuple, row_dtype=np.float32):
        """Pre-lower-and-compile EVERY bucket for rows of ``row_shape``.

        Rides the persistent XLA compilation cache (``bench.py`` proves
        1.15 s cold -> 0.01 s warm across processes), so a restarted
        server re-warms from disk, not from the compiler.  Idempotent;
        returns the number of fresh compiles."""
        import jax

        row_shape = tuple(int(d) for d in row_shape)
        row_dtype = np.dtype(row_dtype)
        with self._lock:
            if self._row_shape is None:
                self._row_shape, self._row_dtype = row_shape, row_dtype
            elif (row_shape != self._row_shape
                  or row_dtype != self._row_dtype):
                raise ValueError(
                    f"engine is warmed for rows {self._row_shape} "
                    f"{self._row_dtype}, not {row_shape} {row_dtype}")
        fresh = 0
        global _PIN_DEPTH
        from bigdl_tpu import tensor as bt
        from bigdl_tpu.serve import xcache
        prev = bt.policy()
        if self._policy is not None:
            with _PIN_LOCK:
                _PIN_DEPTH += 1
            bt.set_policy(self._policy)
        try:
            # record the policy the traces below bake in; submit()
            # compares against it so a later process-policy flip fails
            # fast instead of serving stale-precision executables.
            # Recorded ONLY by the warmup that starts populating the
            # ladder: a re-warmup that compiles nothing must not adopt
            # a drifted key (the existing executables keep their old
            # precision — re-recording would silently defeat the
            # guard), and compiling MORE buckets under a drifted key
            # would mix precisions within one engine — refuse both.
            cur_key = xcache._policy_key()
            with self._lock:
                have = bool(self._executables)
            if not have:
                with self._lock:
                    self._warm_policy_obj = bt.policy()
                    self._warm_policy_key = cur_key
            elif (self._policy is None
                    and cur_key != self._warm_policy_key):
                raise DTypePolicyDriftError(
                    f"cannot re-warm engine {self.name!r} under a "
                    f"drifted dtype policy: its executables were "
                    f"traced under (param/compute/output)="
                    f"{self._warm_policy_key}, the process policy is "
                    f"now {cur_key}.  Restore the policy or build a "
                    f"fresh engine.")
            params, state = self._weights
            for b in self.buckets:
                if b in self._executables:
                    continue
                spec = jax.ShapeDtypeStruct((b,) + row_shape, row_dtype)
                t0 = time.perf_counter()
                # resolve through the SHARED executable cache: another
                # engine over the same architecture, or a validator pass
                # at this batch shape, already paid this compile
                exe, built = xcache.get().get_or_compile(
                    self._fwd.jitted, self._fwd.fn_key,
                    (params, state, spec))
                dt = time.perf_counter() - t0
                with self._lock:
                    self._executables[b] = exe
                self._m_compiles.inc()
                fresh += 1
                logger.info("serve warmup: bucket %d %s in %.3fs", b,
                            "compiled" if built else "cache hit", dt)
        finally:
            if self._policy is not None:
                bt.set_policy(prev)
                with _PIN_LOCK:
                    _PIN_DEPTH -= 1
        return fresh

    def refresh(self):
        """Re-capture (and re-pin) the model's CURRENT params/state.

        The engine freezes weights at construction — training the model
        afterwards does NOT change what is served until this is called.
        Shapes/dtypes must be unchanged, so the per-bucket executables
        (which take params as arguments, not constants) are reused:
        refresh never recompiles.  Implemented as stage+commit, so it is
        atomic against concurrent submits (no future ever observes new
        params paired with old state)."""
        self.stage_weights(self.model.params(), self.model.state())
        self.commit_weights()
        return self

    # -- versioned hot swap (serve/cluster.py rollout protocol) -------------
    def stage_weights(self, params, state, version: int | None = None):
        """Phase 1 of a rollout: pin a new (params, state) pair to device
        WITHOUT serving it.  Serving continues on the committed weights;
        a staged pair costs HBM but no latency.  Shapes must match the
        warmed executables (params are executable ARGUMENTS).  On a
        quantized engine the incoming FULL-PRECISION tree is quantized
        here with the capture recipe, so rollouts ship fp weights and
        every replica applies its own precision."""
        import jax
        params = self._capture(params)
        cur = self._weights[0]
        if jax.tree_util.tree_structure(params) != \
                jax.tree_util.tree_structure(cur):
            raise ValueError("staged params tree does not match the "
                             "serving model's structure")
        # leaf shapes/dtypes must match too: the warmed executables take
        # params as ARGUMENTS at fixed avals, so a wrong-width stage
        # that committed would fail EVERY later batch instead of this
        # rollout (defeating the converge-back-on-failure protocol)
        def _dt(leaf):
            return np.dtype(getattr(leaf, "dtype", type(leaf)))

        for new, old in zip(jax.tree_util.tree_leaves(params),
                            jax.tree_util.tree_leaves(cur)):
            if np.shape(new) != np.shape(old) or _dt(new) != _dt(old):
                raise ValueError(
                    f"staged param leaf {np.shape(new)} {_dt(new)} does "
                    f"not match the served {np.shape(old)} {_dt(old)}")
        staged = (jax.device_put(params), jax.device_put(state))
        with self._lock:
            if version is None:
                version = self.weights_version + 1
            # note: version may be LOWER than the serving version — a
            # rollback-by-version rollout intentionally serves an older
            # store entry; only the WeightStore numbering is monotonic
            self._staged = (int(version), staged)
        # a staged pair costs HBM but no latency — exactly what the
        # ledger's tenant breakdown exists to make visible
        from bigdl_tpu.obs import ledger as obs_ledger
        obs_ledger.note_tenant("staged_weights",
                               obs_ledger.tree_nbytes(staged),
                               engine=self.name)
        return self

    def _clear_staged_tenant(self):
        from bigdl_tpu.obs import ledger as obs_ledger
        obs_ledger.note_tenant("staged_weights", 0, engine=self.name)

    def commit_weights(self) -> int:
        """Phase 2: atomically flip serving to the staged weights.  The
        swap is one tuple assignment under the lock — in-flight batches
        finish on the version they captured; every batch assembled after
        this call serves the new version.  Returns the new version."""
        with self._lock:
            if self._staged is None:
                raise RuntimeError("commit_weights without stage_weights")
            version, staged = self._staged
            self._prev_weights = (self.weights_version, self._weights)
            self._weights = staged
            self.weights_version = version
            self._staged = None
        self._clear_staged_tenant()
        self._m_version.set(version)
        self._emit("weights_commit", version=version)
        return version

    def rollback_weights(self):
        """Drop a staged-but-uncommitted pair (rollout aborted before
        the flip).  No-op when nothing is staged."""
        with self._lock:
            self._staged = None
        self._clear_staged_tenant()
        return self

    def revert_weights(self) -> int:
        """Undo the LAST commit (one-deep history): flip back to the
        previously served pair.  The rollout coordinator uses this when
        a peer replica fails mid-commit, so the fleet converges back to
        one version with zero dropped futures."""
        with self._lock:
            if self._prev_weights is None:
                raise RuntimeError("revert_weights without a prior commit")
            version, weights = self._prev_weights
            self._weights = weights
            self.weights_version = version
            self._prev_weights = None
        self._m_version.set(version)
        self._emit("weights_revert", version=version)
        return version

    # -- submit side --------------------------------------------------------
    def submit(self, x, trace=None) -> Future:
        """Queue one row (shape = model input WITHOUT the batch dim);
        returns a future resolving to that row's output array.
        ``trace`` (an ``obs.trace.Trace``) rides the request and is
        stamped by the H2D and compute stages — the router passes one
        for sampled requests.

        A request whose payload is non-finite fails its OWN future with
        :class:`PoisonedRequestError` (the rest of its micro-batch is
        served) — stricter than the pre-engine Predictor loop, which
        forwarded NaN/Inf rows to the model silently.

        Raises :class:`DTypePolicyDriftError` when the process dtype
        policy no longer matches the one the warmed executables were
        traced under (engines constructed with an explicit ``policy``
        pin their own and are immune to process drift)."""
        self._check_policy_drift()
        req = _Request(np.asarray(x), trace=trace)
        # closed-check and enqueue under the lock: close() flips _closed
        # under the same lock, so a request can never slip into the
        # queue after close()'s final leftover drain (its future would
        # hang forever)
        shed = False
        with self._lock:
            if self._closed:
                raise RuntimeError("ServeEngine is closed")
            depth = self._queue.qsize() + 1
            if self.max_queue is not None and depth > self.max_queue:
                # admission shed: fail fast instead of queuing past any
                # deadline; the future fails, the pipeline never sees it
                self._m_req["shed"].inc()
                shed = True
            else:
                self._m_req["accepted"].inc()
                self._inflight += 1
                self._m_inflight.set(self._inflight)
                self._m_qdepth.set(depth)
                if depth > self._max_queue_depth:
                    self._max_queue_depth = depth
                    self._m_qmax.set(depth)
                self._queue.put(req)   # unbounded put: never blocks
        if shed:
            self._emit("shed", queue_depth=self.max_queue)
            req.future.set_exception(SheddedError(
                f"engine queue full ({self.max_queue} requests)"))
        return req.future

    def _check_policy_drift(self):
        """Fail fast when the ambient dtype policy drifted since warmup
        (the docstring caveat made loud).  Engines with an explicit
        ``policy`` re-pin it around every trace, so only ambient-policy
        engines can drift.  Identity fast path first — the hot submit
        path pays one ``is`` check."""
        if self._policy is not None or self._warm_policy_obj is None:
            return
        if _PIN_DEPTH:
            # a sibling engine's pinned-policy warmup holds the process
            # policy swapped for the duration of its compilation; that
            # transient is trace-time state, not a drift of THIS
            # engine's ambient policy — it restores on exit
            return
        from bigdl_tpu import tensor as bt
        cur = bt.policy()
        if cur is self._warm_policy_obj:
            return
        from bigdl_tpu.serve import xcache
        key = xcache._policy_key()
        if key == self._warm_policy_key:
            # same dtypes under a different policy object: executables
            # are still precision-correct — adopt the new identity
            self._warm_policy_obj = cur
            return
        raise DTypePolicyDriftError(
            f"dtype policy drifted since warmup: engine {self.name!r} "
            f"compiled its executables under "
            f"(param/compute/output)={self._warm_policy_key} but the "
            f"process policy is now {key}.  Restore the policy, pin one "
            f"with ServeEngine(policy=...), or build a fresh engine "
            f"under the new policy.")

    def submit_many(self, rows) -> list:
        """Queue an iterable of rows; returns their futures in order."""
        return [self.submit(r) for r in rows]

    def predict(self, features) -> np.ndarray:
        """Synchronous convenience: submit every row of ``features``
        (n, ...) and return the stacked outputs (n, ...)."""
        futs = self.submit_many(np.asarray(features))
        return np.stack([f.result() for f in futs])

    # -- pipeline stages ----------------------------------------------------
    def _assemble_loop(self):
        while True:
            try:
                first = self._queue.get(timeout=0.05)
            except queue.Empty:
                continue
            if isinstance(first, _End):
                self._h2d_q.put(_END)
                return
            reqs = [first]
            deadline = time.perf_counter() + self.max_wait_s
            while len(reqs) < self.max_batch:
                try:
                    # drain whatever is already queued without paying a
                    # condition-variable wakeup per row (measured ~ms
                    # each under load); the timed wait is only for the
                    # deadline tail
                    nxt = self._queue.get_nowait()
                except queue.Empty:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    try:
                        nxt = self._queue.get(timeout=remaining)
                    except queue.Empty:
                        break
                if isinstance(nxt, _End):
                    # flush what we have, then propagate shutdown
                    self._dispatch(reqs)
                    self._h2d_q.put(_END)
                    return
                reqs.append(nxt)
            self._dispatch(reqs)

    def _dispatch(self, reqs):
        """Validate rows, pad to the bucket, hand to the H2D stage.
        Never raises: a bad batch fails its own futures, the batcher
        thread lives on."""
        good = []
        for r in reqs:
            err = self._vet(r.x)
            if err is None:
                good.append(r)
            else:
                self._fail([r], err)
        if not good:
            return
        try:
            bucket = bucketing.bucket_for(len(good), self.max_batch)
            xs, n = bucketing.pad_rows(np.stack([r.x for r in good]),
                                       bucket)
            # finiteness is vetted on the STACKED batch (one fused
            # reduction, ~5x cheaper than per-row on the hot thread);
            # only a failing batch pays the per-row scan to isolate and
            # fail the poisoned rows, then the clean rest re-dispatches
            if (np.issubdtype(xs.dtype, np.floating)
                    and not np.all(np.isfinite(xs))):
                clean = []
                for r in good:
                    if np.all(np.isfinite(r.x)):
                        clean.append(r)
                    else:
                        self._fail([r], PoisonedRequestError(
                            "request contains non-finite values"))
                if not clean:
                    return
                bucket = bucketing.bucket_for(len(clean), self.max_batch)
                xs, n = bucketing.pad_rows(
                    np.stack([r.x for r in clean]), bucket)
                good = clean
        except BaseException as e:
            self._fail(good, e)
            return
        self._m_bucket[bucket].inc()
        self._m_qdepth.set(self._queue.qsize())
        self._h2d_q.put((good, xs, bucket, n))

    def _vet(self, x):
        """Admission check for one row: shape against the warmed spec.
        Returns an exception to fail the row's future with, or None.
        (Finiteness is checked batch-level in ``_dispatch``.)"""
        if self._row_shape is not None and tuple(x.shape) != self._row_shape:
            return ValueError(
                f"row shape {tuple(x.shape)} != engine shape "
                f"{self._row_shape}")
        return None

    def _h2d_loop(self):
        import jax
        while True:
            item = self._h2d_q.get()
            if isinstance(item, _End):
                self._exec_q.put(_END)
                return
            reqs, xs, bucket, n = item
            try:
                self._chaos_h2d()
                xdev = jax.device_put(xs)
            except BaseException as e:
                self._fail(reqs, e)
                continue
            ts = time.perf_counter()
            for r in reqs:
                if r.trace is not None:
                    r.trace.stamp("h2d", ts)
            self._exec_q.put((reqs, xdev, bucket, n))

    def _chaos_h2d(self):
        from bigdl_tpu.resilience import faults
        inj = faults.get()
        if inj is not None and inj.armed("serve_h2d"):
            if inj.fires("serve_h2d"):
                raise OSError("injected serve_h2d transfer failure")

    def _compute_loop(self):
        while True:
            item = self._exec_q.get()
            if isinstance(item, _End):
                return
            reqs, xdev, bucket, n = item
            try:
                exe = self._executables.get(bucket)
                if exe is None:
                    # first traffic before an explicit warmup: compile
                    # the whole ladder NOW so this is the last cold stop
                    self.warmup(tuple(xdev.shape[1:]), xdev.dtype)
                    exe = self._executables[bucket]
                # ONE read of the (params, state) tuple: a concurrent
                # refresh/commit swaps the whole pair atomically, so a
                # batch always serves a consistent weight version
                params, state = self._weights
                out = np.asarray(exe(params, state, xdev))
            except BaseException as e:
                self._fail(reqs, e)
                continue
            out = bucketing.trim(out, n)
            done = time.perf_counter()
            with self._lock:
                # completed inc'd under the SAME lock as the inflight
                # decrement so stats() never sees the transient where
                # completed+failed+inflight != accepted
                self._inflight -= len(reqs)
                self._m_inflight.set(self._inflight)
                self._m_batches.inc()
                self._m_req["completed"].inc(len(reqs))
            for r in reqs:
                self._m_latency.observe(done - r.t_submit)
                if r.trace is not None:
                    # stamped BEFORE set_result: the router's done
                    # callback runs on this thread and stamps complete
                    # after, keeping the hop chain monotone
                    r.trace.stamp("compute", done)
                    # the version this batch actually served — the
                    # replay tool's weight pin (host-only, traced-only)
                    from bigdl_tpu.obs import recorder as obs_recorder
                    obs_recorder.note(r.trace.trace_id,
                                      weights_version=self.weights_version,
                                      engine=self.name)
            for i, r in enumerate(reqs):
                r.future.set_result(out[i])

    def _fail(self, reqs, exc):
        with self._lock:
            self._inflight -= len(reqs)
            self._m_inflight.set(self._inflight)
            self._m_req["failed"].inc(len(reqs))
        self._emit("error", error=f"{type(exc).__name__}: {exc}",
                   requests=len(reqs))
        for r in reqs:
            if not r.future.done():
                r.future.set_exception(exc)

    # -- telemetry ----------------------------------------------------------
    def _emit(self, kind: str, **fields):
        from bigdl_tpu.obs import events
        events.emit("serve", kind=kind, **fields)

    def latency_quantiles(self, qs=(50, 95, 99)) -> dict:
        """Percentiles from the registry's fixed-bucket histogram —
        quantized to the pinned bounds (obs/metrics.LATENCY_BUCKETS),
        which is exactly what makes them mergeable across replicas."""
        from bigdl_tpu.obs import metrics as obs_metrics
        counts = self._m_latency.counts()
        bounds = self._m_latency.bounds
        return {f"p{int(q)}": obs_metrics.quantile(bounds, counts, q)
                for q in qs}

    def inflight(self) -> int:
        """Requests accepted but not yet resolved (the router's
        least-loaded signal)."""
        with self._lock:
            return self._inflight

    def stats(self) -> dict:
        """Snapshot: latency percentiles (seconds), queue depth, bucket
        hit counts, compile count, and the four monotonic admission
        counters (``accepted``/``shed``/``completed``/``failed``) — a
        thin VIEW over this engine's series in the process metrics
        registry (``obs/metrics.py``); the registry is the source of
        truth the fleet merge and the Prometheus exporter read.

        Counter semantics: monotonic from engine construction, NEVER
        reset — rate-difference two snapshots to get a rate (the router
        does exactly that).  ``completed + failed + inflight ==
        accepted`` at every instant; shed requests appear only in
        ``shed``.  ``served``/``errors`` are the pre-router aliases of
        completed/failed and stay for compatibility."""
        with self._lock:
            # the admission counters are read under the same lock their
            # paired inflight updates happen under, so the snapshot
            # satisfies completed+failed+inflight == accepted exactly
            inflight = self._inflight
            queue_depth = self._queue.qsize()
            max_depth = self._max_queue_depth
            version = self.weights_version
            accepted, shed = self.accepted, self.shed
            completed, failed = self.served, self.errors
        out = {
            "accepted": accepted,
            "shed": shed,
            "completed": completed,
            "failed": failed,
            "inflight": inflight,
            "served": completed,
            "batches": self.batches,
            "errors": failed,
            "compiles": self.compiles,
            "weights_version": version,
            "quant": self.quant,
            "queue_depth": queue_depth,
            "max_queue_depth": max_depth,
            "bucket_hits": {b: int(c.value)
                            for b, c in self._m_bucket.items()},
            "buckets": list(self.buckets),
        }
        out.update(self.latency_quantiles())
        return out

    # -- lifecycle ----------------------------------------------------------
    def drain(self, timeout: float = 30.0):
        """Block until every submitted request has resolved (the batcher
        deadline flushes partial batches, so this terminates)."""
        t0 = time.perf_counter()
        while True:
            with self._lock:
                if self._inflight == 0:
                    return self
            if time.perf_counter() - t0 > timeout:
                raise TimeoutError("serve drain timed out")
            time.sleep(0.002)

    def close(self, drain: bool = True):
        """Stop the engine.  ``drain=True`` (default) serves everything
        already queued first; ``drain=False`` fails pending futures."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        if not drain:
            pending = []
            try:
                while True:
                    r = self._queue.get_nowait()
                    if not isinstance(r, _End):
                        pending.append(r)
            except queue.Empty:
                pass
            if pending:
                self._fail(pending, RuntimeError("ServeEngine closed"))
        self._queue.put(_END)
        self._assembler.join(timeout=30.0)
        self._transfer.join(timeout=30.0)
        self._compute.join(timeout=30.0)
        # a submit racing close() may have queued behind the shutdown
        # sentinel; nothing will serve it now — fail it, don't hang it
        leftovers = []
        try:
            while True:
                r = self._queue.get_nowait()
                if not isinstance(r, _End):
                    leftovers.append(r)
        except queue.Empty:
            pass
        if leftovers:
            self._fail(leftovers, RuntimeError("ServeEngine closed"))
        self._emit("stop", **{k: v for k, v in self.stats().items()
                              if not isinstance(v, (dict, list))})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
