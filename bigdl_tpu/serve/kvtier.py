"""Host-RAM KV tier: evicted prefix pages spill D2H instead of dying
(docs/serving.md "Disaggregated fleet").

The prefix cache (``serve/prefix.py``) lives entirely in the paged
device pool, so its capacity is whatever HBM the live requests leave
over — under allocation pressure the LRU sweep simply frees pages and
their K/V is recomputed from scratch on the next matching request.
Host RAM is roughly an order of magnitude larger than HBM; this module
turns that into a second cache tier:

- **spill** — when the prefix cache evicts a page (its ``on_evict``
  hook), the decoder takes cheap ON-DEVICE slices of the page across
  every cache array and enqueues them here; one background writer
  thread materializes the device→host copy — the async-checkpoint
  writer's pattern (``resilience/checkpoint.py``), so eviction (which
  happens on the admission path) never pays a blocking D2H.  The
  slices are functional jax arrays snapshotted at eviction time, so a
  later reuse of the physical page can never corrupt what was spilled.
- **re-admit** — an admission whose chain walk runs past the device
  cache consults the tier by the SAME chain-hash keys; a hit allocates
  a fresh pool page, writes the host copy back H2D through the
  decoder's compiled re-admit program, and registers the page in the
  prefix cache again — the request gets a prefix HIT that would
  otherwise have been a cold prefill.
- **budget** — entries are LRU inside ``BIGDL_SERVE_KV_HOST_MB``
  (default 0 = tier off); insertions past the budget drop the oldest
  entries (``kv_host_dropped_pages_total``).

Quantized pools need no cooperation: a page's payload is the tuple of
per-array slices — ``(k, v)`` float32 or ``(k, v, kscale, vscale)``
int8+scales — so a spilled quantized page re-admits bit-identical
(the spill/re-admit parity contract ``tests/test_fleet.py`` pins).

Telemetry (mergeable registry, ``obs/metrics.py``, labels
``tier=<name>``): ``kv_host_{spilled,readmitted,dropped}_pages_total``
counters, the ``kv_host_bytes`` / ``kv_host_pages`` gauges, and
spill/re-admit latency histograms on the pinned ``LATENCY_BUCKETS``
(spill latency = the writer thread's materialize+insert; re-admit
latency = the H2D program dispatch on the admission path).
"""
from __future__ import annotations

import itertools
import logging
import os
import queue
import threading
import time
import weakref
from collections import OrderedDict

import numpy as np

logger = logging.getLogger("bigdl_tpu.serve")

ENV_HOST_MB = "BIGDL_SERVE_KV_HOST_MB"

_TIER_SEQ = itertools.count()


def host_mb_default() -> int:
    """The env-configured host-tier budget in MiB (0 = tier off)."""
    try:
        return max(0, int(os.environ.get(ENV_HOST_MB, "0")))
    except ValueError:
        return 0


class HostKVTier:
    """Chain-hash → host page payload store under a byte budget.

    One writer thread owns every D2H materialization; ``spill`` is a
    cheap enqueue from the eviction path.  ``lookup`` is
    NON-destructive — a re-admitted page stays in the tier (LRU
    refreshed) so a second eviction of the same chain refreshes rather
    than re-copies; only budget pressure drops entries.
    """

    def __init__(self, budget_mb: int | None = None,
                 name: str | None = None):
        self.budget_bytes = (host_mb_default() if budget_mb is None
                             else max(0, int(budget_mb))) * (1 << 20)
        self.name = name or f"kvtier{next(_TIER_SEQ)}"
        self._lock = threading.Lock()
        self._entries: "OrderedDict[bytes, tuple]" = OrderedDict()
        self._entry_bytes: dict = {}
        self._bytes = 0
        # writer thread: the checkpoint-writer pattern (outstanding
        # counter under a condvar so flush() cannot return while a
        # spill is still materializing)
        self._q: "queue.Queue" = queue.Queue()
        self._cond = threading.Condition()
        self._outstanding = 0
        self._stop = False

        from bigdl_tpu.obs import metrics as obs_metrics
        reg = obs_metrics.get()
        lab = {"tier": self.name}
        self._m_spilled = reg.counter(
            "kv_host_spilled_pages_total",
            "prefix pages spilled D2H into the host tier", **lab)
        self._m_readmitted = reg.counter(
            "kv_host_readmitted_pages_total",
            "host-tier pages re-admitted H2D as prefix hits", **lab)
        self._m_dropped = reg.counter(
            "kv_host_dropped_pages_total",
            "host-tier pages dropped under the byte budget", **lab)
        self._m_bytes = reg.gauge(
            "kv_host_bytes", "host-tier resident bytes", **lab)
        self._m_pages = reg.gauge(
            "kv_host_pages", "host-tier resident pages", **lab)
        self._m_spill_lat = reg.histogram(
            "kv_host_spill_seconds",
            "per-page D2H materialize latency on the writer thread",
            **lab)
        self._m_readmit_lat = reg.histogram(
            "kv_host_readmit_seconds",
            "per-page H2D re-admit dispatch latency", **lab)

        # tiers are uniquely named and often short-lived (one per
        # decoder under BIGDL_SERVE_KV_HOST_MB) — drop their series at
        # close/GC so the process registry cannot grow without bound
        # (the ContinuousDecoder._drop_series precedent); the held
        # instrument handles keep working for stats() after the drop
        self._drop_series = weakref.finalize(
            self, reg.drop_series, tier=self.name)

        self._thread = threading.Thread(
            target=self._drain, daemon=True,
            name=f"bigdl-serve-{self.name}")
        self._thread.start()

    # -- spill path (eviction side) -----------------------------------------
    def spill(self, key: bytes, device_slices):
        """Enqueue one evicted page: ``device_slices`` is the tuple of
        per-cache-array page slices (``pool[:, pid]`` — functional jax
        arrays, content frozen at eviction time).  Returns immediately;
        the writer thread pays the D2H."""
        with self._cond:
            if self._stop:
                return
            self._outstanding += 1
        self._q.put((key, tuple(device_slices)))

    def _drain(self):
        while True:
            try:
                key, slices = self._q.get(timeout=0.2)
            except queue.Empty:
                if self._stop:
                    return
                continue
            t0 = time.perf_counter()
            try:
                payload = tuple(np.asarray(s) for s in slices)
                self._insert(key, payload)
                self._m_spilled.inc()
                self._m_spill_lat.observe(time.perf_counter() - t0)
            except Exception as e:  # pragma: no cover - telemetry path
                logger.warning("host KV tier spill failed: %s", e)
            finally:
                with self._cond:
                    self._outstanding -= 1
                    self._cond.notify_all()

    def _insert(self, key, payload):
        nbytes = sum(int(a.nbytes) for a in payload)
        with self._lock:
            old = self._entry_bytes.pop(key, None)
            if old is not None:
                del self._entries[key]
                self._bytes -= old
            if nbytes > self.budget_bytes:
                # a single page over budget can never be resident
                self._m_dropped.inc()
                self._refresh_gauges()
                return
            self._entries[key] = payload
            self._entry_bytes[key] = nbytes
            self._bytes += nbytes
            while self._bytes > self.budget_bytes and self._entries:
                k, _ = self._entries.popitem(last=False)
                self._bytes -= self._entry_bytes.pop(k)
                self._m_dropped.inc()
            self._refresh_gauges()

    def _refresh_gauges(self):
        self._m_bytes.set(self._bytes)
        self._m_pages.set(len(self._entries))

    # -- re-admit path (admission side) -------------------------------------
    def lookup(self, key: bytes):
        """The host payload for ``key`` (LRU-refreshed) or ``None``.
        Non-destructive — the entry survives until budget pressure."""
        with self._lock:
            payload = self._entries.get(key)
            if payload is not None:
                self._entries.move_to_end(key)
            return payload

    def note_readmit(self, n_pages: int, seconds: float):
        """Count a completed H2D re-admit (the decoder calls this after
        dispatching its re-admit program)."""
        self._m_readmitted.inc(n_pages)
        self._m_readmit_lat.observe(max(0.0, seconds))

    # -- lifecycle ----------------------------------------------------------
    def flush(self, timeout: float = 30.0) -> bool:
        """Block until every enqueued spill is resident (tests, close).
        Returns False on timeout."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._outstanding > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cond.wait(timeout=left)
        return True

    def stats(self) -> dict:
        with self._lock:
            pages, nbytes = len(self._entries), self._bytes
        return {"name": self.name, "pages": pages, "bytes": nbytes,
                "budget_bytes": self.budget_bytes,
                "spilled": int(self._m_spilled.value),
                "readmitted": int(self._m_readmitted.value),
                "dropped": int(self._m_dropped.value)}

    def close(self, timeout: float = 30.0):
        ok = self.flush(timeout=timeout)
        with self._cond:
            self._stop = True
        # join the writer: an orphaned daemon thread running into
        # interpreter teardown can abort inside the jax runtime
        self._thread.join(timeout=timeout)
        self._drop_series()
        return ok
