"""Hardened frame codec shared by every replica transport
(docs/serving.md "Cross-host fleet").

One frame = one pickled message.  The original stdio protocol was a
bare ``u64 length + pickle`` pair, which was fine between a parent and
the child IT spawned, but the same frames now also cross TCP between
hosts (``serve/remote.py`` / ``tools/replica_agent.py``), where the
reader must assume the peer can be wrong, stale, or corrupt:

- a **magic + protocol-version prefix** rejects a desynchronized or
  foreign byte stream before anything reaches ``pickle.loads``;
- a **max-frame-size bound** (``BIGDL_SERVE_MAX_FRAME_MB``) stops a
  corrupt length word from hanging the reader on a multi-terabyte
  ``read`` (the default is generous — ``stage`` frames legitimately
  carry full model params);
- a **per-frame CRC32** catches payload corruption, so garbage bytes
  fail loudly with the offending CRC instead of being fed to
  ``pickle.loads``;
- **truncation is typed**: a stream that dies mid-frame raises
  :class:`FrameProtocolError` with the got/want byte counts, while a
  clean EOF at a frame boundary returns ``None`` (the normal
  worker-death signal the reader loops already handle).

**What this codec does NOT defend against: a hostile peer.**  CRC32
is a checksum, not a MAC — anyone who can reach the socket can craft
a frame with valid magic/version/CRC around an arbitrary pickle
payload, and unpickling attacker bytes is remote code execution.
Keeping attackers away from ``pickle.loads`` is the transport layer's
job, not the codec's: pickled frames are only ever exchanged between
a parent and the subprocess it spawned (stdio), or between TCP peers
AFTER the replica agent's authentication handshake.  That handshake
is deliberately pickle-free — :func:`read_hello` /
:func:`read_welcome` below parse a fixed binary layout with bounded
fields, so an unauthenticated peer's bytes are never deserialized —
and the agent binds loopback by default, refusing a non-loopback
bind with an empty token (``tools/replica_agent.py``).

Wire layout (big-endian, 16-byte header)::

    +----+----+-------+---------+------------+---------------+
    | 'B'| 'F'| ver u8| flags u8| crc32  u32 | length u64    | payload...
    +----+----+-------+---------+------------+---------------+

Both transports — the stdio pipes of :class:`ProcessReplica` and the
TCP sockets of :class:`RemoteReplica` — speak exactly this framing;
``serve/cluster.py`` re-exports :func:`read_frame`/:func:`write_frame`
under its historical ``_read_frame``/``_write_frame`` names.
"""
from __future__ import annotations

import os
import pickle
import struct
import zlib

MAGIC = b"BF"
PROTOCOL_VERSION = 1

#: magic(2) + version(1) + flags(1) + crc32(4) + length(8)
_HDR = struct.Struct(">2sBBIQ")

ENV_MAX_FRAME_MB = "BIGDL_SERVE_MAX_FRAME_MB"
#: default bound: big enough for a stage frame shipping full model
#: params, small enough that a corrupt length word cannot wedge the
#: reader allocating terabytes
DEFAULT_MAX_FRAME_MB = 4096


class FrameProtocolError(RuntimeError):
    """A frame failed validation (bad magic, version mismatch, length
    over the bound, truncation mid-frame, or CRC mismatch).  Reader
    loops treat it as peer death/desync — the payload is NEVER handed
    to ``pickle.loads``."""


def max_frame_bytes() -> int:
    """The frame-size bound (bytes) from ``BIGDL_SERVE_MAX_FRAME_MB``."""
    try:
        mb = float(os.environ.get(ENV_MAX_FRAME_MB, "") or
                   DEFAULT_MAX_FRAME_MB)
    except ValueError:
        mb = DEFAULT_MAX_FRAME_MB
    return max(1, int(mb * (1 << 20)))


def write_frame(fh, obj, lock=None, max_bytes: int | None = None):
    """Serialize ``obj`` as one frame onto ``fh`` (atomic under
    ``lock`` when given).  An over-bound payload raises
    :class:`FrameProtocolError` BEFORE any byte is written, so the
    stream stays frame-aligned and only the offending message fails."""
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    bound = max_frame_bytes() if max_bytes is None else int(max_bytes)
    if len(payload) > bound:
        raise FrameProtocolError(
            f"refusing to write a {len(payload)}-byte frame: over the "
            f"{bound}-byte bound ({ENV_MAX_FRAME_MB} raises it)")
    header = _HDR.pack(MAGIC, PROTOCOL_VERSION, 0,
                       zlib.crc32(payload), len(payload))
    if lock is not None:
        lock.acquire()
    try:
        fh.write(header + payload)
        fh.flush()
    finally:
        if lock is not None:
            lock.release()


def _read_exact(fh, n: int, what: str):
    """Read exactly ``n`` bytes.  Zero bytes at the start is a clean
    EOF (returns None); anything in between is a typed truncation."""
    buf = b""
    while len(buf) < n:
        chunk = fh.read(n - len(buf))
        if not chunk:
            if not buf:
                return None
            raise FrameProtocolError(
                f"truncated frame {what}: got {len(buf)} of {n} bytes "
                f"before EOF")
        buf += chunk
    return buf


def read_frame(fh, max_bytes: int | None = None):
    """Read and validate one frame from ``fh``.  Returns the decoded
    object, or ``None`` on a clean EOF at a frame boundary.  Any
    malformation — bad magic, version mismatch, over-bound length,
    truncation, CRC mismatch — raises :class:`FrameProtocolError`
    naming the offending value."""
    header = _read_exact(fh, _HDR.size, "header")
    if header is None:
        return None
    magic, version, _flags, crc, n = _HDR.unpack(header)
    if magic != MAGIC:
        raise FrameProtocolError(
            f"bad frame magic {magic!r} (want {MAGIC!r}): stream is "
            f"desynchronized or not a bigdl frame stream")
    if version != PROTOCOL_VERSION:
        raise FrameProtocolError(
            f"frame protocol version {version} does not match this "
            f"reader (v{PROTOCOL_VERSION}); upgrade the older peer")
    bound = max_frame_bytes() if max_bytes is None else int(max_bytes)
    if n > bound:
        raise FrameProtocolError(
            f"frame length {n} exceeds the {bound}-byte bound "
            f"({ENV_MAX_FRAME_MB} raises it); likely a corrupt length "
            f"word")
    payload = _read_exact(fh, n, "payload")
    if payload is None:
        raise FrameProtocolError(
            f"truncated frame payload: got 0 of {n} bytes before EOF")
    actual = zlib.crc32(payload)
    if actual != crc:
        raise FrameProtocolError(
            f"frame CRC mismatch over {n} bytes: header says "
            f"0x{crc:08x}, payload hashes to 0x{actual:08x}")
    return pickle.loads(payload)


# ---------------------------------------------------------------------------
# handshake codec: fixed layout, NO pickle
# ---------------------------------------------------------------------------
#
# The TCP handshake runs before either peer has proven anything, so
# neither side may unpickle the other's bytes yet (see the module
# docstring: CRC32 is not a MAC).  The hello and welcome are therefore
# fixed binary layouts with tightly bounded string fields — parseable
# with struct + utf-8 decode only, every violation a typed
# FrameProtocolError.
#
#   hello   (client → agent):
#     'B' 'H' | ver u8 | flags u8 | acked u64 | token_len u16 |
#     session_len u16 | name_len u16 | token | session | name
#   welcome (agent → client):
#     'B' 'W' | ver u8 | flags u8 | epoch u64 | pid u64 |
#     session_len u16 | error_len u16 | session | error

HELLO_MAGIC = b"BH"
WELCOME_MAGIC = b"BW"
_HELLO_HDR = struct.Struct(">2sBBQHHH")
_WELCOME_HDR = struct.Struct(">2sBBQQHH")

#: bound on each handshake string field — a real hello/welcome is tens
#: of bytes; anything bigger is garbage or an attack
HANDSHAKE_FIELD_MAX = 1024

_HELLO_HAS_SESSION = 0x01
_WELCOME_RESUMED = 0x01
_WELCOME_REFUSED = 0x02


def _handshake_field(value, what: str) -> bytes:
    data = ("" if value is None else str(value)).encode("utf-8")
    if len(data) > HANDSHAKE_FIELD_MAX:
        raise FrameProtocolError(
            f"handshake {what} is {len(data)} bytes (bound "
            f"{HANDSHAKE_FIELD_MAX})")
    return data


def _decode_field(data: bytes, what: str) -> str:
    try:
        return data.decode("utf-8")
    except UnicodeDecodeError as e:
        raise FrameProtocolError(
            f"undecodable handshake {what}: {e}") from None


def _read_handshake(fh, hdr, magic, what: str):
    """Common header read/validation for both handshake directions.
    Returns the unpacked header tuple (without magic/version), or
    ``None`` on a clean EOF."""
    raw = _read_exact(fh, hdr.size, f"{what} header")
    if raw is None:
        return None
    fields = hdr.unpack(raw)
    if fields[0] != magic:
        raise FrameProtocolError(
            f"bad {what} magic {fields[0]!r} (want {magic!r}): peer is "
            f"not speaking the bigdl handshake")
    if fields[1] != PROTOCOL_VERSION:
        raise FrameProtocolError(
            f"{what} protocol version {fields[1]} does not match this "
            f"reader (v{PROTOCOL_VERSION}); upgrade the older peer")
    return fields[2:]


def write_hello(fh, token="", session=None, acked: int = 0,
                name: str = ""):
    """Write the client→agent hello in the fixed pickle-free layout.
    ``session=None`` asks for a fresh session; a string re-attaches."""
    tok = _handshake_field(token, "token")
    ses = _handshake_field(session, "session id")
    nam = _handshake_field(name, "name")
    flags = _HELLO_HAS_SESSION if session is not None else 0
    fh.write(_HELLO_HDR.pack(HELLO_MAGIC, PROTOCOL_VERSION, flags,
                             int(acked), len(tok), len(ses), len(nam))
             + tok + ses + nam)
    fh.flush()


def read_hello(fh):
    """Parse a hello WITHOUT pickle.  Returns ``{"token", "session",
    "acked", "name"}`` (session ``None`` = fresh), ``None`` on clean
    EOF; any malformation raises :class:`FrameProtocolError`."""
    fields = _read_handshake(fh, _HELLO_HDR, HELLO_MAGIC, "hello")
    if fields is None:
        return None
    flags, acked, n_tok, n_ses, n_nam = fields
    for n, what in ((n_tok, "token"), (n_ses, "session id"),
                    (n_nam, "name")):
        if n > HANDSHAKE_FIELD_MAX:
            raise FrameProtocolError(
                f"hello {what} length {n} exceeds the "
                f"{HANDSHAKE_FIELD_MAX}-byte bound")
    body = _read_exact(fh, n_tok + n_ses + n_nam, "hello body")
    if body is None:
        raise FrameProtocolError("truncated hello: header without body")
    token = _decode_field(body[:n_tok], "token")
    session = _decode_field(body[n_tok:n_tok + n_ses], "session id")
    name = _decode_field(body[n_tok + n_ses:], "name")
    return {"token": token,
            "session": session if flags & _HELLO_HAS_SESSION else None,
            "acked": int(acked), "name": name}


def write_welcome(fh, session, epoch: int, resumed: bool, pid: int):
    """Write the agent→client session acceptance (pickle-free)."""
    ses = _handshake_field(session, "session id")
    flags = _WELCOME_RESUMED if resumed else 0
    fh.write(_WELCOME_HDR.pack(WELCOME_MAGIC, PROTOCOL_VERSION, flags,
                               int(epoch), int(pid), len(ses), 0) + ses)
    fh.flush()


def write_refusal(fh, error: str):
    """Write a typed agent→client handshake refusal (pickle-free)."""
    msg = str(error).encode("utf-8")[:HANDSHAKE_FIELD_MAX]
    # re-encode so a truncation cannot split a multibyte character
    msg = msg.decode("utf-8", errors="ignore").encode("utf-8")
    fh.write(_WELCOME_HDR.pack(WELCOME_MAGIC, PROTOCOL_VERSION,
                               _WELCOME_REFUSED, 0, 0, 0, len(msg))
             + msg)
    fh.flush()


def read_welcome(fh):
    """Parse a welcome/refusal WITHOUT pickle.  Returns
    ``{"op": "welcome", "session", "epoch", "resumed", "pid"}`` or
    ``{"op": "error", "error"}``; ``None`` on clean EOF; any
    malformation raises :class:`FrameProtocolError`."""
    fields = _read_handshake(fh, _WELCOME_HDR, WELCOME_MAGIC, "welcome")
    if fields is None:
        return None
    flags, epoch, pid, n_ses, n_err = fields
    for n, what in ((n_ses, "session id"), (n_err, "error")):
        if n > HANDSHAKE_FIELD_MAX:
            raise FrameProtocolError(
                f"welcome {what} length {n} exceeds the "
                f"{HANDSHAKE_FIELD_MAX}-byte bound")
    body = _read_exact(fh, n_ses + n_err, "welcome body") \
        if n_ses + n_err else b""
    if body is None:
        raise FrameProtocolError(
            "truncated welcome: header without body")
    if flags & _WELCOME_REFUSED:
        return {"op": "error",
                "error": _decode_field(body[n_ses:], "error")}
    return {"op": "welcome",
            "session": _decode_field(body[:n_ses], "session id"),
            "epoch": int(epoch), "resumed": bool(flags & _WELCOME_RESUMED),
            "pid": int(pid)}
