"""Hardened frame codec shared by every replica transport
(docs/serving.md "Cross-host fleet").

One frame = one pickled message.  The original stdio protocol was a
bare ``u64 length + pickle`` pair, which was fine between a parent and
the child IT spawned, but the same frames now also cross TCP between
hosts (``serve/remote.py`` / ``tools/replica_agent.py``), where the
reader must assume the peer can be wrong, stale, or hostile:

- a **magic + protocol-version prefix** rejects a desynchronized or
  foreign byte stream before anything reaches ``pickle.loads``;
- a **max-frame-size bound** (``BIGDL_SERVE_MAX_FRAME_MB``) stops a
  corrupt length word from hanging the reader on a multi-terabyte
  ``read`` (the default is generous — ``stage`` frames legitimately
  carry full model params);
- a **per-frame CRC32** catches payload corruption, so garbage bytes
  fail loudly with the offending CRC instead of being fed to
  ``pickle.loads`` (which would execute attacker-shaped opcodes);
- **truncation is typed**: a stream that dies mid-frame raises
  :class:`FrameProtocolError` with the got/want byte counts, while a
  clean EOF at a frame boundary returns ``None`` (the normal
  worker-death signal the reader loops already handle).

Wire layout (big-endian, 16-byte header)::

    +----+----+-------+---------+------------+---------------+
    | 'B'| 'F'| ver u8| flags u8| crc32  u32 | length u64    | payload...
    +----+----+-------+---------+------------+---------------+

Both transports — the stdio pipes of :class:`ProcessReplica` and the
TCP sockets of :class:`RemoteReplica` — speak exactly this framing;
``serve/cluster.py`` re-exports :func:`read_frame`/:func:`write_frame`
under its historical ``_read_frame``/``_write_frame`` names.
"""
from __future__ import annotations

import os
import pickle
import struct
import zlib

MAGIC = b"BF"
PROTOCOL_VERSION = 1

#: magic(2) + version(1) + flags(1) + crc32(4) + length(8)
_HDR = struct.Struct(">2sBBIQ")

ENV_MAX_FRAME_MB = "BIGDL_SERVE_MAX_FRAME_MB"
#: default bound: big enough for a stage frame shipping full model
#: params, small enough that a corrupt length word cannot wedge the
#: reader allocating terabytes
DEFAULT_MAX_FRAME_MB = 4096


class FrameProtocolError(RuntimeError):
    """A frame failed validation (bad magic, version mismatch, length
    over the bound, truncation mid-frame, or CRC mismatch).  Reader
    loops treat it as peer death/desync — the payload is NEVER handed
    to ``pickle.loads``."""


def max_frame_bytes() -> int:
    """The frame-size bound (bytes) from ``BIGDL_SERVE_MAX_FRAME_MB``."""
    try:
        mb = float(os.environ.get(ENV_MAX_FRAME_MB, "") or
                   DEFAULT_MAX_FRAME_MB)
    except ValueError:
        mb = DEFAULT_MAX_FRAME_MB
    return max(1, int(mb * (1 << 20)))


def write_frame(fh, obj, lock=None, max_bytes: int | None = None):
    """Serialize ``obj`` as one frame onto ``fh`` (atomic under
    ``lock`` when given).  An over-bound payload raises
    :class:`FrameProtocolError` BEFORE any byte is written, so the
    stream stays frame-aligned and only the offending message fails."""
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    bound = max_frame_bytes() if max_bytes is None else int(max_bytes)
    if len(payload) > bound:
        raise FrameProtocolError(
            f"refusing to write a {len(payload)}-byte frame: over the "
            f"{bound}-byte bound ({ENV_MAX_FRAME_MB} raises it)")
    header = _HDR.pack(MAGIC, PROTOCOL_VERSION, 0,
                       zlib.crc32(payload), len(payload))
    if lock is not None:
        lock.acquire()
    try:
        fh.write(header + payload)
        fh.flush()
    finally:
        if lock is not None:
            lock.release()


def _read_exact(fh, n: int, what: str):
    """Read exactly ``n`` bytes.  Zero bytes at the start is a clean
    EOF (returns None); anything in between is a typed truncation."""
    buf = b""
    while len(buf) < n:
        chunk = fh.read(n - len(buf))
        if not chunk:
            if not buf:
                return None
            raise FrameProtocolError(
                f"truncated frame {what}: got {len(buf)} of {n} bytes "
                f"before EOF")
        buf += chunk
    return buf


def read_frame(fh, max_bytes: int | None = None):
    """Read and validate one frame from ``fh``.  Returns the decoded
    object, or ``None`` on a clean EOF at a frame boundary.  Any
    malformation — bad magic, version mismatch, over-bound length,
    truncation, CRC mismatch — raises :class:`FrameProtocolError`
    naming the offending value."""
    header = _read_exact(fh, _HDR.size, "header")
    if header is None:
        return None
    magic, version, _flags, crc, n = _HDR.unpack(header)
    if magic != MAGIC:
        raise FrameProtocolError(
            f"bad frame magic {magic!r} (want {MAGIC!r}): stream is "
            f"desynchronized or not a bigdl frame stream")
    if version != PROTOCOL_VERSION:
        raise FrameProtocolError(
            f"frame protocol version {version} does not match this "
            f"reader (v{PROTOCOL_VERSION}); upgrade the older peer")
    bound = max_frame_bytes() if max_bytes is None else int(max_bytes)
    if n > bound:
        raise FrameProtocolError(
            f"frame length {n} exceeds the {bound}-byte bound "
            f"({ENV_MAX_FRAME_MB} raises it); likely a corrupt length "
            f"word")
    payload = _read_exact(fh, n, "payload")
    if payload is None:
        raise FrameProtocolError(
            f"truncated frame payload: got 0 of {n} bytes before EOF")
    actual = zlib.crc32(payload)
    if actual != crc:
        raise FrameProtocolError(
            f"frame CRC mismatch over {n} bytes: header says "
            f"0x{crc:08x}, payload hashes to 0x{actual:08x}")
    return pickle.loads(payload)
