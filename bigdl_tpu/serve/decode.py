"""Continuous-batching decode: a slot-based driver over the
``TransformerLM`` KV-cache step (docs/serving.md).

``models.transformer.lm_decode`` compiles one lock-step scan: every row
starts together, ends together, and a new request waits for the whole
batch to finish.  A serving decoder cannot run lock-step — requests
arrive whenever they arrive and finish at their own lengths.  This
driver keeps a fixed (B, n_pos) KV-cache slab on device and treats its
B rows as **slots**:

- each slot independently consumes its own seed and generates its own
  continuation (per-row positions — ``_lm_forward_one`` scatters the
  cache write and masks attention per row);
- requests are **admitted** into free slots and **retired** at step
  boundaries only, so the device sees one fixed-shape compiled step
  program for the engine's whole lifetime (slot index is a traced
  argument — admission never recompiles);
- the host syncs only every ``sync_interval`` steps (the
  ``BIGDL_OBS_TAPS_CADENCE``-style boundary, env ``BIGDL_SERVE_SYNC``):
  generated tokens feed back device-side, completion steps are known
  arithmetically on the host, and the generated-token slab is
  materialized once per boundary that retires anything — never per
  token.

Greedy decoding only (the serial oracle is ``lm_decode(greedy=True)``;
sampling needs per-slot key streams, which would change the draw order
vs the serial scan and break the bit-parity contract).

**Tensor-parallel serving** (``mesh=``): a model whose KV slab + weights
outgrow one chip's HBM serves by sharding the decode step over the
mesh's ``model`` axis (``parallel/mesh.hybrid_mesh``) with
``parallel/compat.shard_map`` — Megatron-style: attention heads and the
FFN hidden dim split across shards (wq/wk/wv columns + KV-cache head
dim; lin1 rows), each branch's output projection psum-merges once, and
everything else (embeddings, LayerNorms, the LM head) replicates.  The
per-head math is untouched, so TP decode is token-identical to the
single-device driver — the parity contract ``tests/test_serve_cluster.py``
asserts.  The step/admit/retire programs are warmed at construction
through the shared executable cache (``serve/xcache.py``), so admission
under TP stays compile-free exactly like the single-chip path.
"""
from __future__ import annotations

import itertools
import logging
import os
import weakref
from collections import deque
from concurrent.futures import Future

import numpy as np

logger = logging.getLogger("bigdl_tpu.serve")

_DECODER_SEQ = itertools.count()

ENV_SYNC = "BIGDL_SERVE_SYNC"
DEFAULT_SYNC = 8


def sync_interval_default() -> int:
    try:
        return max(1, int(os.environ.get(ENV_SYNC, DEFAULT_SYNC)))
    except ValueError:
        return DEFAULT_SYNC


def _tp_weight_specs(handles, ax: str):
    """PartitionSpec tree mirroring the decode weight pytree for
    Megatron head/hidden sharding over mesh axis ``ax``:

    - attention: wq/wk/wv split on their OUTPUT columns (head-major, so
      a shard holds whole heads) with the matching bias slices; wo
      splits on its input rows; bo replicates (added once, post-psum);
    - FFN: lin1 (hidden, d) splits hidden rows + bias, lin2 (d, hidden)
      splits hidden columns, its bias replicates;
    - embeddings, LayerNorms and the LM head replicate.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    def rep(tree):
        return jax.tree_util.tree_map(lambda _: P(), tree)

    attn = {"wq": P(None, ax), "wk": P(None, ax), "wv": P(None, ax),
            "bq": P(ax), "bk": P(ax), "bv": P(ax),
            "wo": P(ax, None), "bo": P()}
    blocks = []
    for (ln1, m, ln2, lin1, lin2) in handles.blocks:
        if set(m) != set(attn):
            raise ValueError(
                f"attention param keys {sorted(m)} diverged from the TP "
                f"sharding map {sorted(attn)} — update _tp_weight_specs")
        blocks.append((rep(ln1), dict(attn), rep(ln2),
                       {"weight": P(ax, None), "bias": P(ax)},
                       {"weight": P(None, ax), "bias": P()}))
    return {"emb": rep(handles.emb), "blocks": blocks,
            "ln_f": rep(handles.ln_f), "head": rep(handles.head)}


class _DecodeReq:
    __slots__ = ("seed", "n_words", "future", "slot", "steps_needed",
                 "steps_run")

    def __init__(self, seed, n_words):
        self.seed = [int(t) for t in seed]
        self.n_words = int(n_words)
        self.future = Future()
        self.slot = None
        # positions fed through = n_seed + n_words - 1 (lm_decode's n_pos)
        self.steps_needed = len(self.seed) + self.n_words - 1
        self.steps_run = 0


class ContinuousDecoder:
    """Fixed-slab continuous-batching decoder for one ``TransformerLM``.

    ``max_slots`` is the device batch width B; ``n_pos`` the slab's
    position capacity — a request needs ``len(seed) + n_words - 1 <=
    n_pos``.  :meth:`submit` queues a request (future of the full token
    row, seed included, matching ``lm_decode``'s return); :meth:`run`
    drives admitted slots until queue and slots drain.
    """

    def __init__(self, model, max_slots: int = 4, n_pos: int = 64,
                 sync_interval: int | None = None, mesh=None):
        import jax
        import jax.numpy as jnp

        from bigdl_tpu.models.transformer import (_lm_forward_one,
                                                  _lm_handles)
        from bigdl_tpu.optim.local_optimizer import _model_fingerprint
        from bigdl_tpu.serve import xcache

        self.model = model
        self.B = int(max_slots)
        self.n_pos = int(n_pos)
        self.sync_interval = (sync_interval_default()
                              if sync_interval is None
                              else max(1, int(sync_interval)))
        handles = _lm_handles(model)
        self._vocab = handles.vocab
        pe = jnp.asarray(model.modules[1].table(self.n_pos))
        B, n_pos = self.B, self.n_pos
        L, H, hd = handles.n_layers, handles.n_heads, handles.hd

        self.mesh = mesh
        self.tp = (int(mesh.shape["model"])
                   if mesh is not None and "model" in mesh.axis_names
                   else 1)
        fp = _model_fingerprint(model)

        def step_body(local_handles, kc, vc, pos, prev, active, seeds,
                      seed_len, gen, tp_axis=None):
            rows = jnp.arange(B)
            live = active & (pos < n_pos)
            wp = jnp.clip(pos, 0, n_pos - 1)
            tok = jnp.where(pos < seed_len, seeds[rows, wp], prev)
            logp, (kc, vc) = _lm_forward_one(
                tok.astype(jnp.int32), wp, (kc, vc), local_handles,
                n_pos, pe, tp_axis=tp_axis)
            nxt = jnp.argmax(logp, axis=-1).astype(jnp.int32)
            # parked/finished slots must not advance or write tokens
            gen = gen.at[rows, wp].set(jnp.where(live, nxt, gen[rows, wp]))
            prev = jnp.where(live, nxt, prev)
            pos = jnp.where(live, pos + 1, pos)
            return kc, vc, pos, prev, gen

        if self.tp > 1:
            # Megatron head/hidden sharding over the mesh's "model"
            # axis: the step body runs inside shard_map on LOCAL weight
            # shards (passed as an argument pytree — constants cannot
            # shard), with the KV caches split on their head dim.
            if H % self.tp:
                raise ValueError(
                    f"tensor parallelism {self.tp} must divide "
                    f"n_heads={H}")
            for li, (_, _, _, lin1, _) in enumerate(handles.blocks):
                hidden = int(lin1["weight"].shape[0])
                if hidden % self.tp:
                    raise ValueError(
                        f"tensor parallelism {self.tp} must divide the "
                        f"FFN hidden dim ({hidden}, block {li})")
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            from bigdl_tpu.parallel import compat

            ax = "model"
            wspec = _tp_weight_specs(handles, ax)
            # weights pinned to the mesh ONCE, pre-sharded per the spec:
            # passing host arrays each step would re-ship the whole
            # model H2D per decode step
            self._W = jax.device_put(
                {"emb": handles.emb, "blocks": handles.blocks,
                 "ln_f": handles.ln_f, "head": handles.head},
                jax.tree_util.tree_map(
                    lambda sp: NamedSharding(mesh, sp), wspec))
            cache = P(None, None, None, ax)
            rep = P()
            H_local = H // self.tp

            def step_tp(W, kc, vc, pos, prev, active, seeds, seed_len,
                        gen):
                local = handles._replace(
                    mods=None, emb=W["emb"], blocks=W["blocks"],
                    ln_f=W["ln_f"], head=W["head"], n_heads=H_local)
                return step_body(local, kc, vc, pos, prev, active,
                                 seeds, seed_len, gen, tp_axis=ax)

            sharded = compat.shard_map(
                step_tp, mesh=mesh,
                in_specs=(wspec, cache, cache, rep, rep, rep, rep, rep,
                          rep),
                out_specs=(cache, cache, rep, rep, rep))
            self._step = xcache.tracked_jit(
                sharded, ("decode_step", fp, B, n_pos, "tp%d" % self.tp),
                mesh=mesh)
        else:
            self._W = None

            def step(kc, vc, pos, prev, active, seeds, seed_len, gen):
                return step_body(handles, kc, vc, pos, prev, active,
                                 seeds, seed_len, gen)

            self._step = xcache.tracked_jit(
                step, ("decode_step", fp, B, n_pos))

        def admit(kc, vc, pos, active, seeds, seed_len, gen, slot,
                  seed_row, s_len):
            kc = kc.at[:, slot].set(0.0)
            vc = vc.at[:, slot].set(0.0)
            pos = pos.at[slot].set(0)
            active = active.at[slot].set(True)
            seeds = seeds.at[slot].set(seed_row)
            seed_len = seed_len.at[slot].set(s_len)
            gen = gen.at[slot].set(0)
            return kc, vc, pos, active, seeds, seed_len, gen

        def retire(active, slot):
            return active.at[slot].set(False)

        if self.tp > 1:
            # admit/retire ride the SAME shard_map layout as the step:
            # mixing plain-jit programs into the carry chain would hand
            # the step differently-placed inputs on some paths and cost
            # a silent recompile per (program, sharding) combination
            from bigdl_tpu.parallel import compat
            cache, rep = P(None, None, None, "model"), P()
            admit = compat.shard_map(
                admit, mesh=mesh,
                in_specs=(cache, cache, rep, rep, rep, rep, rep, rep,
                          rep, rep),
                out_specs=(cache, cache, rep, rep, rep, rep, rep))
            retire = compat.shard_map(retire, mesh=mesh,
                                      in_specs=(rep, rep),
                                      out_specs=rep)
        self._admit_fn = xcache.tracked_jit(
            admit, ("decode_admit", fp, B, n_pos), mesh=mesh)
        self._retire_fn = xcache.tracked_jit(
            retire, ("decode_retire", fp, B), mesh=mesh)

        z = jnp.zeros
        self._kc = z((L, B, n_pos, H, hd), jnp.float32)
        self._vc = z((L, B, n_pos, H, hd), jnp.float32)
        self._pos = z((B,), jnp.int32)
        self._prev = z((B,), jnp.int32)
        self._active = z((B,), bool)
        self._seeds = z((B, n_pos), jnp.int32)
        self._seed_len = z((B,), jnp.int32)
        self._gen = z((B, n_pos), jnp.int32)

        self._pending: "deque[_DecodeReq]" = deque()
        self._slots: list = [None] * B

        # telemetry: mirrored into the mergeable metrics registry
        # (labelled decoder=<name>) so slot occupancy and throughput
        # show up in the fleet exporter next to the engine numbers
        from bigdl_tpu.obs import metrics as obs_metrics
        self.name = f"decoder{next(_DECODER_SEQ)}"
        reg = obs_metrics.get()
        lab = {"decoder": self.name}
        self._m_steps = reg.counter(
            "decode_steps_total", "decode steps driven", **lab)
        self._m_admitted = reg.counter(
            "decode_admitted_total", "requests admitted into slots", **lab)
        self._m_retired = reg.counter(
            "decode_retired_total", "requests retired from slots", **lab)
        self._m_syncs = reg.counter(
            "decode_host_syncs_total", "boundary device->host fetches",
            **lab)
        self._m_slots = reg.gauge(
            "decode_slots_active", "occupied KV-slab slots", **lab)
        # directly-constructed decoders (the TP-serving entry point)
        # may never see close() — drop the uniquely-labelled series at
        # GC so the process registry cannot grow without bound
        self._drop_series = weakref.finalize(
            self, reg.drop_series, decoder=self.name)
        self.steps = 0
        self.host_syncs = 0
        self.admitted = 0
        self.retired = 0

        self._warm()

    def _run_step(self):
        args = (self._kc, self._vc, self._pos, self._prev, self._active,
                self._seeds, self._seed_len, self._gen)
        if self._W is not None:
            args = (self._W,) + args
        (self._kc, self._vc, self._pos, self._prev,
         self._gen) = self._step(*args)

    def _warm(self):
        """Pre-compile the step/admit/retire programs at construction so
        admission and decode never hit a cold compile (the serving
        zero-cold-compile property, docs/serving.md).

        The warm pass cycles the REAL state machine once — step on the
        fresh slab, admit into slot 0, step on the admit outputs, retire,
        step again — keeping each program's outputs as the live state, so
        every (shape, sharding) combination the serving loop will feed
        each program is compiled here and not mid-stream (jit caches per
        input sharding; under TP the shard_map step and the plain-jit
        admit/retire produce differently-placed carries).  The slot-0
        garbage this writes is erased by ``admit``'s per-slot reset
        before any real request serves."""
        import numpy as np

        self._run_step()
        for _ in range(2):
            # twice: the first admission's carries are the fresh
            # host-placed slab, every later admission's are program
            # outputs — both placement combinations must compile now
            (self._kc, self._vc, self._pos, self._active, self._seeds,
             self._seed_len, self._gen) = self._admit_fn(
                self._kc, self._vc, self._pos, self._active, self._seeds,
                self._seed_len, self._gen, np.int32(0),
                np.zeros((self.n_pos,), np.int32), np.int32(0))
        self._run_step()
        self._active = self._retire_fn(self._active, np.int32(0))
        self._run_step()

    # -- submit -------------------------------------------------------------
    def submit(self, seed_ids, n_words: int) -> Future:
        """Queue one request; the future resolves to the full token row
        (seed + ``n_words`` generated ids), exactly ``lm_decode``'s
        greedy output for the same seed."""
        seed = np.asarray(seed_ids, np.int32)
        if seed.ndim != 1 or seed.size == 0:
            raise ValueError("seed_ids must be one flat non-empty id row")
        if n_words < 1:
            raise ValueError("n_words must be >= 1")
        req = _DecodeReq(seed.tolist(), n_words)
        if req.steps_needed > self.n_pos:
            raise ValueError(
                f"request needs {req.steps_needed} positions but the "
                f"slab holds n_pos={self.n_pos}")
        self._pending.append(req)
        return req.future

    # -- drive --------------------------------------------------------------
    def _admit_waiting(self):
        for slot in range(self.B):
            if self._slots[slot] is not None or not self._pending:
                continue
            req = self._pending.popleft()
            req.slot = slot
            seed_row = np.zeros((self.n_pos,), np.int32)
            seed_row[:len(req.seed)] = req.seed
            (self._kc, self._vc, self._pos, self._active, self._seeds,
             self._seed_len, self._gen) = self._admit_fn(
                self._kc, self._vc, self._pos, self._active, self._seeds,
                self._seed_len, self._gen, np.int32(slot), seed_row,
                np.int32(len(req.seed)))
            self._slots[slot] = req
            self.admitted += 1
            self._m_admitted.inc()

    def run(self):
        """Drive the slab until every submitted request has resolved.
        Admissions and retirements happen only at ``sync_interval``
        step boundaries; the only device->host reads are one
        generated-slab fetch per boundary that retires a request."""
        while self._pending or any(r is not None for r in self._slots):
            self._admit_waiting()
            live = [r for r in self._slots if r is not None]
            if not live:   # pragma: no cover - defensive
                break
            self._m_slots.set(len(live))
            for _ in range(self.sync_interval):
                self._run_step()
            self.steps += self.sync_interval
            self._m_steps.inc(self.sync_interval)
            for r in live:
                r.steps_run += self.sync_interval
            done = [r for r in live if r.steps_run >= r.steps_needed]
            if not done:
                continue
            gen_host = np.asarray(self._gen)   # the boundary host sync
            self.host_syncs += 1
            self._m_syncs.inc()
            for r in done:
                s = len(r.seed)
                toks = gen_host[r.slot, s - 1:s - 1 + r.n_words]
                r.future.set_result(r.seed + [int(t) for t in toks])
                self._active = self._retire_fn(self._active,
                                               np.int32(r.slot))
                self._slots[r.slot] = None
                self.retired += 1
                self._m_retired.inc()
            self._m_slots.set(sum(1 for r in self._slots
                                  if r is not None))
        from bigdl_tpu.obs import events
        events.emit("serve", kind="decode", steps=self.steps,
                    host_syncs=self.host_syncs, admitted=self.admitted,
                    retired=self.retired, slots=self.B)
        return self

    def close(self):
        """Drop this decoder's series from the process metrics registry.
        Decoders are labelled uniquely (``decoder=<name>``), so a
        process that constructs many short-lived decoders (every
        :func:`continuous_decode` call makes one) would otherwise grow
        the registry — and every snapshot/exposition — without bound.
        Also runs at GC for decoders nobody closes; idempotent."""
        self._drop_series()

    def stats(self) -> dict:
        return {"steps": self.steps, "host_syncs": self.host_syncs,
                "admitted": self.admitted, "retired": self.retired,
                "slots": self.B,
                "slots_active": sum(1 for r in self._slots
                                    if r is not None),
                "n_pos": self.n_pos,
                "sync_interval": self.sync_interval, "tp": self.tp,
                "name": self.name}


def continuous_decode(model, seed_rows, n_words, max_slots: int = 4,
                      n_pos: int | None = None,
                      sync_interval: int | None = None, mesh=None):
    """Convenience one-shot: decode every seed row with a shared slab.

    ``n_pos`` defaults to the largest request's need, so a mixed set of
    seed lengths shares one compiled step.  ``mesh`` (with a ``model``
    axis) serves tensor-parallel.  Returns the extended rows in
    submission order (``lm_decode`` greedy semantics per row)."""
    reqs = [np.asarray(s, np.int32) for s in seed_rows]
    if n_pos is None:
        n_pos = max(int(s.size) + int(n_words) - 1 for s in reqs)
    dec = ContinuousDecoder(model, max_slots=max_slots, n_pos=n_pos,
                            sync_interval=sync_interval, mesh=mesh)
    try:
        futs = [dec.submit(s, n_words) for s in reqs]
        dec.run()
        return [f.result() for f in futs]
    finally:
        dec.close()   # one-shot decoder: don't leak its registry series
