"""Continuous-batching decode: a slot-based driver over the
``TransformerLM`` KV-cache step (docs/serving.md).

``models.transformer.lm_decode`` compiles one lock-step scan: every row
starts together, ends together, and a new request waits for the whole
batch to finish.  A serving decoder cannot run lock-step — requests
arrive whenever they arrive and finish at their own lengths.  This
driver treats the rows of a fixed-width device batch as **slots**:

- each slot independently consumes its own seed and generates its own
  continuation (per-row positions — ``_lm_forward_one`` scatters the
  cache write and masks attention per row);
- requests are **admitted** into free slots and **retired** at step
  boundaries only, so the device sees one fixed-shape compiled step
  program for the engine's whole lifetime (slot index is a traced
  argument — admission never recompiles);
- the host syncs only every ``sync_interval`` steps (the
  ``BIGDL_OBS_TAPS_CADENCE``-style boundary, env ``BIGDL_SERVE_SYNC``):
  generated tokens feed back device-side, and the generated-token slab
  is materialized once per boundary that retires anything — never per
  token.

**Streaming delivery** (``serve/streaming.py``, docs/observability.md
"Streaming telemetry"): :meth:`ContinuousDecoder.submit` returns a
:class:`~bigdl_tpu.serve.streaming.StreamFuture` — register
``on_tokens(cb)`` (or ship the fleet payload's ``stream`` flag) and the
request's freshly generated tokens are delivered incrementally at each
sync boundary.  Delivery reuses the boundary's one slab
materialization (a boundary with live streams materializes exactly
once, for delivery AND retirement — never per token, never twice), the
committed stream is byte-identical to the all-at-once result in every
configuration, and consumer callbacks run on a dedicated delivery
thread so a slow or raising consumer can never stall the step loop.
Each streamed request lands a per-request token timeline (admit →
first-token boundary → per-boundary counts → retire) as a ``stream``
obs event plus trace hops when sampled, and feeds the
``decode_ttft_seconds`` / ``decode_itl_seconds`` / ``decode_stream_tokens_total``
SLO surface in the mergeable metrics registry.

**Paged KV (default, env ``BIGDL_SERVE_PAGED``)**: KV storage is a
block-paged pool — ``(layers, n_pages, page_size, heads, hd)`` plus a
per-slot slot→page table carried as traced state — instead of the PR-5
``(B, n_pos)`` slab.  Admit/retire allocate and free fixed-size pages
(``serve/paging.py``), so a short request holds only the pages its own
length needs and live concurrency scales with TOTAL POOLED TOKENS, not
slab width: ``max_slots`` can exceed ``pool_tokens / n_pos`` by far
when traffic skews short.  On top of the pool:

- **prefix caching** (``serve/prefix.py``, env
  ``BIGDL_SERVE_PREFIX_CACHE``): a retiring request donates the full
  pages inside its seed to a token-hash chain cache; a new request
  whose seed matches maps those pages read-only into its own table and
  starts at the (page-aligned) divergence point, skipping that much
  prefill.  Hits/misses and reused pages ride the metrics registry.
- **int8 KV pages** (env ``BIGDL_SERVE_KV_QUANT``, docs/serving.md
  "Quantized serving"): the pools store int8 with per-page-row,
  per-head scales in parallel ``(layers, n_pages, page_size, H)``
  traced arrays (``quant/kv.py``) — the scatter quantizes, the
  page-gathered attention view dequantizes, and because scales are
  pool-indexed like the values, prefix page donation ships them with
  the pages.  ~3-4x pooled tokens at equal HBM (scales included),
  which is live concurrency; greedy output may drift from the fp-KV
  stream within
  the declared budget (``bigdl_tpu.quant.KV_TOKEN_DRIFT_BUDGET``),
  while speculative decode stays EXACTLY identical to the
  non-speculative quantized stream for every k.
- **self-speculative decode** (env ``BIGDL_SERVE_SPEC_K``): the model
  drafts ``k`` tokens per step with a SHALLOW pass over its own first
  ``draft_layers`` blocks (same weights — no second model), then ONE
  batched verify pass over the ``k+1``-token window accepts the longest
  prefix whose drafted tokens match the full model's greedy argmax.
  Committed tokens are exactly the non-speculative greedy stream for
  every ``k`` (the acceptance rule only ever commits argmax-consistent
  tokens), and seed consumption rides the same window — chunked
  prefill for free.  The draft+verify pair is ONE fused program with a
  fixed ``k+1`` window, pre-warmed through the shared executable cache
  at construction, so acceptance-length variance never compiles.

**Sampled decode on the fast path** (``serve/sampling.py``,
docs/serving.md "Sampled decode"): :meth:`ContinuousDecoder.submit`
takes per-request :class:`~bigdl_tpu.serve.sampling.SamplingParams`
(temperature / top-k / top-p / seed / stop sequences / max_tokens)
carried as per-slot TRACED vectors — float temps, int ks, packed stop
buffers and a ``(B, 2)`` per-slot PRNG-key array ride the step program
as data, so a batch mixing greedy and any number of distinct sampling
configs runs the SAME compiled step with zero cold compiles.  Greedy is
the ``temperature == 0`` branch of a ``jnp.where`` whose selected lane
is exactly the historical argmax — greedy streams stay byte-identical
to the sampling-free decoder.  Draw keys are
``fold_in(request_key, DRAW_TAGS * gen_index + tag)`` — a pure function
of the request seed and generated-token index, never of slot, batch mix
or prefix-hit start position — so every sampled request replays
bit-exactly (``tools/request_replay.py``).  Under speculative decode
the argmax prefix-acceptance generalizes to the Leviathan lossless
accept/reject rule (accept draft ``x`` with prob ``min(1, p(x)/q(x))``,
resample the residual on rejection), so spec keeps its amortization at
temperature > 0 while committing EXACTLY the non-speculative sampling
distribution.  Requests with stop sequences retire early at the first
sync boundary after a device-side match — pages and the slot free
immediately instead of burning steps to ``max_tokens``
(``decode_stop_retired_total`` / ``decode_steps_saved_total``).

**Tensor-parallel serving** (``mesh=``): a model whose KV pool + weights
outgrow one chip's HBM serves by sharding the decode step over the
mesh's ``model`` axis (``parallel/mesh.hybrid_mesh``) with
``parallel/compat.shard_map`` — Megatron-style: attention heads and the
FFN hidden dim split across shards (wq/wk/wv columns + the KV pool's
head dim; lin1 rows), each branch's output projection psum-merges once,
and everything else (embeddings, LayerNorms, the LM head) replicates.
The per-head math is untouched, so TP decode is token-identical to the
single-device driver — the parity contract ``tests/test_serve_cluster.py``
asserts.  The step/admit/retire programs are warmed at construction
through the shared executable cache (``serve/xcache.py``), so admission
under TP stays compile-free exactly like the single-chip path.
"""
from __future__ import annotations

import itertools
import logging
import os
import time
import weakref
from collections import deque

import numpy as np

from bigdl_tpu.obs import recorder as obs_recorder
from bigdl_tpu.serve import sampling as smp
from bigdl_tpu.serve.paging import PagePool, RequestTooLongError
from bigdl_tpu.serve.prefix import PrefixCache, chain_keys
from bigdl_tpu.serve.streaming import StreamFuture, TokenDelivery

logger = logging.getLogger("bigdl_tpu.serve")

_DECODER_SEQ = itertools.count()

ENV_SYNC = "BIGDL_SERVE_SYNC"
DEFAULT_SYNC = 8
ENV_PAGED = "BIGDL_SERVE_PAGED"
ENV_PAGE_SIZE = "BIGDL_SERVE_PAGE_SIZE"
DEFAULT_PAGE_SIZE = 16
ENV_PAGES = "BIGDL_SERVE_PAGES"
ENV_PREFIX = "BIGDL_SERVE_PREFIX_CACHE"
ENV_SPEC_K = "BIGDL_SERVE_SPEC_K"
ENV_STOP_SEQS = "BIGDL_SERVE_MAX_STOP_SEQS"
DEFAULT_STOP_SEQS = 2
ENV_STOP_LEN = "BIGDL_SERVE_MAX_STOP_LEN"
DEFAULT_STOP_LEN = 8


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def sync_interval_default() -> int:
    return max(1, _env_int(ENV_SYNC, DEFAULT_SYNC))


def _decoder_gc_cleanup(reg, name, delivery_box):
    """weakref.finalize target for decoders nobody closes: stop the
    lazily created delivery thread (else one blocked daemon thread
    leaks per GC'd streaming decoder) and drop the registry series."""
    for d in delivery_box:
        try:
            d.close(timeout=2.0)
        except Exception:  # pragma: no cover - teardown
            pass
    reg.drop_series(decoder=name)


def _tp_weight_specs(handles, ax: str):
    """PartitionSpec tree mirroring the decode weight pytree for
    Megatron head/hidden sharding over mesh axis ``ax``:

    - attention: wq/wk/wv split on their OUTPUT columns (head-major, so
      a shard holds whole heads) with the matching bias slices; wo
      splits on its input rows; bo replicates (added once, post-psum);
    - FFN: lin1 (hidden, d) splits hidden rows + bias, lin2 (d, hidden)
      splits hidden columns, its bias replicates;
    - embeddings, LayerNorms and the LM head replicate.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    def rep(tree):
        return jax.tree_util.tree_map(lambda _: P(), tree)

    attn = {"wq": P(None, ax), "wk": P(None, ax), "wv": P(None, ax),
            "bq": P(ax), "bk": P(ax), "bv": P(ax),
            "wo": P(ax, None), "bo": P()}
    blocks = []
    for (ln1, m, ln2, lin1, lin2) in handles.blocks:
        if set(m) != set(attn):
            raise ValueError(
                f"attention param keys {sorted(m)} diverged from the TP "
                f"sharding map {sorted(attn)} — update _tp_weight_specs")
        blocks.append((rep(ln1), dict(attn), rep(ln2),
                       {"weight": P(ax, None), "bias": P(ax)},
                       {"weight": P(None, ax), "bias": P()}))
    return {"emb": rep(handles.emb), "blocks": blocks,
            "ln_f": rep(handles.ln_f), "head": rep(handles.head)}


def _pages_needed(steps: int, page_size: int) -> int:
    """Pages a request's full lifetime reserves: ``ceil(steps /
    page_size)``, and nothing more.  The ONE authoritative spot for the
    reservation math (``submit()``'s too-long check and
    ``_try_admit_paged``'s allocation share it) so the two can never
    drift.  In particular speculative decode adds NO page headroom: the
    (k+1)-window's writes past a slot's capacity are valid-gated out
    (``spec_step_body``), so a seed + budget that exactly fills its
    last page admits without a speculative extra page — pinned at the
    boundary by ``tests/test_paged_attention.py``."""
    return -(-steps // page_size)


class _DecodeReq:
    __slots__ = ("seed", "n_words", "future", "slot", "steps_needed",
                 "steps_run", "start_pos", "pages", "rid", "trace",
                 "t_submit", "t_admit", "first_ts", "last_ts",
                 "streamed", "timeline", "params", "stop_retired")

    def __init__(self, seed, n_words, trace=None, params=None):
        self.seed = [int(t) for t in seed]
        self.n_words = int(n_words)
        self.params = params if params is not None else smp.GREEDY
        self.stop_retired = False    # retired early on a stop match
        self.future = StreamFuture()
        self.slot = None
        # positions fed through = n_seed + n_words - 1 (lm_decode's n_pos)
        self.steps_needed = len(self.seed) + self.n_words - 1
        self.steps_run = 0
        self.start_pos = 0       # > 0 on a prefix-cache hit
        self.pages = []          # pool page ids, logical order (paged)
        # per-request token timeline (streaming telemetry)
        self.rid = 0
        self.trace = trace       # obs.trace.Trace for sampled requests
        self.t_submit = time.perf_counter()
        self.t_admit = None      # slot admission boundary
        self.first_ts = None     # first-token boundary
        self.last_ts = None      # last boundary that delivered tokens
        self.streamed = 0        # generated tokens delivered so far
        self.timeline = []       # [(perf_counter ts, n new tokens)]


class ContinuousDecoder:
    """Continuous-batching decoder for one ``TransformerLM``.

    ``max_slots`` is the device batch width B; ``n_pos`` the per-request
    position capacity — a request needs ``len(seed) + n_words - 1 <=
    n_pos``, and one that does not fit fails ITS OWN future with
    :class:`RequestTooLongError` at submit time.  :meth:`submit` queues
    a request (future of the full token row, seed included, matching
    ``lm_decode``'s return); :meth:`run` drives admitted slots until
    queue and slots drain.

    ``paged`` (default from ``BIGDL_SERVE_PAGED``, on) stores KV in a
    block-paged pool of ``n_pages`` × ``page_size`` tokens instead of a
    ``(B, n_pos)`` slab; ``n_pages`` defaults to the slab-equivalent
    ``ceil(n_pos / page_size) * max_slots``.  ``prefix_cache`` enables
    token-hash prefix page reuse, ``spec_k`` > 0 self-speculative
    decode with a ``draft_layers``-deep draft pass (default: half the
    blocks), and ``kv_quant="int8"`` (default from
    ``BIGDL_SERVE_KV_QUANT``) int8 KV pages with per-page-row scales —
    all paged-only.

    ``host_tier`` attaches a host-RAM KV tier
    (:class:`~bigdl_tpu.serve.kvtier.HostKVTier`): prefix pages evicted
    under allocation pressure spill D2H instead of dying, and an
    admission whose chain walk runs past the device cache re-admits
    matching tier pages H2D as prefix hits.  Defaults from
    ``BIGDL_SERVE_KV_HOST_MB`` (> 0 builds an owned tier; requires the
    paged pool with the prefix cache).  ``prefill_adopt`` pre-compiles
    the page re-admit program so :meth:`adopt_pages` can accept KV
    pages shipped by a prefill replica (``serve/fleet.py``).
    """

    def __init__(self, model, max_slots: int = 4, n_pos: int = 64,
                 sync_interval: int | None = None, mesh=None,
                 paged: bool | None = None, page_size: int | None = None,
                 n_pages: int | None = None,
                 prefix_cache: bool | None = None,
                 spec_k: int | None = None,
                 draft_layers: int | None = None,
                 kv_quant: str | None = None,
                 host_tier=None, prefill_adopt: bool = False,
                 max_stop_seqs: int | None = None,
                 max_stop_len: int | None = None,
                 name: str | None = None):
        import jax
        import jax.numpy as jnp

        from bigdl_tpu.models.transformer import (_lm_forward_one,
                                                  _lm_forward_window,
                                                  _lm_handles)
        from bigdl_tpu.optim.local_optimizer import _model_fingerprint
        from bigdl_tpu.quant import kv as kvq
        from bigdl_tpu.quant import kv_mode_default, normalize_mode
        from bigdl_tpu.serve import xcache

        self.model = model
        self.B = int(max_slots)
        self.n_pos = int(n_pos)
        self.sync_interval = (sync_interval_default()
                              if sync_interval is None
                              else max(1, int(sync_interval)))
        self.paged = bool(_env_int(ENV_PAGED, 1)) if paged is None \
            else bool(paged)
        self.page_size = max(1, _env_int(ENV_PAGE_SIZE, DEFAULT_PAGE_SIZE)
                             if page_size is None else int(page_size))
        self.pages_per_slot = -(-self.n_pos // self.page_size)
        if n_pages is None:
            n_pages = _env_int(ENV_PAGES, 0) \
                or self.pages_per_slot * self.B
        self.spec_k = max(0, _env_int(ENV_SPEC_K, 0) if spec_k is None
                          else int(spec_k))
        # packed stop-sequence capacity: every slot carries an
        # (NS, LS) right-aligned token buffer; a submit whose stop list
        # exceeds either dim fails its own future
        self.max_stop_seqs = max(1, _env_int(ENV_STOP_SEQS,
                                             DEFAULT_STOP_SEQS)
                                 if max_stop_seqs is None
                                 else int(max_stop_seqs))
        self.max_stop_len = max(1, _env_int(ENV_STOP_LEN,
                                            DEFAULT_STOP_LEN)
                                if max_stop_len is None
                                else int(max_stop_len))
        use_prefix = bool(_env_int(ENV_PREFIX, 1)) \
            if prefix_cache is None else bool(prefix_cache)
        if kv_quant is None:
            # the env opts the PAGED pool in; a slab decoder (A/B
            # baseline) under the same env quietly serves fp — only an
            # explicit kv_quant= on a slab decoder is a hard error
            self.kv_quant = kv_mode_default() if self.paged else "off"
        else:
            self.kv_quant = normalize_mode(kv_quant, kvq.ON_MODES,
                                           "kv_quant")
        if not self.paged and (self.spec_k or prefix_cache
                               or self.kv_quant != "off"):
            raise ValueError("speculative decode, prefix caching and "
                             "KV quantization need the paged KV pool "
                             "(paged=True)")

        handles = _lm_handles(model)
        self._vocab = handles.vocab
        B, n_pos, ps = self.B, self.n_pos, self.page_size
        L, H, hd = handles.n_layers, handles.n_heads, handles.hd
        self.draft_layers = (max(1, L // 2) if draft_layers is None
                             else min(L, max(1, int(draft_layers))))
        Ld, k = self.draft_layers, self.spec_k
        # host-RAM KV tier: explicit instance, or owned-from-env when
        # BIGDL_SERVE_KV_HOST_MB > 0 (spill rides the prefix cache's
        # on_evict hook, so the tier needs paged + prefix)
        from bigdl_tpu.serve import kvtier
        self._tier_owned = False
        if host_tier is None and self.paged and use_prefix:
            mb = kvtier.host_mb_default()
            if mb > 0:
                host_tier = kvtier.HostKVTier(mb)
                self._tier_owned = True
        if host_tier is not None and not (self.paged and use_prefix):
            raise ValueError("the host KV tier spills evicted prefix "
                             "pages — it needs the paged pool with the "
                             "prefix cache enabled")
        self._tier = host_tier
        if self.paged:
            self._pool = PagePool(int(n_pages), ps)
            on_evict = self._spill_page if self._tier is not None else None
            self._prefix = (PrefixCache(self._pool, on_evict=on_evict)
                            if use_prefix else None)
            n_view = self.pages_per_slot * ps
        else:
            self._pool = self._prefix = None
            n_view = n_pos
        self._n_view = n_view
        pe = jnp.asarray(model.modules[1].table(n_view))

        self.mesh = mesh
        self.tp = (int(mesh.shape["model"])
                   if mesh is not None and "model" in mesh.axis_names
                   else 1)
        fp = _model_fingerprint(model)

        # ---- step bodies --------------------------------------------------
        # ``caches`` is the KV-storage pytree threaded through every
        # program: (k, v) pools, or (k, v, kscale, vscale) under int8
        # KV quantization (the scale arrays are traced state exactly
        # like the pools — serve/decode carries them, quant/kv.py and
        # _lm_forward_window do the math)
        #
        # Per-slot sampling state rides every body as traced vectors:
        # ``temp``/``topk``/``topp`` (B,), ``keys`` (B, 2) uint32,
        # ``stop_buf`` (B, NS, LS) right-aligned + ``stop_len`` (B, NS),
        # and ``finished`` (B,) — a stop-matched row freezes (drops out
        # of ``live``) until the boundary retires it.
        NS, LS = self.max_stop_seqs, self.max_stop_len

        def _next_token(logp, pos, seed_len, temp, topk, topp, keys):
            """The committed token for the write position ``pos``:
            greedy rows take the UNCHANGED argmax (the byte-identity
            lane), sampled rows draw from the filtered distribution
            under the request-keyed stream for this generated index."""
            greedy_tok = jnp.argmax(logp, axis=-1).astype(jnp.int32)
            gidx = jnp.maximum(pos - (seed_len - 1), 0)
            sub = smp.fold_in_rows(
                keys, smp.DRAW_TAGS * gidx + smp.TAG_MAIN)
            samp = smp.sample_tokens(logp, sub, temp, topk,
                                     topp).astype(jnp.int32)
            return jnp.where(temp > 0, samp, greedy_tok)

        def _stop_hit(gen, ends, seed_len, stop_buf, stop_len):
            """Device-side stop-sequence match: does any of the slot's
            stop sequences end EXACTLY at write position ``ends[b, s]``?
            ``ends`` is (B, S); returns (B, S) bool.  The window looks
            backward only, must lie entirely inside the OUTPUT region
            (write positions >= seed_len - 1 — seeds never match), and
            right-aligned buffers make the comparison one fixed-shape
            equality regardless of per-sequence length."""
            rows = jnp.arange(B)
            idx = (ends[:, :, None] - (LS - 1)
                   + jnp.arange(LS)[None, None, :])           # (B,S,LS)
            tok = gen[rows[:, None, None], jnp.clip(idx, 0, n_view - 1)]
            out_ok = idx >= (seed_len - 1)[:, None, None]
            eq = (tok[:, :, None, :] == stop_buf[:, None, :, :]
                  ) & out_ok[:, :, None, :]                 # (B,S,NS,LS)
            need = (jnp.arange(LS)[None, None, None, :]
                    >= (LS - stop_len)[:, None, :, None])
            hit = jnp.where(need, eq, True).all(axis=-1)      # (B,S,NS)
            return ((stop_len > 0)[:, None, :] & hit).any(axis=-1)

        def slab_step_body(local_handles, caches, pos, prev, active,
                           seeds, seed_len, gen, temp, topk, topp,
                           keys, stop_buf, stop_len, finished,
                           tp_axis=None):
            rows = jnp.arange(B)
            live = active & ~finished & (pos < n_pos)
            wp = jnp.clip(pos, 0, n_pos - 1)
            tok = jnp.where(pos < seed_len, seeds[rows, wp], prev)
            logp, caches = _lm_forward_one(
                tok.astype(jnp.int32), wp, caches, local_handles,
                n_pos, pe, tp_axis=tp_axis)
            nxt = _next_token(logp, pos, seed_len, temp, topk, topp,
                              keys)
            # parked/finished slots must not advance or write tokens
            gen = gen.at[rows, wp].set(jnp.where(live, nxt, gen[rows, wp]))
            prev = jnp.where(live, nxt, prev)
            pos = jnp.where(live, pos + 1, pos)
            hit = _stop_hit(gen, wp[:, None], seed_len, stop_buf,
                            stop_len)[:, 0]
            finished = finished | (live & hit)
            return caches, pos, prev, gen, finished

        def paged_step_body(local_handles, caches, ptab, pos, prev,
                            active, seeds, seed_len, cap, gen, temp,
                            topk, topp, keys, stop_buf, stop_len,
                            finished, tp_axis=None, view_pages=None):
            rows = jnp.arange(B)
            live = active & ~finished & (pos < cap)
            wp = jnp.clip(pos, 0, cap - 1)
            tok = jnp.where(pos < seed_len, seeds[rows, wp], prev)
            logp, caches = _lm_forward_one(
                tok.astype(jnp.int32), wp, caches, local_handles,
                n_view, pe, tp_axis=tp_axis, pages=(ptab, ps), valid=live,
                view_pages=view_pages)
            nxt = _next_token(logp, pos, seed_len, temp, topk, topp,
                              keys)
            # frozen rows route their token write out of bounds (dropped)
            gen = gen.at[rows, jnp.where(live, wp, n_view)].set(nxt)
            prev = jnp.where(live, nxt, prev)
            pos = jnp.where(live, pos + 1, pos)
            hit = _stop_hit(gen, wp[:, None], seed_len, stop_buf,
                            stop_len)[:, 0]
            finished = finished | (live & hit)
            return caches, pos, prev, gen, finished

        def spec_step_body(local_full, local_draft, caches, ptab,
                           pos, prev, active, seeds, seed_len, cap, gen,
                           temp, topk, topp, keys, stop_buf, stop_len,
                           finished, acc_hist, tp_axis=None,
                           view_pages=None):
            rows = jnp.arange(B)
            live = active & ~finished & (pos < cap)
            sampled = temp > 0                   # (B,) sampled-row lane
            # -- draft k tokens with the shallow pass (window position 0
            # is the normal step token; seed positions stay forced).
            # Sampled rows DRAW their draft from the filtered shallow
            # distribution (q must be the actual proposal for the
            # accept/reject rule below); greedy rows keep the argmax.
            wp0 = jnp.clip(pos, 0, cap - 1)
            t0 = jnp.where(pos < seed_len,
                           seeds[rows, wp0], prev).astype(jnp.int32)
            toks, qs, d_tok, d_pos = [t0], [], t0, pos
            for _ in range(k):
                d_valid = live & (d_pos < cap)
                dlogp, caches = _lm_forward_one(
                    d_tok, jnp.clip(d_pos, 0, cap - 1), caches,
                    local_draft, n_view, pe, tp_axis=tp_axis,
                    pages=(ptab, ps), valid=d_valid,
                    view_pages=view_pages)
                d_arg = jnp.argmax(dlogp, axis=-1).astype(jnp.int32)
                # proposal draw keyed by the WRITE position of this
                # drafted token (= d_pos before the increment)
                lq = smp.filter_logits(dlogp, temp, topk, topp)
                gq = jnp.maximum(d_pos - (seed_len - 1), 0)
                dsub = smp.fold_in_rows(
                    keys, smp.DRAW_TAGS * gq + smp.TAG_DRAFT)
                d_smp = jax.vmap(jax.random.categorical)(
                    dsub, lq).astype(jnp.int32)
                qs.append(jax.nn.softmax(lq, axis=-1))
                d_pos = d_pos + 1
                d_draft = jnp.where(sampled, d_smp, d_arg)
                d_tok = jnp.where(
                    d_pos < seed_len,
                    seeds[rows, jnp.clip(d_pos, 0, n_view - 1)],
                    d_draft)
                toks.append(d_tok)
            W = jnp.stack(toks, axis=1)                     # (B, k+1)
            qs = jnp.stack(qs, axis=1)                      # (B, k, V)
            p_idx = pos[:, None] + jnp.arange(k + 1)[None, :]
            valid = live[:, None] & (p_idx < cap[:, None])
            wp = jnp.clip(p_idx, 0, n_view - 1)
            # -- ONE batched verify pass with the full model (overwrites
            # the draft's shallow K/V at the same positions)
            logp, caches = _lm_forward_window(
                W, wp, caches, local_full, pe, (ptab, ps),
                valid=valid, tp_axis=tp_axis, view_pages=view_pages)
            g = jnp.argmax(logp, axis=-1).astype(jnp.int32)  # (B, k+1)
            # -- greedy lane (byte-identity): drafted token j+1 survives
            # iff it equals the verify argmax at position j (seed-forced
            # positions always survive), so the committed stream is
            # EXACTLY the non-speculative greedy stream
            forced = p_idx[:, 1:] < seed_len[:, None]
            # valid-masked so a chance match at a garbage position past
            # the slot's page capacity cannot extend the run (it could
            # never commit — consumed caps at cap - pos — but it would
            # inflate the acceptance telemetry)
            match_g = valid[:, 1:] & (forced | (W[:, 1:] == g[:, :k]))
            # -- sampled lane (Leviathan lossless accept/reject): the
            # target distribution p at every window slot, filtered with
            # the SAME per-row params as the draft's q
            pp = jax.nn.softmax(
                smp.filter_logits(logp, temp, topk, topp), axis=-1)
            ga = jnp.maximum(p_idx[:, :k] - (seed_len - 1)[:, None], 0)
            asub = smp.fold_in_rows(
                jnp.broadcast_to(keys[:, None, :],
                                 (B, k, 2)).reshape(B * k, 2),
                (smp.DRAW_TAGS * ga + smp.TAG_ACCEPT).reshape(B * k))
            u = smp.uniform_rows(asub).reshape(B, k)
            p_x = jnp.take_along_axis(pp[:, :k], W[:, 1:, None],
                                      axis=-1)[..., 0]
            q_x = jnp.take_along_axis(qs, W[:, 1:, None],
                                      axis=-1)[..., 0]
            # division-free min(1, p/q) accept: u * q(x) < p(x)
            match_s = valid[:, 1:] & (forced | (u * q_x < p_x))
            match = jnp.where(sampled[:, None], match_s, match_g)
            acc = jnp.cumprod(match.astype(jnp.int32), axis=1).sum(axis=1)
            consumed = jnp.where(live,
                                 jnp.minimum(acc + 1, cap - pos), 0)
            commit = jnp.arange(k + 1)[None, :] < consumed[:, None]
            # committed tokens: greedy rows commit the verify argmax;
            # sampled rows commit their accepted drafts, with the slot
            # at ``acc`` replaced by the residual draw (rejection) or —
            # at slot k with q = 0 — a fresh draw from p (the bonus
            # token), which keeps the committed marginal exactly p
            qa = jnp.concatenate(
                [qs, jnp.zeros_like(qs[:, :1])],
                axis=1)[rows, jnp.clip(acc, 0, k)]
            pa = pp[rows, jnp.clip(acc, 0, k)]
            gfix = jnp.maximum(pos + acc - (seed_len - 1), 0)
            fsub = smp.fold_in_rows(
                keys, smp.DRAW_TAGS * gfix + smp.TAG_FIX)
            c = jax.vmap(jax.random.categorical)(
                fsub, jnp.log(smp.spec_residual(pa, qa))
            ).astype(jnp.int32)
            S = jnp.concatenate([W[:, 1:], jnp.zeros((B, 1), jnp.int32)],
                                axis=1)
            S = jnp.where(jnp.arange(k + 1)[None, :] == acc[:, None],
                          c[:, None], S)
            C = jnp.where(sampled[:, None], S, g)
            gen = gen.at[rows[:, None],
                         jnp.where(commit, wp, n_view)].set(C)
            # -- stop sequences: scan the freshly committed window slots
            # (backward-looking matches only read already-written gen);
            # the first matching slot truncates the commit run and
            # freezes the row for boundary retirement
            hit = _stop_hit(gen, wp, seed_len, stop_buf,
                            stop_len) & commit
            any_hit = hit.any(axis=1)
            jstar = jnp.argmax(hit, axis=1)
            consumed = jnp.where(any_hit,
                                 jnp.minimum(consumed, jstar + 1),
                                 consumed)
            finished = finished | (any_hit & live)
            prev = jnp.where(consumed > 0,
                             C[rows, jnp.clip(consumed - 1, 0, k)], prev)
            # acceptance telemetry covers PURE decode windows only —
            # every drafted position past the seed.  Seed-forced
            # (chunked-prefill) windows "accept" by construction and
            # would skew the histogram toward k no matter how bad the
            # draft actually is.
            rec = live & (p_idx[:, 1] >= seed_len)
            pos = pos + consumed
            acc_hist = acc_hist + jnp.where(
                rec[:, None],
                jax.nn.one_hot(acc, k + 1, dtype=jnp.int32), 0
            ).sum(axis=0)
            return caches, pos, prev, gen, finished, acc_hist

        def _draft_of(local):
            return local._replace(blocks=local.blocks[:Ld],
                                  block_eps=handles.block_eps[:Ld],
                                  n_layers=Ld)

        # ---- program assembly (single-chip or TP shard_map) ---------------
        pool_shape = ((L, self._pool.n_pages, ps, H, hd) if self.paged
                      else (L, B, n_pos, H, hd))
        #: arrays in the KV-storage pytree: (k, v) pools, plus the two
        #: per-page-row scale arrays under int8 KV quantization
        n_caches = 4 if self.kv_quant == "int8" else 2
        kind = "spec" if k else ("paged" if self.paged else "slab")
        key_tail = ((ps, self.pages_per_slot, self._pool.n_pages, k, Ld,
                     self.kv_quant)
                    if self.paged else ())
        if (NS, LS) != (DEFAULT_STOP_SEQS, DEFAULT_STOP_LEN):
            # non-default stop capacity changes the packed-buffer shapes
            # every program takes; keep the default fn_key unchanged
            key_tail = key_tail + ("stop%dx%d" % (NS, LS),)

        if self.tp > 1:
            # Megatron head/hidden sharding over the mesh's "model"
            # axis: the step body runs inside shard_map on LOCAL weight
            # shards (passed as an argument pytree — constants cannot
            # shard), with the KV pools split on their head dim.
            if H % self.tp:
                raise ValueError(
                    f"tensor parallelism {self.tp} must divide "
                    f"n_heads={H}")
            for li, (_, _, _, lin1, _) in enumerate(handles.blocks):
                hidden = int(lin1["weight"].shape[0])
                if hidden % self.tp:
                    raise ValueError(
                        f"tensor parallelism {self.tp} must divide the "
                        f"FFN hidden dim ({hidden}, block {li})")
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            from bigdl_tpu.parallel import compat

            ax = "model"
            wspec = _tp_weight_specs(handles, ax)
            # weights pinned to the mesh ONCE, pre-sharded per the spec:
            # passing host arrays each step would re-ship the whole
            # model H2D per decode step
            self._W = jax.device_put(
                {"emb": handles.emb, "blocks": handles.blocks,
                 "ln_f": handles.ln_f, "head": handles.head},
                jax.tree_util.tree_map(
                    lambda sp: NamedSharding(mesh, sp), wspec))
            # head dim: the pools shard their H axis (dim 3 of both the
            # 5-d value pools AND the 4-d per-page-row scale arrays —
            # scales are per-head exactly so they shard with zero
            # cross-shard traffic, quant/kv.py)
            cache = P(None, None, None, ax)
            cspec = (cache,) * n_caches
            rep = P()
            H_local = H // self.tp

            def _local(W):
                return handles._replace(
                    mods=None, emb=W["emb"], blocks=W["blocks"],
                    ln_f=W["ln_f"], head=W["head"], n_heads=H_local)

        else:
            self._W = None

        # ---- step-program cache -------------------------------------------
        # Paged decoders hold ONE step program per (view-horizon bucket,
        # attention-kernel flag state) instead of a single program:
        #
        # * View-horizon buckets (the pure-XLA micro-opt): the gathered
        #   attention view only needs the pages the CURRENT live set can
        #   reach (max in-use ptab run), not every reserved page — but
        #   the gather width is a static shape, so the horizon is
        #   bucketed to a short pow2 ladder ending at the full
        #   reservation and each bucket gets its own program.  All
        #   buckets are warmed at construction (zero-cold-compile).
        # * Attention-kernel flag state: `transformer._PALLAS_PAGED_ATTN`
        #   / `_PALLAS_SPEC_VERIFY` are read at TRACE time, so a flip on
        #   a warm decoder must select a DIFFERENT program — flag state
        #   rides the fn_key and programs for non-default states build
        #   lazily at the first boundary that needs them (exactly the
        #   expected new compiles once, zero on later waves — pinned by
        #   the jit-trap audit in tests/test_paged_attention.py).
        if self.paged:
            # two-point ladder {1, full}: the single-page bucket owns
            # the common low-latency case (short live set on a big
            # reservation) and every bucket costs one warm step compile
            # per decoder, so the ladder stays deliberately short
            self._view_buckets = sorted({1, self.pages_per_slot})
        else:
            self._view_buckets = [None]

        base_key = ("decode_step_" + kind, fp, B, n_pos) + key_tail

        def _build_step(view_w, flag_state):
            key = base_key
            if view_w is not None and view_w != self.pages_per_slot:
                key = key + ("view%d" % view_w,)
            if any(f != "False" for f in flag_state):
                key = key + ("attn:" + "/".join(flag_state),)
            if self.tp > 1:
                if k:
                    def step_tp(W, *st):
                        local = _local(W)
                        return spec_step_body(local, _draft_of(local),
                                              *st, tp_axis=ax,
                                              view_pages=view_w)
                    n_rep_in, n_rep_out = 16, 5
                elif self.paged:
                    def step_tp(W, *st):
                        return paged_step_body(_local(W), *st,
                                               tp_axis=ax,
                                               view_pages=view_w)
                    n_rep_in, n_rep_out = 15, 4
                else:
                    def step_tp(W, *st):
                        return slab_step_body(_local(W), *st, tp_axis=ax)
                    n_rep_in, n_rep_out = 13, 4
                sharded = compat.shard_map(
                    step_tp, mesh=mesh,
                    in_specs=(wspec, cspec) + (rep,) * n_rep_in,
                    out_specs=(cspec,) + (rep,) * n_rep_out)
                return xcache.tracked_jit(
                    sharded, key + ("tp%d" % self.tp,), mesh=mesh)
            if k:
                def step(*st):
                    return spec_step_body(handles, _draft_of(handles),
                                          *st, view_pages=view_w)
            elif self.paged:
                def step(*st):
                    return paged_step_body(handles, *st,
                                           view_pages=view_w)
            else:
                def step(*st):
                    return slab_step_body(handles, *st)
            return xcache.tracked_jit(step, key)

        self._build_step = _build_step
        self._step_programs = {}
        # the full-reservation default-flag program: the flops-ledger
        # anchor for decode_model_flops_util, and the widest warm step
        self._step = self._step_program(self._view_buckets[-1])

        def _admit_sampling(temp, topk, topp, keys, stop_buf, stop_len,
                            finished, slot, t_v, k_v, p_v, key_row,
                            sb_row, sl_row):
            """The per-slot sampling-state half of admission (shared by
            both layouts): load the request's params/key/stop rows and
            clear the stop-finished flag."""
            temp = temp.at[slot].set(t_v)
            topk = topk.at[slot].set(k_v)
            topp = topp.at[slot].set(p_v)
            keys = keys.at[slot].set(key_row)
            stop_buf = stop_buf.at[slot].set(sb_row)
            stop_len = stop_len.at[slot].set(sl_row)
            finished = finished.at[slot].set(False)
            return temp, topk, topp, keys, stop_buf, stop_len, finished

        if self.paged:
            def admit(ptab, pos, active, seeds, seed_len, cap, gen,
                      temp, topk, topp, keys, stop_buf, stop_len,
                      finished, slot, ptab_row, start, seed_row, s_len,
                      capv, t_v, k_v, p_v, key_row, sb_row, sl_row):
                ptab = ptab.at[slot].set(ptab_row)
                pos = pos.at[slot].set(start)
                active = active.at[slot].set(True)
                seeds = seeds.at[slot].set(seed_row)
                seed_len = seed_len.at[slot].set(s_len)
                cap = cap.at[slot].set(capv)
                gen = gen.at[slot].set(0)
                return (ptab, pos, active, seeds, seed_len, cap, gen
                        ) + _admit_sampling(
                            temp, topk, topp, keys, stop_buf, stop_len,
                            finished, slot, t_v, k_v, p_v, key_row,
                            sb_row, sl_row)

            def retire(ptab, active, slot):
                # frozen rows' K/V writes are valid-gated out, so the
                # table reset is hygiene: freed pages stop being
                # gathered into this slot's (masked) attention view
                return ptab.at[slot].set(0), active.at[slot].set(False)
        else:
            def admit(caches, pos, active, seeds, seed_len, gen,
                      temp, topk, topp, keys, stop_buf, stop_len,
                      finished, slot, seed_row, s_len, t_v, k_v, p_v,
                      key_row, sb_row, sl_row):
                kc, vc = caches
                kc = kc.at[:, slot].set(0.0)
                vc = vc.at[:, slot].set(0.0)
                pos = pos.at[slot].set(0)
                active = active.at[slot].set(True)
                seeds = seeds.at[slot].set(seed_row)
                seed_len = seed_len.at[slot].set(s_len)
                gen = gen.at[slot].set(0)
                return ((kc, vc), pos, active, seeds, seed_len, gen
                        ) + _admit_sampling(
                            temp, topk, topp, keys, stop_buf, stop_len,
                            finished, slot, t_v, k_v, p_v, key_row,
                            sb_row, sl_row)

            def retire(active, slot):
                return active.at[slot].set(False)

        if self.tp > 1:
            # admit/retire ride the SAME shard_map layout as the step:
            # mixing plain-jit programs into the carry chain would hand
            # the step differently-placed inputs on some paths and cost
            # a silent recompile per (program, sharding) combination
            from bigdl_tpu.parallel import compat
            cache, rep = P(None, None, None, "model"), P()
            if self.paged:
                admit = compat.shard_map(
                    admit, mesh=mesh, in_specs=(rep,) * 26,
                    out_specs=(rep,) * 14)
                retire = compat.shard_map(
                    retire, mesh=mesh, in_specs=(rep,) * 3,
                    out_specs=(rep, rep))
            else:
                admit = compat.shard_map(
                    admit, mesh=mesh,
                    in_specs=((cache, cache),) + (rep,) * 21,
                    out_specs=((cache, cache),) + (rep,) * 12)
                retire = compat.shard_map(retire, mesh=mesh,
                                          in_specs=(rep, rep),
                                          out_specs=rep)
        self._admit_fn = xcache.tracked_jit(
            admit, ("decode_admit_" + kind, fp, B, n_pos) + key_tail,
            mesh=mesh)
        self._retire_fn = xcache.tracked_jit(
            retire, ("decode_retire_" + kind, fp, B) + key_tail,
            mesh=mesh)

        # page re-admit program (host-tier H2D / shipped-prefill
        # adoption): write one host page payload into pool page ``pid``
        # across every cache array.  ``pid`` is traced, the payload
        # shapes are fixed, so it compiles ONCE at construction and
        # re-admits never cold-compile mid-stream.
        self._readmit_fn = None
        if self.paged and (self._tier is not None or prefill_adopt):
            def readmit(caches, pid, payload):
                return tuple(c.at[:, pid].set(p)
                             for c, p in zip(caches, payload))
            if self.tp > 1:
                from bigdl_tpu.parallel import compat
                cache, rep = P(None, None, None, "model"), P()
                # payload dims mirror a page slice: values (L, ps, H,
                # hd), scales (L, ps, H) — the head dim shards exactly
                # like the pools, so adoption ships zero cross-shard
                pay = tuple(
                    (P(None, None, "model", None) if i < 2
                     else P(None, None, "model"))
                    for i in range(n_caches))
                readmit = compat.shard_map(
                    readmit, mesh=mesh,
                    in_specs=((cache,) * n_caches, rep, pay),
                    out_specs=(cache,) * n_caches)
            self._readmit_fn = xcache.tracked_jit(
                readmit,
                ("decode_readmit_" + kind, fp, B, n_pos) + key_tail,
                mesh=mesh)

        z = jnp.zeros
        if self.kv_quant == "int8":
            # int8 pools + per-page-row per-head scale arrays; a fresh
            # page's stale rows are never read before their overwrite
            # (same masked-read argument as the fp pool), so zero-init
            # scales are only ever paired with zero-init values
            sshape = kvq.scale_shape(pool_shape)
            self._caches = (z(pool_shape, jnp.int8),
                            z(pool_shape, jnp.int8),
                            z(sshape, jnp.float32),
                            z(sshape, jnp.float32))
        else:
            self._caches = (z(pool_shape, jnp.float32),
                            z(pool_shape, jnp.float32))
        self._pos = z((B,), jnp.int32)
        self._prev = z((B,), jnp.int32)
        self._active = z((B,), bool)
        self._seeds = z((B, n_view), jnp.int32)
        self._seed_len = z((B,), jnp.int32)
        self._gen = z((B, n_view), jnp.int32)
        # per-slot traced sampling state (zeros = the greedy default:
        # temp 0 selects the argmax lane, stop_len 0 never matches)
        self._temp = z((B,), jnp.float32)
        self._topk = z((B,), jnp.int32)
        self._topp = z((B,), jnp.float32)
        self._keys = z((B, 2), jnp.uint32)
        self._stop_buf = z((B, self.max_stop_seqs, self.max_stop_len),
                           jnp.int32)
        self._stop_len = z((B, self.max_stop_seqs), jnp.int32)
        self._finished = z((B,), bool)
        if self.paged:
            self._ptab = z((B, self.pages_per_slot), jnp.int32)
            # capacity starts at one page so clips/masks stay in range
            # for never-admitted slots; admit sets the real value
            self._cap = jnp.full((B,), ps, jnp.int32)
        if k:
            self._acc_hist = z((k + 1,), jnp.int32)
            self._acc_seen = np.zeros((k + 1,), np.int64)
            # host-side copy of the acceptance-length counts (warm pass
            # excluded) — stats()/bench read p50 from here without
            # touching the registry
            self._accept_counts = np.zeros((k + 1,), np.int64)

        self._pending: "deque[_DecodeReq]" = deque()
        self._slots: list = [None] * B

        # telemetry: mirrored into the mergeable metrics registry
        # (labelled decoder=<name>) so slot occupancy and throughput
        # show up in the fleet exporter next to the engine numbers
        from bigdl_tpu.obs import metrics as obs_metrics
        # fleet replicas pass an explicit name so per-replica decoder
        # series stay attributable after the child-registry merge
        self.name = name or f"decoder{next(_DECODER_SEQ)}"
        self._flags_cache = None   # decode_flags() memo
        #: optional WeightStore version this decoder serves — set by
        #: whoever snapshotted the weights (a decode replica has no
        #: rollout machinery of its own); the flight recorder notes it
        #: per request so tools/request_replay.py can pin the exact
        #: served weights
        self.weights_version = None
        reg = obs_metrics.get()
        lab = {"decoder": self.name}
        self._m_steps = reg.counter(
            "decode_steps_total", "decode steps driven", **lab)
        self._m_admitted = reg.counter(
            "decode_admitted_total", "requests admitted into slots", **lab)
        self._m_retired = reg.counter(
            "decode_retired_total", "requests retired from slots", **lab)
        self._m_syncs = reg.counter(
            "decode_host_syncs_total", "boundary device->host fetches",
            **lab)
        self._m_slots = reg.gauge(
            "decode_slots_active", "occupied decode slots", **lab)
        self._m_slots_hwm = reg.gauge(
            "decode_slots_hwm", "live-request high-water mark",
            agg="max", **lab)
        #: KV bytes one pooled token costs across all layers (scales
        #: included under int8 KV quant) — the density lever the
        #: quantized pool pulls (docs/observability.md)
        self.kv_bytes_per_token = kvq.bytes_per_token(
            L, H, hd, self.kv_quant)
        reg.gauge("decode_kv_bytes_per_token",
                  "KV bytes per pooled token incl. scales",
                  **lab).set(self.kv_bytes_per_token)
        #: live decode utilization (docs/observability.md "Performance
        #: observatory"): ledger flops of the compiled step program x
        #: step rate over the boundary window / datasheet peak — set
        #: once per sync boundary, never per token
        self._m_util = reg.gauge(
            "decode_model_flops_util",
            "model flops utilization of the decode step over the last "
            "sync-boundary window", agg="max", **lab)
        self._m_toks = reg.gauge(
            "decode_tokens_per_s",
            "committed tokens per second over the last sync-boundary "
            "window", **lab)
        if self.paged:
            self._m_pages = reg.gauge(
                "decode_pages_in_use", "allocated KV pool pages", **lab)
            reg.gauge("decode_pages_total", "KV pool size in pages",
                      **lab).set(self._pool.n_pages)
            self._m_pfx_hit = reg.counter(
                "decode_prefix_hits_total",
                "requests admitted with >=1 cached prefix page", **lab)
            self._m_pfx_miss = reg.counter(
                "decode_prefix_misses_total",
                "requests admitted with no cached prefix page", **lab)
            self._m_pfx_pages = reg.counter(
                "decode_prefix_pages_total",
                "prefill pages served from the prefix cache", **lab)
        if k:
            self._m_accept = reg.histogram(
                "decode_spec_accept_len",
                "accepted draft tokens per speculative window",
                bounds=obs_metrics.SPEC_ACCEPT_BUCKETS, **lab)
        # streaming SLO surface (docs/observability.md "Streaming
        # telemetry"): TTFT on the shared LATENCY_BUCKETS, ITL on the
        # finer ITL_BUCKETS (on-chip inter-token gaps sit well below
        # the 100 µs latency floor) — both fleet-mergeable
        self._m_ttft = reg.histogram(
            "decode_ttft_seconds",
            "submit-to-first-streamed-token latency", **lab)
        self._m_itl = reg.histogram(
            "decode_itl_seconds",
            "inter-token gap of streamed tokens (per-token, averaged "
            "within a boundary)", bounds=obs_metrics.ITL_BUCKETS, **lab)
        self._m_stream_toks = reg.counter(
            "decode_stream_tokens_total",
            "tokens delivered incrementally at sync boundaries", **lab)
        # sampled decode + stop-sequence early retirement
        # (docs/observability.md "Sampled decode")
        self._m_sampled = reg.counter(
            "decode_sampled_total",
            "sampled (temperature > 0) requests admitted", **lab)
        self._m_stop_retired = reg.counter(
            "decode_stop_retired_total",
            "requests retired early on a stop-sequence match", **lab)
        self._m_steps_saved = reg.counter(
            "decode_steps_saved_total",
            "decode step-slots reclaimed by stop-sequence early "
            "retirement", **lab)
        # directly-constructed decoders (the TP-serving entry point)
        # may never see close() — drop the uniquely-labelled series at
        # GC so the process registry cannot grow without bound, and
        # stop the lazily created delivery thread (the box is filled by
        # _ensure_delivery; a finalizer must not reference self)
        self._delivery_box: list = []
        self._drop_series = weakref.finalize(
            self, _decoder_gc_cleanup, reg, self.name,
            self._delivery_box)
        self.steps = 0
        self.host_syncs = 0
        self.admitted = 0
        self.retired = 0
        self.live_hwm = 0
        self.spec_windows = 0
        self.spec_accepted = 0
        self.sampled = 0           # admitted requests with temp > 0
        self.stop_retired = 0      # requests retired on a stop match
        self.steps_saved = 0       # step-slots reclaimed by early retire
        # streaming lifetime aggregates (stats() / emit_decode_event)
        self.streams = 0           # requests that streamed >= 1 token
        self.stream_tokens = 0
        #: DISTINCT sync boundaries that delivered tokens to at least
        #: one stream (per-request boundary counts live on the
        #: `stream` events' timelines)
        self.stream_boundaries = 0
        self._ttft_sum = 0.0
        self._req_seq = itertools.count(1)
        #: lazy dedicated delivery thread — consumer callbacks and
        #: streaming-future resolution run there, never the step loop
        self._delivery = None

        self._warm()

        # cost truth for the utilization gauge: the step program's
        # compile-time ledger capture (its tracked_jit key), plus the
        # KV pool's static HBM tenant entry — both labelled with this
        # decoder's name so close()'s drop_series reclaims them
        from bigdl_tpu.obs import ledger as obs_ledger
        self._step_flops = obs_ledger.get().flops_for(self._step.fn_key)
        self._peak_flops = obs_ledger.device_peak_flops()
        self._util_t_last = time.perf_counter()
        obs_ledger.note_tenant(
            "kv_pool", sum(obs_ledger.tree_nbytes(c)
                           for c in self._caches),
            decoder=self.name, paged=self.paged, kv_quant=self.kv_quant)

    # -- compiled-program drivers -------------------------------------------
    def _attn_flag_state(self):
        """Current attention-kernel flag state, as the fn_key fragment
        that selects a step program.  Slab decoders never page, so the
        flags cannot affect their program; spec decoders contain both
        the S=1 draft steps and the S=k+1 verify window, so both flags
        select."""
        if not self.paged:
            return ()
        from bigdl_tpu.models import transformer as _tf
        if self.spec_k:
            return (str(_tf._PALLAS_PAGED_ATTN),
                    str(_tf._PALLAS_SPEC_VERIFY))
        return (str(_tf._PALLAS_PAGED_ATTN),)

    def _view_horizon_bucket(self):
        """Smallest warmed view bucket covering every live slot's page
        reservation (the max in-use ptab run).  Idle decoders step at
        the cheapest bucket."""
        live = max((len(r.pages) for r in self._slots if r is not None),
                   default=1)
        for w in self._view_buckets:
            if w >= live:
                return w
        return self._view_buckets[-1]

    def _step_program(self, view_w=None):
        if view_w is None:
            view_w = (self._view_horizon_bucket() if self.paged
                      else self._view_buckets[-1])
        flag_state = self._attn_flag_state()
        sel = (view_w, flag_state)
        prog = self._step_programs.get(sel)
        if prog is None:
            prog = self._build_step(view_w, flag_state)
            self._step_programs[sel] = prog
        return prog

    def _run_step(self, view_w=None):
        if self.paged:
            args = (self._caches, self._ptab, self._pos,
                    self._prev, self._active, self._seeds,
                    self._seed_len, self._cap, self._gen)
        else:
            args = (self._caches, self._pos, self._prev,
                    self._active, self._seeds, self._seed_len, self._gen)
        args = args + (self._temp, self._topk, self._topp, self._keys,
                       self._stop_buf, self._stop_len, self._finished)
        if self.spec_k:
            args = args + (self._acc_hist,)
        if self._W is not None:
            args = (self._W,) + args
        out = self._step_program(view_w)(*args)
        if self.spec_k:
            (self._caches, self._pos, self._prev, self._gen,
             self._finished, self._acc_hist) = out
        else:
            (self._caches, self._pos, self._prev, self._gen,
             self._finished) = out

    def _sampling_rows(self, req):
        """Host-built admit operands for the request's sampling state:
        scalar params, the threefry key row, and the right-aligned
        packed stop buffers (submit() already validated capacity)."""
        p = req.params
        NS, LS = self.max_stop_seqs, self.max_stop_len
        sb_row = np.zeros((NS, LS), np.int32)
        sl_row = np.zeros((NS,), np.int32)
        for j, seq in enumerate(p.stop):
            sb_row[j, LS - len(seq):] = seq
            sl_row[j] = len(seq)
        return (np.float32(p.temperature), np.int32(p.top_k),
                np.float32(p.top_p), smp.key_data(p.seed), sb_row,
                sl_row)

    def _apply_admit(self, slot, req):
        seed_row = np.zeros((self._n_view,), np.int32)
        seed_row[:len(req.seed)] = req.seed
        samp = self._sampling_rows(req)
        state = (self._temp, self._topk, self._topp, self._keys,
                 self._stop_buf, self._stop_len, self._finished)
        if self.paged:
            row = np.zeros((self.pages_per_slot,), np.int32)
            row[:len(req.pages)] = req.pages
            (self._ptab, self._pos, self._active, self._seeds,
             self._seed_len, self._cap, self._gen, self._temp,
             self._topk, self._topp, self._keys, self._stop_buf,
             self._stop_len, self._finished) = self._admit_fn(
                self._ptab, self._pos, self._active, self._seeds,
                self._seed_len, self._cap, self._gen, *state,
                np.int32(slot), row, np.int32(req.start_pos), seed_row,
                np.int32(len(req.seed)),
                np.int32(len(req.pages) * self.page_size), *samp)
        else:
            (self._caches, self._pos, self._active, self._seeds,
             self._seed_len, self._gen, self._temp, self._topk,
             self._topp, self._keys, self._stop_buf, self._stop_len,
             self._finished) = self._admit_fn(
                self._caches, self._pos, self._active, self._seeds,
                self._seed_len, self._gen, *state, np.int32(slot),
                seed_row, np.int32(len(req.seed)), *samp)

    def _apply_retire(self, slot):
        if self.paged:
            self._ptab, self._active = self._retire_fn(
                self._ptab, self._active, np.int32(slot))
        else:
            self._active = self._retire_fn(self._active, np.int32(slot))

    def _warm(self):
        """Pre-compile the step/admit/retire programs at construction so
        admission and decode never hit a cold compile (the serving
        zero-cold-compile property, docs/serving.md).

        The warm pass cycles the REAL state machine once — step on the
        fresh state, admit into slot 0, step on the admit outputs,
        retire, step again — keeping each program's outputs as the live
        state, so every (shape, sharding) combination the serving loop
        will feed each program is compiled here and not mid-stream (jit
        caches per input sharding; under TP the shard_map step and the
        admit/retire programs produce differently-placed carries).  The
        warm admission maps slot 0 at pool page 0 with a one-page
        capacity; whatever K/V it writes there is overwritten
        position-by-position by the page's next real owner before any
        masked-in read."""
        warm = _DecodeReq([0], 1)
        warm.pages = [0] if self.paged else []
        # every view-horizon bucket compiles here (widest first — the
        # fresh host-placed state combo — then the rest on the carried
        # device state, the only placement serving ever feeds them)
        for w in reversed(self._view_buckets):
            self._run_step(view_w=w)
        for _ in range(2):
            # twice: the first admission's carries are the fresh
            # host-placed state, every later admission's are program
            # outputs — both placement combinations must compile now
            self._apply_admit(0, warm)
        self._run_step()
        self._apply_retire(0)
        if self._readmit_fn is not None:
            # the readmit warm writes zeros into page 0 — unallocated at
            # construction, and overwritten position-by-position by its
            # next real owner before any masked-in read (same argument
            # as the warm admission above)
            self._caches = self._readmit_fn(
                self._caches, np.int32(0), self._zero_page_payload())
        self._run_step()
        if self.spec_k:
            # the warm pass ran live speculative windows; exclude them
            # from the acceptance histogram — they judged garbage
            self._acc_seen = np.asarray(self._acc_hist, np.int64)

    # -- host tier + shipped-prefill adoption -------------------------------
    def _page_payload_shape(self, cache) -> tuple:
        """Host payload shape for one pool array's page slice
        (``pool[:, pid]`` — the page dim removed)."""
        return tuple(cache.shape[:1]) + tuple(cache.shape[2:])

    def _zero_page_payload(self) -> tuple:
        return tuple(np.zeros(self._page_payload_shape(c), c.dtype)
                     for c in self._caches)

    def _payload_ok(self, payload) -> bool:
        if len(payload) != len(self._caches):
            return False
        return all(tuple(p.shape) == self._page_payload_shape(c)
                   and p.dtype == c.dtype
                   for c, p in zip(self._caches, payload))

    def _spill_page(self, key, pid):
        """Prefix-cache ``on_evict`` intercept: snapshot the evicted
        page as cheap on-device slices and enqueue them for the tier's
        writer thread (the async-checkpoint pattern — eviction runs on
        the admission path and must not pay a blocking D2H).  The
        slices are functional arrays, so the pool page's next owner can
        never corrupt what was spilled."""
        self._tier.spill(key, tuple(c[:, pid] for c in self._caches))

    def _extend_from_tier(self, seed, shared) -> int:
        """Continue an admission's chain walk past the device cache:
        for each further chain key, prefer a (stranded) device-cache
        entry, else re-admit the host tier's copy H2D through the
        compiled re-admit program and register it back in the prefix
        cache.  Extends ``shared`` in place (every appended page id is
        retained for the slot); returns the number of tier re-admits."""
        ps = self.page_size
        max_pages = max(0, (len(seed) - 1) // ps)
        if len(shared) >= max_pages:
            return 0
        keys = list(chain_keys(seed, max_pages, ps))
        n = 0
        for j in range(len(shared), max_pages):
            pid = self._prefix.lookup(keys[j])   # retained for the slot
            if pid is not None:
                shared.append(pid)
                continue
            payload = self._tier.lookup(keys[j])
            if payload is None:
                break
            t0 = time.perf_counter()
            pids = self._alloc_pages(1)
            if pids is None:
                break
            pid = pids[0]
            self._caches = self._readmit_fn(
                self._caches, np.int32(pid),
                tuple(np.asarray(p) for p in payload))
            self._prefix.adopt(keys[j], pid)     # the cache's reference
            self._pool.retain(pid)               # the slot's reference
            shared.append(pid)
            n += 1
            self._tier.note_readmit(1, time.perf_counter() - t0)
        return n

    def adopt_pages(self, seed, payloads) -> int:
        """Adopt KV pages shipped by a prefill replica
        (``serve/fleet.py``): ``payloads[j]`` is the tuple of host
        arrays for the page holding positions ``j*ps .. (j+1)*ps - 1``
        computed under ``seed`` — the per-array page slices, int8 +
        scales under KV quantization.  Each page lands in the pool
        through the compiled re-admit program and registers in the
        prefix cache under ``seed``'s chain keys, so the request (and
        every later request sharing the prefix) admits with a prefix
        hit instead of a cold prefill.

        Best-effort by design: adoption needs ``prefill_adopt=True``
        (or an attached host tier) and payloads matching this pool's
        page shape/dtype — on any mismatch or pool pressure it adopts
        what it can and returns; the request still decodes correctly
        via colocated prefill.  Returns the number of NEWLY adopted
        pages."""
        if (not self.paged or self._prefix is None
                or self._readmit_fn is None or not payloads):
            return 0
        ps = self.page_size
        n_pages = min(len(payloads), max(0, (len(seed) - 1) // ps))
        adopted = 0
        for key, payload in zip(chain_keys(seed, n_pages, ps), payloads):
            payload = tuple(np.asarray(p) for p in payload)
            if not self._payload_ok(payload):
                logger.warning(
                    "adopt_pages: shipped payload does not match this "
                    "pool's page shape/dtype (prefill kv_quant drift?); "
                    "serving via colocated prefill")
                break
            if self._prefix.has(key):
                continue             # already resident — chain intact
            pids = self._alloc_pages(1)
            if pids is None:
                break                # pool pressure: partial adoption
            self._caches = self._readmit_fn(
                self._caches, np.int32(pids[0]), payload)
            self._prefix.adopt(key, pids[0])
            adopted += 1
        return adopted

    # -- submit -------------------------------------------------------------
    def submit(self, seed_ids, n_words: int, trace=None,
               sampling=None) -> StreamFuture:
        """Queue one request; the future resolves to the full token row
        (seed + up to ``n_words`` generated ids) — exactly
        ``lm_decode``'s greedy output for the same seed by default.  A
        request that cannot ever fit fails ONLY its own future with
        :class:`RequestTooLongError` — other submitted requests are
        untouched.

        ``sampling`` (a :class:`~bigdl_tpu.serve.sampling.SamplingParams`,
        a dict in its ``to_dict`` form, or None for greedy) selects the
        sampled lane: temperature/top-k/top-p draws keyed by the
        request's (resolved) seed, stop token-sequences that retire the
        request early at the boundary after a match — the row then ends
        just past the matched sequence, shorter than ``n_words`` — and
        ``max_tokens`` capping ``n_words``.  A stop list exceeding this
        decoder's packed capacity (``max_stop_seqs`` × ``max_stop_len``)
        fails its own future with ``ValueError``.

        The returned :class:`~bigdl_tpu.serve.streaming.StreamFuture`
        additionally streams: ``on_tokens(cb)`` (or ``request_stream``)
        turns on incremental delivery of the generated tokens at each
        sync boundary, byte-identical to the resolved row's tail.
        ``trace`` (an ``obs.trace.Trace``) gains ``decode_admit`` /
        ``first_token`` / ``retire`` hops as the request moves."""
        seed = np.asarray(seed_ids, np.int32)
        if seed.ndim != 1 or seed.size == 0:
            raise ValueError("seed_ids must be one flat non-empty id row")
        if n_words < 1:
            raise ValueError("n_words must be >= 1")
        params = smp.SamplingParams.of(sampling).resolved()
        if params.max_tokens is not None:
            n_words = min(int(n_words), params.max_tokens)
        req = _DecodeReq(seed.tolist(), n_words, trace=trace,
                         params=params)
        req.rid = next(self._req_seq)
        if trace is not None:
            # flight-recorder identity: everything request_replay needs
            # to rebuild an equivalent decoder for this request (plain
            # host dict merges — the device is never touched)
            obs_recorder.note(
                trace.trace_id, rid=f"{self.name}/{req.rid}",
                decoder=self.name,
                seed_hash=obs_recorder.seed_hash(req.seed),
                seed_len=len(req.seed), n_words=req.n_words,
                flags=self.decode_flags(),
                weights_version=self.weights_version)
            if not params.is_default:
                # the resolved params (seed pinned) — what replay
                # re-submits to redraw the exact token stream
                obs_recorder.note(trace.trace_id,
                                  sampling=params.to_dict())
        if (len(params.stop) > self.max_stop_seqs
                or any(len(s) > self.max_stop_len for s in params.stop)):
            req.future.set_exception(ValueError(
                f"stop list exceeds this decoder's packed capacity "
                f"({self.max_stop_seqs} sequences x "
                f"{self.max_stop_len} tokens); raise max_stop_seqs/"
                f"max_stop_len at construction"))
            return req.future
        too_long = req.steps_needed > self.n_pos
        if self.paged and not too_long:
            too_long = (_pages_needed(req.steps_needed, self.page_size)
                        > self._pool.n_pages)
        if too_long:
            req.future.set_exception(RequestTooLongError(
                f"request needs {req.steps_needed} positions "
                f"(len(seed)={len(req.seed)} + n_words={req.n_words} - 1)"
                f" but this decoder holds n_pos={self.n_pos}"
                + (f" across {self._pool.n_pages} pages of "
                   f"{self.page_size}" if self.paged else "")
                + "; raise n_pos/the pool or split the request"))
            return req.future
        self._pending.append(req)
        return req.future

    # -- drive --------------------------------------------------------------
    def _alloc_pages(self, n):
        """``n`` fresh pool pages, evicting cache-only prefix pages on
        demand (one LRU scan per attempt); None when the pool cannot
        satisfy the request yet."""
        short = n - self._pool.free_count
        if short > 0 and (self._prefix is None
                          or self._prefix.evict(short) < short):
            return None
        return [self._pool.alloc_one() for _ in range(n)]

    def _try_admit_paged(self, req) -> bool:
        shared = (self._prefix.match(req.seed)
                  if self._prefix is not None else [])
        if self._tier is not None:
            # a failed admission leaves tier re-admits in the prefix
            # cache (content already written) — the retry matches them
            self._extend_from_tier(req.seed, shared)
        total = _pages_needed(req.steps_needed, self.page_size)
        fresh = self._alloc_pages(total - len(shared))
        if fresh is None:
            for pid in shared:
                self._pool.release(pid)
            return False
        req.pages = shared + fresh
        req.start_pos = len(shared) * self.page_size
        if self._prefix is not None:
            self._prefix.note_request(len(shared))
            (self._m_pfx_hit if shared else self._m_pfx_miss).inc()
            if shared:
                self._m_pfx_pages.inc(len(shared))
        return True

    def _admit_waiting(self):
        for slot in range(self.B):
            if self._slots[slot] is not None or not self._pending:
                continue
            req = self._pending[0]
            if self.paged and not self._try_admit_paged(req):
                break   # head-of-line: wait for retirements to free pages
            self._pending.popleft()
            req.slot = slot
            self._apply_admit(slot, req)
            self._slots[slot] = req
            req.t_admit = time.perf_counter()
            if req.trace is not None:
                req.trace.stamp("decode_admit", req.t_admit)
                if self.paged:
                    # page/prefix counters at admission (already on the
                    # host — _try_admit_paged computed them)
                    obs_recorder.note(
                        req.trace.trace_id, start_pos=req.start_pos,
                        kv_pages=len(req.pages),
                        prefix_pages=req.start_pos // self.page_size)
            self.admitted += 1
            self._m_admitted.inc()
            if not req.params.greedy:
                self.sampled += 1
                self._m_sampled.inc()
        if self.paged:
            self._m_pages.set(self._pool.in_use)

    def _retire_req(self, req):
        self._apply_retire(req.slot)
        if self.paged:
            donate = 0
            if self._prefix is not None:
                # donate the full pages inside the seed: their K/V is a
                # pure function of the seed prefix, so the next request
                # sharing it skips that much prefill (ownership moves to
                # the cache — no copy; already-shared pages just drop
                # this slot's reference)
                donate = min(len(req.seed) // self.page_size,
                             len(req.pages))
                self._prefix.insert(req.seed, req.pages[:donate])
            for pid in req.pages[donate:]:
                self._pool.release(pid)
            self._m_pages.set(self._pool.in_use)
        self._slots[req.slot] = None
        self.retired += 1
        self._m_retired.inc()

    def _drain_accept_hist(self):
        """Fold the device-accumulated acceptance-length vector into the
        registry histogram (bulk bucket adds — one tiny fetch per
        boundary, never one observation per window)."""
        cur = np.asarray(self._acc_hist, np.int64)
        delta = cur - self._acc_seen
        self._acc_seen = cur
        for a, n in enumerate(delta):
            n = int(n)
            if n > 0:
                self._m_accept.observe_n(float(a), n)
                self._accept_counts[a] += n
                self.spec_windows += n
                self.spec_accepted += n * a

    def outstanding(self) -> int:
        """Queued + live requests — the fleet replica's inflight signal."""
        return (len(self._pending)
                + sum(1 for r in self._slots if r is not None))

    def step_boundary(self) -> int:
        """One admit → ``sync_interval``-step window → retire cycle —
        the unit :meth:`run` loops and a fleet decode replica's driver
        thread calls incrementally (``serve/fleet.py``).  Returns the
        number of slots served this boundary (0 = nothing admissible:
        drained, or — defensively — a stalled queue whose futures were
        just failed)."""
        spec = self.spec_k > 0
        w0, a0 = self.spec_windows, self.spec_accepted
        self._admit_waiting()
        live = [r for r in self._slots if r is not None]
        # stop-sequence rows make completion data-dependent exactly like
        # speculative decode: those boundaries fetch the position row
        # (plus the finished flags) — greedy no-stop streams keep the
        # pre-sampling host-sync count
        has_stop = any(r.params.stop for r in live)
        if not live:
            # idle boundary: restart the utilization window so wait
            # time between submissions is not charged to the next one
            self._util_t_last = time.perf_counter()
            if self._pending:   # pragma: no cover - defensive
                # submit() guarantees every queued request can fit an
                # empty pool, so an empty slab with work pending is a
                # bug — fail the futures loudly instead of dropping them
                for req in self._pending:
                    req.future.set_exception(RuntimeError(
                        "decoder stalled with no admissible request"))
                self._pending.clear()
            return 0
        self.live_hwm = max(self.live_hwm, len(live))
        self._m_slots.set(len(live))
        self._m_slots_hwm.set(self.live_hwm)
        for _ in range(self.sync_interval):
            self._run_step()
        self.steps += self.sync_interval
        self._m_steps.inc(self.sync_interval)
        pos_host = fin_host = None
        if spec or has_stop:
            pos_host = np.asarray(self._pos)
            if has_stop:
                # rides the same boundary fetch — ONE host sync
                fin_host = np.asarray(self._finished)
            self.host_syncs += 1
            self._m_syncs.inc()
            if spec:
                self._drain_accept_hist()
        if not spec:
            for r in live:
                r.steps_run += self.sync_interval
        if pos_host is not None:
            done = [r for r in live
                    if int(pos_host[r.slot]) >= r.steps_needed
                    or (fin_host is not None and bool(fin_host[r.slot]))]
        else:
            done = [r for r in live
                    if r.start_pos + r.steps_run >= r.steps_needed]
        # ONE slab materialization per boundary, shared by streaming
        # delivery AND retirement — streaming never adds a second fetch
        # to a boundary, and a boundary with neither live streams nor
        # retirements still fetches nothing (the pre-streaming count)
        streaming = [r for r in live if r.future.streaming]
        gen_host = None
        if done or streaming:
            gen_host = np.asarray(self._gen)   # the boundary host sync
            if not spec:
                self.host_syncs += 1
                self._m_syncs.inc()
        delivered = False
        if streaming:
            ts = time.perf_counter()
            for r in streaming:
                consumed = (int(pos_host[r.slot])
                            if pos_host is not None
                            else r.start_pos + r.steps_run)
                delivered |= self._feed_stream(r, gen_host, consumed,
                                               ts)
        if done:
            ts = time.perf_counter()
            for r in done:
                s = len(r.seed)
                final, n_gen = r.steps_needed, r.n_words
                if pos_host is not None:
                    # stop-retired rows froze early: the row ends just
                    # past the matched sequence (pos overshoot on
                    # normal rows is clipped back to n_words)
                    final = int(pos_host[r.slot])
                    n_gen = max(1, min(r.n_words, final - (s - 1)))
                toks = gen_host[r.slot, s - 1:s - 1 + n_gen]
                row = r.seed + [int(t) for t in toks]
                if n_gen < r.n_words:
                    # stop-sequence early retirement: the slot + pages
                    # free NOW instead of after the row's remaining
                    # step budget — count the reclaimed step-slots
                    r.stop_retired = True
                    saved = r.steps_needed - final
                    self.stop_retired += 1
                    self.steps_saved += saved
                    self._m_stop_retired.inc()
                    self._m_steps_saved.inc(saved)
                if r.trace is not None:
                    # the committed row — request_replay's oracle.
                    # Reuses the boundary's ONE slab materialization;
                    # no added sync, no per-token host work beyond the
                    # row already built for the future
                    obs_recorder.note(r.trace.trace_id, tokens=row)
                    if r.stop_retired:
                        obs_recorder.note(r.trace.trace_id,
                                          stop_retired=True)
                    if self.spec_k:
                        obs_recorder.note(
                            r.trace.trace_id,
                            spec_windows=self.spec_windows,
                            spec_accepted=self.spec_accepted)
                # retire BEFORE resolving: a serial client waiting on
                # this future may submit again the instant it resolves,
                # and the dispatch decision it triggers (least-loaded /
                # affinity, serve/fleet.py) must see this slot free —
                # resolving first leaves a window where outstanding()
                # still counts the finished request (the fleet drill's
                # old flake)
                self._retire_req(r)
                if r.future.streaming:
                    # catch-up (a consumer registered this boundary),
                    # then the stream epilogue; the resolution rides
                    # the delivery FIFO so the final chunk is always
                    # delivered before result() unblocks.  The catch-up
                    # bound is the row's ACTUAL final consumption — a
                    # stop-retired stream must never over-deliver past
                    # its truncation point
                    delivered |= self._feed_stream(
                        r, gen_host, min(final, r.steps_needed), ts)
                    self._finish_stream(r, ts)
                    self._ensure_delivery().resolve(r.future, row)
                else:
                    r.future.set_result(row)
            self._m_slots.set(sum(1 for r in self._slots
                                  if r is not None))
        if delivered:
            self.stream_boundaries += 1
        if spec:
            # a speculative window commits its accepted drafts plus the
            # verify token — both counters were drained this boundary
            tokens = ((self.spec_windows - w0)
                      + (self.spec_accepted - a0))
        else:
            tokens = len(live) * self.sync_interval
        self._note_util(tokens)
        return len(live)

    # -- streaming delivery -------------------------------------------------
    def _ensure_delivery(self) -> TokenDelivery:
        if self._delivery is None:
            self._delivery = TokenDelivery(name=self.name)
            self._delivery_box.append(self._delivery)
        return self._delivery

    def _feed_stream(self, req, gen_host, consumed: int,
                     ts: float) -> bool:
        """Deliver the tokens that became visible this boundary for one
        streaming request: everything generated past what was already
        delivered, read from the boundary's ONE slab materialization.
        Stamps the request timeline and the TTFT/ITL histograms; the
        actual consumer callbacks run on the delivery thread.
        Idempotent per boundary (``streamed`` only grows); returns
        whether anything was delivered."""
        s = len(req.seed)
        avail = min(int(consumed), req.steps_needed) - (s - 1)
        new = avail - req.streamed
        if new <= 0:
            return False
        toks = [int(t) for t in
                gen_host[req.slot, s - 1 + req.streamed:s - 1 + avail]]
        start = req.streamed
        req.streamed = avail
        if req.first_ts is None:
            req.first_ts = ts
            self.streams += 1
            self._m_ttft.observe(ts - req.t_submit)
            self._ttft_sum += ts - req.t_submit
            if req.trace is not None:
                req.trace.stamp("first_token", ts)
        else:
            # per-token gaps, averaged within the boundary: n tokens
            # landing dt after the previous delivery are n observations
            # of dt/n (co-delivered tokens share the window; the first
            # boundary's tokens belong to TTFT, not ITL)
            gap = ts - req.last_ts
            if gap > 0:
                self._m_itl.observe_n(gap / new, new)
        req.last_ts = ts
        req.timeline.append((ts, new))
        self.stream_tokens += new
        self._m_stream_toks.inc(new)
        self._ensure_delivery().enqueue(req.future, toks, start, ts)
        return True

    def _finish_stream(self, req, ts: float):
        """The per-request stream epilogue at retire: the ``retire``
        trace hop and one ``stream`` obs event carrying the token
        timeline (admit → first token → per-boundary counts → retire)
        — what the obs_report token waterfall renders."""
        if req.trace is not None:
            req.trace.stamp("retire", ts)
        if req.first_ts is None:   # pragma: no cover - n_words >= 1
            return
        from bigdl_tpu.obs import events
        rel = req.t_submit
        events.emit(
            "serve", kind="stream", request=f"{self.name}/{req.rid}",
            decoder=self.name, tokens=req.streamed,
            n_seed=len(req.seed),
            admit_ms=(None if req.t_admit is None
                      else round((req.t_admit - rel) * 1e3, 3)),
            ttft_ms=round((req.first_ts - rel) * 1e3, 3),
            retire_ms=round((ts - rel) * 1e3, 3),
            boundaries=len(req.timeline),
            timeline=[[round((t - rel) * 1e3, 3), n]
                      for t, n in req.timeline])

    def _note_util(self, tokens: int):
        """``decode_model_flops_util`` + ``decode_tokens_per_s``: one
        gauge set per sync boundary (the decode cadence unit — never
        per token or per step).  The window is boundary-entry to
        boundary-entry wall, so asynchronously queued device work
        amortizes across boundaries without forcing an extra host
        sync; flops come from the step program's compile-time ledger
        capture."""
        now = time.perf_counter()
        wall, self._util_t_last = now - self._util_t_last, now
        if wall <= 0:
            return
        self._m_toks.set(tokens / wall)
        if self._step_flops:
            self._m_util.set(self._step_flops * self.sync_interval
                             / (wall * self._peak_flops))

    def run(self):
        """Drive the decoder until every submitted request has resolved.
        Admissions and retirements happen only at ``sync_interval``
        step boundaries; the only device->host reads are one
        generated-slab fetch per boundary that retires a request (plus,
        under speculative decode, one (B,)-int position fetch per
        boundary — acceptance lengths make completion data-dependent)."""
        while self._pending or any(r is not None for r in self._slots):
            if self.step_boundary() == 0:
                break
        self.emit_decode_event()
        return self

    def emit_decode_event(self):
        """The lifetime ``decode`` obs event (``run`` emits one per
        drain; a fleet replica emits one at close)."""
        from bigdl_tpu.obs import events
        extra = {}
        if self.paged:
            ps = self._pool.stats()
            extra.update(paged=True, page_size=self.page_size,
                         pages=ps["pages"], pages_hwm=ps["in_use_hwm"],
                         live_hwm=self.live_hwm)
            if self._prefix is not None:
                extra.update(prefix_hits=self._prefix.hits,
                             prefix_misses=self._prefix.misses,
                             prefix_pages=self._prefix.pages_reused)
            if self._tier is not None:
                ts = self._tier.stats()
                extra.update(kv_host_spilled=ts["spilled"],
                             kv_host_readmitted=ts["readmitted"],
                             kv_host_dropped=ts["dropped"],
                             kv_host_bytes=ts["bytes"])
        if self.kv_quant != "off":
            extra.update(kv_quant=self.kv_quant,
                         kv_bytes_per_token=self.kv_bytes_per_token)
        if self.spec_k:
            extra.update(spec_k=self.spec_k,
                         spec_windows=self.spec_windows,
                         accept_mean=(self.spec_accepted
                                      / max(1, self.spec_windows)))
        if self.sampled:
            # sampled-vs-greedy split (greedy = admitted - sampled)
            extra.update(sampled=self.sampled,
                         greedy=self.admitted - self.sampled)
        if self.stop_retired:
            extra.update(stop_retired=self.stop_retired,
                         steps_saved=self.steps_saved)
        if self.streams:
            # required-when-streaming (events schema v4)
            extra.update(streaming=True, streams=self.streams,
                         stream_tokens=self.stream_tokens,
                         stream_boundaries=self.stream_boundaries,
                         first_token_ms=(self._ttft_sum / self.streams
                                         * 1e3))
        events.emit("serve", kind="decode", steps=self.steps,
                    host_syncs=self.host_syncs, admitted=self.admitted,
                    retired=self.retired, slots=self.B, **extra)
        return self

    def close(self):
        """Drop this decoder's series from the process metrics registry
        and release the prefix cache's page holds.  Decoders are
        labelled uniquely (``decoder=<name>``), so a process that
        constructs many short-lived decoders (every
        :func:`continuous_decode` call makes one) would otherwise grow
        the registry — and every snapshot/exposition — without bound.
        The series drop also runs at GC for decoders nobody closes;
        idempotent."""
        if self._delivery is not None:
            # FIFO drain: every pending chunk and streaming resolution
            # lands before the thread stops (then joined — the orphaned
            # daemon-thread-at-teardown lesson, Router.close)
            self._delivery.close()
            self._delivery = None
        if self._prefix is not None:
            self._prefix.drop_all()
        if self._tier is not None and self._tier_owned:
            self._tier.close()
            self._tier = None
        self._drop_series()

    def decode_flags(self) -> dict:
        """The constructor recipe ``tools/request_replay.py`` needs to
        rebuild an equivalent decoder for a recorded request: every
        flag that shapes the committed token stream or the KV layout.
        Built once — the flight recorder notes it per traced request."""
        if self._flags_cache is None:
            self._flags_cache = {
                "max_slots": self.B, "n_pos": self.n_pos,
                "sync_interval": self.sync_interval,
                "paged": self.paged, "page_size": self.page_size,
                "n_pages": (self._pool.n_pages if self.paged
                            else None),
                "prefix_cache": self._prefix is not None,
                "spec_k": self.spec_k,
                "draft_layers": self.draft_layers,
                "kv_quant": self.kv_quant,
                "max_stop_seqs": self.max_stop_seqs,
                "max_stop_len": self.max_stop_len}
        return self._flags_cache

    def stats(self) -> dict:
        out = {"steps": self.steps, "host_syncs": self.host_syncs,
               "admitted": self.admitted, "retired": self.retired,
               "slots": self.B,
               "slots_active": sum(1 for r in self._slots
                                   if r is not None),
               "live_hwm": self.live_hwm,
               "n_pos": self.n_pos, "paged": self.paged,
               "sync_interval": self.sync_interval, "tp": self.tp,
               "name": self.name, "kv_quant": self.kv_quant,
               "kv_bytes_per_token": self.kv_bytes_per_token,
               "sampled": self.sampled,
               "stop_retired": self.stop_retired,
               "steps_saved": self.steps_saved}
        if self.paged:
            out["pool"] = self._pool.stats()
            if self._prefix is not None:
                out["prefix"] = self._prefix.stats()
            if self._tier is not None:
                out["kv_host"] = self._tier.stats()
        if self.streams:
            out["stream"] = {
                "streams": self.streams,
                "tokens": self.stream_tokens,
                "boundaries": self.stream_boundaries,
                "ttft_mean_ms": self._ttft_sum / self.streams * 1e3}
        if self.spec_k:
            counts = self._accept_counts
            total = int(counts.sum())
            p50 = None
            if total:
                p50 = int(np.searchsorted(np.cumsum(counts),
                                          (total + 1) // 2))
            out.update(spec_k=self.spec_k,
                       spec_windows=self.spec_windows,
                       spec_accepted=self.spec_accepted,
                       accept_hist=[int(c) for c in counts],
                       accept_p50=p50,
                       accept_mean=(self.spec_accepted
                                    / max(1, self.spec_windows)))
        return out


def continuous_decode(model, seed_rows, n_words, max_slots: int = 4,
                      n_pos: int | None = None,
                      sync_interval: int | None = None, mesh=None,
                      **decoder_kwargs):
    """Convenience one-shot: decode every seed row with a shared decoder.

    ``n_pos`` defaults to the largest request's need, so a mixed set of
    seed lengths shares one compiled step.  ``mesh`` (with a ``model``
    axis) serves tensor-parallel; extra keyword arguments (``paged``,
    ``page_size``, ``n_pages``, ``prefix_cache``, ``spec_k``, ...) pass
    through to :class:`ContinuousDecoder`.  Returns the extended rows in
    submission order (``lm_decode`` greedy semantics per row)."""
    reqs = [np.asarray(s, np.int32) for s in seed_rows]
    if n_pos is None:
        n_pos = max(int(s.size) + int(n_words) - 1 for s in reqs)
    dec = ContinuousDecoder(model, max_slots=max_slots, n_pos=n_pos,
                            sync_interval=sync_interval, mesh=mesh,
                            **decoder_kwargs)
    try:
        futs = [dec.submit(s, n_words) for s in reqs]
        dec.run()
        return [f.result() for f in futs]
    finally:
        dec.close()   # one-shot decoder: don't leak its registry series
