"""TPU-native serving stack (docs/serving.md).

The reference exposed batch inference as DLClassifier / ``Module.predict``
over Spark partitions; this package is the throughput-oriented TPU
counterpart, reusing the training stack's pipeline idioms:

- :mod:`bigdl_tpu.serve.bucketing` — power-of-two batch buckets +
  zero-pad/trim helpers (shared with the validators' tail batches);
- :mod:`bigdl_tpu.serve.xcache` — the SHARED executable cache keyed by
  (fn, shapes, mesh, dtype-policy); train dispatch, ``optim.validate``
  and every serve replica resolve compiles through it, so all entry
  points get the zero-cold-compile property;
- :mod:`bigdl_tpu.serve.engine` — :class:`ServeEngine`: futures-based
  submit API, size-or-deadline micro-batching, a dedicated H2D transfer
  thread, device-pinned weights (atomic versioned hot swap) and an
  ahead-of-time compiled executable per bucket;
- :mod:`bigdl_tpu.serve.decode` — :class:`ContinuousDecoder`: slot-based
  continuous batching over the ``TransformerLM`` KV-cache step, with
  admissions/retirements at step boundaries, cadenced host syncs, and
  optional tensor-parallel serving over a mesh ``model`` axis;
- :mod:`bigdl_tpu.serve.streaming` — :class:`StreamFuture` /
  :class:`SafeFuture`: incremental per-token delivery at each sync
  boundary (``on_tokens``; byte-identical to the all-at-once result,
  dedup-by-index across requeues and process hops), callback-safe
  futures, and the dedicated delivery thread — the TTFT/ITL SLO
  surface (docs/observability.md "Streaming telemetry");
- :mod:`bigdl_tpu.serve.paging` / :mod:`bigdl_tpu.serve.prefix` — the
  block-paged KV pool behind the decoder (:class:`PagePool` refcounted
  page allocation; concurrency scales with pooled tokens, not slab
  width) and token-hash prefix caching (:class:`PrefixCache` — shared
  system prompts map cached pages and skip their prefill);
- :mod:`bigdl_tpu.serve.router` — :class:`Router`: SLO admission in
  front of N replicas (priority classes, deadlines, shed-on-overload,
  least-loaded dispatch, requeue-on-replica-death);
- :mod:`bigdl_tpu.serve.cluster` — :class:`ReplicaPool` /
  :class:`WeightStore`: in-process or subprocess replica fleets with
  two-phase (stage → atomic flip, rollback on failure) weight rollout;
- :mod:`bigdl_tpu.serve.fleet` / :mod:`bigdl_tpu.serve.kvtier` — the
  disaggregated decode fleet (:class:`DecodeFleet`): prefix-affinity
  routing (dispatch to the replica whose cache holds the longest
  matching chain), dedicated prefill replicas shipping seed KV pages
  over the replica frames (colocated-prefill fallback on death), and a
  per-replica host-RAM KV tier (:class:`HostKVTier`) that spills
  evicted prefix pages D2H and re-admits them on chain-hash hit;
- :mod:`bigdl_tpu.serve.frames` / :mod:`bigdl_tpu.serve.remote` — the
  hardened frame codec both transports share (magic + version prefix,
  size bound, per-frame CRC32; malformation raises a typed
  :class:`FrameProtocolError` instead of reaching ``pickle.loads``)
  and the cross-host fleet (docs/serving.md "Cross-host fleet"):
  :class:`RemoteReplica` speaks the stdio op set over TCP to a
  ``tools/replica_agent.py`` per host, distinguishing a network blip
  (reconnect + same-session re-attach inside ``BIGDL_SERVE_LIVENESS_S``
  — zero requeues, zero duplicate token chunks) from replica death
  (the existing DeadReplicaError → requeue-exactly-once path), with
  :class:`HostInventory` leasing ``BIGDL_SERVE_HOSTS`` addresses to
  the pool/fleet/autoscaler.

Quantized serving (``bigdl_tpu/quant``, docs/serving.md "Quantized
serving"): ``BIGDL_SERVE_QUANT`` serves per-channel int8/fp8 weights
through the ServeEngine (dequant-on-the-fly, quant recipe in the xcache
key) and ``BIGDL_SERVE_KV_QUANT`` stores the paged decode pool as int8
with per-page-row scales — both default off, gated by the
``tools/quant_check.py`` accuracy budget.

Flags: ``BIGDL_SERVE_MAX_BATCH`` (default 64), ``BIGDL_SERVE_MAX_WAIT_MS``
(default 2), ``BIGDL_SERVE_SYNC`` (decode boundary interval, default 8),
``BIGDL_SERVE_PAGED`` (block-paged KV decode, default on),
``BIGDL_SERVE_PAGE_SIZE`` (tokens per KV page, default 16),
``BIGDL_SERVE_PAGES`` (pool size in pages, default slab-equivalent),
``BIGDL_SERVE_PREFIX_CACHE`` (prefix page reuse, default on),
``BIGDL_SERVE_SPEC_K`` (self-speculative draft length, default 0 = off),
``BIGDL_SERVE_QUANT`` (weight quantization: off/int8/fp8, default off),
``BIGDL_SERVE_KV_QUANT`` (int8 KV pages, default off),
``BIGDL_SERVE_REPLICAS`` (pool size, default 2), ``BIGDL_SERVE_SLO_MS``
(default request deadline, 0 = none), ``BIGDL_SERVE_SLO_TTFT_MS`` /
``BIGDL_SERVE_SLO_ITL_MS`` (per-token SLO class for streaming requests
— projected FIRST-token completion drives shed-before-miss; 0 = none),
``BIGDL_SERVE_SHED`` (overload shedding, default on), ``BIGDL_SERVE_AFFINITY`` (prefix-affinity fleet
dispatch, default on), ``BIGDL_SERVE_PREFILL_REPLICAS`` (dedicated
prefill replicas, default 0), ``BIGDL_SERVE_KV_HOST_MB`` (host-RAM KV
tier budget per decode replica, default 0 = off),
``BIGDL_OBS_TRACE_SAMPLE`` (request-trace sample rate, default 0),
``BIGDL_SERVE_EXPORT_PORT`` (metrics pull exporter —
docs/observability.md "Serving telemetry") and the autoscaler loop
(``serve/autoscale.py``, docs/serving.md "Autoscaling"):
``BIGDL_SERVE_AUTOSCALE`` (default off),
``BIGDL_SERVE_MIN_REPLICAS`` / ``BIGDL_SERVE_MAX_REPLICAS`` (bounds,
default 1/8), ``BIGDL_SERVE_SCALE_INTERVAL`` (cadence seconds,
default 2); the cross-host fleet (``serve/remote.py``,
docs/serving.md "Cross-host fleet"): ``BIGDL_SERVE_HOSTS``
(replica-agent inventory, ``host:port,host:port``),
``BIGDL_SERVE_TOKEN`` (shared handshake secret),
``BIGDL_SERVE_LIVENESS_S`` (blip-vs-death budget, default 2),
``BIGDL_SERVE_SESSION_TTL_S`` (agent-side detached-session reap,
default 30) and ``BIGDL_SERVE_MAX_FRAME_MB`` (frame-size bound,
default 4096).
"""
from bigdl_tpu.serve import bucketing, xcache  # noqa: F401
from bigdl_tpu.serve.autoscale import Autoscaler  # noqa: F401
from bigdl_tpu.serve.bucketing import (  # noqa: F401
    bucket_for, bucket_sizes, pad_rows, trim, valid_mask,
)
from bigdl_tpu.serve.cluster import (  # noqa: F401
    LocalReplica, ProcessReplica, ReplicaPool, ReplicaSpawnError,
    RolloutError, WeightStore,
)
from bigdl_tpu.serve.decode import (  # noqa: F401
    ContinuousDecoder, continuous_decode,
)
from bigdl_tpu.serve.engine import (  # noqa: F401
    DTypePolicyDriftError, PoisonedRequestError, ServeEngine,
    SheddedError,
)
from bigdl_tpu.serve.fleet import (  # noqa: F401
    AffinityIndex, DecodeFleet, DecodeReplica, FleetRouter,
    PrefillReplica, ProcessDecodeReplica, ProcessPrefillReplica,
)
from bigdl_tpu.serve.frames import FrameProtocolError  # noqa: F401
from bigdl_tpu.serve.kvtier import HostKVTier  # noqa: F401
from bigdl_tpu.serve.remote import (  # noqa: F401
    HostInventory, RemoteDecodeReplica, RemotePrefillReplica,
    RemoteReplica, spawn_agent,
)
from bigdl_tpu.serve.paging import (  # noqa: F401
    PagePool, RequestTooLongError,
)
from bigdl_tpu.serve.prefix import PrefixCache, chain_keys  # noqa: F401
from bigdl_tpu.serve.router import (  # noqa: F401
    DeadReplicaError, Router,
)
from bigdl_tpu.serve.streaming import (  # noqa: F401
    SafeFuture, StreamFuture, TokenDelivery,
)

__all__ = [
    "bucketing", "xcache", "bucket_sizes", "bucket_for", "pad_rows",
    "trim", "valid_mask", "ServeEngine", "PoisonedRequestError",
    "DTypePolicyDriftError",
    "SheddedError", "ContinuousDecoder", "continuous_decode", "Router",
    "DeadReplicaError", "ReplicaPool", "LocalReplica", "ProcessReplica",
    "WeightStore", "RolloutError", "ReplicaSpawnError", "Autoscaler",
    "PagePool", "PrefixCache",
    "RequestTooLongError", "chain_keys", "DecodeFleet", "FleetRouter",
    "AffinityIndex", "DecodeReplica", "PrefillReplica",
    "ProcessDecodeReplica", "ProcessPrefillReplica", "HostKVTier",
    "SafeFuture", "StreamFuture", "TokenDelivery",
    "FrameProtocolError", "RemoteReplica", "RemoteDecodeReplica",
    "RemotePrefillReplica", "HostInventory", "spawn_agent",
]
