"""TPU-native serving engine (docs/serving.md).

The reference exposed batch inference as DLClassifier / ``Module.predict``
over Spark partitions; this package is the throughput-oriented TPU
counterpart, reusing the training stack's pipeline idioms:

- :mod:`bigdl_tpu.serve.bucketing` — power-of-two batch buckets +
  zero-pad/trim helpers (shared with the validators' tail batches);
- :mod:`bigdl_tpu.serve.engine` — :class:`ServeEngine`: futures-based
  submit API, size-or-deadline micro-batching, a dedicated H2D transfer
  thread, device-pinned weights and an ahead-of-time compiled executable
  per bucket (zero cold compiles after warmup);
- :mod:`bigdl_tpu.serve.decode` — :class:`ContinuousDecoder`: slot-based
  continuous batching over the ``TransformerLM`` KV-cache step, with
  admissions/retirements at step boundaries and cadenced host syncs.

Flags: ``BIGDL_SERVE_MAX_BATCH`` (default 64), ``BIGDL_SERVE_MAX_WAIT_MS``
(default 2), ``BIGDL_SERVE_SYNC`` (decode boundary interval, default 8).
"""
from bigdl_tpu.serve import bucketing  # noqa: F401
from bigdl_tpu.serve.bucketing import (  # noqa: F401
    bucket_for, bucket_sizes, pad_rows, trim, valid_mask,
)
from bigdl_tpu.serve.decode import (  # noqa: F401
    ContinuousDecoder, continuous_decode,
)
from bigdl_tpu.serve.engine import (  # noqa: F401
    PoisonedRequestError, ServeEngine,
)

__all__ = [
    "bucketing", "bucket_sizes", "bucket_for", "pad_rows", "trim",
    "valid_mask", "ServeEngine", "PoisonedRequestError",
    "ContinuousDecoder", "continuous_decode",
]
