"""Disaggregated serving fleet: prefix-affinity routing, prefill/decode
split, and the host-RAM KV tier behind one admission point
(docs/serving.md "Disaggregated fleet").

The single-replica serving levers are all in place — paged KV with
prefix reuse (``serve/prefix.py``), int8 KV pages (``quant/kv.py``),
the SLO router (``serve/router.py``) — but a FLEET of N decoders is
still dumb: each replica's prefix cache is private, so a shared-prefix
workload sees roughly 1/N the hit rate, and every admission burst runs
its prefill on the same chips that are mid-decode for live streams.
This module is the DistServe/Splitwise-style decomposition built from
the repo's own parts:

- **Prefix-affinity routing** (:class:`FleetRouter`,
  ``BIGDL_SERVE_AFFINITY``): the router sees every request's tokens
  and the prefix chain-hash (``serve/prefix.chain_keys``) is
  deterministic, so admission hashes the seed's page chain and
  dispatches to the replica whose cache holds the LONGEST matching
  chain — recovering near single-replica hit rates on N replicas.  The
  router's view (:class:`AffinityIndex`) is an optimistic LRU mirror
  updated at dispatch (the request's own pages are donated at retire);
  a stale entry costs one replica-local miss, never correctness.  No
  match falls back to least-loaded; EDF deadlines, shed-before-miss
  and requeue-on-replica-death are inherited unchanged from
  :class:`~bigdl_tpu.serve.router.Router`.
- **Prefill/decode disaggregation** (:class:`PrefillReplica`,
  ``BIGDL_SERVE_PREFILL_REPLICAS``): prefill is compute-bound (one
  ``_lm_forward_window`` pass over the seed), decode is HBM/latency
  bound.  Dedicated prefill replicas compute the seed's full KV pages
  (int8 + per-page scales when the fleet runs quantized KV) and ship
  them — over the existing length-prefixed ProcessReplica frames for
  subprocess fleets — to the chosen decode replica, which adopts them
  into its prefix cache (``ContinuousDecoder.adopt_pages``) and admits
  the request at the page-aligned divergence point.  A prefill replica
  dying mid-burst loses ZERO futures: the dispatch falls back to
  colocated prefill (the decode replica computes its own seed KV),
  only the offload is lost.
- **Host-RAM KV tier** (``serve/kvtier.py``,
  ``BIGDL_SERVE_KV_HOST_MB``): each decode replica's evicted prefix
  pages spill D2H and re-admit on chain-hash hit — the per-replica
  effective prefix cache grows by roughly host/HBM.

Shipped, spilled and locally-written pages all hold bit-identical K/V
(the window pass is the same math the decode step runs; quantized
pages ship value+scale verbatim), so the fleet's decoded streams stay
token-identical to single-replica ``lm_decode`` — the parity contract
``tests/test_fleet.py`` pins across shipping, spilling and quantized
pages.

Request payloads are plain dicts ``{"seed": [...], "n_words": n}``
(pickle-friendly across the frame protocol); :class:`DecodeFleet` is
the facade that builds the replicas and the router and exposes
``submit(seed, n_words)``.
"""
from __future__ import annotations

import itertools
import logging
import os
import sys
import threading
import time
from collections import OrderedDict
from concurrent.futures import Future

import numpy as np

from bigdl_tpu.serve import cluster as cluster_ops
from bigdl_tpu.serve.cluster import (ENV_SPAWN_FAIL, DynamicMembership,
                                     ProcessReplica, _read_frame,
                                     _write_frame)
from bigdl_tpu.serve.decode import (DEFAULT_PAGE_SIZE, ENV_PAGE_SIZE,
                                    ContinuousDecoder, _env_int)
from bigdl_tpu.serve.kvtier import HostKVTier, host_mb_default
from bigdl_tpu.serve.prefix import chain_keys
from bigdl_tpu.serve.router import (DeadReplicaError, Router,
                                    replicas_default)
from bigdl_tpu.serve.streaming import StreamFuture

logger = logging.getLogger("bigdl_tpu.serve")

ENV_AFFINITY = "BIGDL_SERVE_AFFINITY"
ENV_PREFILL = "BIGDL_SERVE_PREFILL_REPLICAS"

_FLEET_SEQ = itertools.count()


def affinity_default() -> bool:
    return os.environ.get(ENV_AFFINITY, "1") != "0"


def prefill_replicas_default() -> int:
    try:
        return max(0, int(os.environ.get(ENV_PREFILL, "0")))
    except ValueError:
        return 0


def _page_size_default(decoder_kwargs: dict) -> int:
    ps = decoder_kwargs.get("page_size")
    return max(1, int(ps) if ps is not None
               else _env_int(ENV_PAGE_SIZE, DEFAULT_PAGE_SIZE))


# ---------------------------------------------------------------------------
# the router's optimistic view of each replica's prefix cache
# ---------------------------------------------------------------------------

class AffinityIndex:
    """Replica → LRU set of prefix chain keys the router believes that
    replica's cache holds.

    Optimistic by design: entries are noted at DISPATCH (the request's
    seed pages will be donated to that replica's cache at retire), and
    replica-side eviction is never reported back — a stale entry makes
    one dispatch land on a replica that misses locally (and then
    re-caches), which is exactly the least-loaded baseline's cost.  The
    per-replica LRU bound keeps the mirror a rough shadow of the real
    cache size, so staleness is bounded too."""

    def __init__(self, max_keys: int = 4096):
        self.max_keys = int(max_keys)
        self._lock = threading.Lock()
        self._chains: dict = {}    # name -> OrderedDict(key -> True)

    def note(self, name: str, keys):
        with self._lock:
            d = self._chains.setdefault(name, OrderedDict())
            for k in keys:
                if k in d:
                    d.move_to_end(k)
                else:
                    d[k] = True
            while len(d) > self.max_keys:
                d.popitem(last=False)

    def match_len(self, name: str, keys) -> int:
        """Longest leading run of ``keys`` noted for ``name`` (the
        chain property: page j is only useful if 0..j-1 match too)."""
        with self._lock:
            d = self._chains.get(name)
            if not d:
                return 0
            n = 0
            for k in keys:
                if k not in d:
                    break
                d.move_to_end(k)
                n += 1
            return n

    def forget(self, name: str):
        with self._lock:
            self._chains.pop(name, None)

    def stats(self) -> dict:
        with self._lock:
            return {name: len(d) for name, d in self._chains.items()}


# ---------------------------------------------------------------------------
# decode replicas
# ---------------------------------------------------------------------------

class DecodeReplica:
    """An in-process continuous-batching decode replica: one
    :class:`~bigdl_tpu.serve.decode.ContinuousDecoder` plus a driver
    thread calling ``step_boundary`` whenever work is queued, wearing
    the router's replica surface (``submit/inflight/alive/stats``).

    ``submit`` takes the fleet payload ``{"seed", "n_words"}`` with
    optional shipped prefill ``"pages"`` (adopted into the prefix cache
    before the request queues, so admission sees a prefix hit) and
    never blocks on device work: requests land in a host-side inbox
    the driver drains at each boundary, so a step window mid-flight on
    this replica cannot head-of-line block the router's dispatcher.
    ``host_mb`` > 0 attaches a per-replica host KV tier; with
    ``host_mb=None`` the decoder's own ``BIGDL_SERVE_KV_HOST_MB`` path
    applies (which correctly skips the tier for non-paged decoders)."""

    #: flight-recorder transport attribution (obs/recorder.py)
    transport = "inproc"

    def __init__(self, model, name: str = "decode0",
                 host_mb: int | None = None, host_tier=None,
                 **decoder_kwargs):
        self.name = name
        self._tier_owned = False
        if host_tier is None and host_mb is not None and int(host_mb) > 0:
            host_tier = HostKVTier(int(host_mb), name=f"{name}-tier")
            self._tier_owned = True
        decoder_kwargs.setdefault("prefix_cache", True)
        self.decoder = ContinuousDecoder(
            model, host_tier=host_tier, prefill_adopt=True,
            name=name, **decoder_kwargs)
        self._tier = host_tier
        self._cv = threading.Condition()
        self._inbox: list = []      # (payload dict, proxy future)
        self._closed = False
        self._dead = False
        self._inflight: dict = {}   # id(future) -> proxy (death sweep)
        self._thread = threading.Thread(
            target=self._drive, daemon=True,
            name=f"bigdl-serve-{name}-driver")
        self._thread.start()

    # -- replica surface ----------------------------------------------------
    def submit(self, x, trace=None) -> Future:
        fut = StreamFuture()
        if isinstance(x, dict) and x.get("stream"):
            # stream intent travels in the payload (it can cross a
            # process boundary ahead of the consumer pipe): the driver
            # pipes the decoder's chunks into this proxy from admission
            fut.request_stream()
        with self._cv:
            if self._dead or self._closed:
                raise DeadReplicaError(
                    f"decode replica {self.name} is closed")
            self._inbox.append((x, fut, trace))
            self._inflight[id(fut)] = fut
            self._cv.notify()
        fut.add_done_callback(
            lambda f: self._inflight.pop(id(f), None))
        if trace is not None:
            # one replica-side hop: registered before the router's
            # done-callback, so it lands before the terminal "complete"
            fut.add_done_callback(lambda _f: trace.stamp("compute"))
        return fut

    def inflight(self) -> int:
        with self._cv:
            queued = len(self._inbox)
        return queued + self.decoder.outstanding()

    def alive(self) -> bool:
        return (not self._dead and not self._closed
                and self._thread.is_alive())

    def stats(self) -> dict:
        return {"role": "decode", "name": self.name,
                **self.decoder.stats()}

    def registry_snapshot(self):
        """None: an in-process replica's series already live in this
        process's registry (the ``ReplicaPool`` merge contract)."""
        return None

    # -- driver -------------------------------------------------------------
    def _admit_inbox(self, items):
        """Adopt shipped pages and queue inbox requests on the decoder
        (driver thread only — the decoder is single-threaded state)."""
        for x, fut, trace in items:
            try:
                if x.get("pages"):
                    try:
                        self.decoder.adopt_pages(x["seed"], x["pages"])
                    except Exception:
                        # adoption is an optimization; the request
                        # decodes correctly via colocated prefill
                        logger.warning(
                            "replica %s: shipped-page adoption failed",
                            self.name, exc_info=True)
                inner = self.decoder.submit(x["seed"], x["n_words"],
                                            trace=trace,
                                            sampling=x.get("sampling"))
            except Exception as e:
                if not fut.done():
                    fut.set_exception(e)
                continue
            if fut.streaming:
                # chunks flow decoder → proxy on the decoder's
                # delivery thread, before the result copy below (the
                # delivery FIFO resolves `inner` after its last chunk)
                inner.pipe_to(fut)
            inner.add_done_callback(
                lambda f, proxy=fut: self._copy_result(f, proxy))

    @staticmethod
    def _copy_result(inner, proxy):
        if proxy.done():
            return
        exc = inner.exception()
        if exc is not None:
            proxy.set_exception(exc)
        else:
            proxy.set_result(inner.result())

    def _drive(self):
        while True:
            with self._cv:
                while (not self._closed and not self._dead
                        and not self._inbox
                        and self.decoder.outstanding() == 0):
                    self._cv.wait(timeout=0.05)
                if self._dead or (self._closed and not self._inbox
                                  and self.decoder.outstanding() == 0):
                    return
                items, self._inbox = self._inbox, []
            # device work runs OUTSIDE the lock: submit() stays
            # wait-free while a step window is in flight
            try:
                self._admit_inbox(items)
                self.decoder.step_boundary()
            except Exception as e:  # pragma: no cover - device fault
                self._fail_outstanding(e)
                return

    def _fail_outstanding(self, exc):
        self._dead = True
        err = DeadReplicaError(
            f"decode replica {self.name} driver died: "
            f"{type(exc).__name__}: {exc}")
        logger.warning("decode replica %s driver died", self.name,
                       exc_info=True)
        for fut in list(self._inflight.values()):
            if not fut.done():
                fut.set_exception(err)
        self._inflight.clear()
        self._inbox = []

    def kill(self):
        """Simulated replica death (chaos drills): every outstanding
        future fails with :class:`DeadReplicaError` — the router's
        requeue path takes it from there."""
        with self._cv:
            self._dead = True
            self._fail_outstanding(RuntimeError("killed"))
            self._cv.notify_all()
        self._thread.join(timeout=10.0)

    def close(self, drain: bool = True):
        with self._cv:
            if not drain and not self._dead:
                self._fail_outstanding(RuntimeError("closed undrained"))
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=60.0)
        self.decoder.emit_decode_event()
        self.decoder.close()
        if self._tier is not None and self._tier_owned:
            self._tier.close()


def pages_nbytes(pages) -> int:
    """Wire weight (bytes) of one shipped KV page payload list — the
    numpy buffers only, the measure behind ``fleet_ship_bytes_total``
    (int8 pages carry value+scale and land near 3.2x tokens/byte vs
    float32; bench_serve's ``ship_bytes_per_s`` column reads this)."""
    total = 0
    for page in pages or ():
        for arr in (page if isinstance(page, (tuple, list)) else (page,)):
            nb = getattr(arr, "nbytes", None)
            if nb is not None:
                total += int(nb)
    return total


def _note_ship_bytes(replica: str, transport: str, pages):
    """Count one prefill→decode page shipment's bytes onto
    ``fleet_ship_bytes_total{transport,replica}``."""
    if not pages:
        return
    try:
        from bigdl_tpu.obs import metrics as obs_metrics
        obs_metrics.get().counter(
            "fleet_ship_bytes_total",
            "KV page payload bytes shipped prefill→decode, by wire",
            transport=transport, replica=replica,
        ).inc(pages_nbytes(pages))
    except Exception:   # pragma: no cover - registry mid-teardown
        pass


class ProcessDecodeReplica(ProcessReplica):
    """A decode replica in its own OS process (its own jax runtime /
    chip slice), speaking the cluster frame protocol with a fleet
    worker (:func:`fleet_main`).  Shipped prefill pages ride the submit
    frame as plain numpy payloads; death fails outstanding futures with
    :class:`DeadReplicaError` exactly like the engine replicas."""

    _WORKER_MODULE = "bigdl_tpu.serve.fleet"

    def _init_frame(self, model, worker_kwargs) -> dict:
        return {"op": "init", "role": "decode", "model": model,
                "decoder": worker_kwargs}

    def submit(self, x, trace=None) -> Future:
        _note_ship_bytes(self.name, "stdio", x.get("pages"))
        return self._send(
            "submit", _trace=trace,
            seed=[int(t) for t in x["seed"]],
            n_words=int(x["n_words"]), pages=x.get("pages"),
            stream=bool(x.get("stream")),
            sampling=x.get("sampling"),
            trace=None if trace is None else trace.to_wire())


# ---------------------------------------------------------------------------
# prefill replicas
# ---------------------------------------------------------------------------

class PrefillReplica:
    """A dedicated prefill worker: one compiled
    ``_lm_forward_window`` pass over the seed per pow2 page-count
    bucket, returning the seed's full KV pages as host payloads the
    decode replicas adopt.

    Only pages every position of which lies strictly inside the seed
    are shippable — ``(len(seed) - 1) // page_size``, the same cap as a
    prefix-cache match (the last seed position is re-fed on the decode
    replica for the first logits).  Seeds longer than
    ``max_seed_pages * page_size`` ship their leading chain and the
    decode replica prefills the rest colocated.  ``kv_quant`` must
    match the decode replicas' pools (int8 pages ship value+scale
    verbatim — bit-identical adoption)."""

    def __init__(self, model, name: str = "prefill0",
                 page_size: int | None = None, max_seed_pages: int = 8,
                 kv_quant: str | None = None):
        import jax.numpy as jnp

        from bigdl_tpu.models.transformer import (_lm_forward_window,
                                                  _lm_handles)
        from bigdl_tpu.optim.local_optimizer import _model_fingerprint
        from bigdl_tpu.quant import kv as kvq
        from bigdl_tpu.quant import kv_mode_default, normalize_mode
        from bigdl_tpu.serve import xcache

        self.name = name
        self.page_size = (max(1, int(page_size)) if page_size is not None
                          else _env_int(ENV_PAGE_SIZE, DEFAULT_PAGE_SIZE))
        self.kv_quant = (kv_mode_default() if kv_quant is None
                         else normalize_mode(kv_quant, kvq.ON_MODES,
                                             "kv_quant"))
        self._closed = False
        self._inflight = 0
        self._lock = threading.Lock()
        self.prefills = 0        # this replica's lifetime (stats());
        self.pages_shipped = 0   # the registry counters merge fleetwide
        h = _lm_handles(model)
        L, H, hd = h.n_layers, h.n_heads, h.hd
        ps = self.page_size
        self.buckets = []
        b = 1
        while b <= max(1, int(max_seed_pages)):
            self.buckets.append(b)
            b *= 2
        self.max_pages = self.buckets[-1]
        pe = jnp.asarray(model.modules[1].table(self.max_pages * ps))
        fp = _model_fingerprint(model)
        quant = self.kv_quant == "int8"

        def make(npages):
            S = npages * ps
            ptab = jnp.arange(npages, dtype=jnp.int32)[None, :]
            pos = jnp.arange(S, dtype=jnp.int32)[None, :]

            def prefill_fn(seed_row, valid):
                z = jnp.zeros
                shape = (L, npages, ps, H, hd)
                if quant:
                    ss = kvq.scale_shape(shape)
                    caches = (z(shape, jnp.int8), z(shape, jnp.int8),
                              z(ss, jnp.float32), z(ss, jnp.float32))
                else:
                    caches = (z(shape, jnp.float32),
                              z(shape, jnp.float32))
                _, caches = _lm_forward_window(
                    seed_row, pos, caches, h, pe, (ptab, ps),
                    valid=valid)
                return caches

            return xcache.tracked_jit(
                prefill_fn,
                ("fleet_prefill", fp, npages, ps, self.kv_quant))

        self._progs = {b: make(b) for b in self.buckets}

        from bigdl_tpu.obs import metrics as obs_metrics
        reg = obs_metrics.get()
        lab = {"replica": self.name}
        self._m_reqs = reg.counter(
            "fleet_prefill_requests_total",
            "seeds prefilled on a dedicated prefill replica", **lab)
        self._m_pages = reg.counter(
            "fleet_prefill_pages_total",
            "KV pages computed and shipped by prefill replicas", **lab)
        self._m_lat = reg.histogram(
            "fleet_prefill_seconds", "seed prefill wall time", **lab)
        # uniquely-labelled, possibly short-lived: drop the series at
        # close/GC (the decoder/tier precedent); held handles keep
        # serving stats() after the drop
        import weakref
        self._drop_series = weakref.finalize(
            self, reg.drop_series, replica=self.name)

        # warm every bucket at construction: the prefill path inherits
        # the serving zero-cold-compile property
        for b in self.buckets:
            row = np.zeros((1, b * ps), np.int32)
            valid = np.zeros((1, b * ps), bool)
            np.asarray(self._progs[b](row, valid)[0])

        self._pool = None   # lazy single-thread executor for async calls

    # -- prefill ------------------------------------------------------------
    def prefill(self, seed) -> list:
        """The shippable KV pages for ``seed``: a list of per-page
        payload tuples (the decoder's per-array page slices), computed
        with the SAME window math the decode step runs — adoption is
        bit-identical to local prefill."""
        t0 = time.perf_counter()
        ps = self.page_size
        n_ship = min(max(0, (len(seed) - 1) // ps), self.max_pages)
        if n_ship == 0:
            return []
        bucket = next(b for b in self.buckets if b >= n_ship)
        n_tok = n_ship * ps
        row = np.zeros((1, bucket * ps), np.int32)
        row[0, :n_tok] = np.asarray(seed[:n_tok], np.int32)
        valid = np.zeros((1, bucket * ps), bool)
        valid[0, :n_tok] = True
        caches = self._progs[bucket](row, valid)
        host = [np.asarray(c) for c in caches]
        pages = [tuple(a[:, j] for a in host) for j in range(n_ship)]
        with self._lock:
            self.prefills += 1
            self.pages_shipped += len(pages)
        self._m_reqs.inc()
        self._m_pages.inc(len(pages))
        self._m_lat.observe(time.perf_counter() - t0)
        return pages

    def prefill_async(self, seed) -> Future:
        """``prefill`` on this replica's own worker thread — the
        router's dispatch loop must not block on a window pass."""
        from concurrent.futures import ThreadPoolExecutor
        with self._lock:
            if self._closed:
                raise DeadReplicaError(
                    f"prefill replica {self.name} is closed")
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=1,
                    thread_name_prefix=f"bigdl-serve-{self.name}")
            self._inflight += 1
        fut = self._pool.submit(self.prefill, seed)
        fut.add_done_callback(lambda _f: self._dec())
        return fut

    def _dec(self):
        with self._lock:
            self._inflight -= 1

    # -- replica surface ----------------------------------------------------
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def alive(self) -> bool:
        return not self._closed

    def stats(self) -> dict:
        return {"role": "prefill", "name": self.name,
                "page_size": self.page_size, "kv_quant": self.kv_quant,
                "buckets": list(self.buckets),
                "prefills": self.prefills,
                "pages_shipped": self.pages_shipped}

    def registry_snapshot(self):
        return None

    def close(self, drain: bool = True):
        with self._lock:
            self._closed = True
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=drain)
        self._drop_series()


class ProcessPrefillReplica(ProcessReplica):
    """A prefill replica in its own OS process; ``prefill_async`` rides
    the frame protocol and resolves to the page payload list.  Death
    fails in-flight prefills with :class:`DeadReplicaError`, which the
    fleet router converts into colocated prefill — never a lost
    request."""

    _WORKER_MODULE = "bigdl_tpu.serve.fleet"

    def _init_frame(self, model, worker_kwargs) -> dict:
        return {"op": "init", "role": "prefill", "model": model,
                "prefill": worker_kwargs}

    def prefill_async(self, seed) -> Future:
        return self._send("prefill", seed=[int(t) for t in seed])

    def prefill(self, seed, timeout: float = 120.0) -> list:
        return self.prefill_async(seed).result(timeout=timeout)


# ---------------------------------------------------------------------------
# the affinity router
# ---------------------------------------------------------------------------

class FleetRouter(Router):
    """:class:`~bigdl_tpu.serve.router.Router` with prefix-affinity
    dispatch and the prefill-replica hop.

    ``_pick_for``: hash the request seed's page chain and prefer the
    live replica whose :class:`AffinityIndex` mirror holds the longest
    matching run (``fleet_affinity_hits_total``); no match falls back
    to least-loaded (``fleet_affinity_misses_total``).  ``_submit_to``:
    when prefill replicas are configured and the seed spans at least
    one full page, the seed's KV pages are computed on a prefill
    replica and shipped with the request; ANY prefill failure (death
    included) falls back to colocated prefill on the decode replica —
    the request itself is never lost, and decode-replica death still
    rides the base requeue-once idempotence machinery."""

    def __init__(self, replicas, prefill=None, affinity: bool | None = None,
                 page_size: int | None = None, index_keys: int = 4096,
                 affinity_max_skew: int = 8, **router_kwargs):
        self.page_size = (max(1, int(page_size)) if page_size is not None
                          else _env_int(ENV_PAGE_SIZE, DEFAULT_PAGE_SIZE))
        self.affinity_enabled = (affinity_default() if affinity is None
                                 else bool(affinity))
        #: load guard: an affinity pick whose backlog exceeds the
        #: least-loaded replica's by more than this many requests is
        #: overridden — a hot prefix family (steep Zipf) must not
        #: funnel onto one replica while the rest idle; re-caching the
        #: chain on a second replica costs one miss, a deadline shed
        #: costs the request
        self.affinity_max_skew = max(0, int(affinity_max_skew))
        self.index = AffinityIndex(max_keys=index_keys)
        self.prefill_replicas = list(prefill or [])
        self._prefill_dead: set = set()
        self._aff_counters: dict = {}
        super().__init__(replicas, **router_kwargs)
        from bigdl_tpu.obs import metrics as obs_metrics
        reg = obs_metrics.get()
        for r in self.replicas:
            reg.gauge("serve_replica_role", "replica role (1 = present)",
                      role="decode", replica=getattr(r, "name", "?"),
                      router=self.name).set(1)
        for p in self.prefill_replicas:
            reg.gauge("serve_replica_role", "replica role (1 = present)",
                      role="prefill", replica=getattr(p, "name", "?"),
                      router=self.name).set(1)
        self._m_ship = reg.counter(
            "fleet_prefill_shipped_total",
            "requests dispatched with prefill-replica pages",
            router=self.name)
        self._m_fallback = reg.counter(
            "fleet_prefill_fallback_total",
            "requests served via colocated prefill after a prefill "
            "miss/failure", router=self.name)
        self._m_skip = reg.counter(
            "fleet_prefill_skipped_total",
            "prefill hops skipped because the affinity pick already "
            "caches the chain", router=self.name)

    # -- affinity dispatch --------------------------------------------------
    def _aff_counter(self, replica_name: str, outcome: str):
        key = (replica_name, outcome)
        with self._lock:
            c = self._aff_counters.get(key)
        if c is None:
            from bigdl_tpu.obs import metrics as obs_metrics
            c = obs_metrics.get().counter(
                f"fleet_affinity_{outcome}_total",
                "affinity dispatch outcomes per decode replica",
                replica=replica_name, router=self.name)
            with self._lock:
                c = self._aff_counters.setdefault(key, c)
        return c

    def _seed_keys(self, req) -> list:
        x = req.x
        seed = x.get("seed") if isinstance(x, dict) else None
        if not seed:
            return []
        n = max(0, (len(seed) - 1) // self.page_size)
        return list(chain_keys(seed, n, self.page_size))

    def _pick_for(self, req):
        if not self.affinity_enabled:
            return self._pick()
        keys = self._seed_keys(req)
        best, best_match = None, 0
        if keys:
            # drain-marked replicas are not affinity candidates: a
            # scale-down victim only finishes what it already holds
            for r in self.live_replicas(draining=False):
                m = self.index.match_len(getattr(r, "name", ""), keys)
                if m > best_match:
                    best, best_match = r, m
        load = 0
        if best is not None:
            try:
                if not best.alive():
                    raise RuntimeError("replica died")
                load = best.inflight()
            except Exception:
                self._mark_dead(best)
                best = None
        if best is not None:
            with self._lock:
                load += len(self._outstanding.get(id(best), {}))
            # load guard: never let a hot family starve idle replicas
            ll_replica, ll_load = self._pick()
            if (ll_replica is not None and ll_replica is not best
                    and load > ll_load + self.affinity_max_skew):
                best = None
        if best is None:
            replica, load = self._pick()
            if replica is not None and keys:
                # bookkeeping is DEFERRED to dispatch (_submit_to): a
                # request shed before dispatch must not inflate the
                # miss count or seed the index with undonated chains
                req.affinity = 0
                req.aff_note = (getattr(replica, "name", "?"), keys,
                                "misses")
            return replica, load
        name = getattr(best, "name", "?")
        req.affinity = best_match
        req.aff_note = (name, keys, "hits")
        return best, load

    def _consume_aff_note(self, req):
        note, req.aff_note = req.aff_note, None
        if note:
            name, keys, outcome = note
            self._aff_counter(name, outcome).inc()
            self.index.note(name, keys)
            if req.trace is not None:
                from bigdl_tpu.obs import recorder as obs_recorder
                obs_recorder.note(req.trace.trace_id,
                                  affinity=outcome,
                                  affinity_pages=req.affinity)

    def _mark_dead(self, replica):
        self.index.forget(getattr(replica, "name", ""))
        super()._mark_dead(replica)

    def _role_gauge(self, replica, present: bool, role: str = "decode"):
        from bigdl_tpu.obs import metrics as obs_metrics
        obs_metrics.get().gauge(
            "serve_replica_role", "replica role (1 = present)",
            role=role, replica=getattr(replica, "name", "?"),
            router=self.name).set(1 if present else 0)

    def add_replica(self, replica):
        super().add_replica(replica)
        self._role_gauge(replica, True)
        return replica

    def remove_replica(self, replica):
        super().remove_replica(replica)
        self.index.forget(getattr(replica, "name", ""))
        # drop the role series entirely (not just zero it): serve_top
        # derives the replica set from the series LABELS, and a fleet
        # under autoscale churn would otherwise accumulate one stale
        # series per ever-lived replica
        try:
            from bigdl_tpu.obs import metrics as obs_metrics
            obs_metrics.get().drop_series(
                replica=getattr(replica, "name", "?"), role="decode",
                router=self.name)
        except Exception:   # pragma: no cover - registry mid-teardown
            pass

    # -- the prefill hop ----------------------------------------------------
    def _pick_prefill(self):
        best, best_load = None, None
        for p in self.prefill_replicas:
            if id(p) in self._prefill_dead:
                continue
            try:
                if not p.alive():
                    self._mark_prefill_dead(p)
                    continue
                load = p.inflight()
            except Exception:
                self._mark_prefill_dead(p)
                continue
            if best_load is None or load < best_load:
                best, best_load = p, load
        return best

    def _mark_prefill_dead(self, replica):
        with self._lock:
            if id(replica) in self._prefill_dead:
                return
            self._prefill_dead.add(id(replica))
        name = getattr(replica, "name", repr(replica))
        logger.warning("serve fleet: prefill replica %s marked dead; "
                       "falling back to colocated prefill", name)
        self._emit("replica_dead", replica=name, role="prefill")

    @staticmethod
    def _note_prefill(req, outcome: str, pages: int | None = None):
        """Prefill-ship attribution on the request's flight record."""
        if req.trace is not None:
            from bigdl_tpu.obs import recorder as obs_recorder
            obs_recorder.note(req.trace.trace_id, prefill=outcome,
                              shipped_pages=pages)

    def _submit_direct(self, replica, req, x):
        if req.trace is not None and self._accepts_trace(replica):
            return replica.submit(x, trace=req.trace)
        return replica.submit(x)

    def _submit_to(self, replica, req):
        # past the shed check now — commit the affinity bookkeeping
        self._consume_aff_note(req)
        x = req.x
        if (not self.prefill_replicas or not isinstance(x, dict)
                or x.get("pages") is not None
                or (len(x.get("seed") or []) - 1) // self.page_size < 1):
            return super()._submit_to(replica, req)
        n_ship = (len(x["seed"]) - 1) // self.page_size
        if req.affinity is not None and req.affinity >= n_ship:
            # the affinity pick predicts the replica already caches the
            # whole shippable chain — the prefill hop would recompute
            # pages the admission will match locally.  Affinity does
            # not just route better, it SHEDS prefill work.
            self._m_skip.inc()
            self._note_prefill(req, "skipped")
            return super()._submit_to(replica, req)
        pf = self._pick_prefill()
        if pf is None:
            self._m_fallback.inc()
            self._note_prefill(req, "fallback")
            return super()._submit_to(replica, req)

        outer = StreamFuture()
        if req.future.streaming:
            # mark intent NOW: the async prefill hop may land (and
            # pipe the replica chunks in) before the base router
            # registers its outer→client pipe — the backlog replays to
            # that late registration, so no chunk is lost either way
            outer.request_stream()

        def land(pages):
            x2 = dict(x)
            if pages:
                x2["pages"] = pages
                self._m_ship.inc()
                self._note_prefill(req, "shipped", len(pages))
            else:
                self._m_fallback.inc()
                self._note_prefill(req, "fallback")
            try:
                inner = self._submit_direct(replica, req, x2)
            except Exception as e:
                outer.set_exception(e)
                return
            if outer.streaming and hasattr(inner, "pipe_to"):
                # the base router pipes from `outer`; chain the replica
                # chunks through it (index-preserving)
                inner.pipe_to(outer)
            inner.add_done_callback(_copy)

        def _copy(inner):
            exc = inner.exception()
            if exc is not None:
                outer.set_exception(exc)
            else:
                outer.set_result(inner.result())

        def on_prefill(f):
            pages = None
            try:
                pages = f.result()
            except Exception as e:
                # the prefill hop is best-effort: ANY failure (replica
                # death included) serves via colocated prefill — the
                # future is never lost to the offload
                if isinstance(e, DeadReplicaError):
                    self._mark_prefill_dead(pf)
                else:
                    logger.warning("prefill on %s failed; colocated "
                                   "prefill serves the request: %s",
                                   getattr(pf, "name", pf), e)
            land(pages)

        try:
            pfut = pf.prefill_async(x["seed"])
        except Exception:
            self._mark_prefill_dead(pf)
            self._m_fallback.inc()
            return super()._submit_to(replica, req)
        pfut.add_done_callback(on_prefill)
        return outer

    # -- telemetry ----------------------------------------------------------
    def stats(self) -> dict:
        out = super().stats()
        with self._lock:   # the dispatcher inserts counters lazily
            counters = list(self._aff_counters.items())
        hits = sum(int(c.value) for (_, o), c in counters
                   if o == "hits")
        misses = sum(int(c.value) for (_, o), c in counters
                     if o == "misses")
        out.update(affinity=self.affinity_enabled,
                   affinity_hits=hits, affinity_misses=misses,
                   prefill_replicas=len(self.prefill_replicas),
                   prefill_shipped=int(self._m_ship.value),
                   prefill_fallback=int(self._m_fallback.value),
                   prefill_skipped=int(self._m_skip.value),
                   index=self.index.stats())
        return out


# ---------------------------------------------------------------------------
# the fleet facade
# ---------------------------------------------------------------------------

class DecodeFleet(DynamicMembership):
    """N decode replicas (+ optional prefill replicas) behind one
    :class:`FleetRouter` — the disaggregated-serving entry point.

    ``DecodeFleet(model, n_decode=2, n_prefill=1)`` builds in-process
    replicas; ``process=True`` spawns each as its own OS process over
    the cluster frame protocol.  ``replicas=`` / ``prefill=`` inject
    pre-built replicas (tests, heterogeneous fleets, per-replica chaos
    env).  Requests flow ``fleet.submit(seed, n_words, priority=,
    slo_ms=)`` → affinity/least-loaded dispatch → (optional prefill
    hop) → decode replica; every admission/SLO/requeue guarantee is the
    base router's.

    Knobs: ``BIGDL_SERVE_REPLICAS`` (decode count default),
    ``BIGDL_SERVE_PREFILL_REPLICAS``, ``BIGDL_SERVE_AFFINITY``,
    ``BIGDL_SERVE_KV_HOST_MB`` (per-replica host tier) plus every
    decoder knob (page size, spec-k, KV quant...)."""

    def __init__(self, model=None, n_decode: int | None = None,
                 n_prefill: int | None = None, process: bool = False,
                 replicas=None, prefill=None,
                 affinity: bool | None = None, host_mb: int | None = None,
                 slo_ms: float | None = None, shed: bool | None = None,
                 est_ms: float = 50.0, trace_sample: float | None = None,
                 max_seed_pages: int = 8, decode_env=None,
                 prefill_env=None, name: str | None = None,
                 replica_factory=None, remote: bool | None = None,
                 hosts=None, token=None, **decoder_kwargs):
        ps = _page_size_default(decoder_kwargs)
        decoder_kwargs["page_size"] = ps
        kv_quant = decoder_kwargs.get("kv_quant")
        self.name = name or f"fleet{next(_FLEET_SEQ)}"
        self._model = model
        self._process = bool(process)
        self._decoder_kwargs = dict(decoder_kwargs)
        self._host_mb = host_mb
        self._decode_env = decode_env
        self._replica_factory = replica_factory
        # cross-host decode fleet: lease replica-agent addresses instead
        # of spawning local children (docs/serving.md "Cross-host
        # fleet"); prefill replicas stay local — pages ship to the
        # remote decoders over TCP (fleet_ship_bytes_total{transport})
        self._inventory = None
        if remote or (remote is None and hosts is not None):
            from bigdl_tpu.serve import remote as remote_mod
            self._inventory = remote_mod.HostInventory(hosts, token=token)
        self._scale_lock = threading.RLock()
        self._warming = 0
        self._next_decode = 0
        if replicas is None:
            if model is None and replica_factory is None:
                raise ValueError("DecodeFleet needs a model, replicas, "
                                 "or a replica_factory")
            n = (replicas_default() if n_decode is None
                 else max(1, int(n_decode)))
            replicas = []
            try:
                for _ in range(n):
                    replicas.append(
                        self._spawn_replica(self._next_name()))
            except Exception:
                # one bad replica fails construction cleanly: close the
                # good ones, leak no subprocess (the ReplicaPool /
                # ReplicaSpawnError contract)
                for r in replicas:
                    try:
                        r.close(drain=False)
                    except Exception:   # pragma: no cover - teardown
                        pass
                raise
        self.replicas = list(replicas)
        self._next_decode = max(self._next_decode, len(self.replicas))
        if prefill is None:
            m = (prefill_replicas_default() if n_prefill is None
                 else max(0, int(n_prefill)))
            if m and model is None:
                raise ValueError("prefill replicas need the model")
            if process:
                prefill = [
                    ProcessPrefillReplica(
                        model, name=f"prefill{i}", env=prefill_env,
                        page_size=ps, max_seed_pages=max_seed_pages,
                        kv_quant=kv_quant)
                    for i in range(m)]
            else:
                prefill = [
                    PrefillReplica(model, name=f"prefill{i}",
                                   page_size=ps,
                                   max_seed_pages=max_seed_pages,
                                   kv_quant=kv_quant)
                    for i in range(m)]
        self.prefill_replicas = list(prefill)
        self.router = FleetRouter(
            self.replicas, prefill=self.prefill_replicas,
            affinity=affinity, page_size=ps, slo_ms=slo_ms, shed=shed,
            est_ms=est_ms, trace_sample=trace_sample)
        self._init_membership()
        from bigdl_tpu.obs import events
        events.emit("serve", kind="fleet_start",
                    replicas=len(self.replicas),
                    prefill_replicas=len(self.prefill_replicas),
                    affinity=self.router.affinity_enabled,
                    page_size=ps)
        from bigdl_tpu.serve import autoscale as autoscale_mod
        if autoscale_mod.autoscale_default():
            self.start_autoscaler()

    # -- dynamic membership (docs/serving.md "Autoscaling") -----------------
    def _next_name(self) -> str:
        n = self._next_decode
        self._next_decode += 1
        return f"decode{n}"

    def _spawn_replica(self, name: str, env=None):
        """Build one decode replica the way this fleet was configured
        (``replica_factory`` > remote lease > subprocess > in-process).
        Construction IS the warmup: the decoder pre-compiles its
        step/admit/retire programs through the xcache (an identical
        configuration costs zero new compiles) before the router may
        dispatch to it."""
        if self._replica_factory is not None:
            return self._replica_factory(name)
        if self._model is None:
            raise RuntimeError(
                "dynamic membership needs the fleet's model (this "
                "fleet was built from pre-built replicas; pass "
                "replica_factory= to scale it)")
        if self._inventory is not None:
            from bigdl_tpu.serve import remote as remote_mod
            addr = self._inventory.lease()
            try:
                return remote_mod.RemoteDecodeReplica(
                    addr, self._model, name=name,
                    token=self._inventory.token,
                    on_release=self._inventory.release,
                    host_mb=self._host_mb, **self._decoder_kwargs)
            except Exception:
                self._inventory.release(addr)
                raise
        if self._process:
            return ProcessDecodeReplica(
                self._model, name=name,
                env=env if env is not None else self._decode_env,
                host_mb=self._host_mb, **self._decoder_kwargs)
        return DecodeReplica(self._model, name=name,
                             host_mb=self._host_mb,
                             **self._decoder_kwargs)

    # membership()/_update_membership()/remove_replica()/
    # start_autoscaler() come from DynamicMembership — only the decode
    # replicas scale (prefill replicas are not autoscaled)

    def add_replica(self, name: str | None = None,
                    reason: str = "manual", env=None):
        """Spawn and warm one decode replica, then register it with the
        affinity router (``scale``/``up`` event; the ReplicaPool
        contract — decode replicas carry no weight versions, so warmup
        is the construction compile pass alone)."""
        from bigdl_tpu.obs import events
        with self._scale_lock:
            if name is None:
                name = self._next_name()
            self._warming += 1
        self._update_membership()
        try:
            replica = self._spawn_replica(name, env=env)
        except Exception:
            with self._scale_lock:
                self._warming -= 1
            self._update_membership()
            raise
        with self._scale_lock:
            self.replicas.append(replica)
            self.router.add_replica(replica)
            self._warming -= 1
        self._update_membership()
        self._m_scale["up"].inc()
        events.emit("scale", kind="up", replica=name, reason=reason,
                    replicas=len(self.replicas))
        return replica

    # -- request path -------------------------------------------------------
    def submit(self, seed, n_words: int, priority: int = 1,
               slo_ms: float | None = None, ttft_ms: float | None = None,
               on_tokens=None, stream: bool = False,
               sampling=None) -> Future:
        """One decode request through the fleet.  ``on_tokens`` (or
        ``stream=True``) turns on incremental token delivery: chunks
        flow decode replica → router → the returned
        :class:`~bigdl_tpu.serve.streaming.StreamFuture` (across the
        frame protocol for subprocess replicas), byte-identical to the
        resolved row's tail, and the request joins the per-token SLO
        class (``ttft_ms`` / ``BIGDL_SERVE_SLO_TTFT_MS``).

        ``sampling`` (:class:`~bigdl_tpu.serve.sampling.SamplingParams`
        or its dict form) rides the request payload: the PRNG seed is
        RESOLVED here — before the payload can be requeued after a
        replica death — so re-delivery redraws the exact same token
        stream."""
        x = {"seed": [int(t) for t in seed], "n_words": int(n_words)}
        if stream or on_tokens is not None:
            x["stream"] = True
        if sampling is not None:
            from bigdl_tpu.serve.sampling import SamplingParams
            params = SamplingParams.of(sampling).resolved()
            if not params.is_default:
                x["sampling"] = params.to_dict()
        return self.router.submit(x, priority=priority, slo_ms=slo_ms,
                                  ttft_ms=ttft_ms, on_tokens=on_tokens)

    def submit_many(self, seeds, n_words: int, priority: int = 1,
                    slo_ms: float | None = None) -> list:
        return [self.submit(s, n_words, priority=priority, slo_ms=slo_ms)
                for s in seeds]

    # -- telemetry ----------------------------------------------------------
    def merged_registry(self) -> dict:
        """One snapshot covering the whole fleet (the ``ReplicaPool``
        merge contract: this process's registry + every subprocess
        replica's snapshot)."""
        from bigdl_tpu.obs import metrics as obs_metrics
        snaps = [obs_metrics.get().snapshot()]
        for r in list(self.replicas) + list(self.prefill_replicas):
            try:
                snap = r.registry_snapshot()
                if snap:
                    snaps.append(snap)
            except Exception:  # pragma: no cover - racing a death
                logger.warning("telemetry pull failed for replica %s",
                               getattr(r, "name", r))
        return obs_metrics.merge(snaps)

    def stats(self) -> dict:
        out = {"router": self.router.stats(), "replicas": []}
        for r in list(self.replicas) + list(self.prefill_replicas):
            entry = {"name": getattr(r, "name", repr(r)),
                     "role": "prefill" if r in self.prefill_replicas
                     else "decode", "alive": False}
            try:
                entry["alive"] = r.alive()
                if entry["alive"]:
                    entry.update(r.stats())
            except Exception:  # pragma: no cover - racing a death
                pass
            out["replicas"].append(entry)
        return out

    # -- lifecycle ----------------------------------------------------------
    def drain(self, timeout: float = 120.0):
        self.router.drain(timeout)
        return self

    def close(self, drain: bool = True):
        if self.autoscaler is not None:
            self.autoscaler.close()
            self.autoscaler = None
        if drain:
            try:
                self.router.drain()
            except TimeoutError:  # pragma: no cover - shutdown path
                pass
        rstats = self.router.stats()
        self.router.close()
        for r in list(self.replicas) + list(self.prefill_replicas):
            try:
                r.close(drain=drain)
            except Exception:  # pragma: no cover
                pass
        from bigdl_tpu.obs import events
        events.emit("serve", kind="fleet_stop",
                    replicas=len(self.replicas),
                    prefill_replicas=len(self.prefill_replicas),
                    affinity_hits=rstats.get("affinity_hits", 0),
                    affinity_misses=rstats.get("affinity_misses", 0),
                    prefill_shipped=rstats.get("prefill_shipped", 0),
                    prefill_fallback=rstats.get("prefill_fallback", 0))
        try:
            from bigdl_tpu.obs import metrics as obs_metrics
            obs_metrics.get().drop_series(pool=self.name)
        except Exception:   # pragma: no cover - registry mid-teardown
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# subprocess fleet worker
# ---------------------------------------------------------------------------

class DecodeOps(cluster_ops.WorkerOps):
    """Fleet decode-worker ops: ``submit`` with optional shipped pages
    and incremental token frames (each chunk crosses the wire with its
    absolute start index, so the parent-side StreamFuture dedup holds
    across the process/TCP hop)."""

    role = "decode"

    def __init__(self, init, send):
        super().__init__(send)
        self.target = DecodeReplica(init["model"],
                                    **init.get("decoder", {}))

    def _handle_role(self, op, rid, msg) -> bool:
        if op != "submit":
            return super()._handle_role(op, rid, msg)
        self._chaos_kill()
        from bigdl_tpu.obs import trace as obs_trace
        x = {"seed": msg["seed"], "n_words": msg["n_words"]}
        if msg.get("pages"):
            x["pages"] = msg["pages"]
        if msg.get("stream"):
            x["stream"] = True
        if msg.get("sampling"):
            x["sampling"] = msg["sampling"]
        tr = (obs_trace.Trace.from_wire(msg["trace"])
              if msg.get("trace") else None)
        fut = self.target.submit(x, trace=tr)
        if msg.get("stream"):
            fut.on_tokens_indexed(
                lambda toks, start, r=rid: self.send(
                    {"op": "tokens", "id": r, "tokens": toks,
                     "start": start}))
        fut.add_done_callback(
            lambda f, r=rid, t=tr: self._reply(r, f, t))
        return True


class PrefillOps(cluster_ops.WorkerOps):
    """Fleet prefill-worker ops: ``prefill`` resolving to the seed's
    shippable KV page payloads."""

    role = "prefill"

    def __init__(self, init, send):
        super().__init__(send)
        self.target = PrefillReplica(init["model"],
                                     **init.get("prefill", {}))

    def _handle_role(self, op, rid, msg) -> bool:
        if op != "prefill":
            return super()._handle_role(op, rid, msg)
        self._chaos_kill()
        fut = self.target.prefill_async(msg["seed"])
        fut.add_done_callback(lambda f, r=rid: self._reply(r, f))
        return True


def build_fleet_ops(init, send):
    """The fleet-role dispatcher behind
    :func:`bigdl_tpu.serve.cluster.build_worker_ops` — decode and
    prefill workers share the base op set with the engine workers."""
    role = init.get("role")
    if role == "decode":
        return DecodeOps(init, send)
    if role == "prefill":
        return PrefillOps(init, send)
    raise ValueError(f"unknown fleet worker role {init.get('role')!r}")


def fleet_main(stdin=None, stdout=None):
    """Entry point of a fleet ProcessReplica child: host one decode or
    prefill replica (the init frame's ``role``) and answer frames until
    EOF/close — :func:`bigdl_tpu.serve.cluster.worker_main` with the
    fleet ops (:class:`DecodeOps` / :class:`PrefillOps`).

    ``BIGDL_FAULTS=serve_kill@at=N`` kills this process at the Nth
    submitted request / prefill — the chaos site behind the fleet
    drill's prefill-death and decode-requeue assertions."""
    return cluster_ops.worker_main(stdin, stdout)


if __name__ == "__main__":
    sys.exit(fleet_main())
