"""Token-hash prefix caching over the paged KV pool
(docs/serving.md "Paged KV + speculative decode").

Requests that share a leading prompt — the fleet's system-prompt
pattern — recompute identical K/V for identical prefixes: causal
attention makes the K/V at position ``p`` a pure function of tokens
``0..p``.  With block-paged KV (``serve/paging.py``) that redundancy is
a page-granular cache: a retiring request DONATES the full pages whose
positions lie entirely inside its seed to this cache (ownership
transfer, no copy — the pages already hold the right values), and a new
request whose seed matches a cached chain maps those pages into its own
page table read-only and starts decoding at the divergence point,
skipping that much prefill outright.

Keys are the vLLM-style per-page hash chain: page ``j``'s key digests
tokens ``0 .. (j+1)*page_size`` — the whole prefix through that page,
not the page's tokens alone — so two prompts share page ``j`` only when
they agree on EVERYTHING before it.  Divergence is therefore
page-aligned, which is what makes sharing copy-free: a partial page is
never shared, so the first page a request writes is always its own
("copy-on-write" degenerates to "allocate-fresh-at-the-aligned
boundary").

A matched request still re-feeds at least its last seed position — the
first generated token comes from the logits there — so a match is
capped at ``len(seed) - 1`` positions.

Quantized pools (``BIGDL_SERVE_KV_QUANT``, docs/serving.md "Quantized
serving") need no cooperation from this cache: the per-page-row scale
arrays are indexed by PHYSICAL page id exactly like the value pools
(``quant/kv.py``), so donating a page id ships its scales with it and
a hit dequantizes to bit-identical K/V — the hit-vs-cold output
equality contract survives quantization unchanged.

Eviction is LRU over chain entries whose page nobody else holds
(refcount 1 = cache-only); the decoder evicts on demand when an
admission cannot find free pages.  Evicting a mid-chain entry strands
its descendants unreachable — they stop being refreshed and drain out
of the same LRU sweep, so reclamation is eventual, not leaked.

``on_evict`` lets a second tier intercept the page content before the
pool reclaims it (the host-RAM KV tier, ``serve/kvtier.py``) without
this module importing the tier: the hook fires AFTER the chain entry
is removed and BEFORE the pool reference drops, so the page's content
is still addressable and a hook that re-enters the cache (or the pool
free-list) observes consistent state — the mid-allocation regression
``tests/test_fleet.py`` pins.  A hook failure is logged and the
eviction completes; the page is never leaked for a telemetry error.
"""
from __future__ import annotations

import hashlib
import logging
from collections import OrderedDict

import numpy as np

logger = logging.getLogger("bigdl_tpu.serve")


def chain_keys(seed, n_pages: int, page_size: int):
    """Yield page ``j``'s chain key for ``j = 0 .. n_pages - 1``:
    ``digest(parent_key || tokens of page j)``, an incremental digest
    over the whole prefix through page ``j`` (O(tokens) for the whole
    chain, not O(tokens²) as rehashing each prefix from scratch would
    be) — two prompts share a key only when they agree on everything
    before it."""
    toks = np.asarray(seed[:n_pages * page_size], np.int32)
    key = b""
    for j in range(n_pages):
        h = hashlib.sha1(key)
        h.update(toks[j * page_size:(j + 1) * page_size].tobytes())
        key = h.digest()
        yield key


#: back-compat alias (the public name is :func:`chain_keys` — the
#: fleet router and the host tier key on the same chain)
_chain_keys = chain_keys


class PrefixCache:
    """Chain-hash → page-id map over one :class:`~bigdl_tpu.serve.paging.PagePool`.

    The cache owns one reference on every page it holds; :meth:`match`
    retains matched pages for the requesting slot (the caller releases
    them at retire through :meth:`insert`'s duplicate path or
    ``pool.release``).

    ``on_evict(key, pid)`` — optional tier intercept: called once per
    evicted entry while the page content is still live (see module
    docstring for the ordering/failure contract)."""

    def __init__(self, pool, on_evict=None):
        self.pool = pool
        self.on_evict = on_evict
        self._entries: "OrderedDict[bytes, int]" = OrderedDict()
        self.hits = 0          # requests that matched >= 1 page
        self.misses = 0        # requests that matched none
        self.pages_reused = 0  # total pages served from the cache
        self.inserted = 0      # pages donated into the cache
        self.evicted = 0       # pages evicted back to the pool
        self.adopted = 0       # pages adopted (prefill ship / re-admit)

    def __len__(self) -> int:
        return len(self._entries)

    def match(self, seed) -> list:
        """Longest cached chain of full pages agreeing with ``seed``,
        capped at ``len(seed) - 1`` positions (the last seed position
        must be re-fed to produce the first generated token).  Returns
        the page ids in logical order, each RETAINED for the caller.
        Does NOT touch the hit/miss counters — an admission attempt can
        fail allocation after matching and retry later; the decoder
        calls :meth:`note_request` once per request actually admitted."""
        ps = self.pool.page_size
        max_pages = max(0, (len(seed) - 1) // ps)
        pids = []
        for key in chain_keys(seed, max_pages, ps):
            pid = self._entries.get(key)
            if pid is None:
                break
            self._entries.move_to_end(key)
            pids.append(pid)
        for pid in pids:
            self.pool.retain(pid)
        return pids

    def has(self, key: bytes) -> bool:
        return key in self._entries

    def lookup(self, key: bytes):
        """The page id cached under one chain key (LRU-touched and
        RETAINED for the caller), or ``None``.  The per-key counterpart
        of :meth:`match` for callers that walk the chain themselves
        (the tier re-admit path interleaves cache and tier lookups)."""
        pid = self._entries.get(key)
        if pid is None:
            return None
        self._entries.move_to_end(key)
        self.pool.retain(pid)
        return pid

    def adopt(self, key: bytes, pid: int) -> bool:
        """Register a freshly written page under ``key`` — the prefill
        ship / host-tier re-admit entry point: ownership of the
        caller's reference transfers to the cache (exactly
        :meth:`insert`'s contract for one page whose chain key is
        already known).  False (and the reference is released) when the
        key is already cached."""
        if key in self._entries:
            self._entries.move_to_end(key)
            self.pool.release(pid)
            return False
        self._entries[key] = pid
        self.adopted += 1
        return True

    def note_request(self, matched_pages: int):
        """Count one admitted request against the hit/miss ledger."""
        if matched_pages > 0:
            self.hits += 1
            self.pages_reused += matched_pages
        else:
            self.misses += 1

    def insert(self, seed, pids):
        """Donate a retiring request's leading pages: ``pids[j]`` must
        hold the K/V of positions ``j*ps .. (j+1)*ps - 1`` computed
        under ``seed``.  Ownership of each page transfers to the cache
        (the caller's reference is consumed); when the chain key is
        already cached — including the pages this very request matched
        at admit — the caller's reference is simply released."""
        ps = self.pool.page_size
        for key, pid in zip(chain_keys(seed, len(pids), ps), pids):
            have = self._entries.get(key)
            if have is not None:
                self._entries.move_to_end(key)
                self.pool.release(pid)
            else:
                self._entries[key] = pid
                self.inserted += 1

    def evict(self, n: int) -> int:
        """Free up to ``n`` least-recently-used cache-only pages
        (refcount 1 — shared pages some live slot still maps are
        skipped) in ONE scan; returns the number freed.  One scan per
        allocation attempt keeps admission under cache pressure linear
        in the cache size, not entries x pages."""
        freed = 0
        for key in list(self._entries):
            if freed >= n:
                break
            pid = self._entries.get(key)
            if pid is None:     # a hook re-entered and evicted it
                continue
            if self.pool.refcount(pid) == 1:
                # entry removed BEFORE the hook fires so a re-entrant
                # hook (alloc/evict from inside the intercept) sees a
                # consistent cache; the page is released AFTER so the
                # hook can still snapshot its content — and released
                # even when the hook fails (no leak for telemetry)
                del self._entries[key]
                if self.on_evict is not None:
                    try:
                        self.on_evict(key, pid)
                    except Exception:
                        logger.warning(
                            "prefix on_evict hook failed for page %d",
                            pid, exc_info=True)
                self.pool.release(pid)
                self.evicted += 1
                freed += 1
        return freed

    def evict_one(self) -> bool:
        """Free the single LRU cache-only page; False when nothing is
        evictable."""
        return self.evict(1) == 1

    def drop_all(self):
        """Release every cache-held page (decoder teardown)."""
        while self._entries:
            _, pid = self._entries.popitem(last=False)
            self.pool.release(pid)

    def stats(self) -> dict:
        return {"entries": len(self._entries), "hits": self.hits,
                "misses": self.misses, "pages_reused": self.pages_reused,
                "inserted": self.inserted, "evicted": self.evicted,
                "adopted": self.adopted}
