"""SLO-driven autoscaler: the closed loop over the serving fleet's
merged telemetry (docs/serving.md "Autoscaling").

Every mechanism a production fleet needs already exists — the SLO
router (shed/requeue), fleet telemetry (``ReplicaPool.merged_registry``
— true registry merge, pooled quantiles), two-phase weight rollout,
alert rules with hysteresis — but the control loop was a human:
``BIGDL_SERVE_REPLICAS`` pinned the replica count at construction.
:class:`Autoscaler` closes the loop:

- **watch**: on a cadence, pull one merged-registry snapshot and
  compute the overload signals with EXACTLY the windowed-delta
  arithmetic ``serve_top``/``obs/alerts.py`` use — windowed p99
  (``metrics.windowed_counts`` bucket deltas), queue depth
  (point-in-time gauge totals), shed rate (counter deltas over the
  window, router admission-stage sheds folded in once), and SLO burn
  (``alerts.slo_burn`` — (shed+failed)/offered over the window,
  divided by the error budget);
- **decide**: breach any up-signal for ``up_n`` consecutive ticks →
  scale up; fully idle (zero queue, zero sheds, offered rate per
  replica under the floor) for ``down_n`` consecutive ticks → scale
  down.  Asymmetric hysteresis (fast up, slow down) plus a cooldown
  after every committed action keep a value dancing on the bound from
  flapping the fleet;
- **act**: ``pool.add_replica()`` — which spawns, warms through the
  xcache and the WeightStore's COMMITTED version, and only then joins
  the dispatch set — or ``pool.remove_replica()`` — drain-only mark,
  wait to zero backlog, close; zero dropped futures — inside the
  ``[min_replicas, max_replicas]`` bounds.

Cross-host fleets scale through the same two calls: with
``BIGDL_SERVE_HOSTS`` set, ``add_replica`` leases the next agent
address from the :class:`~bigdl_tpu.serve.remote.HostInventory` and
``remove_replica``/death releases it; an exhausted inventory raises
``ReplicaSpawnError`` — the same typed failure local spawn uses — so
the breaker below freezes scaling instead of crash-looping when the
machine pool is spent (docs/serving.md "Cross-host fleet").

Spawn failure is survived, not crash-looped: each scale-up cycle
retries ``spawn_retries`` times with jittered exponential backoff
(seeded — drills replay byte-identically), and ``breaker_n``
consecutive failed cycles open a circuit breaker: the
``fleet_scale_frozen`` gauge goes 1 (a default alert rule fires on
it), a ``scale``/``frozen`` event lands in the log, and no further
spawns are attempted until ``breaker_reset_s`` passes (then ONE
half-open attempt; success closes the breaker and emits
``unfrozen``).

Every committed decision emits a schema-validated ``scale`` obs event
(``obs/events.SCALE_KINDS``), so the whole scale/recovery timeline
renders in ``tools/obs_report.py`` and the capstone chaos drill can
assert on it.

The Autoscaler is duck-typed over any pool exposing
``merged_registry() / membership() / add_replica(reason=) /
remove_replica(reason=, timeout=)`` — :class:`~bigdl_tpu.serve.cluster.
ReplicaPool` and :class:`~bigdl_tpu.serve.fleet.DecodeFleet` both do.

Flags: ``BIGDL_SERVE_AUTOSCALE`` (auto-start at pool construction,
default off), ``BIGDL_SERVE_MIN_REPLICAS`` / ``BIGDL_SERVE_MAX_REPLICAS``
(bounds, default 1/8), ``BIGDL_SERVE_SCALE_INTERVAL`` (cadence seconds,
default 2).
"""
from __future__ import annotations

import logging
import os
import random
import threading
import time
from collections import deque

from bigdl_tpu.obs import alerts as obs_alerts
from bigdl_tpu.obs import metrics as obs_metrics

logger = logging.getLogger("bigdl_tpu.serve")

ENV_AUTOSCALE = "BIGDL_SERVE_AUTOSCALE"
ENV_MIN_REPLICAS = "BIGDL_SERVE_MIN_REPLICAS"
ENV_MAX_REPLICAS = "BIGDL_SERVE_MAX_REPLICAS"
ENV_INTERVAL = "BIGDL_SERVE_SCALE_INTERVAL"

DEFAULT_MIN_REPLICAS = 1
DEFAULT_MAX_REPLICAS = 8
DEFAULT_INTERVAL_S = 2.0


def autoscale_default() -> bool:
    return os.environ.get(ENV_AUTOSCALE, "0") != "0"


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def min_replicas_default() -> int:
    return max(1, _env_int(ENV_MIN_REPLICAS, DEFAULT_MIN_REPLICAS))


def max_replicas_default() -> int:
    return max(1, _env_int(ENV_MAX_REPLICAS, DEFAULT_MAX_REPLICAS))


def interval_default() -> float:
    return max(0.05, _env_float(ENV_INTERVAL, DEFAULT_INTERVAL_S))


class Autoscaler:
    """Watch → decide → act over a replica pool's merged registry.

    ``evaluate_once(snapshot=, now=)`` is the testable core: one tick
    with injectable snapshot/clock, returning the computed signals, the
    decision and whether an action committed.  ``start()`` runs it on a
    cadence thread; ``close()`` stops and joins it (the sampler/Router
    lifecycle contract).

    Up-signal thresholds (any breach counts): ``up_queue_depth``
    (queue depth per live replica), ``up_shed_per_s`` (windowed shed
    rate), ``up_burn`` (multikind SLO burn — the serve_top column
    math), ``up_p99_ms`` (windowed fleet p99; 0 disables).  Down:
    ``down_idle_rps`` — windowed offered rate per live replica below
    this with zero queue and zero sheds counts one idle tick."""

    def __init__(self, pool, min_replicas: int | None = None,
                 max_replicas: int | None = None,
                 interval: float | None = None, window_s: float = 10.0,
                 budget: float = 0.01, up_queue_depth: float = 8.0,
                 up_shed_per_s: float = 0.5, up_burn: float = 1.0,
                 up_p99_ms: float = 0.0, down_idle_rps: float = 0.5,
                 up_n: int = 1, down_n: int = 5,
                 cooldown_s: float | None = None,
                 drain_timeout: float = 120.0, spawn_retries: int = 3,
                 backoff_s: float = 0.25, backoff_jitter: float = 0.5,
                 breaker_n: int = 3, breaker_reset_s: float = 60.0,
                 seed: int = 0, emit_events: bool = True):
        self.pool = pool
        self.min_replicas = (min_replicas_default() if min_replicas is None
                             else max(1, int(min_replicas)))
        self.max_replicas = (max_replicas_default() if max_replicas is None
                             else max(1, int(max_replicas)))
        if self.max_replicas < self.min_replicas:
            raise ValueError(f"max_replicas {self.max_replicas} < "
                             f"min_replicas {self.min_replicas}")
        self.interval = (interval_default() if interval is None
                         else max(0.05, float(interval)))
        self.window_s = float(window_s)
        self.budget = float(budget)
        self.up_queue_depth = float(up_queue_depth)
        self.up_shed_per_s = float(up_shed_per_s)
        self.up_burn = float(up_burn)
        self.up_p99_ms = float(up_p99_ms)
        self.down_idle_rps = float(down_idle_rps)
        self.up_n = max(1, int(up_n))
        self.down_n = max(1, int(down_n))
        #: post-action quiet period: the signal window must refill with
        #: post-change traffic before the next decision can commit
        self.cooldown_s = (3.0 * self.interval if cooldown_s is None
                           else max(0.0, float(cooldown_s)))
        self.drain_timeout = float(drain_timeout)
        self.spawn_retries = max(1, int(spawn_retries))
        self.backoff_s = max(0.0, float(backoff_s))
        self.backoff_jitter = max(0.0, float(backoff_jitter))
        self.breaker_n = max(1, int(breaker_n))
        self.breaker_reset_s = max(0.0, float(breaker_reset_s))
        self._rng = random.Random(seed)
        self._emit_events = emit_events

        self._lock = threading.Lock()
        self._hist: deque = deque()       # (now, snapshot)
        self._up_streak = 0
        self._down_streak = 0
        self._last_action_at: float | None = None
        self._spawn_failures = 0          # consecutive failed up-cycles
        self._frozen_until: float | None = None
        self._stop = threading.Event()
        self._thread = None
        self.evaluations = 0              # cadence audit hook
        self.scale_ups = 0
        self.scale_downs = 0

        pool_name = getattr(pool, "name", "pool")
        reg = obs_metrics.get()
        self._m_failures = reg.counter(
            "fleet_scale_failures_total",
            "failed replica spawn attempts (autoscaler retry loop)",
            pool=pool_name)
        # declared at 0 up front (the alert_active precedent): serve_top
        # and the default fleet_scale_frozen alert rule can read "not
        # frozen" instead of "no autoscaler"
        self._m_frozen = reg.gauge(
            "fleet_scale_frozen",
            "1 while the spawn circuit breaker is open", agg="max",
            pool=pool_name)
        self._m_frozen.set(0.0)

    # -- signals ------------------------------------------------------------
    def _window_snap(self, now: float):
        """Oldest retained snapshot inside the window (fallback: the
        oldest held — a shorter window biases rates toward firing
        later, never spuriously; the alert engine's rule)."""
        chosen = None
        for ts, snap in self._hist:
            if ts >= now - self.window_s:
                chosen = (ts, snap)
                break
        if chosen is None and self._hist:
            chosen = self._hist[0]
        return chosen

    @staticmethod
    def _shed_total(snap) -> float:
        """Engine sheds + router ADMISSION-stage sheds (the disjoint
        stages contract: replica-stage sheds already live in the engine
        counters — serve_top's fold-once rule)."""
        if not snap:
            return 0.0
        return (obs_metrics.family_total(snap, "serve_requests_total",
                                         outcome="shed")
                + obs_metrics.family_total(snap, "router_requests_total",
                                           outcome="shed",
                                           stage="admission"))

    def signals(self, cur: dict, now: float, membership: dict) -> dict:
        """The decision inputs for one tick, computed from the current
        merged snapshot against the windowed reference — serve_top's
        exact column math (pure given (snapshot, now, membership):
        drills feed synthetic registries through it)."""
        ref = self._window_snap(now)
        prev, dt = (None, 0.0) if ref is None else (ref[1], now - ref[0])
        live = max(1, int(membership.get("live", 1)))
        queue = (obs_metrics.family_total(cur, "serve_queue_depth")
                 + obs_metrics.family_total(cur, "router_queue_depth"))

        def delta(name, **match):
            d = obs_metrics.family_total(cur, name, **match) - (
                obs_metrics.family_total(prev, name, **match)
                if prev else 0.0)
            return max(d, 0.0)

        shed_per_s = ((self._shed_total(cur) - self._shed_total(prev))
                      / dt if prev is not None and dt > 0 else 0.0)
        shed_per_s = max(shed_per_s, 0.0)
        offered = (delta("serve_requests_total", outcome="accepted")
                   + (self._shed_total(cur) - self._shed_total(prev)
                      if prev is not None else 0.0)) \
            if prev is not None else 0.0
        offered_per_s = offered / dt if dt > 0 else 0.0
        burn = (obs_alerts.slo_burn(cur, prev, self.budget)
                if prev is not None else None)
        # p99 only once a window EXISTS: windowed_counts falls back to
        # the lifetime histogram with no prev, which is the right call
        # for a dashboard column but would let stale pre-loop latencies
        # trigger a scale-up on the very first tick
        p99 = None
        if prev is not None:
            wc = obs_metrics.windowed_counts(cur, prev,
                                             "serve_latency_seconds")
            if wc is not None and sum(wc[1]) > 0:
                p99 = obs_metrics.quantile(wc[0], wc[1], 99)
        return {
            "queue": queue,
            "queue_per_replica": queue / live,
            "shed_per_s": shed_per_s,
            "burn": burn,
            "p99_ms": None if p99 is None else p99 * 1e3,
            "offered_per_s": offered_per_s,
            "offered_per_replica": offered_per_s / live,
            "live": live,
            "window_s": dt,
        }

    # -- decision -----------------------------------------------------------
    def frozen(self, now: float | None = None) -> bool:
        """True while the spawn circuit breaker is open (scale-ups are
        suppressed; after ``breaker_reset_s`` one half-open attempt is
        allowed)."""
        with self._lock:
            until = self._frozen_until
        if until is None:
            return False
        return (time.monotonic() if now is None else now) < until

    def _breach_reasons(self, sig: dict) -> list:
        reasons = []
        if sig["queue_per_replica"] > self.up_queue_depth:
            reasons.append(f"queue/replica {sig['queue_per_replica']:.1f}"
                           f" > {self.up_queue_depth:g}")
        if sig["shed_per_s"] > self.up_shed_per_s:
            reasons.append(f"shed rate {sig['shed_per_s']:.2f}/s > "
                           f"{self.up_shed_per_s:g}/s")
        if sig["burn"] is not None and sig["burn"] > self.up_burn:
            reasons.append(f"slo burn {sig['burn']:.2f} > "
                           f"{self.up_burn:g}")
        if (self.up_p99_ms > 0 and sig["p99_ms"] is not None
                and sig["p99_ms"] > self.up_p99_ms):
            reasons.append(f"p99 {sig['p99_ms']:.1f} ms > "
                           f"{self.up_p99_ms:g} ms")
        return reasons

    def decide(self, sig: dict, membership: dict,
               now: float) -> tuple:
        """``("up"|"down"|None, reason)`` — hysteresis, cooldown and
        bounds applied; no side effects beyond the streak counters."""
        in_cooldown = (self._last_action_at is not None
                       and now - self._last_action_at < self.cooldown_s)
        reasons = self._breach_reasons(sig)
        if reasons:
            self._down_streak = 0
            self._up_streak += 1
            if self._up_streak < self.up_n or in_cooldown:
                return None, None
            total = (membership.get("live", 0)
                     + membership.get("warming", 0))
            if total >= self.max_replicas:
                return None, f"at max_replicas {self.max_replicas}"
            return "up", "; ".join(reasons)
        self._up_streak = 0
        idle = (sig["queue"] == 0 and sig["shed_per_s"] == 0
                and sig["offered_per_replica"] < self.down_idle_rps)
        if not idle:
            self._down_streak = 0
            return None, None
        self._down_streak += 1
        if self._down_streak < self.down_n or in_cooldown:
            return None, None
        if membership.get("live", 0) <= self.min_replicas:
            return None, f"at min_replicas {self.min_replicas}"
        return "down", (f"idle {self._down_streak} ticks: "
                        f"offered/replica "
                        f"{sig['offered_per_replica']:.2f}/s < "
                        f"{self.down_idle_rps:g}/s, queue 0")

    # -- actions ------------------------------------------------------------
    def _emit(self, kind: str, **fields):
        if not self._emit_events:
            return
        try:
            from bigdl_tpu.obs import events
            events.emit("scale", kind=kind, **fields)
        except Exception:   # pragma: no cover - telemetry must not kill
            logger.warning("scale event emit failed", exc_info=True)

    def scale_up(self, reason: str, now: float | None = None) -> bool:
        """One scale-up cycle: ``spawn_retries`` attempts with jittered
        exponential backoff; exhausting them counts one breaker strike.
        ``breaker_n`` strikes open the breaker (``fleet_scale_frozen``
        gauge + ``frozen`` event) — degraded to an alert, never a crash
        loop.  Success closes an open breaker (``unfrozen``)."""
        now = time.monotonic() if now is None else now
        err = None
        for attempt in range(1, self.spawn_retries + 1):
            try:
                replica = self.pool.add_replica(reason=reason)
            except Exception as e:
                err = e
                self._m_failures.inc()
                self._emit("spawn_failed", attempt=attempt,
                           error=f"{type(e).__name__}: {e}")
                logger.warning("autoscaler: replica spawn attempt "
                               "%d/%d failed: %s", attempt,
                               self.spawn_retries, e)
                if attempt < self.spawn_retries and self.backoff_s:
                    delay = (self.backoff_s * (2 ** (attempt - 1))
                             * (1.0 + self.backoff_jitter
                                * self._rng.random()))
                    time.sleep(delay)
                continue
            with self._lock:
                self._spawn_failures = 0
                was_frozen = self._frozen_until is not None
                self._frozen_until = None
            if was_frozen:
                self._m_frozen.set(0.0)
                self._emit("unfrozen")
            self.scale_ups += 1
            self._last_action_at = now
            logger.info("autoscaler: scaled up (+%s): %s",
                        getattr(replica, "name", replica), reason)
            return True
        with self._lock:
            self._spawn_failures += 1
            failures = self._spawn_failures
            trip = (failures >= self.breaker_n
                    and self._frozen_until is None)
            if trip or self._frozen_until is not None:
                self._frozen_until = now + self.breaker_reset_s
        if trip:
            self._m_frozen.set(1.0)
            self._emit("frozen", failures=failures,
                       error=f"{type(err).__name__}: {err}",
                       reset_s=self.breaker_reset_s)
            logger.error("autoscaler: spawn circuit breaker OPEN after "
                         "%d consecutive failed cycles (last: %s); "
                         "fleet_scale_frozen raised", failures, err)
        return False

    def scale_down(self, reason: str, now: float | None = None) -> bool:
        now = time.monotonic() if now is None else now
        try:
            self.pool.remove_replica(reason=reason,
                                     timeout=self.drain_timeout)
        except (ValueError, TimeoutError) as e:
            logger.warning("autoscaler: scale-down skipped: %s", e)
            return False
        self.scale_downs += 1
        self._last_action_at = now
        logger.info("autoscaler: scaled down: %s", reason)
        return True

    # -- the tick -----------------------------------------------------------
    def evaluate_once(self, snapshot=None, now=None) -> dict:
        """One watch→decide→act tick.  ``snapshot``/``now`` injectable
        (drills feed synthetic registries and a logical clock); returns
        ``{"signals", "decision", "reason", "acted"}``."""
        if now is None:
            now = time.monotonic()
        if snapshot is None:
            try:
                snapshot = self.pool.merged_registry()
            except Exception as e:  # pragma: no cover - racing close
                logger.warning("autoscaler snapshot pull failed: %s", e)
                return {"signals": None, "decision": None,
                        "reason": None, "acted": False}
        membership = self.pool.membership()
        sig = self.signals(snapshot, now, membership)
        decision, reason = self.decide(sig, membership, now)
        acted = False
        if decision == "up":
            if not self.frozen(now):
                acted = self.scale_up(reason, now)
            else:
                decision, reason = None, "breaker open (frozen)"
        elif decision == "down":
            acted = self.scale_down(reason, now)
        if acted:
            self._up_streak = self._down_streak = 0
        # history AFTER evaluation: windowed signals difference the
        # current snapshot against strictly older ones
        self._hist.append((now, snapshot))
        horizon = self.window_s * 1.25 + self.interval
        while len(self._hist) > 2 and self._hist[0][0] < now - horizon:
            self._hist.popleft()
        self.evaluations += 1
        return {"signals": sig, "decision": decision, "reason": reason,
                "acted": acted}

    # -- cadence thread -----------------------------------------------------
    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                self.evaluate_once()
            except Exception:   # pragma: no cover - defensive
                logger.warning("autoscaler tick failed", exc_info=True)

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name="bigdl-serve-autoscale")
            self._thread.start()
        return self

    def close(self, timeout: float = None):
        """Stop-event + bounded join (the sampler/Router lifecycle
        contract) — idempotent.  The join bound covers a tick that is
        mid-drain on a scale-down."""
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=(self.drain_timeout + 10.0
                            if timeout is None else timeout))
        self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
