"""Quantized serving: reduced-precision weights and KV pages
(docs/serving.md "Quantized serving").

The serving stack's only reduced-precision path used to be the
``DTypePolicy`` bf16 compute scope (``serve/engine.py``); this package
adds the density levers that actually shrink HBM:

- :mod:`bigdl_tpu.quant.weights` — per-channel symmetric int8 (and,
  where the installed XLA supports the dtype, fp8 ``e4m3``) weight
  quantization of Linear / conv / attention-projection weights, with an
  optional activation-aware calibration pass (LLM.int8() / AWQ-style
  clip search).  Serving executables take ``(qweights, scales)`` as
  ARGUMENTS and dequantize on the fly inside the compiled forward, so
  the quantized path rides the same ``serve/xcache.py`` AOT keys as
  full precision — with the quant recipe folded into the function key
  so the two never collide.
- :mod:`bigdl_tpu.quant.calibrate` — the calibration pass: run a
  calibration split through the model (the ``optim.validate`` loop's
  iteration idiom, eagerly, with activation taps installed on the
  quantizable layers) and collect per-input-channel amax; the same
  sweep returns the fp32 baseline metrics the accuracy budget is
  declared against.
- :mod:`bigdl_tpu.quant.kv` — int8 KV page storage for the block-paged
  decode pool (``serve/decode.py``): per-page-row, per-head scales
  carried as parallel pool-indexed traced arrays, quantize-on-scatter /
  dequantize-on-gather inside ``models/transformer._lm_forward_window``.
  Because scales are indexed by PHYSICAL page id, prefix-cache page
  donation (``serve/prefix.py``) ships them with the pages for free.

Adoption is gated like kernels (docs/performance.md adoption rule):
``BIGDL_SERVE_QUANT`` / ``BIGDL_SERVE_KV_QUANT`` default **off**, the
calibration+accuracy harness ``tools/quant_check.py`` pins top1/top5
within the declared budget below, and the spec-decode acceptance-length
histogram (``decode_spec_accept_len``) is the LM-quality canary — a
quantized draft that tanks acceptance shows up immediately.
"""
from __future__ import annotations

import os

#: weight-quantization mode for serving engines: off | int8 | fp8
ENV_QUANT = "BIGDL_SERVE_QUANT"
#: KV-page quantization mode for the paged decoder: off | int8
ENV_KV_QUANT = "BIGDL_SERVE_KV_QUANT"

#: the declared accuracy budget (tools/quant_check.py, the acceptance
#: gate in docs/serving.md): quantized top1/top5 on the real_data.py
#: harness must be within this of the fp32 baseline
WEIGHT_TOP1_BUDGET = 0.02
WEIGHT_TOP5_BUDGET = 0.02
#: greedy-decode drift budget for int8 KV pages: the fraction of
#: generated tokens allowed to diverge from the fp-KV stream on the
#: bench model (tools/bench_serve.py --decode-sweep --check)
KV_TOKEN_DRIFT_BUDGET = 0.10


def normalize_mode(raw, allowed: tuple, what: str) -> str:
    """ONE normalizer for every quant-mode knob (env vars and the
    ``ServeEngine(quant=)`` / ``ContinuousDecoder(kv_quant=)`` kwargs):
    off-ish spellings collapse to ``"off"``, anything else must be in
    ``allowed``.  ``what`` names the knob in the error."""
    raw = str(raw).strip().lower()
    if raw in ("", "0", "off", "none"):
        return "off"
    if raw in allowed:
        return raw
    raise ValueError(
        f"{what}={raw!r} is not a known quantization mode "
        f"(expected one of {('off',) + allowed})")


def _mode(env: str, allowed: tuple) -> str:
    return normalize_mode(os.environ.get(env, ""), allowed, env)


def weight_mode_default() -> str:
    """``BIGDL_SERVE_QUANT`` resolved to off/int8/fp8 (default off)."""
    from bigdl_tpu.quant.weights import ON_MODES
    return _mode(ENV_QUANT, ON_MODES)


def kv_mode_default() -> str:
    """``BIGDL_SERVE_KV_QUANT`` resolved to off/int8 (default off)."""
    from bigdl_tpu.quant.kv import ON_MODES
    return _mode(ENV_KV_QUANT, ON_MODES)


from bigdl_tpu.quant.weights import (  # noqa: E402,F401
    UnsupportedQuantError, WeightQuantizer, dequantize_params,
    quantize_channelwise, supports_fp8,
)
from bigdl_tpu.quant.calibrate import Calibration, collect  # noqa: E402,F401
from bigdl_tpu.quant import kv  # noqa: E402,F401

__all__ = [
    "ENV_QUANT", "ENV_KV_QUANT", "normalize_mode",
    "weight_mode_default", "kv_mode_default",
    "WEIGHT_TOP1_BUDGET", "WEIGHT_TOP5_BUDGET", "KV_TOKEN_DRIFT_BUDGET",
    "WeightQuantizer", "UnsupportedQuantError", "quantize_channelwise",
    "dequantize_params", "supports_fp8", "Calibration", "collect", "kv",
]
