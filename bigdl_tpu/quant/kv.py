"""int8 KV-page storage for the block-paged decode pool
(docs/serving.md "Quantized serving"; the pool itself is
``serve/paging.py`` + ``models/transformer._lm_forward_window``).

The paged KV pools are ``(layers, n_pages, page_size, H, hd)`` float32;
at serving batch sizes they ARE the HBM budget, so int8 storage roughly
quadruples pooled tokens at equal bytes — which is live concurrency,
because the paged decoder admits by pooled tokens (``--decode-sweep``).

Scheme: **per-page-row, per-head scales** — one float32 scale per
``(layer, page, in-page position, head)`` covering that row's ``hd``
values, stored in parallel ``(layers, n_pages, page_size, H)`` pool
arrays carried as traced state next to the pools themselves.  Finer
than one scale per page on purpose, for three load-bearing properties:

- a scatter never touches neighbouring rows, so there is no
  requantize-the-page step and no scale coupling between requests that
  share a page read-only (prefix cache);
- scales are indexed by PHYSICAL page id exactly like the values, so
  prefix-cache page donation (``serve/prefix.py``) ships the scales
  with the pages — a prefix hit dequantizes to bit-identical K/V and
  the hit-vs-cold output equality contract survives quantization;
- speculative decode stays EXACTLY identical to the non-speculative
  quantized stream for every draft length: rejected draft positions
  are overwritten value+scale by the next verify window, and a page's
  committed rows never change representation afterwards (a per-page
  running amax would let a rejected draft outlier permanently coarsen
  the page — ``tests/test_quant.py`` pins the identity).

Per-head (not per-``(H, hd)`` row) because under tensor parallelism the
scale arrays shard on their head dim with the SAME PartitionSpec as the
pools — each shard quantizes its local heads with zero cross-shard
communication.

Write: ``q = clip(round(k / s), ±127)`` with ``s = max|k|_hd / 127``;
read: the page-gathered attention view multiplies the gathered scale
rows back in.  Worst-case error is ``amax/254`` per head-row.  The
quantize/dequantize helpers here are traced inside the decode step
(``_lm_forward_window``); everything stays jnp.
"""
from __future__ import annotations

import numpy as np

QMAX = 127.0
EPS = 1e-8
#: modes the paged decoder accepts — THE source of truth for
#: ``kv_mode_default()`` and ``ContinuousDecoder(kv_quant=)``
#: validation (fp8 KV is not offered: e4m3 has ~2 decimal digits —
#: attention logits visibly drift — and the int8 path already caps
#: storage at 1 byte/value)
MODES = ("off", "int8")
#: MODES minus "off": what normalize_mode() accepts beyond off-ish
ON_MODES = tuple(m for m in MODES if m != "off")

scale_dtype = np.float32
storage_dtype = np.int8


def quantize_rows(x):
    """Quantize ``(..., H, hd)`` K/V rows per head: returns
    ``(q int8 (..., H, hd), scales f32 (..., H))``.  Traced (jnp) —
    this runs inside the compiled decode step on every scatter."""
    import jax.numpy as jnp

    amax = jnp.max(jnp.abs(x), axis=-1)
    s = jnp.maximum(amax, EPS) / QMAX
    q = jnp.clip(jnp.round(x / s[..., None]), -QMAX, QMAX)
    return q.astype(jnp.int8), s.astype(jnp.float32)


def dequantize_view(q, s):
    """Dequantize a gathered view: ``q`` int8 ``(..., H, hd)`` with
    scales ``(..., H)`` back to float32."""
    import jax.numpy as jnp

    return q.astype(jnp.float32) * s[..., None]


def scale_shape(pool_shape) -> tuple:
    """Scale-array shape for a ``(L, n_pages, page_size, H, hd)`` pool:
    the same pool minus the ``hd`` dim."""
    return tuple(pool_shape[:-1])


def bytes_per_token(n_layers: int, n_heads: int, head_dim: int,
                    mode: str = "off") -> int:
    """KV bytes one pooled token costs across all layers (K and V,
    scales included) — the ``decode_kv_bytes_per_token`` gauge and the
    equal-HBM pool sizing in ``tools/bench_serve.py --decode-sweep``."""
    if mode == "int8":
        per_layer = 2 * (n_heads * head_dim * 1 + n_heads * 4)
    else:
        per_layer = 2 * n_heads * head_dim * 4
    return n_layers * per_layer
