"""Per-channel weight quantization for serving (docs/serving.md
"Quantized serving").

The scheme is symmetric per-OUTPUT-channel quantization (LLM.int8(),
Dettmers et al. 2022): each output channel ``c`` of a weight stores
``q = round(W_c / s_c)`` in int8 with one float scale ``s_c =
amax_c / 127``, so the worst-case round-trip error is ``amax_c / 254``
per channel — the bound ``tests/test_quant.py`` pins.  With a
calibration (``quant/calibrate.py``) the scale comes from an
activation-aware clip search (AWQ-flavored, Lin et al. 2023): per
output channel, pick the clip ratio minimizing the ACTIVATION-WEIGHTED
quantization error, so channels whose inputs run hot keep precision
where it matters and channels feeding dead inputs may clip outliers.

fp8 ``e4m3`` is the same recipe with the mantissa doing the rounding
(scale maps amax to the format's ±448 range).  It is CAPABILITY-GATED:
:func:`supports_fp8` probes the installed XLA once (the jax/jaxlib
span this framework runs on includes versions without fp8 lowering on
every backend), and :class:`WeightQuantizer` raises
:class:`UnsupportedQuantError` with a clear "unsupported on this XLA"
message instead of failing somewhere inside a trace.

Serving integration: quantized weights are executable ARGUMENTS, never
constants — :func:`quantized_eval_fn` builds the jitted forward
``fwd(qpack, state, x)`` that dequantizes on the fly (one fused
``int8 -> f32 * scale`` per weight, which XLA folds into the consumer
matmul's prologue) and wraps it in ``xcache.ShapedCallable`` with the
quant recipe folded into the function key, so quantized and
full-precision replicas of one architecture ride the same shared
executable cache without ever colliding (``serve/xcache.py``).

Which leaves quantize is declared by the layers themselves: module
classes carry a ``quant_spec`` mapping param name -> (out_axis,
in_axis) (``nn/linear.py``, ``nn/conv.py``, ``nn/attention.py``), and
:func:`quant_leaf_specs` walks the module tree in step with the params
tree — biases, LayerNorm gains, BN statistics and everything else stay
fp32.
"""
from __future__ import annotations

import numpy as np

#: weight-quantization modes — THE source of truth for
#: ``weight_mode_default()``, ``ServeEngine(quant=)`` validation and
#: :class:`WeightQuantizer` (the kv.MODES pattern)
MODES = ("off", "int8", "fp8")
ON_MODES = tuple(m for m in MODES if m != "off")

INT8_QMAX = 127.0
FP8_MAX = 448.0          # float8_e4m3fn finite max
#: clip ratios searched by the activation-aware calibration pass
CLIP_RATIOS = (1.0, 0.95, 0.9, 0.85, 0.8, 0.7, 0.6, 0.5)

_FP8_SUPPORT = None      # capability probe result, cached per process


class UnsupportedQuantError(RuntimeError):
    """The requested quantization mode is not available on this
    toolchain (e.g. fp8 on an XLA without ``float8_e4m3fn`` lowering).
    Raised at construction — never from inside a trace."""


def supports_fp8() -> bool:
    """True when the installed jax/XLA can store and convert
    ``float8_e4m3fn`` on the current backend.  Probed ONCE with a tiny
    round-trip (the capability-gate idiom: a feature is used only after
    this process proved it works, never inferred from version strings).
    """
    global _FP8_SUPPORT
    if _FP8_SUPPORT is None:
        try:
            import jax.numpy as jnp
            x = jnp.asarray(np.full((2,), 1.5, np.float32),
                            jnp.float8_e4m3fn)
            _FP8_SUPPORT = bool(
                np.allclose(np.asarray(x.astype(jnp.float32)), 1.5))
        except Exception:
            _FP8_SUPPORT = False
    return _FP8_SUPPORT


def _fp8_dtype():
    import ml_dtypes
    return np.dtype(ml_dtypes.float8_e4m3fn)


def is_quantized_leaf(leaf) -> bool:
    """True for leaves holding quantized storage (int8 or fp8)."""
    dt = np.dtype(getattr(leaf, "dtype", np.float32))
    return dt == np.int8 or "float8" in dt.name


def _search_clip(w, amax, out_axis, red, act_amax, in_axis, mode):
    """Per-output-channel clip ratio minimizing the activation-weighted
    quantization error ``sum(|W - dq(W)| * act_amax)`` over
    :data:`CLIP_RATIOS`.  Returns ratios shaped like ``amax``."""
    if act_amax is None:
        return np.ones_like(amax)
    act = np.asarray(act_amax, np.float32).reshape(-1)
    if act.size != w.shape[in_axis]:
        # grouped conv or a shape the taps did not see: fall back to
        # plain min-max rather than mis-broadcasting the weights
        return np.ones_like(amax)
    shp = [1] * w.ndim
    shp[in_axis] = -1
    a = act.reshape(shp)
    best_err = None
    best = np.ones_like(amax)
    for r in CLIP_RATIOS:
        clip = amax * r
        if mode == "int8":
            s = clip / INT8_QMAX
            dq = np.clip(np.rint(w / s), -INT8_QMAX, INT8_QMAX) * s
        else:
            s = clip / FP8_MAX
            dq = np.clip(w / s, -FP8_MAX, FP8_MAX).astype(
                _fp8_dtype()).astype(np.float32) * s
        err = np.sum(np.abs(w - dq) * a, axis=red, keepdims=True)
        if best_err is None:
            best_err, best = err, np.full_like(amax, r)
        else:
            take = err < best_err
            best_err = np.where(take, err, best_err)
            best = np.where(take, r, best)
    return best


def quantize_channelwise(w, out_axis: int, mode: str = "int8",
                         act_amax=None, in_axis: int | None = None):
    """Quantize one weight leaf per output channel; returns
    ``(q, scale)`` with ``scale`` keep-dims shaped so ``q.astype(f32) *
    scale`` broadcasts back to ``w``'s shape.  ``act_amax`` (a vector
    over the input-channel axis) arms the activation-aware clip search.
    """
    w = np.asarray(w, np.float32)
    red = tuple(i for i in range(w.ndim) if i != out_axis)
    amax = np.maximum(np.max(np.abs(w), axis=red, keepdims=True), 1e-12)
    if act_amax is not None and in_axis is not None:
        amax = amax * _search_clip(w, amax, out_axis, red, act_amax,
                                   in_axis, mode)
    if mode == "int8":
        scale = amax / INT8_QMAX
        q = np.clip(np.rint(w / scale), -INT8_QMAX,
                    INT8_QMAX).astype(np.int8)
    elif mode == "fp8":
        if not supports_fp8():
            raise UnsupportedQuantError(
                "fp8 (e4m3) is unsupported on this XLA — the "
                "supports_fp8() capability probe failed; serve int8 or "
                "full precision instead")
        scale = amax / FP8_MAX
        q = np.clip(w / scale, -FP8_MAX, FP8_MAX).astype(_fp8_dtype())
    else:
        raise ValueError(f"unknown quantization mode {mode!r}")
    return q, scale.astype(np.float32)


def quant_leaf_specs(model):
    """Walk the module tree in step with the params-tree layout and
    yield ``(path, (out_axis, in_axis))`` for every quantizable leaf,
    where ``path`` indexes ``model.params()`` (child name segments,
    then ``("~", leaf_name)``).  Layers opt in by declaring
    ``quant_spec`` (``nn/linear.py`` / ``nn/conv.py`` /
    ``nn/attention.py``)."""
    out = []

    def walk(mod, path):
        spec = getattr(type(mod), "quant_spec", None)
        if spec:
            for name, axes in spec.items():
                if name in mod._params:
                    out.append((path + ("~", name), tuple(axes)))
        for cname, child in mod._modules.items():
            walk(child, path + (cname,))

    walk(model, ())
    return out


_KEEP = object()


def _tree_substitute(tree, updates, default=_KEEP):
    """Copy a nested-dict params tree, substituting ``updates[path]``
    where present; elsewhere keep the original leaf (the quantized
    tree) or place ``default`` (the scale tree's unit scales) — see
    :meth:`WeightQuantizer.quantize`."""
    def rec(node, path):
        if isinstance(node, dict):
            return {k: rec(v, path + (k,)) for k, v in node.items()}
        if path in updates:
            return updates[path]
        return node if default is _KEEP else default
    return rec(tree, ())


def dequantize_params(qpack):
    """Rebuild the fp32 params tree from ``{"q": ..., "scale": ...}``.
    Runs under jit (the serving forward's prologue — XLA fuses the cast
    and the per-channel multiply into the consumer) and eagerly (the
    accuracy harness evaluates the EXACT values the engine serves)."""
    import jax
    import jax.numpy as jnp

    def dq(q, s):
        if is_quantized_leaf(q):
            return q.astype(jnp.float32) * s
        return q

    return jax.tree_util.tree_map(dq, qpack["q"], qpack["scale"])


class WeightQuantizer:
    """One model's quantization recipe: which leaves, which mode, which
    calibration.  :meth:`quantize` maps a full-precision params tree to
    the ``{"q", "scale"}`` pack the serving executables take as
    arguments — the engine calls it once at capture and again for every
    staged rollout, so a hot weight swap re-quantizes with the SAME
    recipe (``serve/engine.py``)."""

    def __init__(self, model, mode: str, calibration=None):
        if mode not in ON_MODES:
            raise ValueError(f"unknown quantization mode {mode!r}")
        if mode == "fp8" and not supports_fp8():
            raise UnsupportedQuantError(
                "fp8 (e4m3) weights are unsupported on this XLA — the "
                "supports_fp8() capability probe failed (serve "
                "BIGDL_SERVE_QUANT=int8 instead)")
        self.model = model
        self.mode = mode
        self.calibration = calibration
        self.leaves = quant_leaf_specs(model)
        if not self.leaves:
            raise ValueError(
                "model has no quantizable leaves (no module declares a "
                "quant_spec) — nothing to serve quantized")
        #: folded into the serving fn_key (serve/xcache.py): quantized
        #: and full-precision executables of one architecture must
        #: never resolve to the same cache entry
        self.recipe_key = (mode,
                           "calib" if calibration is not None else
                           "minmax", len(self.leaves))

    def _act_amax(self, path):
        if self.calibration is None:
            return None
        return self.calibration.amax.get(path[:-2])

    def quantize(self, params):
        """Full-precision params tree -> ``{"q": tree, "scale": tree}``.
        Both trees share the ORIGINAL tree structure (non-quantized
        leaf positions hold the fp leaf / a unit scale), so the
        engine's staged-rollout structure checks keep working
        unchanged."""
        q_up, s_up = {}, {}

        def leaf_at(tree, path):
            for k in path:
                tree = tree[k]
            return tree

        for path, (out_ax, in_ax) in self.leaves:
            w = leaf_at(params, path)
            q, s = quantize_channelwise(
                w, out_ax, self.mode, act_amax=self._act_amax(path),
                in_axis=in_ax)
            q_up[path], s_up[path] = q, s
        return {"q": _tree_substitute(params, q_up),
                "scale": _tree_substitute(params, s_up,
                                          default=np.float32(1.0))}

    def stats(self) -> dict:
        return {"mode": self.mode, "leaves": len(self.leaves),
                "calibrated": self.calibration is not None}


def quantized_eval_fn(model, quantizer: WeightQuantizer):
    """The quantized counterpart of ``optim.local_optimizer._eval_fn``:
    a jitted ``fwd(qpack, state, x)`` that dequantizes INSIDE the
    compiled forward (weights stay int8/fp8 in HBM; the executable
    takes ``(qweights, scales)`` as arguments, so rollouts never
    recompile) routed through the shared executable cache under a
    fn_key extended with the quant recipe."""
    import jax

    from bigdl_tpu.nn.module import Context
    from bigdl_tpu.optim.local_optimizer import _model_fingerprint
    from bigdl_tpu.serve import xcache

    fp = _model_fingerprint(model)

    @jax.jit
    def fwd(qpack, s, x):
        p = dequantize_params(qpack)
        out, _ = model.apply(p, x, s, Context(training=False,
                                              key=jax.random.PRNGKey(0)))
        return out

    return xcache.ShapedCallable(
        fwd, fn_key=("eval_quant", quantizer.recipe_key, fp))
