"""Activation calibration for weight quantization (docs/serving.md
"Quantized serving").

Per-channel min-max quantization treats every weight column alike; the
activation-aware recipe (AWQ, Lin et al. 2023) observes that error only
matters where activations actually flow, so the clip search in
``quant/weights.py`` weights each channel's quantization error by the
amax of the activations feeding it.  This module collects those amax
vectors: :func:`collect` drives a calibration split through the model
with taps installed on the quantizable layer classes and records, per
module, the per-INPUT-channel ``max |x|`` across every batch.

The sweep is the ``optim.validate`` loop's iteration idiom — same
``dataset.data(train=False)`` batches, same ValidationMethod algebra —
run EAGERLY (taps are host-side recorders; under jit they would see
tracers and record nothing).  ``methods=`` optionally computes fp32
validation results over the same batches (``Calibration.baseline``)
for callers whose calibration split IS their eval split;
``tools/quant_check.py`` anchors its budget on the full-set
``validate`` pass instead and skips it.

Taps are class-level ``_forward`` wrappers installed for the duration
of the sweep only (a context manager restores the originals even on
error) and keyed by module INSTANCE, then resolved to params-tree
paths, so the result lines up with ``quant_leaf_specs``'s addressing.
For :class:`~bigdl_tpu.nn.attention.MultiHeadSelfAttention` the block
input's amax stands in for all four projections (``wo``'s true input is
the attention output; same width, and the approximation only steers a
clip search).
"""
from __future__ import annotations

import contextlib

import numpy as np


class Calibration:
    """Result of one calibration sweep: ``amax`` maps a module's
    params-tree path (child-name segments) to its per-input-channel
    activation amax vector; ``baseline`` holds the fp32 validation
    results computed in the same pass (``[(method, result)]`` or [])."""

    def __init__(self, amax: dict, n_batches: int, n_records: int,
                 baseline=None):
        self.amax = amax
        self.n_batches = n_batches
        self.n_records = n_records
        self.baseline = baseline or []

    def __len__(self):
        return len(self.amax)


def _tapped_classes():
    from bigdl_tpu.nn.attention import MultiHeadSelfAttention
    from bigdl_tpu.nn.conv import (SpatialConvolution,
                                   SpatialDilatedConvolution)
    from bigdl_tpu.nn.linear import Linear
    # class -> input-channel axis of the recorded activation (negative
    # axes count from the end; conv activations are NCHW)
    return {Linear: -1, SpatialConvolution: 1,
            SpatialDilatedConvolution: 1, MultiHeadSelfAttention: -1}


@contextlib.contextmanager
def _activation_taps(sink: dict):
    """Patch the quantizable layer classes' ``_forward`` to record each
    eager call's per-input-channel amax into ``sink[id(module)]``
    (max-merged across batches).  Traced calls pass through untouched —
    a concurrent jit cannot corrupt the sink with tracers."""
    import jax

    classes = _tapped_classes()
    originals = {}

    def wrap(cls, orig, ch_axis):
        def fwd(self, P, x, S, ctx):
            if not isinstance(x, jax.core.Tracer):
                try:
                    arr = np.asarray(x)
                    ax = ch_axis % arr.ndim
                    red = tuple(i for i in range(arr.ndim) if i != ax)
                    amax = np.max(np.abs(arr), axis=red)
                    prev = sink.get(id(self))
                    sink[id(self)] = (amax if prev is None
                                      else np.maximum(prev, amax))
                except Exception:
                    pass   # a table input or exotic shape: skip the tap
            return orig(self, P, x, S, ctx)
        return fwd

    try:
        for cls, ch_axis in classes.items():
            originals[cls] = cls._forward
            cls._forward = wrap(cls, originals[cls], ch_axis)
        yield
    finally:
        for cls, orig in originals.items():
            cls._forward = orig


def _module_paths(model) -> dict:
    """id(module) -> params-tree path (child-name segments)."""
    out = {}

    def walk(mod, path):
        out[id(mod)] = path
        for name, child in mod._modules.items():
            walk(child, path + (name,))

    walk(model, ())
    return out


def collect(model, dataset, methods=None, max_batches: int = 8,
            params=None, state=None) -> Calibration:
    """Run up to ``max_batches`` of ``dataset``'s eval split through
    ``model`` eagerly with activation taps installed; returns the
    :class:`Calibration` (per-module input-channel amax + the fp32
    baseline results for ``methods``, validate-style)."""
    import jax

    from bigdl_tpu.nn.module import Context

    params = model.params() if params is None else params
    state = model.state() if state is None else state
    methods = list(methods or [])
    sink: dict = {}
    totals = [None] * len(methods)
    n_batches = n_records = 0
    ctx = Context(training=False, key=jax.random.PRNGKey(0))
    with _activation_taps(sink):
        for batch in dataset.data(train=False):
            data = np.asarray(batch.data)
            out, _ = model.apply(params, data, state, ctx)
            for i, m in enumerate(methods):
                r = m(out, batch.labels)
                totals[i] = r if totals[i] is None else totals[i] + r
            n_batches += 1
            n_records += int(data.shape[0])
            if n_batches >= max_batches:
                break
    if not n_batches:
        raise ValueError("calibration split yielded no batches")
    paths = _module_paths(model)
    amax = {paths[mid]: v for mid, v in sink.items() if mid in paths}

    # calibration telemetry: gauges next to the serving numbers so a
    # fleet operator can see what the quantized replicas were tuned on
    # (docs/observability.md "Quantized serving" rows)
    try:
        from bigdl_tpu.obs import metrics as obs_metrics
        reg = obs_metrics.get()
        reg.gauge("quant_calib_batches",
                  "batches in the last calibration sweep").set(n_batches)
        reg.gauge("quant_calib_records",
                  "records in the last calibration sweep").set(n_records)
        reg.gauge("quant_calib_layers",
                  "layers with collected activation amax").set(len(amax))
    except Exception:   # pragma: no cover - obs layer unavailable
        pass
    return Calibration(amax, n_batches, n_records,
                       baseline=list(zip(methods, totals)))
