"""Pull exporter for the metrics registry (docs/observability.md
"Serving telemetry").

A :class:`MetricsExporter` is a tiny threaded HTTP endpoint over any
zero-argument ``snapshot_fn`` returning an ``obs/metrics.py`` snapshot
dict (usually ``ReplicaPool.merged_registry`` — the fleet view — or
``metrics.get().snapshot`` for one process):

- ``GET /metrics``  — Prometheus text exposition (version 0.0.4); what
  a scraper or ``curl`` reads.
- ``GET /snapshot`` — the raw snapshot as JSON (``{"ts": ...,
  "snapshot": ...}``); what ``tools/serve_top.py`` polls, and the
  format :func:`bigdl_tpu.obs.metrics.merge` accepts directly.

``port=0`` binds an ephemeral port (tests, serve_top drills);
``exporter.url`` is the resolved address.  The server runs on one
daemon thread and never touches the serving hot path — cost is paid by
the scraper, per pull.

File sibling: :meth:`MetricsExporter.write_jsonl` (or
``metrics.append_snapshot_jsonl``) appends timestamped snapshots to a
JSONL file for offline analysis where no scraper runs.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from bigdl_tpu.obs import metrics as obs_metrics

logger = logging.getLogger("bigdl_tpu.obs")

ENV_PORT = "BIGDL_SERVE_EXPORT_PORT"


def export_port_default() -> int | None:
    """``BIGDL_SERVE_EXPORT_PORT`` as an int, or None when unset/empty
    (no exporter is auto-started)."""
    raw = os.environ.get(ENV_PORT, "").strip()
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        logger.warning("ignoring malformed %s=%r", ENV_PORT, raw)
        return None


class MetricsExporter:
    """Serve ``snapshot_fn()`` at ``/metrics`` (Prometheus text) and
    ``/snapshot`` (JSON).  ``close()`` (or the context manager) shuts
    the listener down; a snapshot_fn failure answers 500 and is logged,
    never raised into the serving process."""

    def __init__(self, snapshot_fn, port: int = 0,
                 host: str = "127.0.0.1"):
        self.snapshot_fn = snapshot_fn
        exporter = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):   # noqa: N802 - http.server API
                try:
                    if self.path.split("?")[0] == "/metrics":
                        body = obs_metrics.render_prometheus(
                            exporter.snapshot_fn()).encode()
                        ctype = "text/plain; version=0.0.4"
                    elif self.path.split("?")[0] == "/snapshot":
                        body = json.dumps(
                            {"ts": time.time(),
                             "snapshot": exporter.snapshot_fn()}).encode()
                        ctype = "application/json"
                    else:
                        self.send_error(404)
                        return
                except Exception as e:
                    logger.warning("exporter snapshot failed: %s", e)
                    self.send_error(500, str(e))
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):   # silence per-request stderr
                pass

        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self.host, self.port = self._server.server_address[:2]
        self.url = f"http://{self.host}:{self.port}"
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="bigdl-obs-exporter")
        self._thread.start()
        logger.info("metrics exporter listening at %s", self.url)

    def write_jsonl(self, path: str):
        """Append one timestamped snapshot to ``path`` (the file-based
        export for runs nothing scrapes)."""
        obs_metrics.append_snapshot_jsonl(path, self.snapshot_fn())
        return path

    def close(self):
        self._server.shutdown()
        self._server.server_close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
