"""Compile-time cost/memory ledger + HBM accounting
(docs/observability.md "Performance observatory").

Every executable this process runs flows through one chokepoint — the
shared executable cache (``serve/xcache.py``) — yet XLA's own
``cost_analysis()``/``memory_analysis()`` used to be consulted ad-hoc
(``bench.py``, ``tools/profile_step.py``), so MFU existed only as an
offline bench number and nobody could answer "where did HBM go" at
runtime.  This module is the shared cost-truth plane:

- :class:`CostLedger` — a process-wide ledger of every compiled
  executable's flops, bytes-accessed and (for AOT compiles) peak/temp/
  argument HBM, captured AT COMPILE TIME and keyed by the same keys the
  executable cache resolves (``ExecutableCache.key_for``).  Warm
  dispatches never touch the ledger: ``xcache`` calls :meth:`capture_*`
  only on the dispatch that compiles.  Each capture publishes
  ``ledger_*`` registry gauges (agg ``max`` — the same key IS the same
  program, so merging replica snapshots is idempotent, per-replica cost
  truth without double counting) and emits a schema-validated
  ``ledger`` obs event, so ``ReplicaPool.merged_registry()`` carries
  fleet cost truth next to the serving numbers.
- Live utilization readers: the optimizer loops marry
  :meth:`CostLedger.newest` flops with their windowed step walls to
  publish ``train_mfu``; the continuous decoder publishes
  ``decode_model_flops_util`` per sync boundary.  ``bench.py`` and
  ``tools/profile_step.py`` resolve their flops through
  :meth:`capture_compiled` — one code path, one number, so the bench
  MFU and the ledger MFU can never silently diverge (the cross-check
  ``tests/test_obs_ledger.py`` pins).
- Static HBM tenants: the known large device allocations (KV page
  pools + scale arrays, served/staged weight packs, host-side
  ``WeightStore`` snapshots) register their bytes via
  :func:`note_tenant` so ``tools/obs_report.py`` renders an HBM
  breakdown table.
- :class:`DeviceMemorySampler` — a cadence thread over
  ``utils/profiler.device_memory_stats()`` publishing in-use/limit/
  watermark gauges and ``ledger``/``hbm`` timeline events.  Close is
  stop-event + join (the ``Router.close`` SIGABRT lesson: a daemon
  thread racing interpreter teardown must be joined, not abandoned).

Master switch ``BIGDL_LEDGER=0`` disables capture entirely (the
executable cache works unchanged); everything here is best-effort by
design — a telemetry bug must never fail a compile.
"""
from __future__ import annotations

import hashlib
import itertools
import logging
import math
import os
import threading
import time

logger = logging.getLogger("bigdl_tpu.obs")

ENV_LEDGER = "BIGDL_LEDGER"
ENV_HBM_SAMPLE = "BIGDL_OBS_HBM_SAMPLE"

#: bf16 dense peak flops per chip (datasheet) — the MFU denominator.
#: One table for bench.py, the live gauges and the report tools: two
#: peak tables would let two MFUs diverge by construction.
PEAK_FLOPS = {
    "TPU v2": 45e12, "TPU v3": 123e12, "TPU v4": 275e12,
    "TPU v5 lite": 197e12, "TPU v5e": 197e12, "TPU v5": 459e12,
    "TPU v5p": 459e12, "TPU v6 lite": 918e12, "TPU v6e": 918e12,
}

DEFAULT_PEAK = 197e12   # v5e — matches bench.py's historical default


def device_peak_flops(device=None) -> float:
    """Datasheet peak for ``device`` (default: the first jax device).
    Unknown kinds (CPU, new chips) fall back to the v5e number so MFU
    stays finite and comparable across the toolchain."""
    if device is None:
        try:
            import jax
            device = jax.devices()[0]
        except Exception:   # pragma: no cover - jax-less context
            return DEFAULT_PEAK
    kind = getattr(device, "device_kind", "")
    for k, v in PEAK_FLOPS.items():
        if kind.startswith(k):
            return v
    return DEFAULT_PEAK


def enabled() -> bool:
    return os.environ.get(ENV_LEDGER, "1") != "0"


def _fn_label(fn_key) -> str:
    """Stable short label for the gauge's ``fn`` dimension: the leading
    element of a tuple key (``train_step``, ``decode_step_paged``, ...)
    or the whole key's string."""
    if isinstance(fn_key, tuple) and fn_key:
        return str(fn_key[0])
    return str(fn_key)


def _key_hash(key) -> str:
    """8-hex digest of a ledger key — the gauge label that keeps two
    shapes of the same fn distinct without exploding label size."""
    return hashlib.md5(repr(key).encode()).hexdigest()[:8]


def _cost_dict(analysis) -> dict:
    """Normalize XLA's cost analysis: newer jax returns a list of
    per-computation dicts (this container's 0.4.37 does), older a dict.
    Indexing the list form with ``["flops"]`` is the TypeError that
    silently nan'd bench MFU — normalizing HERE is why every probe must
    resolve through the ledger."""
    if analysis is None:
        return {}
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else {}
    return dict(analysis)


class LedgerEntry:
    """One compiled executable's cost truth.  ``flops``/
    ``bytes_accessed`` come from cost analysis (jit and AOT captures);
    the ``*_bytes`` HBM fields only from AOT captures (memory analysis
    needs the compiled object) and are None on jit-path entries."""

    __slots__ = ("fn_key", "key", "flops", "bytes_accessed",
                 "argument_bytes", "output_bytes", "temp_bytes",
                 "generated_code_bytes", "peak_bytes", "source", "ts",
                 "seq")

    def __init__(self, fn_key, key, flops=float("nan"),
                 bytes_accessed=float("nan"), argument_bytes=None,
                 output_bytes=None, temp_bytes=None,
                 generated_code_bytes=None, source="aot", seq=0):
        self.fn_key = fn_key
        self.key = key
        self.flops = float(flops)
        self.bytes_accessed = float(bytes_accessed)
        self.argument_bytes = argument_bytes
        self.output_bytes = output_bytes
        self.temp_bytes = temp_bytes
        self.generated_code_bytes = generated_code_bytes
        #: the executable's whole-program HBM footprint while running:
        #: arguments + outputs + XLA scratch + device code
        self.peak_bytes = None
        if temp_bytes is not None:
            self.peak_bytes = int((argument_bytes or 0)
                                  + (output_bytes or 0) + temp_bytes
                                  + (generated_code_bytes or 0))
        self.source = source
        self.ts = time.time()
        self.seq = seq

    def as_dict(self) -> dict:
        # fn_key reprs embed whole model fingerprints (kilobytes); the
        # event carries a capped prefix — `key` is the unique handle
        fk = repr(self.fn_key)
        if len(fk) > 120:
            fk = fk[:120] + "..."
        d = {"fn": _fn_label(self.fn_key), "fn_key": fk,
             "key": _key_hash(self.key), "flops": self.flops,
             "bytes_accessed": self.bytes_accessed,
             "source": self.source}
        for k in ("argument_bytes", "output_bytes", "temp_bytes",
                  "generated_code_bytes", "peak_bytes"):
            v = getattr(self, k)
            if v is not None:
                d[k] = int(v)
        return d


class CostLedger:
    """Process-wide compile-time cost ledger.  Thread-safe (serve
    replicas warm concurrently with a validating training thread, like
    the executable cache it mirrors)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}        # key -> LedgerEntry (insertion-ordered)
        self._seq = itertools.count()
        self.captures = 0         # fresh captures (the warm-path audit
        #                           pins this to the compile count)

    # -- capture (compile-time only) ---------------------------------------
    def _record(self, entry: LedgerEntry):
        with self._lock:
            if entry.key in self._entries:
                return self._entries[entry.key]
            entry.seq = next(self._seq)
            self._entries[entry.key] = entry
            self.captures += 1
        self._publish(entry)
        return entry

    def capture_compiled(self, fn_key, compiled, key=None):
        """Ledger a ``jax.stages.Compiled`` (the AOT path): cost AND
        memory analysis.  ``key`` defaults to a per-call sequence so
        standalone probes (bench, profile_step) get distinct entries;
        ``xcache`` passes its own cache key.  Returns the entry (or
        None when the ledger is disabled) and never raises."""
        if not enabled():
            return None
        try:
            ca = _cost_dict(compiled.cost_analysis())
            kw = dict(flops=ca.get("flops", float("nan")),
                      bytes_accessed=ca.get("bytes accessed",
                                            float("nan")))
            try:
                ma = compiled.memory_analysis()
            except Exception:
                ma = None
            if ma is not None:
                kw.update(
                    argument_bytes=int(ma.argument_size_in_bytes),
                    output_bytes=int(ma.output_size_in_bytes),
                    temp_bytes=int(ma.temp_size_in_bytes),
                    generated_code_bytes=int(
                        ma.generated_code_size_in_bytes))
            if key is None:
                key = (fn_key, "call", id(compiled))
            return self._record(LedgerEntry(fn_key, key, source="aot",
                                            **kw))
        except Exception as e:   # pragma: no cover - defensive
            logger.warning("ledger AOT capture failed for %r: %s",
                           fn_key, e)
            return None

    def capture_lowered(self, fn_key, key, jitted, args):
        """Ledger a tracked-jit key from its LOWERING only (no second
        XLA compile): ``Lowered.cost_analysis()`` yields flops/bytes
        without building an executable, so the extra compile-time cost
        is one trace, and the first real dispatch still owns the
        compile.  HBM fields stay None (memory analysis needs the
        compiled object).  Must run BEFORE the dispatch — the dispatch
        may donate the argument buffers."""
        if not enabled():
            return None
        try:
            with self._lock:
                if key in self._entries:
                    return self._entries[key]
            ca = _cost_dict(jitted.lower(*args).cost_analysis())
            return self._record(LedgerEntry(
                fn_key, key, source="jit",
                flops=ca.get("flops", float("nan")),
                bytes_accessed=ca.get("bytes accessed", float("nan"))))
        except Exception as e:   # pragma: no cover - defensive
            logger.warning("ledger jit capture failed for %r: %s",
                           fn_key, e)
            return None

    def _publish(self, entry: LedgerEntry):
        """Registry gauges + the ``ledger`` obs event for one fresh
        capture.  agg='max': the same key is the same program, so a
        fleet merge of identical entries is idempotent, not additive."""
        try:
            from bigdl_tpu.obs import metrics
            reg = metrics.get()
            lab = {"fn": _fn_label(entry.fn_key),
                   "key": _key_hash(entry.key)}
            if math.isfinite(entry.flops):
                reg.gauge("ledger_flops",
                          "per-dispatch flops of one compiled "
                          "executable (XLA cost analysis)",
                          agg="max", **lab).set(entry.flops)
            if math.isfinite(entry.bytes_accessed):
                reg.gauge("ledger_bytes_accessed",
                          "per-dispatch HBM bytes accessed (XLA cost "
                          "analysis)", agg="max",
                          **lab).set(entry.bytes_accessed)
            if entry.peak_bytes is not None:
                reg.gauge("ledger_peak_hbm_bytes",
                          "whole-program HBM while running: args + "
                          "outputs + scratch + code", agg="max",
                          **lab).set(entry.peak_bytes)
        except Exception:   # pragma: no cover - obs layer mid-teardown
            pass
        try:
            from bigdl_tpu.obs import events
            events.emit("ledger", kind="exec", **entry.as_dict())
        except Exception:   # pragma: no cover - defensive
            pass

    # -- lookup (the MFU readers) ------------------------------------------
    def newest(self, fn_key):
        """Most recently captured entry whose fn_key equals ``fn_key``
        (the optimizer/decoder step programs re-key per shape; the
        newest shape is the one running)."""
        with self._lock:
            best = None
            for e in self._entries.values():
                if e.fn_key == fn_key and (best is None
                                           or e.seq > best.seq):
                    best = e
            return best

    def flops_for(self, fn_key) -> float | None:
        """Finite per-dispatch flops for ``fn_key``'s newest entry, or
        None (absent / analysis unavailable)."""
        e = self.newest(fn_key)
        if e is None or not math.isfinite(e.flops):
            return None
        return e.flops

    def entries(self) -> list:
        with self._lock:
            return list(self._entries.values())

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries),
                    "captures": self.captures}

    def clear(self):
        with self._lock:
            self._entries.clear()
            self.captures = 0


# -- process-wide singleton -------------------------------------------------

_LEDGER: CostLedger | None = None
_LOCK = threading.Lock()


def get() -> CostLedger:
    global _LEDGER
    if _LEDGER is None:
        with _LOCK:
            if _LEDGER is None:
                _LEDGER = CostLedger()
    return _LEDGER


def reset():
    """Drop every entry (tests; wired into the suite's autouse fixture
    like ``serve.xcache``/``obs.metrics``).  Also stops an env-started
    memory sampler so its thread never outlives the test that made it."""
    get().clear()
    stop_global_sampler()


# -- static HBM tenants -----------------------------------------------------

def note_tenant(tenant: str, nbytes, **labels):
    """Register one known large allocation's CURRENT bytes (KV page
    pools incl. scale arrays, weight packs, staged rollout pairs,
    host-side WeightStore snapshots).  Gauge semantics: call again with
    the new size (0 frees it from the breakdown); series labelled with
    the owner's own labels (``decoder=...``/``engine=...``) so the
    owner's existing ``drop_series`` teardown reclaims them.  Also
    emits a ``ledger`` event (kind=tenant) so obs_report can render
    the breakdown without a live registry.  Best-effort, never raises."""
    try:
        from bigdl_tpu.obs import metrics
        metrics.get().gauge(
            "hbm_tenant_bytes",
            "bytes held by one named large allocation",
            tenant=tenant, **labels).set(float(nbytes))
    except Exception:   # pragma: no cover - obs layer unavailable
        pass
    try:
        from bigdl_tpu.obs import events
        events.emit("ledger", kind="tenant", tenant=tenant,
                    bytes=int(nbytes), **labels)
    except Exception:   # pragma: no cover - defensive
        pass


def tree_nbytes(tree) -> int:
    """Total array bytes of a pytree (tenant sizing helper).  Never
    raises: the call sites are construction/staging paths where a
    telemetry bug must not fail serving — a leaf that cannot be sized
    (extended dtypes like PRNG keys, exotic objects) contributes 0."""
    import numpy as np

    try:
        import jax
        leaves = jax.tree_util.tree_leaves(tree)
    except Exception:   # pragma: no cover - jax-less context
        leaves = [tree]
    total = 0
    for leaf in leaves:
        try:
            size = getattr(leaf, "size", None)
            dt = getattr(leaf, "dtype", None)
            if size is None or dt is None:
                leaf = np.asarray(leaf)
                size, dt = leaf.size, leaf.dtype
            total += int(size) * int(np.dtype(dt).itemsize)
        except Exception:   # unsizable leaf: skip, never raise
            continue
    return total


# -- device-memory sampler --------------------------------------------------

class DeviceMemorySampler:
    """Cadence thread over ``utils/profiler.device_memory_stats()``:
    publishes per-device ``hbm_bytes_in_use`` / ``hbm_bytes_limit`` /
    ``hbm_bytes_peak`` gauges (agg='max' — several replicas share the
    physical device; summing would invent HBM) and one ``ledger`` event
    (kind=hbm) per tick, the timeline obs_report renders.

    Lifecycle: ``start()`` spawns the daemon thread, ``close()`` sets
    the stop event and JOINS it (bounded) — never leave the thread
    racing interpreter teardown.  Backends that expose no memory stats
    (CPU PJRT) sample cleanly to nothing; ``stats_fn`` is injectable
    for tests."""

    def __init__(self, interval: float = 10.0, stats_fn=None,
                 registry=None, emit_events: bool = True):
        if stats_fn is None:
            from bigdl_tpu.utils.profiler import device_memory_stats
            stats_fn = device_memory_stats
        self.interval = max(float(interval), 1e-3)
        self._stats_fn = stats_fn
        self._registry = registry
        self._emit_events = emit_events
        self._stop = threading.Event()
        self._thread = None
        self._peaks = {}          # device -> watermark bytes
        self.samples = 0          # ticks that saw at least one device

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from bigdl_tpu.obs import metrics
        return metrics.get()

    def sample_once(self) -> dict:
        """One tick: read, publish, return the per-device dict actually
        observed ({} when the backend exposes nothing)."""
        try:
            raw = self._stats_fn() or {}
        except Exception as e:   # pragma: no cover - backend hiccup
            logger.warning("device memory sample failed: %s", e)
            return {}
        seen = {}
        for dev, st in raw.items():
            if not st:
                continue
            in_use = st.get("bytes_in_use")
            if in_use is None:
                continue
            peak = max(int(st.get("peak_bytes_in_use", 0)), int(in_use),
                       self._peaks.get(dev, 0))
            self._peaks[dev] = peak
            seen[dev] = {"in_use": int(in_use), "peak": peak}
            limit = st.get("bytes_limit")
            if limit is not None:
                seen[dev]["limit"] = int(limit)
        if not seen:
            return {}
        self.samples += 1
        try:
            reg = self._reg()
            for dev, row in seen.items():
                reg.gauge("hbm_bytes_in_use", "device HBM in use",
                          agg="max", device=dev).set(row["in_use"])
                reg.gauge("hbm_bytes_peak",
                          "device HBM in-use watermark", agg="max",
                          device=dev).set(row["peak"])
                if "limit" in row:
                    reg.gauge("hbm_bytes_limit", "device HBM capacity",
                              agg="max", device=dev).set(row["limit"])
        except Exception:   # pragma: no cover - obs layer mid-teardown
            pass
        if self._emit_events:
            try:
                from bigdl_tpu.obs import events
                events.emit(
                    "ledger", kind="hbm",
                    in_use=sum(r["in_use"] for r in seen.values()),
                    peak=sum(r["peak"] for r in seen.values()),
                    limit=sum(r.get("limit", 0) for r in seen.values()),
                    devices=seen)
            except Exception:   # pragma: no cover - defensive
                pass
        return seen

    def _run(self):
        while not self._stop.wait(self.interval):
            self.sample_once()

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name="bigdl-hbm-sampler")
            self._thread.start()
        return self

    def close(self, timeout: float = 10.0):
        """Stop-event + bounded join — idempotent."""
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=timeout)
        self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()


_GLOBAL_SAMPLER: DeviceMemorySampler | None = None


def maybe_start_sampler_from_env() -> DeviceMemorySampler | None:
    """Start (once) the process-wide sampler when
    ``BIGDL_OBS_HBM_SAMPLE=<seconds>`` is set — called by the long-
    lived entry points (ReplicaPool construction, optimizer run start)
    so a serving or training process self-measures without code
    changes.  Returns the sampler (or None when the env is unset/0)."""
    global _GLOBAL_SAMPLER
    raw = os.environ.get(ENV_HBM_SAMPLE, "").strip()
    if not raw:
        return None
    try:
        interval = float(raw)
    except ValueError:
        logger.warning("ignoring malformed %s=%r", ENV_HBM_SAMPLE, raw)
        return None
    if interval <= 0:
        return None
    with _LOCK:
        if _GLOBAL_SAMPLER is None:
            _GLOBAL_SAMPLER = DeviceMemorySampler(
                interval=interval).start()
    return _GLOBAL_SAMPLER


def stop_global_sampler():
    global _GLOBAL_SAMPLER
    s = _GLOBAL_SAMPLER
    _GLOBAL_SAMPLER = None
    if s is not None:
        s.close()
