"""Nested wall-clock spans around the training loop's phases
(docs/observability.md).

The reference's Metrics.scala names flat counters ("computing time
average", "get weights average"); spans keep that — every span IS a
``optim.Metrics`` entry named ``span: <path>`` — and add three things:

- nesting: ``span("dispatch")`` inside ``span("epoch")`` records the
  path ``epoch/dispatch``, so the report reads as a tree;
- device-trace visibility: each span body runs under a
  ``jax.profiler`` TraceAnnotation (``utils/profiler.annotation``), so
  the same phase names line up in XProf/TensorBoard traces;
- a cross-process breakdown with the deadlock-safe pattern Metrics
  already has: the TOP-LEVEL phase names are declared as distributed
  entries on EVERY process at construction (``Metrics.declare``), so the
  epoch-end ``collect_per_node`` gather walks the identical name list on
  every host even when a phase only ran on process 0 (checkpoint
  writes), and process 0 can render the per-host table afterwards from
  the cache alone.
"""
from __future__ import annotations

import time
from contextlib import contextmanager

#: top-level phases every optimizer declares — the fixed, every-process
#: name set that keeps the per-node allgather deadlock-free.  ``h2d``
#: is the host→device batch transfer (inline, or credited from the
#: prefetch transfer thread via :meth:`SpanTracker.record`); ``host-wait``
#: is the cadence-boundary device→host sync the loops pay instead of a
#: per-step ``float(loss)`` (docs/observability.md "host pipeline").
PHASES = ("data-load", "h2d", "dispatch", "host-wait", "aggregate",
          "validate", "checkpoint")

_PREFIX = "span: "


class SpanTracker:
    def __init__(self, metrics, phases=PHASES):
        self.metrics = metrics
        self.phases = tuple(phases)
        self._stack: list = []
        self._paths: list = []   # insertion-ordered distinct span paths
        for name in self.phases:
            metrics.declare(_PREFIX + name, distributed=True)

    @contextmanager
    def span(self, name: str):
        """Time a phase; nested calls build slash paths.  Top-level
        phases from ``PHASES`` feed the distributed per-host breakdown;
        ad-hoc/nested names stay process-local."""
        from bigdl_tpu.utils.profiler import annotation
        path = "/".join([s for s in self._stack] + [name])
        self._stack.append(name)
        t0 = time.perf_counter()
        try:
            with annotation(name):
                yield
        finally:
            self._stack.pop()
            dt = time.perf_counter() - t0
            if path not in self._paths:
                self._paths.append(path)
            self.metrics.add(_PREFIX + path, dt,
                             distributed=(path in self.phases))

    def record(self, name: str, seconds: float, count: int = 1):
        """Credit an externally-timed interval to a span — work measured
        on a background thread (the prefetch pipeline's H2D transfers)
        whose timing the main thread drains and books here.  ``count=0``
        adds seconds to an interval already counted once (accumulating a
        phase across drains without inflating its sample count)."""
        if seconds <= 0 and count <= 0:
            return
        if name not in self._paths:
            self._paths.append(name)
        self.metrics.accumulate(_PREFIX + name, seconds, count=count,
                                distributed=(name in self.phases))

    # -- rendering ---------------------------------------------------------
    def rows(self):
        """(path, depth, mean_s, total_s, count) per span, tree order."""
        out = []
        for path in sorted(self._paths):
            total, count = self.metrics.get(_PREFIX + path)
            out.append((path, path.count("/"), self.metrics.mean(
                _PREFIX + path), total, count))
        return out

    def report(self, unit: str = "s") -> str:
        """Process-local span tree (mean/total/count per phase)."""
        lines = [f"{'span':<32} {'mean_' + unit:>10} {'total_' + unit:>10} "
                 f"{'count':>7}"]
        for path, depth, mean, total, count in self.rows():
            label = "  " * depth + path.rsplit("/", 1)[-1]
            lines.append(f"{label:<32} {mean:>10.4f} {total:>10.4f} "
                         f"{count:>7d}")
        return "\n".join(lines)

    def per_host_report(self) -> str:
        """Per-process mean seconds for each top-level phase.

        CONTRACT: multi-process callers must have run
        ``metrics.collect_per_node()`` (a collective every process joins,
        e.g. the end of ``DistriOptimizer.optimize``) first — this method
        then reads the cached snapshot and is safe from process 0 alone.
        """
        rows = [(name, self.metrics.per_node(_PREFIX + name))
                for name in self.phases]
        n_hosts = max(len(vals) for _, vals in rows)
        header = f"{'phase':<14}" + "".join(
            f"{'host' + str(i):>12}" for i in range(n_hosts))
        lines = [header]
        for name, vals in rows:
            lines.append(f"{name:<14}" + "".join(
                f"{v:>12.4f}" for v in vals))
        return "\n".join(lines)

    def emit_phase_events(self, events_log, step: int):
        """One ``phase`` event per span path (cumulative mean + count),
        emitted at epoch boundaries and run end."""
        if events_log is None:
            return
        for path, _, mean, total, count in self.rows():
            if count:
                events_log.emit("phase", name=path, seconds=mean,
                                total=total, count=count, step=int(step))
