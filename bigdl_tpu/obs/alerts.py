"""Declarative alert engine over metrics-registry snapshots
(docs/observability.md "Performance observatory").

``serve_top`` re-derived SLO burn and queue pressure inside its render
loop; the autoscaler story (ROADMAP item 5) needs those judgements to
live in the shared telemetry plane, evaluated against ANY registry —
one process's (``metrics.get().snapshot``) or the whole fleet's
(``ReplicaPool.merged_registry``).  An :class:`AlertEngine` holds a
short snapshot history and evaluates a list of declarative
:class:`Rule`\\ s on a cadence; rule kinds:

- ``threshold`` — a counter/gauge family total vs a bound (queue depth,
  pages in use);
- ``rate``      — a counter's per-second delta over ``window_s`` (shed
  rate, error rate);
- ``burn``      — multiwindow SLO burn rate (the Google-SRE pattern):
  (shed+failed)/offered divided by the error budget, required to exceed
  the threshold over BOTH a short and a long window — the short window
  makes detection fast, the long window keeps one blip from paging;
- ``baseline``  — regression vs a rolling self-baseline: the latest
  sample of a gauge vs the median of its own recent history (step-time
  regression needs no absolute bound).  A HISTOGRAM metric samples its
  windowed ``q``-quantile instead (the ``itl_regression`` default:
  windowed ITL p50 vs its own rolling median);
- ``quantile``  — a histogram family's windowed ``q``-quantile vs an
  absolute bound (bucket-count deltas over ``window_s``, exactly
  serve_top's windowed-quantile math — the ``ttft_burn`` default:
  windowed TTFT p95 above the per-token SLO budget);
- ``headroom``  — ``1 - used/limit`` of a gauge pair below a floor
  (HBM headroom).

Transitions carry hysteresis (``for_n`` consecutive breaches to fire,
``clear_n`` consecutive OKs to resolve) so a value dancing on the bound
cannot flap pages.  Each transition emits a schema-validated ``alert``
event (kind firing/resolved) and mirrors an ``alert_active`` gauge
(agg='max': any replica firing marks the fleet) so the exporter,
``serve_top``'s ``alerts:`` line and ``obs_report``'s alert timeline
all read the same truth.

The evaluation thread follows the sampler's lifecycle contract:
``close()`` sets the stop event and JOINS the thread (bounded).
Evaluation happens only at cadence boundaries — the serving/training
hot paths never see this module.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque

from bigdl_tpu.obs import metrics as obs_metrics

logger = logging.getLogger("bigdl_tpu.obs")

KINDS = ("threshold", "rate", "burn", "baseline", "quantile",
         "headroom")


class Rule:
    """One declarative alert rule.  Pure data + validation; evaluation
    lives in the engine so a rule set can be listed/serialized."""

    def __init__(self, name: str, kind: str, metric: str | None = None,
                 match: dict | None = None, op: str = ">",
                 threshold: float = 0.0, window_s: float = 60.0,
                 short_s: float = 60.0, long_s: float = 600.0,
                 budget: float = 0.01, baseline_n: int = 16,
                 min_n: int = 4, used: str | None = None,
                 limit: str | None = None, for_n: int = 1,
                 clear_n: int = 1, q: float = 50.0,
                 description: str = ""):
        if kind not in KINDS:
            raise ValueError(f"unknown rule kind {kind!r} "
                             f"(known: {KINDS})")
        if op not in (">", "<"):
            raise ValueError(f"rule op must be '>' or '<': {op!r}")
        if kind in ("threshold", "rate", "baseline",
                    "quantile") and not metric:
            raise ValueError(f"rule {name!r} ({kind}) needs a metric")
        if kind == "headroom" and not (used and limit):
            raise ValueError(f"rule {name!r} (headroom) needs "
                             f"used= and limit= metric names")
        self.name = name
        self.kind = kind
        self.metric = metric
        self.match = dict(match or {})
        self.op = "<" if kind == "headroom" else op
        self.threshold = float(threshold)
        self.window_s = float(window_s)
        self.short_s = float(short_s)
        self.long_s = float(long_s)
        self.budget = float(budget)
        self.baseline_n = int(baseline_n)
        self.min_n = int(min_n)
        self.used = used
        self.limit = limit
        self.for_n = max(1, int(for_n))
        self.clear_n = max(1, int(clear_n))
        self.q = float(q)
        self.description = description

    def max_window(self) -> float:
        if self.kind == "burn":
            return self.long_s
        return self.window_s

    def __repr__(self):
        return (f"Rule({self.name!r}, {self.kind!r}, "
                f"threshold={self.threshold})")


def slo_burn(cur: dict, prev: dict | None, budget: float) -> float | None:
    """Burn rate between two snapshots: (shed+failed)/offered over the
    window, divided by the error budget — EXACTLY serve_top's column
    math (offered = accepted+shed so each request counts once; router
    admission-stage sheds never reached an engine counter).  None when
    the window offered nothing (no traffic is not an SLO violation)."""
    def tot(snap, **match):
        return obs_metrics.family_total(snap, "serve_requests_total",
                                        **match) if snap else 0.0

    def admission(snap):
        return obs_metrics.family_total(
            snap, "router_requests_total", outcome="shed",
            stage="admission") if snap else 0.0

    d = {k: max(tot(cur, outcome=k) - tot(prev, outcome=k), 0.0)
         for k in ("accepted", "shed", "failed")}
    d["shed"] += max(admission(cur) - admission(prev), 0.0)
    offered = d["accepted"] + d["shed"]
    if offered <= 0:
        return None
    return (d["shed"] + d["failed"]) / offered / max(budget, 1e-9)


class AlertEngine:
    """Evaluate ``rules`` against ``snapshot_fn()`` on demand
    (:meth:`evaluate_once`) or on a cadence (:meth:`start`).

    Keeps a (ts, snapshot) history deque spanning the longest rule
    window; windowed values difference the newest snapshot against the
    oldest one inside the window (counters are monotonic, so a replica
    restart mid-window clamps to 0 instead of going negative)."""

    def __init__(self, snapshot_fn, rules, registry=None,
                 interval: float = 5.0, emit_events: bool = True):
        self.snapshot_fn = snapshot_fn
        self.rules = list(rules)
        self.interval = max(float(interval), 1e-3)
        self._registry = registry
        self._emit_events = emit_events
        self._hist: deque = deque()
        self._state = {r.name: {"active": False, "breach": 0, "ok": 0,
                                "value": None} for r in self.rules}
        self._baselines = {r.name: deque(maxlen=max(r.baseline_n, 2))
                           for r in self.rules if r.kind == "baseline"}
        self._stop = threading.Event()
        self._thread = None
        self._lock = threading.Lock()
        self.evaluations = 0      # cadence audit hook
        # declare every rule's gauge at 0 up front, so the exporter
        # carries the family from the first scrape and serve_top can
        # render "alerts: none" while quiet (a family that only
        # appears on first firing is indistinguishable from no alert
        # engine at all)
        try:
            reg = self._registry if self._registry is not None \
                else obs_metrics.get()
            for r in self.rules:
                reg.gauge("alert_active",
                          "1 while the named alert rule is firing",
                          agg="max", rule=r.name).set(0.0)
        except Exception:   # pragma: no cover - obs layer unavailable
            pass

    # -- value computation --------------------------------------------------
    def _window_snap(self, now: float, window_s: float):
        """Oldest retained snapshot still inside [now - window, now]
        (None until the history spans the window start)."""
        chosen = None
        for ts, snap in self._hist:
            if ts >= now - window_s:
                chosen = (ts, snap)
                break
        # too-young history: fall back to the oldest we have (a shorter
        # window biases a RATE toward firing later, never spuriously)
        if chosen is None and self._hist:
            chosen = self._hist[0]
        return chosen

    def _span_snap(self, now: float, window_s: float):
        """Newest retained snapshot at least ``window_s`` old — the
        delta against it SPANS the window.  None until the history is
        old enough (unlike :meth:`_window_snap` there is no fallback:
        burn is a ratio, not time-normalized, so a short span does not
        bias it conservative)."""
        chosen = None
        for ts, snap in self._hist:
            if ts <= now - window_s:
                chosen = (ts, snap)
            else:
                break
        return chosen

    def _window_hist_quantile(self, rule: Rule, cur: dict, now: float):
        """The windowed ``q``-quantile of a histogram family
        (``metrics.windowed_counts`` — the same windowing rule
        serve_top's columns use; bucket deltas against the oldest
        in-window snapshot, lifetime when history is younger than the
        window).  None when the window saw no observations (idle is
        not a latency violation)."""
        ref = self._window_snap(now, rule.window_s)
        wc = obs_metrics.windowed_counts(
            cur, ref[1] if ref is not None else None, rule.metric,
            **rule.match)
        if wc is None or sum(wc[1]) == 0:
            return None
        return obs_metrics.quantile(wc[0], wc[1], rule.q)

    def _value(self, rule: Rule, cur: dict, now: float):
        if rule.kind == "threshold":
            return obs_metrics.family_total(cur, rule.metric,
                                            **rule.match)
        if rule.kind == "quantile":
            return self._window_hist_quantile(rule, cur, now)
        if rule.kind == "rate":
            ref = self._window_snap(now, rule.window_s)
            if ref is None or now <= ref[0]:
                return None
            d = (obs_metrics.family_total(cur, rule.metric, **rule.match)
                 - obs_metrics.family_total(ref[1], rule.metric,
                                            **rule.match))
            return max(d, 0.0) / (now - ref[0])
        if rule.kind == "burn":
            shorts = self._window_snap(now, rule.short_s)
            # the long reference must actually SPAN long_s: with young
            # history a within-window lookup would degenerate both
            # windows to the same young snapshot and one startup blip
            # would page — exactly what the multiwindow pattern exists
            # to prevent
            longs = self._span_snap(now, rule.long_s)
            if shorts is None or longs is None:
                return None
            bs = slo_burn(cur, shorts[1], rule.budget)
            bl = slo_burn(cur, longs[1], rule.budget)
            if bs is None or bl is None:
                return None
            # multiwindow: BOTH windows must exceed, so the comparable
            # value is the smaller burn of the two
            return min(bs, bl)
        if rule.kind == "baseline":
            fam = cur.get(rule.metric)
            if fam is not None and fam.get("type") == "histogram":
                # histogram metric: the regression sample is the
                # windowed quantile (e.g. ITL p50) — same hysteresis
                # and rolling-median machinery as the gauge path
                sample = self._window_hist_quantile(rule, cur, now)
                if sample is None:
                    return None
            else:
                sample = obs_metrics.family_total(cur, rule.metric,
                                                  **rule.match)
            hist = self._baselines[rule.name]
            if sample <= 0:
                return None
            prior = sorted(hist)
            # append only on CHANGE: the gauge updates at its own
            # cadence (flush boundaries), slower than the evaluation
            # tick — re-appending an unchanged regressed value would
            # drag the rolling median up to it and self-resolve the
            # alert while the regression persists
            if not hist or hist[-1] != sample:
                hist.append(sample)
            if len(prior) < rule.min_n:
                return None
            baseline = prior[len(prior) // 2]     # median of history
            if baseline <= 0:
                return None
            return sample / baseline
        if rule.kind == "headroom":
            used = obs_metrics.family_total(cur, rule.used, **rule.match)
            limit = obs_metrics.family_total(cur, rule.limit,
                                             **rule.match)
            if limit <= 0:
                return None
            return 1.0 - used / limit
        return None   # pragma: no cover - kinds validated in Rule

    # -- transitions --------------------------------------------------------
    def _transition(self, rule: Rule, kind: str, value):
        try:
            reg = self._registry
            if reg is None:
                reg = obs_metrics.get()
            reg.gauge("alert_active",
                      "1 while the named alert rule is firing",
                      agg="max", rule=rule.name).set(
                          1.0 if kind == "firing" else 0.0)
        except Exception:   # pragma: no cover - obs layer mid-teardown
            pass
        if self._emit_events:
            try:
                from bigdl_tpu.obs import events
                events.emit("alert", kind=kind, rule=rule.name,
                            value=float(value), threshold=rule.threshold,
                            rule_kind=rule.kind,
                            description=rule.description)
            except Exception:   # pragma: no cover - defensive
                pass
        logger.info("alert %s: %s (value=%.4g threshold=%.4g)",
                    kind, rule.name, value, rule.threshold)

    def evaluate_once(self, snapshot=None, now=None) -> list:
        """One evaluation pass; returns the transitions fired this pass
        as ``(rule_name, 'firing'|'resolved', value)`` tuples.  Safe to
        call concurrently with the cadence thread (locked)."""
        with self._lock:
            if now is None:
                now = time.time()
            if snapshot is None:
                try:
                    snapshot = self.snapshot_fn()
                except Exception as e:  # pragma: no cover - racing close
                    logger.warning("alert snapshot pull failed: %s", e)
                    return []
            transitions = []
            for rule in self.rules:
                st = self._state[rule.name]
                value = self._value(rule, snapshot, now)
                st["value"] = value
                breached = False
                if value is not None:
                    breached = (value > rule.threshold if rule.op == ">"
                                else value < rule.threshold)
                if breached:
                    st["breach"] += 1
                    st["ok"] = 0
                    if not st["active"] and st["breach"] >= rule.for_n:
                        st["active"] = True
                        self._transition(rule, "firing", value)
                        transitions.append((rule.name, "firing", value))
                else:
                    st["ok"] += 1
                    st["breach"] = 0
                    if st["active"] and st["ok"] >= rule.clear_n:
                        st["active"] = False
                        self._transition(
                            rule, "resolved",
                            value if value is not None else 0.0)
                        transitions.append((rule.name, "resolved",
                                            value))
            # history AFTER evaluation: windowed rules difference the
            # current snapshot against strictly older ones
            self._hist.append((now, snapshot))
            horizon = max([r.max_window() for r in self.rules],
                          default=0.0) * 1.25 + self.interval
            while len(self._hist) > 2 and \
                    self._hist[0][0] < now - horizon:
                self._hist.popleft()
            self.evaluations += 1
            return transitions

    def active(self) -> list:
        """Names of currently-firing rules (sorted)."""
        with self._lock:
            return sorted(n for n, st in self._state.items()
                          if st["active"])

    def state(self) -> dict:
        with self._lock:
            return {n: dict(st) for n, st in self._state.items()}

    # -- cadence thread -----------------------------------------------------
    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                self.evaluate_once()
            except Exception as e:  # pragma: no cover - defensive
                logger.warning("alert evaluation failed: %s", e)

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name="bigdl-obs-alerts")
            self._thread.start()
        return self

    def close(self, timeout: float = 10.0):
        """Stop-event + bounded join (the sampler/Router lifecycle
        contract) — idempotent."""
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=timeout)
        self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()


def default_rules(budget: float = 0.01, queue_depth: float = 64.0,
                  shed_per_s: float = 1.0, burn: float = 1.0,
                  step_time_factor: float = 2.0,
                  hbm_headroom: float = 0.05, short_s: float = 60.0,
                  long_s: float = 600.0,
                  ttft_slo_ms: float | None = None,
                  itl_factor: float = 3.0,
                  itl_slo_ms: float | None = None) -> list:
    """The shipped rule set (docs/observability.md has the table):
    SLO burn (multiwindow), shed rate, queue depth, train step-time
    regression vs a rolling self-baseline, HBM headroom,
    ``fleet_scale_frozen`` (the autoscaler's spawn circuit breaker —
    fires the moment the gauge goes 1), plus the
    per-token streaming pair — ``ttft_burn`` (windowed TTFT p95 above
    the first-token SLO budget; ``ttft_slo_ms`` defaults to
    ``BIGDL_SERVE_SLO_TTFT_MS``, falling back to 500 ms when no class
    is declared, and an EXPLICIT 0 disables the rule) and
    ``itl_regression`` (windowed ITL p50 above ``itl_factor``x its own
    rolling median — stalls show up without an absolute bound).  A
    DECLARED inter-token budget (``itl_slo_ms``, default
    ``BIGDL_SERVE_SLO_ITL_MS``; 0 = none) additionally arms an
    absolute ``itl_burn`` rule: windowed ITL p95 above the budget."""
    # same env names the router's per-token SLO class reads
    # (serve/streaming.py ttft_ms_default/itl_ms_default); parsed
    # locally so the obs layer never drags the serve package (and jax)
    # into a training-only process just to arm alerts
    if ttft_slo_ms is None:
        ttft_slo_ms = _slo_env_ms("BIGDL_SERVE_SLO_TTFT_MS") or 500.0
    if itl_slo_ms is None:
        itl_slo_ms = _slo_env_ms("BIGDL_SERVE_SLO_ITL_MS")
    extra = []
    if ttft_slo_ms and ttft_slo_ms > 0:
        extra.append(Rule(
            "ttft_burn", "quantile", metric="decode_ttft_seconds",
            q=95, threshold=ttft_slo_ms / 1e3, window_s=short_s,
            clear_n=2,
            description="windowed time-to-first-token p95 above the "
                        f"{ttft_slo_ms:g} ms streaming SLO budget"))
    if itl_slo_ms and itl_slo_ms > 0:
        extra.append(Rule(
            "itl_burn", "quantile", metric="decode_itl_seconds", q=95,
            threshold=itl_slo_ms / 1e3, window_s=short_s, clear_n=2,
            description="windowed inter-token latency p95 above the "
                        f"{itl_slo_ms:g} ms streaming SLO budget"))
    return [
        Rule("slo_burn", "burn", budget=budget, threshold=burn,
             short_s=short_s, long_s=long_s, clear_n=2,
             description="error budget burning faster than it accrues "
                         "over both windows"),
        Rule("shed_rate", "rate", metric="serve_requests_total",
             match={"outcome": "shed"}, window_s=short_s,
             threshold=shed_per_s, clear_n=2,
             description="admission shedding sustained above "
                         f"{shed_per_s}/s"),
        Rule("queue_depth", "threshold", metric="serve_queue_depth",
             threshold=queue_depth,
             description="fleet queue depth above bound"),
        Rule("step_time_regression", "baseline",
             metric="train_step_wall_seconds",
             threshold=step_time_factor, min_n=4, for_n=2, clear_n=2,
             description="windowed train step wall above "
                         f"{step_time_factor}x its rolling median"),
        Rule("hbm_headroom", "headroom", used="hbm_bytes_in_use",
             limit="hbm_bytes_limit", threshold=hbm_headroom,
             description="free HBM below "
                         f"{hbm_headroom:.0%} of capacity"),
        Rule("itl_regression", "baseline", metric="decode_itl_seconds",
             q=50, threshold=itl_factor, window_s=short_s, min_n=4,
             for_n=2, clear_n=2,
             description="windowed inter-token latency p50 above "
                         f"{itl_factor}x its rolling median"),
        Rule("fleet_scale_frozen", "threshold",
             metric="fleet_scale_frozen", threshold=0.5,
             description="the autoscaler's spawn circuit breaker is "
                         "open: repeated replica spawn failure — the "
                         "fleet cannot grow (serve/autoscale.py)"),
    ] + extra


def _slo_env_ms(name: str) -> float:
    """A millisecond SLO budget env var (0/-/malformed = none) —
    mirrors serve/streaming's parse without importing the serve
    package."""
    import os
    try:
        return max(0.0, float(os.environ.get(name, "0") or 0))
    except ValueError:
        return 0.0
