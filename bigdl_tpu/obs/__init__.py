"""Unified runtime telemetry (docs/observability.md).

Four cooperating parts, wired through the optimizers, Engine and the
resilience layer:

- ``taps``: in-jit scalar taps (grad norm, param norm, update ratio,
  non-finite counts) returned by the SAME compiled train step, host-
  materialized only every ``BIGDL_OBS_TAPS_CADENCE`` steps;
- ``events``: schema-versioned JSONL event stream per process + an
  in-memory ring buffer (``BIGDL_OBS_DIR`` enables the file sink);
- ``spans``: nested wall-clock phase spans layered on ``optim.Metrics``
  and ``jax.profiler`` annotations, gathered once per run via the
  deadlock-safe ``collect_per_node`` pattern;
- ``diagnostics``: crash bundles (ring tail, device memory, config,
  thread stacks) dumped on watchdog trips, preemption and non-finite
  aborts;
- ``summary``: TensorBoard-compatible scalar export (the
  ``TrainSummary``/``ValidationSummary`` parity piece), no TF dep;
- ``metrics``: typed process-wide counters/gauges/fixed-bucket
  histograms that merge EXACTLY across serve replicas and processes,
  with Prometheus text + JSONL snapshot export (``obs/export.py`` is
  the pull endpoint, ``tools/serve_top.py`` the terminal dashboard);
- ``trace``: sampled per-request trace contexts for the serving stack
  (``BIGDL_OBS_TRACE_SAMPLE``), emitted as ``trace`` events;
- ``recorder``: the always-on per-request flight recorder
  (``BIGDL_OBS_RECORDER``) — tail-based trace retention plus schema-v7
  ``forensic`` bundles for anomalous requests, the records
  ``tools/request_replay.py`` re-executes deterministically;
- ``ledger``: the compile-time cost/memory ledger (flops, bytes,
  peak HBM per compiled executable, captured at the executable-cache
  chokepoint), live ``train_mfu``/``decode_model_flops_util`` truth,
  static HBM tenant accounting and the cadence device-memory sampler;
- ``alerts``: declarative alert rules (threshold / windowed rate /
  multiwindow SLO burn / baseline regression / HBM headroom) evaluated
  against any registry snapshot — local or fleet-merged — with
  hysteresis, ``alert`` events and ``alert_active`` gauges.

Master switch: ``BIGDL_OBS=0`` turns the event/diagnostic machinery
off; ``BIGDL_OBS_TAPS=0`` removes the taps from the compiled step.
``tools/obs_report.py`` renders a run directory into markdown.
"""
# NOTE: ``export`` is deliberately NOT imported eagerly — it drags in
# http.server, which every training run and subprocess replica would
# otherwise pay at import time; its consumers (serve/cluster.py, the
# exporter tests) import it lazily.
from bigdl_tpu.obs import (  # noqa: F401
    alerts, diagnostics, events, ledger, metrics, recorder, spans, taps,
    trace,
)
from bigdl_tpu.obs.diagnostics import dump_crash_bundle  # noqa: F401
from bigdl_tpu.obs.events import (  # noqa: F401
    SCHEMA_VERSION, EventLog, read_events, validate_event,
)
from bigdl_tpu.obs.metrics import (  # noqa: F401
    LATENCY_BUCKETS, Registry, parse_prometheus, render_prometheus,
)
from bigdl_tpu.obs.trace import Sampler, Trace  # noqa: F401
from bigdl_tpu.obs.spans import PHASES, SpanTracker  # noqa: F401
from bigdl_tpu.obs.summary import (  # noqa: F401
    ScalarWriter, TrainSummary, ValidationSummary, read_scalars,
)
from bigdl_tpu.obs.taps import TAP_NAMES, TapsMonitor  # noqa: F401
