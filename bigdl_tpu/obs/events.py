"""Structured event log — schema-versioned JSONL per process plus an
in-memory ring buffer (docs/observability.md).

The reference explains a run through the driver log (Optimizer.header
progress lines + Metrics summaries); that is unparseable after the fact
and says nothing about *why* a step was skipped or a host died.  Here
every notable runtime moment — step, phase, validation, checkpoint,
fault injection, watchdog trip, preemption, abort — is one JSON object
with a fixed schema, so ``tools/obs_report.py`` (or any jq one-liner)
can reconstruct the run, and the crash-bundle path
(``obs/diagnostics.py``) can dump the last-N events even when the
process is going down inside a signal handler or a watchdog thread.

Layout: one ``events.p<process_index>.jsonl`` per process under the run
directory (``BIGDL_OBS_DIR`` or :func:`configure`), mirroring the
one-log-per-executor shape of the reference's Spark stdout collection.
With no run directory the log is ring-only: events are still retained
in memory for crash bundles, nothing touches the filesystem.

Master switch ``BIGDL_OBS=0`` disables the subsystem entirely (``get``
returns None and the convenience :func:`emit` becomes a no-op).
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque

logger = logging.getLogger("bigdl_tpu.obs")

#: bump when an event type gains/loses REQUIRED fields; readers accept
#: unknown optional fields at any version.  v2: `serve` events grew
#: per-kind required fields (SERVE_KINDS) and the `trace` type landed.
#: v3: the `ledger` (compile-time cost/HBM truth) and `alert`
#: (declarative rule transitions) types landed, each with per-kind
#: required fields (LEDGER_KINDS / ALERT_KINDS).  v4: the `stream`
#: serve kind landed (one streamed decode request's token timeline),
#: and `decode` events that report streaming (``streaming: true``)
#: must carry `first_token_ms` + `stream_boundaries`.  v5: the `scale`
#: type landed (autoscaler/dynamic-membership decisions, SCALE_KINDS)
#: plus the `replica_added`/`replica_draining`/`replica_removed`
#: serve kinds the router emits on membership changes.  v6: the
#: `remote` type landed (cross-host TCP replica lifecycle,
#: REMOTE_KINDS: connect/blip/reattach/partition/death — the
#: blip-vs-death audit trail docs/serving.md "Cross-host fleet"
#: documents).  v7: the `forensic` type landed (obs/recorder.py
#: tail-based request forensics, FORENSIC_KINDS: one anomalous
#: request's full flight-recorder record + ring-neighbor context —
#: the non-fatal analog of the crash bundle).
SCHEMA_VERSION = 7

ENV_OBS = "BIGDL_OBS"
ENV_DIR = "BIGDL_OBS_DIR"
ENV_RING = "BIGDL_OBS_RING"
ENV_MAX_MB = "BIGDL_OBS_MAX_MB"
ENV_KEEP = "BIGDL_OBS_KEEP"

#: required fields per event type (beyond the common envelope); optional
#: fields (taps, straggler_dropped, skips, ...) are free-form
EVENT_TYPES = {
    "run_start": ("flags",),
    "run_end": ("steps", "wall"),
    "step": ("step", "loss", "lr", "throughput"),
    "phase": ("name", "seconds"),
    "validation": ("step", "method", "value"),
    "checkpoint": ("step", "path"),
    "fault": ("site", "step"),
    # the input pipeline failed to hide the fetch: the consuming loop
    # waited `seconds` for the prefetch queue at `step` (queue was empty)
    "prefetch_stall": ("step", "seconds"),
    # serving lifecycle/telemetry (serve/engine.py, serve/decode.py,
    # serve/router.py, serve/cluster.py): kind-specific required fields
    # in SERVE_KINDS below; error events carry the failed request count
    # + message, stop events a stats snapshot, rollout events the weight
    # version (the hot-swap audit trail, docs/serving.md)
    "serve": ("kind",),
    # one sampled request's hop chain (obs/trace.py): hops is a list of
    # [phase, perf_counter_ts] pairs, status in {ok, shed, failed}
    "trace": ("trace_id", "status", "hops"),
    "watchdog": ("stale",),
    # elastic recovery lifecycle (resilience/elastic.py): kind-specific
    # required fields in RECOVER_KINDS below — the trip→quiesce→reform→
    # reshard→resume chain is the recovery timeline obs_report renders
    "recover": ("kind",),
    "preempt": ("step",),
    "abort": ("step", "reason"),
    "crash_bundle": ("reason", "path"),
    # compile-time cost/HBM ledger (obs/ledger.py): kind-specific
    # required fields in LEDGER_KINDS — exec captures, tenant bytes,
    # device-memory samples (the obs_report HBM timeline)
    "ledger": ("kind",),
    # declarative alert transitions (obs/alerts.py): firing/resolved
    # with the rule name + the value/threshold that judged it
    "alert": ("kind", "rule"),
    # autoscaler / dynamic-membership decisions (serve/autoscale.py,
    # ReplicaPool.add_replica/remove_replica): kind-specific required
    # fields in SCALE_KINDS — the scale/recovery timeline obs_report
    # renders and the capstone chaos drill asserts on
    "scale": ("kind",),
    # cross-host replica transport lifecycle (serve/remote.py,
    # tools/replica_agent.py): kind-specific required fields in
    # REMOTE_KINDS — connect/blip/reattach/partition/death, the trail
    # that distinguishes a survived network blip (reattach, zero
    # requeues) from a real death (requeue-exactly-once)
    "remote": ("kind",),
    # one anomalous request's forensic bundle (obs/recorder.py, schema
    # v7): the FlightRecorder's full per-request record plus the ring's
    # neighboring-request context, emitted at the anomalous terminal
    # state — kind-specific required fields in FORENSIC_KINDS
    "forensic": ("kind", "trace_id", "record"),
}

#: per-kind REQUIRED fields for `serve` events (v2).  An unknown kind is
#: a validation error — a silent typo'd kind would vanish from every
#: postmortem query.  Fields here are the ones downstream tools key on
#: (obs_report's rollout timeline needs the version, the requeue audit
#: needs the replica name); everything else stays free-form.
SERVE_KINDS = {
    "start": (),
    "stop": (),
    "error": ("error",),
    "decode": ("steps",),
    # one streamed decode request's per-token timeline (serve/decode.py
    # emits at retire): tokens delivered, submit→first-token latency,
    # and the per-boundary [ms-since-submit, token-count] pairs the
    # obs_report token waterfall renders (schema v4)
    "stream": ("tokens", "ttft_ms", "timeline"),
    "shed": (),
    "weights_commit": ("version",),
    "weights_revert": ("version",),
    "router_start": ("replicas",),
    "router_stop": (),
    "replica_dead": ("replica",),
    # dynamic membership (schema v5): a replica joining the dispatch
    # set, entering drain-only state, or leaving the pool entirely
    "replica_added": ("replica",),
    "replica_draining": ("replica",),
    "replica_removed": ("replica",),
    "fleet_start": ("replicas",),
    "fleet_stop": ("replicas",),
    "rollout_begin": ("version",),
    "rollout_commit": ("version",),
    "rollout_rollback": ("version", "phase"),
}

#: per-kind REQUIRED fields for `recover` events (schema v2, same
#: contract as SERVE_KINDS): an unknown kind is a validation error.
#: world sizes ride the reform/reshard/resume kinds so a postmortem can
#: read the membership change without correlating other streams;
#: `resume` carries the recovery pause (seconds from trip to the first
#: post-reform dispatch) — the number the bounded-pause acceptance
#: drill asserts on.
RECOVER_KINDS = {
    "trip": ("stale",),
    "quiesce": ("step",),
    "reform": ("world_before", "world_after"),
    "reshard": ("world_after",),
    "resume": ("step", "world_before", "world_after", "pause_s"),
    "abort": ("reason",),
}

#: per-kind REQUIRED fields for `ledger` events (schema v3, same
#: contract as SERVE_KINDS): an unknown kind is a validation error.
#: `exec` is one compiled executable's cost truth (obs/ledger.py
#: capture), `tenant` a named large allocation's current bytes,
#: `hbm` one device-memory sampler tick (the report's HBM timeline).
LEDGER_KINDS = {
    "exec": ("fn", "flops", "bytes_accessed"),
    "tenant": ("tenant", "bytes"),
    "hbm": ("in_use",),
}

#: per-kind REQUIRED fields for `alert` events (schema v3): every
#: transition carries the value that judged it and the rule's bound,
#: so a postmortem reads the margin without replaying the registry.
ALERT_KINDS = {
    "firing": ("value", "threshold"),
    "resolved": ("value", "threshold"),
}

#: per-kind REQUIRED fields for `scale` events (schema v5, the
#: SERVE_KINDS contract): an unknown kind is a validation error.  `up`
#: and `down` are committed membership changes and carry the replica
#: plus the POLICY REASON that drove the decision (the audit trail the
#: capstone drill reads back); `spawn_failed` is one failed spawn
#: attempt inside the retry/backoff loop, `frozen`/`unfrozen` the
#: circuit-breaker transitions that stop a crash loop.
SCALE_KINDS = {
    "up": ("replica", "reason"),
    "down": ("replica", "reason"),
    "spawn_failed": ("error", "attempt"),
    "frozen": ("failures",),
    "unfrozen": (),
}

#: per-kind REQUIRED fields for `remote` events (v6) — the cross-host
#: transport lifecycle.  `blip` marks a lost connection still inside
#: the liveness budget (reconnect in progress), `reattach` the
#: successful resume of the SAME session (carries the measured outage),
#: `partition` the agent-side chaos injection, `death` the client-side
#: conversion to DeadReplicaError after the budget expired.
REMOTE_KINDS = {
    "connect": ("replica", "address"),
    "blip": ("replica",),
    "reattach": ("replica", "blip_s"),
    "partition": ("len_s",),
    "death": ("replica",),
}

#: per-kind REQUIRED fields for `forensic` events (schema v7, the
#: SERVE_KINDS contract): an unknown kind is a validation error.  Each
#: kind is one way a request ends anomalous; the `record` field carries
#: the FlightRecorder's full per-request record (obs/recorder.py) and
#: `context` the ring's neighboring-request summaries.  `slo_miss`
#: names which budget was blown (`slo` in {deadline, ttft, e2e});
#: `slow` carries the latency and the tail bound that judged it;
#: `partition` marks a request in flight across a RemoteReplica blip.
FORENSIC_KINDS = {
    "error": ("error",),
    "shed": ("stage",),
    "requeue": ("attempts",),
    "slo_miss": ("slo",),
    "slow": ("e2e_ms", "bound_ms"),
    "replica_death": ("replica",),
    "partition": ("replica",),
}

_COMMON = ("v", "ts", "proc", "type")

_KINDED = {"serve": SERVE_KINDS, "recover": RECOVER_KINDS,
           "ledger": LEDGER_KINDS, "alert": ALERT_KINDS,
           "scale": SCALE_KINDS, "remote": REMOTE_KINDS,
           "forensic": FORENSIC_KINDS}


def validate_event(event: dict) -> dict:
    """Check one decoded event against the schema; returns the event or
    raises ValueError naming the violation.  Used by the smoke script
    and report tool so a malformed emitter fails CI, not a postmortem."""
    if not isinstance(event, dict):
        raise ValueError(f"event must be an object, got {type(event)}")
    for k in _COMMON:
        if k not in event:
            raise ValueError(f"event missing common field {k!r}: {event}")
    if not isinstance(event["v"], int):
        raise ValueError(f"schema version must be int: {event['v']!r}")
    if event["v"] > SCHEMA_VERSION:
        raise ValueError(f"event schema v{event['v']} is newer than this "
                         f"reader (v{SCHEMA_VERSION})")
    etype = event["type"]
    required = EVENT_TYPES.get(etype)
    if required is None:
        raise ValueError(f"unknown event type {etype!r} "
                         f"(known: {sorted(EVENT_TYPES)})")
    missing = [k for k in required if k not in event]
    if missing:
        raise ValueError(f"{etype!r} event missing {missing}: {event}")
    kinds = _KINDED.get(etype)
    if kinds is not None:
        kind = event["kind"]
        per_kind = kinds.get(kind)
        if per_kind is None:
            raise ValueError(f"unknown {etype} kind {kind!r} "
                             f"(known: {sorted(kinds)})")
        missing = [k for k in per_kind if k not in event]
        if missing:
            raise ValueError(
                f"{etype}/{kind} event missing {missing}: {event}")
    if etype == "serve":
        kind = event["kind"]
        if kind == "decode" and event.get("streaming"):
            # required-when-streaming (schema v4): a decode run that
            # claims streaming must carry its SLO aggregates
            missing = [k for k in ("first_token_ms", "stream_boundaries")
                       if k not in event]
            if missing:
                raise ValueError(
                    f"streaming decode event missing {missing}: {event}")
        if kind == "stream":
            tl = event["timeline"]
            if (not isinstance(tl, list) or not tl
                    or not all(isinstance(b, (list, tuple)) and len(b) == 2
                               for b in tl)):
                raise ValueError(
                    f"stream timeline must be a non-empty list of "
                    f"[ms, tokens] pairs: {tl!r}")
    if etype == "trace":
        hops = event["hops"]
        if (not isinstance(hops, list) or not hops
                or not all(isinstance(h, (list, tuple)) and len(h) == 2
                           for h in hops)):
            raise ValueError(
                f"trace hops must be a non-empty list of "
                f"[phase, ts] pairs: {hops!r}")
    return event


def _process_index() -> int:
    """Lazy jax process index (0 pre-init / jax-less contexts, e.g. a
    watchdog thread before the distributed client is up)."""
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


class EventLog:
    """Ring buffer + optional JSONL sink for one process.

    Thread-safe: the training loop, the watchdog monitor thread and a
    signal-handler epilogue may all emit concurrently."""

    def __init__(self, run_dir: str | None = None, ring: int | None = None,
                 process_index: int | None = None,
                 max_mb: float | None = None, keep: int | None = None):
        if ring is None:
            ring = int(os.environ.get(ENV_RING, "512"))
        if max_mb is None:
            try:
                max_mb = float(os.environ.get(ENV_MAX_MB, "0") or 0)
            except ValueError:
                max_mb = 0.0
        if keep is None:
            try:
                keep = int(os.environ.get(ENV_KEEP, "2"))
            except ValueError:
                keep = 2
        self.run_dir = run_dir
        self._proc = process_index
        self._ring = deque(maxlen=max(int(ring), 1))
        self._lock = threading.Lock()
        self._sinks = []     # extra per-event callbacks (add_sink)
        self._fh = None
        self.path = None
        #: JSONL size cap (bytes; 0 = unlimited): a week-long serving
        #: run must not fill the disk.  On overflow the current file
        #: rotates to `<path>.1` with keep-last semantics (like
        #: `BIGDL_CKPT_KEEP`): the newest `keep` rotated segments
        #: survive, older ones are deleted.  The in-memory ring — and
        #: therefore crash bundles — is unaffected by rotation.
        self._max_bytes = int(float(max_mb) * (1 << 20))
        self._keep = max(1, int(keep))
        self.rotations = 0
        if run_dir:
            os.makedirs(run_dir, exist_ok=True)
            self.path = os.path.join(
                run_dir, f"events.p{self.process_index()}.jsonl")
            self._fh = open(self.path, "a")

    def process_index(self) -> int:
        if self._proc is None:
            self._proc = _process_index()
        return self._proc

    def _record(self, event: dict):
        """Ring-append + file-write one event under the lock (the one
        write path both :meth:`emit` and :meth:`append_foreign` share).
        Never raises: a full disk must not kill the training loop."""
        self._ring.append(event)
        if self._fh is not None:
            try:
                self._fh.write(json.dumps(event, default=_jsonable))
                self._fh.write("\n")
                self._fh.flush()
                if self._max_bytes and self._fh.tell() >= self._max_bytes:
                    self._rotate()
            except (OSError, ValueError) as e:
                logger.warning("event sink write failed: %s", e)

    def _rotate(self):
        """Shift the full JSONL to ``<path>.1`` (``.1``→``.2``, ...;
        segments beyond ``keep`` deleted) and reopen a fresh file.
        Called under the lock from :meth:`_record`; best-effort — a
        rotation failure must not kill the emitter."""
        try:
            self._fh.close()
            last = self.path + f".{self._keep}"
            if os.path.exists(last):
                os.unlink(last)
            for j in range(self._keep - 1, 0, -1):
                src = self.path + f".{j}"
                if os.path.exists(src):
                    os.replace(src, self.path + f".{j + 1}")
            os.replace(self.path, self.path + ".1")
            self.rotations += 1
        except OSError as e:   # pragma: no cover - fs race/perm
            logger.warning("event log rotation failed: %s", e)
        finally:
            self._fh = open(self.path, "a")

    def emit(self, etype: str, **fields) -> dict:
        """Append one event (common envelope added here).  Never raises
        past the sink: a full disk must not kill the training loop."""
        event = {"v": SCHEMA_VERSION, "ts": time.time(),
                 "proc": self.process_index(), "type": etype}
        event.update(fields)
        with self._lock:
            self._record(event)
            sinks = list(self._sinks)
        for sink in sinks:   # outside the lock: a sink may be slow/deadlocky
            try:
                sink(event)
            except Exception as e:
                logger.warning("event sink callback failed: %s", e)
        return event

    def add_sink(self, fn):
        """Register a per-event callback (called with the event dict
        after ring/file write).  Subprocess replicas use this to stream
        their events to the parent over the frame protocol
        (serve/cluster.py) — ending the stderr/DEVNULL blackout.
        Callback errors are swallowed: telemetry fan-out must never
        break an emitter."""
        with self._lock:
            self._sinks.append(fn)
        return fn

    def append_foreign(self, event: dict, **extra) -> dict:
        """Record an event that already carries another process's
        envelope (a replica child's, forwarded over stdio frames) into
        THIS log's ring and file sink.  ``extra`` fields (e.g.
        ``replica=<name>``) are added so the merged stream stays
        attributable; the child's own ``ts``/``proc``/``type`` are kept
        verbatim.  Not fanned out to sinks (no forwarding loops)."""
        event = dict(event)
        event.update(extra)
        with self._lock:
            self._record(event)
        return event

    def ring_events(self) -> list:
        """Snapshot of the in-memory ring (oldest first)."""
        with self._lock:
            return list(self._ring)

    def close(self):
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


def _jsonable(v):
    """json.dumps default: numpy/jax scalars degrade to floats, anything
    else to repr — an event must never fail to serialize."""
    try:
        return float(v)
    except (TypeError, ValueError):
        return repr(v)


def read_events(path: str) -> list:
    """Decode one JSONL file (no validation — see validate_event)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


# -- process-wide log (env-configured; tests use configure) ----------------

_LOG: EventLog | None = None
_LOADED = False


def enabled() -> bool:
    return os.environ.get(ENV_OBS, "1") != "0"


def get() -> EventLog | None:
    """The process event log, or None when obs is off (``BIGDL_OBS=0``).
    Created lazily: ring-only unless ``BIGDL_OBS_DIR`` names a run
    directory.  ``configure``/``reset`` override."""
    global _LOG, _LOADED
    if not _LOADED:
        _LOADED = True
        if enabled():
            run_dir = os.environ.get(ENV_DIR, "").strip() or None
            _LOG = EventLog(run_dir=run_dir)
    return _LOG


def configure(run_dir: str | None = None, ring: int | None = None,
              process_index: int | None = None,
              max_mb: float | None = None,
              keep: int | None = None) -> EventLog:
    """Install a process event log programmatically (launchers, tests)."""
    global _LOG, _LOADED
    if _LOG is not None:
        _LOG.close()
    _LOG = EventLog(run_dir=run_dir, ring=ring, process_index=process_index,
                    max_mb=max_mb, keep=keep)
    _LOADED = True
    return _LOG


def reset():
    """Close and forget the process log (re-reads env on next get())."""
    global _LOG, _LOADED
    if _LOG is not None:
        _LOG.close()
    _LOG = None
    _LOADED = False


def emit(etype: str, **fields):
    """Convenience: emit to the process log if obs is on; no-op (None)
    otherwise.  Swallows everything — emission sites include fault
    injectors and exit paths where a telemetry bug must not mask the
    real failure."""
    try:
        log = get()
        if log is None:
            return None
        return log.emit(etype, **fields)
    except Exception as e:  # pragma: no cover - defensive
        logger.warning("event emit failed: %s", e)
        return None
